"""Elastic sampler fleet: N independent rollout engines behind one
``generate()`` — lose a sampler, not the run.

The Podracer-shaped layer for disaggregated RLHF (docs/RLHF.md
"Disaggregated sampler fleet"): a learner pod feeds weight refits to N
sampler members, each a supervised :class:`RolloutEngine` pinned to its
own single-thread executor (the serving-fleet member idiom), and the
members stream completed *trajectory groups* — one unique prompt with
its G seeded samples — back through a bounded multi-producer queue.
Three robustness mechanisms make the fleet many-and-lossy:

**Refit fanout.** ``publish_params`` walks the broadcast-tree wave
schedule (:func:`~dla_tpu.serving.fleet.broadcast_waves`): each wave's
publishes run concurrently on the target members' executors, so refit
wall time is bounded by the tree depth (``O(log N)`` waves), not by N
serial publishes (``bench.py rollout-fleet`` pins the ratio). Every
member publish gets a per-member timeout and bounded retry; a member
that exhausts its retries keeps sampling with its OLD weights (its
groups carry an older version tag — the per-trajectory staleness the
pipeline corrects for), and a member that fails
``retire_after_failures`` consecutive fanouts is retired instead of
ever stalling the learner's step loop.

**Trajectory sharding.** Completed groups land on the bounded queue
tagged with the emitting member's slot, param version (the learner
update count stamped at its last successful refit), membership epoch,
and the rollout index they were generated for — the collector accepts
a group only from its current owner for the current rollout (and the
queue is drained at each rollout start), so a slow retired member can
never leak rows across a rollout boundary. The consumer side
reassembles strictly in group order —
completion order can never change the arrays — and
:func:`shard_trajectory_groups` deterministically slices groups across
learner data-parallel ranks. Because members refit at different times
(a fanout-failed member lags), staleness is a per-trajectory vector
(``row_versions``), not a batch scalar.

**Elastic gang semantics.** Every member beats an in-process lease
(the ``resilience/elastic.py`` lease+epoch idiom, wall-clock TTL) from
its drive loop. A dead/wedged/silent member stops beating; the
collector detects the stale lease within one TTL, retires the member
(membership epoch bump), and reassigns its unfinished prompt indices
to survivors. Reassigned groups regenerate **bit-identically** from
the journaled (prompt, seed) pairs: token streams are pure functions
of (seed, token index) — never of placement — so any partition of
groups over any surviving member set yields the same arrays (given
equal member versions). ``sampler=I:rollout_step=N:lost|slow`` fault
plans (resilience.faults) drive all of this deterministically; the
fleet can re-grow to target size through the same engine factory
(``regrow: true``).
"""
from __future__ import annotations

import contextlib
import dataclasses
import queue
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeout
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dla_tpu.generation.engine import GenerationConfig
from dla_tpu.ops.sampling import SamplingParams
from dla_tpu.resilience.faults import Fault, FaultPlan
from dla_tpu.rollout.engine import (RolloutEngine, RolloutMetrics,
                                    RolloutStopped, assemble_rows)
from dla_tpu.serving.fleet import broadcast_waves
from dla_tpu.serving.scheduler import TERMINAL_STATES
from dla_tpu.serving.server import ServingConfig
from dla_tpu.telemetry.registry import MetricRegistry
from dla_tpu.telemetry.trace import get_tracer, register_trace_gauges
from dla_tpu.telemetry.trace_context import TraceContext


class SamplerFleetMetrics:
    """The ``rollout/fleet/*`` CATALOG panel. Lives on the FLEET's
    registry (shared with the fleet-level :class:`RolloutMetrics`), not
    any member's — member engines retire and respawn, the fleet object
    does not, so these totals are monotone across both by construction
    (the delta-mirror rule every fleet-scoped panel follows)."""

    def __init__(self, registry: Optional[MetricRegistry] = None):
        r = self.registry = registry or MetricRegistry()
        self.samplers_active = r.gauge("rollout/fleet/samplers_active")
        self.refit_fanout_ms = r.gauge("rollout/fleet/refit_fanout_ms")
        self.retired_samplers = r.counter("rollout/fleet/retired_samplers")
        self.reassigned_rollouts = r.counter(
            "rollout/fleet/reassigned_rollouts")
        self.trajectory_queue_depth = r.gauge(
            "rollout/fleet/trajectory_queue_depth")
        # span-drop accounting for the fleet process's tracer ring
        # (members share it), the trainer tracer's contract
        register_trace_gauges(r)

    def snapshot(self) -> Dict[str, float]:
        return {
            "rollout/fleet/samplers_active": self.samplers_active.value,
            "rollout/fleet/refit_fanout_ms": self.refit_fanout_ms.value,
            "rollout/fleet/retired_samplers": self.retired_samplers.value,
            "rollout/fleet/reassigned_rollouts":
                self.reassigned_rollouts.value,
            "rollout/fleet/trajectory_queue_depth":
                self.trajectory_queue_depth.value,
        }


@dataclasses.dataclass(frozen=True)
class SamplerFleetConfig:
    """``ppo.rollout.fleet``: sampler-fleet shape and failure policy.

    ``refit_delay_s`` is a bench/chaos knob — a per-member sleep inside
    each publish, making the serial-vs-broadcast fanout A/B
    deterministic on CPU (``bench.py rollout-fleet``)."""
    samplers: int = 2
    fanout_branch: int = 2          # broadcast-tree children per holder
    refit_timeout_s: float = 30.0   # per-member publish deadline
    refit_retries: int = 1          # extra attempts after the first
    retire_after_failures: int = 2  # consecutive failed fanouts -> retire
    lease_ttl_s: float = 5.0        # heartbeat staleness -> member lost
    step_wedge_s: float = 60.0      # in-step grace (first step compiles)
    collect_poll_s: float = 0.05    # queue poll + lease check cadence
    traj_queue_cap: int = 8         # bounded group queue (backpressure)
    regrow: bool = False            # respawn to target size next rollout
    min_samplers: int = 1           # fewer survivors than this -> raise
    refit_delay_s: float = 0.0      # bench knob: sleep per member publish

    def __post_init__(self):
        if self.samplers < 1:
            raise ValueError(
                f"fleet.samplers must be >= 1, got {self.samplers}")
        if self.min_samplers < 1 or self.min_samplers > self.samplers:
            raise ValueError(
                f"fleet.min_samplers must be in [1, samplers], got "
                f"{self.min_samplers}")

    @classmethod
    def from_config(cls, cfg: Optional[Dict]) -> "SamplerFleetConfig":
        cfg = dict(cfg or {})
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(cfg) - known
        if unknown:
            raise ValueError(
                f"unknown ppo.rollout.fleet keys {sorted(unknown)}; "
                f"known: {sorted(known)}")
        return cls(**cfg)


@dataclasses.dataclass
class TrajectoryGroup:
    """One completed trajectory group: prompt ``group``'s G seeded
    samples as host arrays (the per-group slice of the
    ``build_generate_fn`` output contract), staleness-tagged with the
    emitting member's param ``version`` (learner update count at its
    last successful refit) and the fleet membership ``epoch``.
    ``rollout`` is the fleet rollout index the group was generated FOR:
    the collector discards any group whose tag does not match the
    rollout it is assembling, so a slow retired-but-alive member can
    never leak rollout N's rows into rollout N+1."""
    group: int
    member: int
    version: int
    epoch: int
    rows: Dict[str, np.ndarray]
    rollout: int = 0
    error: Optional[BaseException] = None   # drive-crash sentinel
    # {"trace", "span"} hex ids of the dispatch that produced this group
    # (None with tracing disabled): the merged fleet timeline can tie a
    # consumed group back to the member-side drive that generated it
    trace: Optional[Dict[str, str]] = None


def shard_trajectory_groups(groups: Sequence[TrajectoryGroup],
                            dp_ranks: int) -> List[List[TrajectoryGroup]]:
    """Deterministically shard completed groups across learner
    data-parallel ranks: sort by group index (completion order never
    leaks into placement) and deal contiguous, size-balanced slices —
    the first ``len % dp`` ranks take one extra group, matching how a
    global batch splits over a data axis."""
    if dp_ranks < 1:
        raise ValueError(f"dp_ranks must be >= 1, got {dp_ranks}")
    ordered = sorted(groups, key=lambda g: g.group)
    base, rem = divmod(len(ordered), dp_ranks)
    shards: List[List[TrajectoryGroup]] = []
    at = 0
    for r in range(dp_ranks):
        take = base + (1 if r < rem else 0)
        shards.append(ordered[at:at + take])
        at += take
    return shards


# On the virtual CPU mesh, every sharded program needs all 8 device
# participants to rendezvous inside XLA's intra-op thread pool; N member
# threads plus the learner dispatching concurrently can starve the pool
# and deadlock the rendezvous (observed live on a 1-core box: two
# drive-loop run_ids plus a train step interleaved, all stuck; also
# reproduced with just ONE member program against the learner's train
# step). The gate serializes the fleet's dispatches against each other
# AND — via :func:`learner_dispatch_gate` — against the learner's
# sharded programs, so exactly one multi-participant program runs at a
# time. Process-wide on purpose: two fleets in one process
# (chaos-vs-planned A/Bs) share the one CPU runtime. None on TPU,
# where the runtime queues per-device and members own their own
# slices.
_CPU_DISPATCH_GATE = threading.Lock()


def _read_jax_flag(name: str) -> Optional[bool]:
    """Current value of a JAX config flag, or None if this JAX version
    exposes no way to read it (in which case the caller skips the
    restore rather than guessing)."""
    try:
        return bool(getattr(jax.config, name))
    except AttributeError:
        pass
    try:
        return bool(jax.config._value_holders[name].value)
    except Exception:
        return None


def ensure_cpu_sync_dispatch() -> None:
    """Disable async dispatch for the CPU backend. MUST run before the
    process's first jax computation: the flag is read ONCE when the CPU
    client is created, and updating it afterwards is a no-op — so the
    :class:`SamplerFleet` constructor's own update only protects
    processes that build the fleet before touching jax (the test
    suite's conftest sets it at import for the same reason; a training
    CLI builds the learner first and needs this called up front).
    Harmless when the backend is TPU — the flag only shapes the cpu
    client."""
    jax.config.update("jax_cpu_enable_async_dispatch", False)


def learner_dispatch_gate():
    """Context manager serializing the CALLER's XLA dispatch with fleet
    members' (see ``_CPU_DISPATCH_GATE``). The learner's rollout loop
    wraps its score/update section in this so its sharded programs
    never interleave with a member's — members queue at the gate
    (lease-safe: a queued ``_drive`` refreshes ``step_started``) and
    resume the moment the learner's section ends. Null away from the
    cpu backend, where overlap is the point, not a hazard."""
    if jax.default_backend() == "cpu":
        return _CPU_DISPATCH_GATE
    return contextlib.nullcontext()


class _Sampler:
    """One fleet member: a supervised RolloutEngine pinned to its own
    single-thread executor (serializes that member's JAX dispatch —
    drive loops and refit publishes share the one thread). Cross-thread
    fields (killed/slow flags, retirement) are guarded by the fleet's
    ``_state_lock``."""

    def __init__(self, slot: int, engine: RolloutEngine, version: int):
        self.slot = slot
        self.engine = engine
        self.pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"dla-sampler-{slot}")
        self.version = version        # learner updates at last refit;
        #                               written on the member's own
        #                               executor thread (_publish_one)
        self.refit_failures = 0       # consecutive; fanout caller only
        self.retired = False
        self.killed = False           # sampler=lost fired: go silent
        self.kill_budget = 0          # groups still allowed once killed
        self.slow_s = 0.0             # sampler=slow: sleep per step
        # wall-clock mark while the drive is INSIDE driver.step(): a
        # member can't beat mid-step, and a first step that's busy
        # compiling can outlive any honest lease TTL — the collector
        # grants in-step members step_wedge_s before declaring death
        self.step_started: Optional[float] = None

    @property
    def driver(self):
        """The submit/step/result surface: the supervisor when
        supervised (rebuild + replay on engine failure), else the bare
        engine."""
        return self.engine.supervisor or self.engine.engine


class SamplerFleet:
    """N rollout engines behind the single-engine rollout surface
    (``generate`` / ``publish_params`` / ``request_stop`` / ``close`` /
    ``metrics``), so :class:`~dla_tpu.rollout.pipeline.RolloutPipeline`
    and :class:`~dla_tpu.rollout.refit.WeightRefitter` run unchanged on
    a fleet. See the module docstring for the robustness contract."""

    is_fleet = True       # pipeline marker: per-trajectory staleness

    def __init__(self, model, params, gen: GenerationConfig,
                 cfg: ServingConfig, fleet_cfg: SamplerFleetConfig, *,
                 samples_per_prompt: int = 1,
                 supervisor=True,
                 metrics: Optional[RolloutMetrics] = None,
                 now=time.monotonic):
        self.model = model
        self.gen = gen
        self.cfg = cfg
        self.fleet_cfg = fleet_cfg
        self.G = int(samples_per_prompt)
        # members are always supervised: reassignment and re-grow both
        # lean on the factory/replay machinery
        self._supervisor = supervisor if supervisor else True
        self._params = params
        self._now = now
        self.metrics = metrics or RolloutMetrics()
        self.fleet_metrics = SamplerFleetMetrics(self.metrics.registry)
        # sampler=/rollout_step= entries are fleet-scoped: ONE plan with
        # one one-shot state, polled here — member engines get an empty
        # plan (cfg.fault_plan="" parses empty; None would re-read the
        # env var per member and multiply every entry by N)
        self.faults = (FaultPlan.parse(cfg.fault_plan)
                       if cfg.fault_plan is not None
                       else FaultPlan.from_env())
        self._member_cfg = dataclasses.replace(cfg, fault_plan="")
        self.rollouts_started = 0
        self.epoch = 0                # membership epoch: retire/grow
        self.version = 0              # last successfully fanned version
        self._stop_requested = threading.Event()
        # _state_lock guards the cross-thread state: leases, member
        # flags, epoch. Held for field flips only; the trajectory queue
        # is its own synchronization
        self._state_lock = threading.Lock()
        self._leases: Dict[int, float] = {}
        self._traj_q: "queue.Queue[TrajectoryGroup]" = queue.Queue(
            maxsize=int(fleet_cfg.traj_queue_cap))
        self._samplers: List[_Sampler] = []   # retired stay (accounting)
        self._next_slot = 0
        # group -> (prompt tokens, G seeds, G max_new): the
        # bit-identical regeneration source for reassignment
        self._journal: Dict[int, Tuple] = {}
        # group -> TraceContext of its CURRENT dispatch (empty with
        # tracing disabled): _reassign parents the replacement dispatch
        # span under the original one, so a chaos run's merged timeline
        # shows reassignment as a child of the dispatch it replaced
        self._dispatch_ctx: Dict[int, TraceContext] = {}
        # N member threads stepping sharded programs on the SAME virtual
        # CPU mesh interleave collective participants across rendezvous
        # and deadlock the inline CPU runtime; synchronous dispatch is
        # the documented escape (tests/conftest.py applies it suite-wide
        # for the same reason). The update below only bites if the CPU
        # client does not exist yet — the flag is baked in at client
        # creation, which is why fleet-building CLIs call
        # ensure_cpu_sync_dispatch() before their first jax use, and
        # why _dispatch_gate exists as the in-process second layer. The
        # flag is process-global, so the prior value is saved and
        # restored on close() — the override must not outlive the
        # fleet. No-op on TPU, where the runtime queues per-device and
        # samplers own their own slices.
        self._prev_async_dispatch: Optional[bool] = None
        self._dispatch_gate: Optional[threading.Lock] = None
        if jax.default_backend() == "cpu":
            self._prev_async_dispatch = _read_jax_flag(
                "jax_cpu_enable_async_dispatch")
            jax.config.update("jax_cpu_enable_async_dispatch", False)
            self._dispatch_gate = _CPU_DISPATCH_GATE
        for _ in range(int(fleet_cfg.samplers)):
            self._spawn()

    @property
    def engine(self):
        """The first active member's LIVE serving engine — the
        fleet-level answer to ``RolloutEngine.engine`` for callers
        that want a recorder/step counter (WeightRefitter's refit
        event)."""
        members = self.active() or self._samplers
        if not members:
            raise RuntimeError("sampler fleet has no members")
        return members[0].engine.engine

    # ---------------------------------------------------------- membership

    def _spawn(self) -> _Sampler:
        slot = self._next_slot
        self._next_slot += 1
        eng = RolloutEngine(self.model, self._params, self.gen,
                            self._member_cfg,
                            samples_per_prompt=self.G,
                            supervisor=self._supervisor,
                            metrics=RolloutMetrics())
        m = _Sampler(slot, eng, self.version)
        with self._state_lock:
            self._samplers.append(m)
            self._leases[slot] = self._now()
        self.fleet_metrics.samplers_active.set(len(self.active()))
        return m

    def active(self) -> List[_Sampler]:
        """Members the fleet still schedules onto. A ``killed``
        (fault-injected) member stays here until its lease expires —
        the fleet must not "know" a member is about to die; detection
        is the lease's job."""
        with self._state_lock:
            return [m for m in self._samplers if not m.retired]

    def _retire(self, m: _Sampler, reason: str) -> None:
        with self._state_lock:
            if m.retired:
                return
            m.retired = True
            self.epoch += 1
        self.fleet_metrics.retired_samplers.inc()
        self.fleet_metrics.samplers_active.set(len(self.active()))
        self._record("sampler_retired", slot=m.slot, reason=reason,
                     epoch=self.epoch)

    def _record(self, event: str, **fields) -> None:
        """Fleet events land on the first live member's flight recorder
        (the fleet has no engine of its own); best-effort — a fleet
        down to zero members still has its exception to tell the
        story."""
        for m in self.active() or self._samplers:
            try:
                m.engine.engine.recorder.record(event, **fields)
                return
            except Exception:
                continue

    # --------------------------------------------------------------- refit

    def publish_params(self, params, donate: bool = False,
                       version: Optional[int] = None) -> None:
        """Broadcast-tree refit fanout. Wave k's publishes are
        submitted to their members' executors together and harvested
        with ``refit_timeout_s`` per member + ``refit_retries``
        resubmits; wall time is bounded by the wave count
        (``broadcast_waves``), not N. A member that exhausts retries
        keeps its old version (per-trajectory staleness covers it);
        ``retire_after_failures`` consecutive failed fanouts retire it.
        The learner never waits on a wedged member longer than
        ``(1 + retries) * timeout``."""
        t0 = self._now()
        fc = self.fleet_cfg
        members = self.active()
        for wave in broadcast_waves(len(members), fc.fanout_branch):
            pubs: List[Tuple[_Sampler, Future]] = [
                (members[i], members[i].pool.submit(
                    self._publish_one, members[i], params, donate,
                    version))
                for i in wave]
            for m, fut in pubs:
                ok = False
                for attempt in range(1 + int(fc.refit_retries)):
                    try:
                        fut.result(timeout=fc.refit_timeout_s)
                        ok = True
                        break
                    except FutureTimeout:
                        # the member's single executor thread is wedged
                        # (or merely slow): a resubmit would queue
                        # BEHIND the stuck attempt on that same thread
                        # and can never run sooner, so retries only
                        # burn learner time — give up now. If the
                        # original later completes it applies params on
                        # the member's own drive thread, so m.version
                        # and the rows it tags stay consistent; this
                        # fanout still records the failure because the
                        # learner could not confirm it in time.
                        break
                    except Exception:
                        pass   # publish raised (validation/transient):
                        #        the thread is alive, a retry can help
                    if attempt < int(fc.refit_retries):
                        fut = m.pool.submit(self._publish_one, m,
                                            params, donate, version)
                if ok:
                    m.refit_failures = 0
                else:
                    m.refit_failures += 1
                    self._record("sampler_refit_failed", slot=m.slot,
                                 failures=m.refit_failures)
                    if m.refit_failures >= int(fc.retire_after_failures):
                        self._retire(m, "refit_timeout")
        self._params = params            # grow/respawn source tree
        if version is not None:
            self.version = int(version)
        self.fleet_metrics.refit_fanout_ms.set((self._now() - t0) * 1e3)

    def publish_params_serial(self, params, donate: bool = False,
                              version: Optional[int] = None) -> None:
        """N sequential member publishes — the pre-fanout baseline the
        ``bench.py rollout-fleet`` A/B measures against. No timeout or
        retirement: this is the stall-the-learner behavior the
        broadcast fanout exists to replace."""
        for m in self.active():
            m.pool.submit(self._publish_one, m, params, False,
                          version).result()
        self._params = params
        if version is not None:
            self.version = int(version)

    def _publish_one(self, m: _Sampler, params, donate: bool,
                     version: Optional[int]) -> None:
        """Runs ON the member's executor thread: the same thread that
        drives the engine, so the pointer swap never races a decode
        dispatch, and ``m.version`` is only ever written here."""
        if self.fleet_cfg.refit_delay_s > 0:
            time.sleep(self.fleet_cfg.refit_delay_s)
        with self._dispatch_gate or contextlib.nullcontext():
            m.engine.publish_params(params, donate=donate,
                                    version=version)
        if version is not None:
            m.version = int(version)

    # ------------------------------------------------------------ rollouts

    def generate(self, ids: np.ndarray, mask: np.ndarray,
                 seeds: Sequence[int],
                 max_new: Optional[Sequence[int]] = None
                 ) -> Dict[str, jnp.ndarray]:
        """One fleet rollout: journal every (prompt, seeds) group,
        partition groups round-robin over the active members, drive
        them concurrently, collect staleness-tagged groups off the
        bounded queue (reassigning any lost member's groups to
        survivors), and reassemble in group order. Output contract =
        ``RolloutEngine.generate`` + ``row_versions`` (int32 ``[B*G]``,
        the per-trajectory behavior-param version tags)."""
        ids = np.asarray(ids)
        mask = np.asarray(mask)
        b_unique, p_width = ids.shape
        rows = b_unique * self.G
        seeds = list(seeds)
        if len(seeds) != rows:
            raise ValueError(
                f"need {rows} seeds ({b_unique} prompts x G={self.G}), "
                f"got {len(seeds)}")
        if max_new is not None and len(max_new) != rows:
            raise ValueError(
                f"max_new must have {rows} entries, got {len(max_new)}")
        idx = self.rollouts_started
        self.rollouts_started += 1
        fc = self.fleet_cfg
        if fc.regrow:
            # bounded attempts: a factory that keeps producing wedged
            # members must not turn the rollout into a spawn loop
            attempts = int(fc.samplers)
            while len(self.active()) < int(fc.samplers) and attempts > 0:
                attempts -= 1
                grown = self._spawn()
                # a fresh member starts from the CURRENT tree+version;
                # same deadline as the fanout — regrow must never stall
                # the learner on a wedged fresh member either
                fut = grown.pool.submit(self._publish_one, grown,
                                        self._params, False, self.version)
                try:
                    fut.result(timeout=fc.refit_timeout_s)
                except Exception:   # FutureTimeout or a raised publish
                    self._retire(grown, "regrow_refit_failed")
                    continue
                with self._state_lock:
                    self.epoch += 1
                self._record("sampler_grown", slot=grown.slot,
                             epoch=self.epoch)
        self._poll_sampler_faults(idx)
        self._poll_rollout_faults(idx)
        members = self.active()
        if len(members) < int(fc.min_samplers):
            raise RuntimeError(
                f"sampler fleet below min_samplers: {len(members)} < "
                f"{fc.min_samplers}")
        tracer = get_tracer()
        # rollout root context, minted at the dispatch origin (the
        # trace-context contract: mint at origin, child() per hop);
        # skipped entirely when tracing is off — no ids, no span work
        root = TraceContext.mint() if tracer.enabled else None
        tr_t0 = tracer.now()
        with self._state_lock:
            self._journal.clear()
            self._dispatch_ctx.clear()
            for i in range(b_unique):
                toks = [int(t) for t, m in zip(ids[i], mask[i]) if m]
                g_seeds = [int(s)
                           for s in seeds[i * self.G:(i + 1) * self.G]]
                g_new = (None if max_new is None
                         else [int(x) for x in
                               max_new[i * self.G:(i + 1) * self.G]])
                self._journal[i] = (toks, g_seeds, g_new)
        n_pad = (int(self.gen.max_new_tokens) if max_new is None
                 else max(int(x) for x in max_new))
        shape = (p_width, n_pad)
        owner: Dict[int, int] = {}
        assignment: Dict[int, List[int]] = {m.slot: [] for m in members}
        for g in range(b_unique):
            m = members[g % len(members)]
            assignment[m.slot].append(g)
            owner[g] = m.slot
        t0 = self._now()
        steps0 = {m.slot: m.engine._decode_steps_total()
                  for m in self._samplers}
        self._record("fleet_rollout_begin", rollout=idx,
                     groups=b_unique, samplers=len(members))
        # drain stale leftovers before dispatching: a member retired
        # mid-collect (lease expiry) may have emitted its group after
        # the reassigned copy won, and nothing consumes the queue
        # between rollouts
        try:
            while True:
                self._traj_q.get_nowait()
        except queue.Empty:
            pass
        for m in members:
            if assignment[m.slot]:
                self._dispatch_drive(m, assignment[m.slot], shape, idx,
                                     parent=root)
        done = self._collect(idx, b_unique, owner, shape)
        out = self._assemble(done, b_unique)
        t1 = self._now()
        tokens = int(np.sum(np.asarray(out["response_mask"])))
        steps = sum(m.engine._decode_steps_total()
                    - steps0.get(m.slot, 0) for m in self._samplers)
        fm = self.metrics
        fm.rollouts.inc()
        if t1 > t0:
            fm.gen_tokens_per_s.set(tokens / (t1 - t0))
        if tokens:
            fm.slot_steps_per_token.set(
                steps * self.cfg.num_slots / tokens)
        self.fleet_metrics.trajectory_queue_depth.set(
            self._traj_q.qsize())
        # a killed member that drained its budget merely looks idle;
        # make the shrink explicit at the rollout boundary
        for m in list(self._samplers):
            if m.killed and not m.retired:
                self._retire(m, "sampler_lost")
        if root is not None:
            tracer.complete("fleet_rollout", tr_t0, tracer.now(),
                            cat="rollout",
                            args=dict(rollout=idx, groups=b_unique,
                                      samplers=len(members),
                                      **root.tags()))
        return out

    def _dispatch_drive(self, m: _Sampler, groups: List[int],
                        shape: Tuple[int, int], idx: int,
                        parent: Optional[TraceContext] = None,
                        name: str = "sampler_dispatch") -> None:
        """Reset the member's lease (it may have idled since its last
        drive — an instant re-expiry is not a death) and queue the
        drive on its executor. With tracing on, ``parent`` is the
        rollout root (initial dispatch) or the ORIGINAL dispatch's
        context (reassignment) — the dispatch span parents under it,
        and the drive span under the dispatch."""
        dtags = None
        if parent is not None:
            tracer = get_tracer()
            ctx = parent.child()
            with self._state_lock:
                for g in groups:
                    self._dispatch_ctx[g] = ctx
            t = tracer.now()
            tracer.complete(name, t, t, cat="rollout",
                            args=dict(slot=m.slot, rollout=idx,
                                      groups=len(groups),
                                      **ctx.tags(parent)))
            dtags = ctx.child().tags(ctx)
        with self._state_lock:
            self._leases[m.slot] = self._now()
        m.pool.submit(self._drive, m, groups, shape, idx, dtags)

    def _drive(self, m: _Sampler, groups: List[int],
               shape: Tuple[int, int], idx: int,
               dtags: Optional[Dict[str, str]] = None) -> None:
        """Runs ON the member's executor: submit the assigned groups'
        G seeded requests, step the supervised engine, beat the lease
        each step, and emit each group onto the bounded queue as its
        last request reaches a terminal state. A ``killed`` member
        honors its remaining ``kill_budget`` then goes silent (no
        beats, no emissions) — the collector's lease check finds the
        corpse. A member retired mid-drive (lease expired while merely
        slow) notices at the next loop check and exits: its groups were
        reassigned, so anything it would still produce is garbage."""
        p_width, n_pad = shape
        tracer = get_tracer()
        drive_t0 = tracer.now()
        try:
            driver = m.driver
            pending: Dict[int, List[int]] = {}
            for g in groups:
                with self._state_lock:
                    toks, g_seeds, g_new = self._journal[g]
                rids = []
                for k, seed in enumerate(g_seeds):
                    sp = SamplingParams(
                        temperature=float(self.gen.temperature),
                        top_p=float(self.gen.top_p),
                        top_k=int(self.gen.top_k),
                        seed=seed & 0xFFFFFFFF,
                        do_sample=bool(self.gen.do_sample))
                    n_new = (int(self.gen.max_new_tokens)
                             if g_new is None else int(g_new[k]))
                    rids.append(driver.submit(toks, n_new, sampling=sp))
                pending[g] = rids
            while pending:
                if self._stop_requested.is_set():
                    return
                with self._state_lock:
                    dead = m.killed and m.kill_budget <= 0
                    retired = m.retired
                    slow_s = m.slow_s
                if dead:
                    return               # silent: no beat, no emission
                if retired:
                    return               # reassigned: stop producing
                if slow_s > 0:
                    time.sleep(slow_s)
                now = self._now()
                with self._state_lock:
                    self._leases[m.slot] = now
                    m.step_started = now
                try:
                    if driver.has_work():
                        # gate waits look mid-step to the collector:
                        # step_started is already set, so step_wedge_s
                        # (not the lease TTL) covers a queued member.
                        # A wait can outlive even that grace (the
                        # learner holds the gate across its first-step
                        # compiles), so refresh step_started while
                        # queued: waiting at the gate is queued, not
                        # wedged
                        gate = self._dispatch_gate
                        if gate is None:
                            driver.step()
                        else:
                            while not gate.acquire(timeout=5.0):
                                if self._stop_requested.is_set():
                                    return
                                with self._state_lock:
                                    if m.retired:
                                        return
                                    m.step_started = self._now()
                            try:
                                driver.step()
                            finally:
                                gate.release()
                finally:
                    with self._state_lock:
                        m.step_started = None
                        self._leases[m.slot] = self._now()
                for g in list(pending):
                    reqs = [driver.result(rid) for rid in pending[g]]
                    if not all(r.state in TERMINAL_STATES for r in reqs):
                        continue
                    # assemble_rows raises on any non-FINISHED terminal
                    rows = assemble_rows(driver.result, pending.pop(g),
                                         p_width, n_pad,
                                         int(self.gen.pad_token_id))
                    self._emit(m, g, rows, idx)
                    with self._state_lock:
                        if m.killed:
                            m.kill_budget -= 1
                            if m.kill_budget <= 0:
                                return   # budget spent: die mid-drive
        except RolloutStopped:
            return
        except BaseException as exc:
            # drive crash (supervisor breaker open, ...): tell the
            # collector immediately instead of waiting out a lease TTL
            with self._state_lock:
                ep = self.epoch
            try:
                self._traj_q.put(
                    TrajectoryGroup(group=-1, member=m.slot,
                                    version=m.version, epoch=ep,
                                    rows={}, rollout=idx, error=exc),
                    timeout=1.0)
            except queue.Full:
                pass
        finally:
            if dtags is not None:
                tracer.complete("sampler_drive", drive_t0, tracer.now(),
                                cat="rollout",
                                args=dict(slot=m.slot, rollout=idx,
                                          groups=len(groups), **dtags))

    def _emit(self, m: _Sampler, g: int,
              rows: Dict[str, np.ndarray], idx: int) -> None:
        with self._state_lock:
            ep = self.epoch
            ctx = self._dispatch_ctx.get(g)
        tg = TrajectoryGroup(group=g, member=m.slot, version=m.version,
                             epoch=ep, rows=rows, rollout=idx,
                             trace=ctx.tags() if ctx is not None
                             else None)
        while not self._stop_requested.is_set():
            with self._state_lock:
                retired = m.retired
            if retired:
                # retired mid-backpressure: the group was reassigned
                # and nothing will ever consume this emission — drop it
                # rather than spin on a bounded queue forever
                return
            try:
                self._traj_q.put(tg, timeout=0.1)
                return
            except queue.Full:
                # backpressure: keep beating so a slow CONSUMER never
                # reads as a dead producer
                with self._state_lock:
                    self._leases[m.slot] = self._now()

    def _collect(self, idx: int, b_unique: int, owner: Dict[int, int],
                 shape: Tuple[int, int]) -> Dict[int, TrajectoryGroup]:
        """Consumer side: drain the queue until every group arrived,
        checking leases on every poll timeout. A stale lease retires
        the member and reassigns its unfinished groups to survivors
        (journaled prompts + seeds -> bit-identical regeneration). Only
        groups tagged with THIS rollout index and emitted by the
        group's CURRENT owner are accepted: a stale emission from a
        prior rollout, or from a member retired after its groups were
        reassigned, is discarded — the owner regenerates bit-identically
        from the journal, so a discard is never a hole."""
        done: Dict[int, TrajectoryGroup] = {}
        while len(done) < b_unique:
            if self._stop_requested.is_set():
                raise RolloutStopped("fleet rollout aborted: closing")
            try:
                tg = self._traj_q.get(
                    timeout=self.fleet_cfg.collect_poll_s)
            except queue.Empty:
                self._check_leases(idx, b_unique, owner, done, shape)
                continue
            self.fleet_metrics.trajectory_queue_depth.set(
                self._traj_q.qsize())
            if tg.rollout != idx:
                # stale leak from a prior rollout (slow retired member
                # still flushing): its rows belong to other prompts
                self._record("stale_group_discarded", rollout=idx,
                             stale_rollout=tg.rollout, group=tg.group,
                             slot=tg.member)
                continue
            if tg.error is not None:
                by_slot = {m.slot: m for m in self._samplers}
                m = by_slot.get(tg.member)
                if m is not None and not m.retired:
                    self._retire(
                        m, f"drive_error:{type(tg.error).__name__}")
                    self._reassign(idx, b_unique, owner, done, shape,
                                   m.slot)
                continue
            if owner.get(tg.group) != tg.member:
                # emitter lost ownership (retired + reassigned) before
                # this arrival was consumed; the new owner's copy is
                # the canonical one
                continue
            done.setdefault(tg.group, tg)
        return done

    def _check_leases(self, idx: int, b_unique: int,
                      owner: Dict[int, int],
                      done: Dict[int, TrajectoryGroup],
                      shape: Tuple[int, int]) -> None:
        now = self._now()
        ttl = float(self.fleet_cfg.lease_ttl_s)
        wedge = float(self.fleet_cfg.step_wedge_s)
        for m in list(self.active()):
            remaining = [g for g in range(b_unique)
                         if g not in done and owner.get(g) == m.slot]
            if not remaining:
                continue
            with self._state_lock:
                last = self._leases.get(m.slot, 0.0)
                step_started = m.step_started
            if now - last <= ttl:
                continue
            if step_started is not None and now - step_started <= wedge:
                # mid-step, not silent: the step is merely long (first
                # steps compile). Only a step outliving step_wedge_s is
                # treated as a wedged member.
                continue
            self._record("sampler_lost", slot=m.slot, rollout=idx,
                         lease_age_s=round(now - last, 3))
            self._retire(m, "lease_expired")
            self._reassign(idx, b_unique, owner, done, shape, m.slot)

    def _reassign(self, idx: int, b_unique: int, owner: Dict[int, int],
                  done: Dict[int, TrajectoryGroup],
                  shape: Tuple[int, int], dead_slot: int) -> None:
        orphans = [g for g in range(b_unique)
                   if g not in done and owner.get(g) == dead_slot]
        if not orphans:
            return
        survivors = self.active()
        if not survivors:
            raise RuntimeError(
                f"sampler fleet lost its last member with "
                f"{len(orphans)} trajectory groups in flight")
        per: Dict[int, List[int]] = {s.slot: [] for s in survivors}
        for j, g in enumerate(orphans):
            s = survivors[j % len(survivors)]
            owner[g] = s.slot
            per[s.slot].append(g)
        by_slot = {s.slot: s for s in survivors}
        # parent each replacement dispatch under the orphans' ORIGINAL
        # dispatch span: the merged timeline then shows the reassignment
        # as a child of the dispatch it replaced, not a fresh root
        with self._state_lock:
            orig = self._dispatch_ctx.get(orphans[0])
        for slot, groups in per.items():
            if groups:
                self._dispatch_drive(by_slot[slot], groups, shape, idx,
                                     parent=orig,
                                     name="sampler_reassign_dispatch")
        self.fleet_metrics.reassigned_rollouts.inc(len(orphans))
        self._record("sampler_reassigned", rollout=idx,
                     from_slot=dead_slot, groups=len(orphans),
                     epoch=self.epoch)

    def _assemble(self, done: Dict[int, TrajectoryGroup],
                  b_unique: int) -> Dict[str, jnp.ndarray]:
        groups = [done[g] for g in range(b_unique)]   # group order
        out: Dict[str, jnp.ndarray] = {}
        for key in ("sequences", "sequence_mask", "response_tokens",
                    "response_mask", "response_logps", "lengths",
                    "prompt_lens"):
            out[key] = jnp.asarray(np.concatenate(
                [tg.rows[key] for tg in groups], axis=0))
        out["row_versions"] = jnp.asarray(np.concatenate(
            [np.full((int(tg.rows["lengths"].shape[0]),), tg.version,
                     np.int32) for tg in groups]))
        return out

    # -------------------------------------------------------------- faults

    def _poll_sampler_faults(self, idx: int) -> None:
        """Fire due ``sampler=I:rollout_step=N:lost|slow`` entries.
        ``lost``: member I completes at most one more group this
        rollout, then goes silent (lease expiry does the detecting).
        ``slow``: member I sleeps ``arg`` seconds (default 0.05) before
        each engine step this rollout — an early-warning event fires,
        but nothing retires unless the lag outlives the lease TTL."""
        if not self.faults:
            return
        by_slot = {m.slot: m for m in self._samplers}
        while True:
            f = self.faults.take("lost", idx, site="sampler")
            if f is None:
                break
            m = by_slot.get(int(f.host or 0))
            if m is None or m.retired:
                continue
            with self._state_lock:
                m.killed = True
                m.kill_budget = 1
            self._record("sampler_fault", slot=m.slot, rollout=idx,
                         fault="lost")
        while True:
            f = self.faults.take("slow", idx, site="sampler")
            if f is None:
                break
            m = by_slot.get(int(f.host or 0))
            if m is None or m.retired:
                continue
            with self._state_lock:
                m.slow_s = 0.05 if f.arg is None else float(f.arg)
            self._record("sampler_slow", slot=m.slot, rollout=idx,
                         lag_s=m.slow_s)

    def _poll_rollout_faults(self, idx: int) -> None:
        """Fleet translation of ``rollout_step=`` entries: same
        re-arming the single-engine RolloutEngine does, landed on the
        FIRST active member's live engine (one one-shot plan at fleet
        level — member engines carry empty plans)."""
        if not self.faults:
            return
        members = self.active()
        if not members:
            return
        eng = members[0].engine.engine
        for kind in ("device_error", "nan_logits", "wedge"):
            f = self.faults.take(kind, idx, site="rollout_step")
            if f is None:
                continue
            if kind == "wedge":
                at, arg = eng.engine_steps + 1, f.arg
            else:
                at = eng.engine_steps + (2 if f.arg is None
                                         else max(1, int(f.arg)))
                arg = None
            self._record("rollout_fault", rollout=idx, fault=kind,
                         engine_step=at, slot=members[0].slot)
            eng.faults.add(Fault(step=at, kind=kind, arg=arg,
                                 site="engine_step"))

    # ----------------------------------------------------------- lifecycle

    def request_stop(self) -> None:
        """Abort in-flight drives promptly (pipeline close path)."""
        self._stop_requested.set()
        for m in self._samplers:
            m.engine.request_stop()

    def close(self) -> None:
        self.request_stop()
        for m in self._samplers:
            # wait=False: a wedged member's executor must not block
            # teardown — its drive loop exits at the next stop check
            m.pool.shutdown(wait=False)
        for m in self._samplers:
            try:
                m.engine.close()
            except Exception:
                pass
        if self._prev_async_dispatch is not None:
            jax.config.update("jax_cpu_enable_async_dispatch",
                              self._prev_async_dispatch)
            self._prev_async_dispatch = None
