"""RolloutEngine: drive the serving engine through one RLHF rollout.

One rollout = one prompt batch generated to completion. Each unique
prompt is submitted G = ``samples_per_prompt`` times with distinct
per-request seeds; with the prefix cache on, the G-group's prompt pages
alias (one prefill per unique prompt — the serving analog of
``build_generate_fn``'s in-graph ``group_size`` expansion). The engine
drains with continuous batching — short rows retire early and their
slots immediately serve other rows, recovering the padding waste the
fixed-shape batch path pays — and the results reassemble into the same
right-padded arrays ``train_rlhf.py``'s scoring and PPO/GAE/reinforce
updates already consume.

Determinism: each row's token stream is a pure function of its
(seed, token index) — see ops.sampling — so a rollout's outputs are
independent of slot assignment, admission order, evictions, and
supervisor restarts. Sync-mode rollouts are bit-identical to the
seeded ``build_generate_fn`` path (pinned by test).
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from dla_tpu.generation.engine import GenerationConfig
from dla_tpu.ops.sampling import SamplingParams
from dla_tpu.resilience.faults import Fault
from dla_tpu.serving.resilience import Supervisor, SupervisorConfig
from dla_tpu.serving.scheduler import RequestState
from dla_tpu.serving.server import ServingConfig, ServingEngine
from dla_tpu.telemetry.registry import MetricRegistry


class RolloutMetrics:
    """The ``rollout/*`` CATALOG panel (telemetry.registry): rollout
    throughput, padding-waste recovery, refit cost, and async
    staleness. Lives on the RolloutEngine (not the serving engine's
    registry) so it survives supervisor rebuilds."""

    def __init__(self, registry: Optional[MetricRegistry] = None):
        r = self.registry = registry or MetricRegistry()
        self.rollouts = r.counter("rollout/rollouts")
        self.gen_tokens_per_s = r.gauge("rollout/gen_tokens_per_s")
        self.slot_steps_per_token = r.gauge("rollout/slot_steps_per_token")
        self.padding_waste_recovered = r.gauge(
            "rollout/padding_waste_recovered")
        self.refits = r.counter("rollout/refits")
        self.refit_ms = r.gauge("rollout/refit_ms")
        self.staleness = r.gauge("rollout/staleness_updates")
        self.stale_rollouts = r.counter("rollout/stale_rollouts")
        self.discarded_rollouts = r.counter("rollout/discarded_rollouts")

    def snapshot(self) -> Dict[str, float]:
        return {
            "rollout/rollouts": self.rollouts.value,
            "rollout/gen_tokens_per_s": self.gen_tokens_per_s.value,
            "rollout/slot_steps_per_token":
                self.slot_steps_per_token.value,
            "rollout/padding_waste_recovered":
                self.padding_waste_recovered.value,
            "rollout/refits": self.refits.value,
            "rollout/refit_ms": self.refit_ms.value,
            "rollout/staleness_updates": self.staleness.value,
            "rollout/stale_rollouts": self.stale_rollouts.value,
            "rollout/discarded_rollouts": self.discarded_rollouts.value,
        }


class RolloutStopped(RuntimeError):
    """Raised out of an in-flight drain when ``request_stop()`` fired:
    the pipeline is closing and the partially generated rollout will
    never be consumed."""


def assemble_rows(fetch: Callable[[int], object], order: Sequence[int],
                  p_width: int, n: int, pad: int) -> Dict[str, np.ndarray]:
    """Reassemble finished requests into the ``build_generate_fn``
    output contract as host arrays: right-padded ``[B, P+N]`` sequences
    (prompt immediately followed by response — what left_align produces
    for right-padded prompts) and ``[B, N]`` response arrays.
    ``fetch(rid)`` returns the request record; every request must have
    FINISHED (rollouts admit no other terminal state). Shared by the
    single-engine RolloutEngine and the per-group assembly in
    rollout.actor_fleet."""
    rows = len(order)
    seq = np.full((rows, p_width + n), pad, np.int32)
    seq_mask = np.zeros((rows, p_width + n), np.int32)
    resp = np.full((rows, n), pad, np.int32)
    resp_mask = np.zeros((rows, n), np.int32)
    lps = np.zeros((rows, n), np.float32)
    prompt_lens = np.zeros((rows,), np.int32)
    for row, rid in enumerate(order):
        req = fetch(rid)
        if req.state is not RequestState.FINISHED:
            raise RuntimeError(
                f"rollout request {rid} ended {req.state.value!r} "
                f"({req.finish_reason!r}); rollouts require every "
                "request to finish — disable deadlines/shedding on "
                "the rollout engine")
        p = req.prompt_tokens
        g = req.generated
        gl = req.generated_logprobs
        prompt_lens[row] = len(p)
        seq[row, :len(p)] = p
        seq_mask[row, :len(p)] = 1
        seq[row, len(p):len(p) + len(g)] = g
        seq_mask[row, len(p):len(p) + len(g)] = 1
        resp[row, :len(g)] = g
        resp_mask[row, :len(g)] = 1
        lps[row, :len(g)] = gl
    lengths = prompt_lens + resp_mask.sum(axis=1).astype(np.int32)
    return {"sequences": seq, "sequence_mask": seq_mask,
            "response_tokens": resp, "response_mask": resp_mask,
            "response_logps": lps, "lengths": lengths,
            "prompt_lens": prompt_lens}


class RolloutEngine:
    """The ServingEngine as RLHF rollout actor.

    ``generate(ids, mask, seeds)`` takes the batch path's inputs —
    right-padded prompt ids/mask ``[B, P]`` and per-row seeds
    ``[B * G]`` — and returns the batch path's outputs (sequences,
    response tokens/mask/logps, lengths) plus ``prompt_lens``, all
    fixed-shape ``[B*G, ...]`` device arrays.

    ``supervisor`` (a dict of SupervisorConfig fields, or ``True`` for
    defaults) wraps the engine in the serving Supervisor: engine
    failures mid-rollout tear down, rebuild with the CURRENT published
    params, and replay — the rollout completes with bit-identical
    outputs. ``rollout_step=`` fault-plan entries are polled at each
    rollout's start and re-armed as ``engine_step=`` entries a few
    engine steps ahead, so injected failures land mid-rollout.
    """

    def __init__(self, model, params, gen: GenerationConfig,
                 cfg: ServingConfig, *,
                 samples_per_prompt: int = 1,
                 supervisor=None,
                 metrics: Optional[RolloutMetrics] = None):
        self.model = model
        self.gen = gen
        self.cfg = cfg
        self.G = int(samples_per_prompt)
        if self.G < 1:
            raise ValueError("samples_per_prompt must be >= 1")
        self._params = params
        # every engine generation ever built (supervisor rebuilds append):
        # per-rollout decode-step deltas sum across generations
        self._engines: List[ServingEngine] = []
        self.metrics = metrics or RolloutMetrics()
        self.rollouts_started = 0
        self.version = 0             # learner-update stamp of _params
        self._stop_requested = threading.Event()

        def factory() -> ServingEngine:
            eng = ServingEngine(model, self._params, gen, cfg)
            self._engines.append(eng)
            return eng

        if supervisor:
            sup_cfg = (SupervisorConfig()
                       if supervisor is True
                       else SupervisorConfig.from_config(dict(supervisor)))
            self.supervisor: Optional[Supervisor] = Supervisor(
                factory, sup_cfg)
        else:
            self.supervisor = None
            factory()

    # ------------------------------------------------------------- plumbing

    @property
    def engine(self) -> ServingEngine:
        """The CURRENT engine generation (rebuilds swap it)."""
        if self.supervisor is not None:
            return self.supervisor.engine
        return self._engines[-1]

    def publish_params(self, params, donate: bool = False,
                       version: Optional[int] = None) -> None:
        """Swap the live engine's param tree in place (structure/shape/
        dtype-validated — zero recompiles) AND the factory's source, so
        a supervisor rebuild mid-rollout comes back with the refitted
        weights, not the originals. With speculative decoding on, the
        engine re-quantizes the int8 self-draft from the published tree
        in the same call — the draft never serves stale weights.
        ``version`` optionally stamps the tree with the learner update
        count it came from (the staleness tag the fleet pipeline reads
        per trajectory)."""
        self.engine.publish_params(params, donate=donate)
        self._params = params
        if version is not None:
            self.version = int(version)

    def request_stop(self) -> None:
        """Abort an in-flight drain promptly: the next ``_drain`` loop
        iteration raises :class:`RolloutStopped` instead of stepping the
        engine again. Called by ``RolloutPipeline.close()`` so a
        generator thread mid-generation releases the engine before the
        supervisor is torn down, instead of close() waiting out the
        whole rollout (or forever, on a wedged engine)."""
        self._stop_requested.set()

    def close(self) -> None:
        if self.supervisor is not None:
            self.supervisor.close()
        else:
            self.engine.close()

    def _decode_steps_total(self) -> int:
        return sum(int(e.metrics.decode_steps.value)
                   for e in self._engines)

    def _poll_rollout_faults(self) -> None:
        """Translate due ``rollout_step=`` plan entries into
        ``engine_step=`` entries a few engine steps ahead on the live
        engine — the failure then fires MID-rollout (requests partially
        generated), exercising restart-during-rollout. The plan object
        is carried across supervisor rebuilds, so one-shot consumption
        survives the restart the entry provokes."""
        plan = getattr(self.engine, "faults", None)
        if not plan:
            return
        idx = self.rollouts_started
        eng = self.engine
        for kind in ("device_error", "nan_logits", "wedge"):
            f = plan.take(kind, idx, site="rollout_step")
            if f is None:
                continue
            if kind == "wedge":
                # arg keeps its engine_step meaning (sleep seconds)
                at, arg = eng.engine_steps + 1, f.arg
            else:
                # arg = engine-step offset into the rollout (default 2:
                # past the first prefill+decode, well before drain)
                at = eng.engine_steps + (2 if f.arg is None
                                         else max(1, int(f.arg)))
                arg = None
            eng.recorder.record("rollout_fault", rollout=idx,
                                fault=kind, engine_step=at)
            plan.add(Fault(step=at, kind=kind, arg=arg,
                           site="engine_step"))

    # ------------------------------------------------------------- rollouts

    def generate(self, ids: np.ndarray, mask: np.ndarray,
                 seeds: Sequence[int],
                 max_new: Optional[Sequence[int]] = None
                 ) -> Dict[str, jnp.ndarray]:
        """Run one rollout: ``ids``/``mask`` are ``[B, P]`` right-padded
        unique prompts, ``seeds`` is ``[B * G]`` per-row sampling seeds
        laid out grouped (``[p0 s0..sG-1, p1 s0..sG-1, ...]`` — the
        ``group_size`` layout). Submits all B*G requests (G seeded
        copies per prompt share prefix-cache pages), drains the engine,
        and reassembles fixed-shape right-padded arrays. ``max_new``
        optionally overrides ``gen.max_new_tokens`` per row (bench's
        long-tail mix)."""
        ids = np.asarray(ids)
        mask = np.asarray(mask)
        b_unique, p_width = ids.shape
        rows = b_unique * self.G
        seeds = list(seeds)
        if len(seeds) != rows:
            raise ValueError(
                f"need {rows} seeds ({b_unique} prompts x G={self.G}), "
                f"got {len(seeds)}")
        if max_new is not None and len(max_new) != rows:
            raise ValueError(
                f"max_new must have {rows} entries, got {len(max_new)}")
        driver = self.supervisor if self.supervisor is not None \
            else self.engine
        idx = self.rollouts_started
        self.rollouts_started += 1
        self._poll_rollout_faults()
        eng = self.engine
        eng.recorder.record("rollout_begin", step=eng.engine_steps,
                            rollout=idx, requests=rows)
        steps0 = self._decode_steps_total()
        t0 = eng.now()
        order: List[int] = []
        for i in range(b_unique):
            toks = [int(t) for t, m in zip(ids[i], mask[i]) if m]
            for g in range(self.G):
                row = i * self.G + g
                sp = SamplingParams(
                    temperature=float(self.gen.temperature),
                    top_p=float(self.gen.top_p),
                    top_k=int(self.gen.top_k),
                    seed=int(seeds[row]) & 0xFFFFFFFF,
                    do_sample=bool(self.gen.do_sample))
                n_new = (int(self.gen.max_new_tokens) if max_new is None
                         else int(max_new[row]))
                order.append(driver.submit(toks, n_new, sampling=sp))
        self._drain(driver)
        out = self._assemble(driver, order, p_width, max_new)
        eng = self.engine          # may have been rebuilt mid-rollout
        t1 = eng.now()
        steps = self._decode_steps_total() - steps0
        tokens = int(np.sum(np.asarray(out["response_mask"])))
        m = self.metrics
        m.rollouts.inc()
        if t1 > t0:
            m.gen_tokens_per_s.set(tokens / (t1 - t0))
        if tokens:
            m.slot_steps_per_token.set(
                steps * self.cfg.num_slots / tokens)
        eng.recorder.record("rollout_complete", step=eng.engine_steps,
                            rollout=idx, tokens=tokens,
                            decode_steps=steps)
        return out

    def _drain(self, driver, max_steps: int = 100000) -> None:
        for _ in range(max_steps):
            if self._stop_requested.is_set():
                raise RolloutStopped("rollout aborted: pipeline closing")
            if not driver.has_work():
                return
            driver.step()
        raise RuntimeError(
            f"rollout did not drain in {max_steps} engine steps")

    def _assemble(self, driver, order: List[int], p_width: int,
                  max_new: Optional[Sequence[int]]
                  ) -> Dict[str, jnp.ndarray]:
        n = int(self.gen.max_new_tokens) if max_new is None \
            else max(int(x) for x in max_new)
        host = assemble_rows(driver.result, order, p_width, n,
                             int(self.gen.pad_token_id))
        return {k: jnp.asarray(v) for k, v in host.items()}
