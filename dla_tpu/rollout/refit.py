"""In-place weight refit: publish updated policy params into the live
rollout engine between rollouts.

The refit contract (docs/RLHF.md):

- ``param_fn()`` produces the rollout-ready tree — the trainer's
  ``rollout_params()`` path: LoRA adapters merged into the frozen base
  and/or int8 rollout quantization. Same structure/shapes/dtypes every
  time, so the engine's jit fingerprints never change: ZERO recompiles
  across refits (pinned by test).
- The swap is a host pointer update; the decode/prefill dispatches
  simply read the new tree on their next call. No engine rebuild, no
  KV-cache invalidation — in-flight paged KV was computed under the old
  weights, which is exactly the staleness the pipeline's importance
  correction accounts for (refits happen at rollout boundaries, when
  the engine is drained, so in practice nothing is in flight).
- ``donate=True`` frees the OLD tree's device buffers eagerly at
  publish. Only safe when ``param_fn`` builds a FRESH tree each call
  (merge/quantize do); a passthrough ``rollout_params`` that returns
  the trainer's live tree must NOT donate — the learner still owns it.
"""
from __future__ import annotations

import time
from typing import Callable, Optional

import jax

from dla_tpu.rollout.engine import RolloutEngine, RolloutMetrics


class WeightRefitter:
    """Publishes ``param_fn()`` into a :class:`RolloutEngine`.

    >>> refitter = WeightRefitter(rollout, rollout_params, donate=True)
    >>> refitter.refit()          # between rollouts
    """

    def __init__(self, rollout: RolloutEngine,
                 param_fn: Callable[[], object], *,
                 donate: bool = False,
                 metrics: Optional[RolloutMetrics] = None):
        self.rollout = rollout
        self.param_fn = param_fn
        self.donate = donate
        self.metrics = metrics or rollout.metrics

    def refit(self, params=None, version: Optional[int] = None) -> float:
        """Build (or take) the new tree and publish it. Returns the
        refit wall time in ms (param build + validation + swap;
        ``block_until_ready`` so queued merge/quantize work is charged
        here, not to the first decode). ``version`` stamps the tree
        with the learner update count it came from — the staleness tag
        fleet members carry per trajectory. Against a
        :class:`~dla_tpu.rollout.actor_fleet.SamplerFleet` the publish
        is the broadcast-tree fanout, so this one call refits every
        member in tree-depth (not N) wall time."""
        t0 = time.perf_counter()
        new = self.param_fn() if params is None else params
        new = jax.block_until_ready(new)
        self.rollout.publish_params(new, donate=self.donate,
                                    version=version)
        ms = (time.perf_counter() - t0) * 1000.0
        self.metrics.refits.inc()
        self.metrics.refit_ms.set(ms)
        eng = self.rollout.engine
        eng.recorder.record("weight_refit", step=eng.engine_steps,
                            ms=round(ms, 3), donate=self.donate)
        return ms
