"""Reward model: transformer backbone + pooled scalar head.

Replaces the reference's ``AutoModel`` + dropout + Linear(hidden, 1) head
(src/models/reward_model.py:38-64). Pooling modes match the reference:
``last_token`` indexes the hidden state at attention_mask.sum()-1
(reward_model.py:56-59); ``mean`` is a masked mean (reward_model.py:61-64).

Dropout on the pooled feature (reward_model.py:44) is implemented but is a
no-op unless a dropout rng is threaded in (deterministic eval by default —
the TPU-first stance is that stochastic layers take explicit rngs).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dla_tpu.models.config import ModelConfig
from dla_tpu.models.transformer import Transformer

Params = Dict[str, Any]


class RewardModel:
    def __init__(self, cfg: ModelConfig, pooling: str = "last_token",
                 dropout: float = 0.0):
        if pooling not in ("last_token", "mean"):
            raise ValueError(f"Unknown pooling '{pooling}'")
        self.backbone = Transformer(cfg)
        self.cfg = cfg
        self.pooling = pooling
        self.dropout = dropout

    def init(self, rng: jax.Array) -> Params:
        brng, hrng = jax.random.split(rng)
        params = self.backbone.init(brng)
        params.pop("lm_head", None)  # backbone only — no unembedding
        params["reward_head"] = {
            "w": (jax.random.normal(hrng, (self.cfg.hidden_size, 1), jnp.float32)
                  * (self.cfg.hidden_size ** -0.5)
                  ).astype(jnp.dtype(self.cfg.param_dtype)),
            "b": jnp.zeros((1,), jnp.dtype(self.cfg.param_dtype)),
        }
        return params

    def partition_specs(self) -> Params:
        specs = self.backbone.partition_specs()
        specs.pop("lm_head", None)
        specs["reward_head"] = {"w": P("fsdp", None), "b": P(None)}
        return specs

    def init_lora(self, rng: jax.Array) -> Params:
        """Backbone adapters only; the scalar head trains full-rank (it is
        1 column — adapters would be pure overhead). The trainer composes
        {"lora": this, "reward_head": head} as its trainable tree."""
        return self.backbone.init_lora(rng)

    def lora_partition_specs(self) -> Params:
        return self.backbone.lora_partition_specs()

    def merge_lora(self, base_params: Params, trainable: Params) -> Params:
        """Fold trainable {"lora": adapters, "reward_head": head} into the
        frozen backbone -> a standalone plain reward-model tree (for the
        `merged` export RLHF chains from)."""
        merged = self.backbone.merge_lora(base_params, trainable["lora"])
        merged["reward_head"] = trainable["reward_head"]
        return merged

    def apply(self, params: Params, input_ids: jnp.ndarray,
              attention_mask: jnp.ndarray,
              dropout_rng: Optional[jax.Array] = None,
              lora: Optional[Params] = None,
              with_aux: bool = False,
              segment_ids: Optional[jnp.ndarray] = None,
              n_segments: int = 0):
        """[B, T] -> [B] scalar rewards (fp32). ``dropout_rng`` drives
        both the pooled-feature dropout and (split) LoRA dropout.
        ``with_aux`` additionally returns the backbone's MoE aux tuple
        (None for dense backbones) so the pairwise-loss trainer can
        regularize the router.

        With ``segment_ids`` + static ``n_segments`` (packed preference
        rows, data/packing.py — segments numbered from 1), pooling runs
        PER SEGMENT and the result is [B, n_segments] — each segment
        pools exactly as it would as a standalone row (the backbone
        masks cross-segment attention and restarts positions), so
        packed rewards equal unpacked rewards. Absent segments read 0
        and must be dropped by the caller's pair mask."""
        lora_rng = None
        if dropout_rng is not None and lora is not None:
            dropout_rng, lora_rng = jax.random.split(dropout_rng)
        h, moe_aux = self.backbone.hidden_states_with_aux(
            params, input_ids, attention_mask, segment_ids=segment_ids,
            lora=lora, dropout_rng=lora_rng)
        mask = attention_mask.astype(jnp.float32)
        if segment_ids is not None:
            if not n_segments:
                raise ValueError("segment_ids needs a static n_segments")
            oh = (segment_ids[:, :, None]
                  == jnp.arange(1, n_segments + 1)[None, None, :]
                  ).astype(jnp.float32) * mask[:, :, None]  # [B, T, S]
            if self.pooling == "last_token":
                t_idx = jnp.arange(h.shape[1])[None, :, None]
                # rows are contiguous per segment: last real token of
                # segment s = max index where oh is on (0 if absent)
                idx = jnp.max(jnp.where(oh > 0, t_idx, -1), axis=1)
                pooled = jnp.take_along_axis(
                    h, jnp.maximum(idx, 0)[:, :, None], axis=1)  # [B,S,D]
            else:
                pooled = jnp.einsum("btd,bts->bsd", h, oh) / (
                    jnp.sum(oh, axis=1)[..., None] + 1e-8)
        elif self.pooling == "last_token":
            idx = jnp.maximum(mask.sum(axis=1).astype(jnp.int32) - 1, 0)
            pooled = jnp.take_along_axis(h, idx[:, None, None], axis=1)[:, 0]
        else:
            pooled = (h * mask[..., None]).sum(axis=1) / (
                mask.sum(axis=1, keepdims=True) + 1e-8)
        pooled = pooled.astype(jnp.float32)
        if dropout_rng is not None and self.dropout > 0.0:
            keep = jax.random.bernoulli(
                dropout_rng, 1.0 - self.dropout, pooled.shape)
            pooled = jnp.where(keep, pooled / (1.0 - self.dropout), 0.0)
        head = params["reward_head"]
        rewards = (pooled @ head["w"].astype(jnp.float32)
                   + head["b"].astype(jnp.float32))[..., 0]
        return (rewards, moe_aux) if with_aux else rewards

    __call__ = apply
