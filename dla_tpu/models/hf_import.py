"""Import HuggingFace Llama/Mistral-family weights from a local directory.

Replaces the weight-loading half of the reference's
``AutoModelForCausalLM.from_pretrained`` (src/models/base_model.py:30-35):
reads ``config.json`` + ``*.safetensors`` (or ``pytorch_model.bin``) and
produces this framework's stacked-layer param pytree:

  HF [out, in] Linear weights are transposed to [in, out] (we compute
  ``x @ w``), and per-layer tensors are stacked along a leading [L] dim to
  match the scan-over-layers layout (dla_tpu.models.transformer).

Zero-egress: only local files are read; there is no hub download here.
"""
from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, Optional

import numpy as np

from dla_tpu.models.config import ModelConfig
from dla_tpu.ops.rotary import validate_rope_scaling


def _validated_rope_scaling(hf_cfg):
    """rope_scaling from a config.json, normalized/refused by the one
    whitelist ops/rotary.py implements (None for default-type dicts).
    YaRN dicts omitting original_max_position_embeddings get the
    checkpoint's max_position_embeddings injected — HF's own fallback,
    which ops/rotary cannot see from inside the op."""
    rs = validate_rope_scaling(hf_cfg.get("rope_scaling"))
    rope_type = rs and rs["rope_type"]  # normalized by validate
    if (rope_type == "yarn"
            and "original_max_position_embeddings" not in rs
            and "max_position_embeddings" in hf_cfg):
        rs["original_max_position_embeddings"] = int(
            hf_cfg["max_position_embeddings"])
    if (rope_type == "dynamic"
            and "max_position_embeddings" not in rs
            and "max_position_embeddings" in hf_cfg):
        # dynamic NTK stretches relative to the TRAINED context, which
        # lives at the top level of config.json
        rs["max_position_embeddings"] = int(
            hf_cfg["max_position_embeddings"])
    if rope_type == "longrope":
        # phi-3 keeps the pretraining context at the TOP level of
        # config.json and derives the attention factor from the
        # extension ratio (HF _compute_longrope_parameters); fold both
        # into the dict so ops/rotary needs no config back-reference.
        # The TOP-LEVEL value wins over a dict-level one — HF reads the
        # config attribute for both the switch point and the factor.
        max_pos = hf_cfg.get("max_position_embeddings")
        orig = hf_cfg.get("original_max_position_embeddings")
        if orig:
            rs["original_max_position_embeddings"] = int(orig)
            if max_pos:
                rs["factor"] = float(max_pos) / float(orig)
        elif ("original_max_position_embeddings" not in rs and max_pos):
            rs["original_max_position_embeddings"] = int(max_pos)
    return rs


def hf_config_to_model_config(hf_cfg: Dict[str, Any], **overrides) -> ModelConfig:
    """Map a Llama/Mistral/Qwen2- or Phi-style HF config.json to
    ModelConfig."""
    model_type = str(hf_cfg.get("model_type", "")).lower()
    if model_type == "phi":
        return _phi_config(hf_cfg, overrides)
    n_heads = int(hf_cfg["num_attention_heads"])
    fields = dict(
        vocab_size=int(hf_cfg["vocab_size"]),
        hidden_size=int(hf_cfg["hidden_size"]),
        intermediate_size=int(hf_cfg["intermediate_size"]),
        num_layers=int(hf_cfg["num_hidden_layers"]),
        num_heads=n_heads,
        num_kv_heads=int(hf_cfg.get("num_key_value_heads", n_heads)),
        head_dim=hf_cfg.get("head_dim"),
        rope_theta=float(hf_cfg.get("rope_theta", 10000.0)),
        rms_norm_eps=float(hf_cfg.get("rms_norm_eps", 1e-5)),
        tie_embeddings=bool(hf_cfg.get("tie_word_embeddings", False)),
        max_seq_length=int(hf_cfg.get("max_position_embeddings", 4096)),
        # qwen2 carries q/k/v biases; llama configs may also set
        # attention_bias explicitly
        attention_bias=bool(hf_cfg.get("attention_bias",
                                       model_type == "qwen2")),
    )
    rs = _validated_rope_scaling(hf_cfg)
    if rs:
        fields["rope_scaling"] = rs
    if model_type == "gemma":
        # gated GELU MLP, sqrt(hidden)-scaled embeddings, (1+w) norms
        # (folded into the stored weights at import), tied unembedding
        # (GemmaConfig defaults tie_word_embeddings=True)
        fields["arch"] = "gemma"
        fields["tie_embeddings"] = bool(
            hf_cfg.get("tie_word_embeddings", True))
    if model_type == "gemma2":
        # gemma plus: post-attn/post-ffw norms (4 RMSNorms per block),
        # attention + final logit softcapping, query_pre_attn_scalar
        # softmax scale, and alternating-layer SWA (even layers slide —
        # HF Gemma2's is_sliding = not layer_idx % 2 == pattern 2 with
        # the (l+1) % pattern != 0 rule). Gemma2Config has no
        # use_sliding_window knob: a set sliding_window always applies.
        fields["arch"] = "gemma2"
        fields["tie_embeddings"] = bool(
            hf_cfg.get("tie_word_embeddings", True))
        fields["attn_logit_softcap"] = float(
            hf_cfg.get("attn_logit_softcapping") or 0.0)
        fields["final_logit_softcap"] = float(
            hf_cfg.get("final_logit_softcapping") or 0.0)
        qpas = hf_cfg.get("query_pre_attn_scalar")
        if qpas:
            fields["query_pre_attn_scalar"] = int(qpas)
        if hf_cfg.get("sliding_window"):
            fields["sliding_window"] = int(hf_cfg["sliding_window"])
            fields["sliding_window_pattern"] = 2
    if model_type == "mixtral" or "num_local_experts" in hf_cfg:
        fields["num_experts"] = int(hf_cfg.get("num_local_experts", 8))
        fields["num_experts_per_token"] = int(
            hf_cfg.get("num_experts_per_tok", 2))
    # mistral sliding-window attention; qwen2 ships sliding_window but
    # HF Qwen2Config defaults use_sliding_window to FALSE — an absent
    # key must follow the per-model-type transformers default (round-3
    # advisor finding). Whitelist the families whose HF configs apply a
    # set sliding_window unconditionally (no use_sliding_window knob);
    # any other type with the key absent stays full-causal rather than
    # silently windowing.
    sw = hf_cfg.get("sliding_window")
    # phi3 (like mistral/mixtral) has no use_sliding_window knob: a set
    # sliding_window always applies
    sw_default_on = model_type in ("mistral", "mixtral", "phi3")
    if sw and hf_cfg.get("use_sliding_window", sw_default_on):
        # qwen2's max_window_layers: the FIRST mwl layers run full
        # attention, SWA applies to layers i >= mwl (transformers
        # configuration_qwen2.py layer_types derivation). This
        # architecture's window is all-layers, so only mwl == 0 (SWA
        # everywhere) is representable; mwl >= L means SWA is disabled
        # entirely; anything between is per-layer — refuse rather than
        # silently windowing the full-attention layers. An absent key
        # means the HF default (28), not 0.
        mwl = hf_cfg.get("max_window_layers")
        if mwl is None and model_type == "qwen2":
            mwl = 28
        n_layers = int(hf_cfg["num_hidden_layers"])
        if mwl is None or int(mwl) == 0:
            fields["sliding_window"] = int(sw)
        elif int(mwl) >= n_layers:
            pass  # every layer full-attention: window never applies
        else:
            raise ValueError(
                f"partial sliding-window scheme (max_window_layers={mwl} "
                f"of {n_layers} layers full-attention) is not supported; "
                "sliding_window here is all-layers")
    fields.update(overrides)
    return ModelConfig(**fields)


def _phi_config(hf_cfg: Dict[str, Any], overrides) -> ModelConfig:
    """microsoft/phi-2-style config.json: parallel block, partial rotary,
    LayerNorm (layer_norm_eps, not rms_norm_eps), biased projections."""
    n_heads = int(hf_cfg["num_attention_heads"])
    fields = dict(
        vocab_size=int(hf_cfg["vocab_size"]),
        hidden_size=int(hf_cfg["hidden_size"]),
        intermediate_size=int(hf_cfg["intermediate_size"]),
        num_layers=int(hf_cfg["num_hidden_layers"]),
        num_heads=n_heads,
        num_kv_heads=int(hf_cfg.get("num_key_value_heads") or n_heads),
        rope_theta=float(hf_cfg.get("rope_theta", 10000.0)),
        rms_norm_eps=float(hf_cfg.get("layer_norm_eps", 1e-5)),
        tie_embeddings=bool(hf_cfg.get("tie_word_embeddings", False)),
        max_seq_length=int(hf_cfg.get("max_position_embeddings", 2048)),
        arch="phi",
        rotary_pct=float(hf_cfg.get("partial_rotary_factor", 0.5)),
    )
    rs = _validated_rope_scaling(hf_cfg)
    if rs:
        fields["rope_scaling"] = rs
    fields.update(overrides)
    return ModelConfig(**fields)


def read_hf_config(model_dir) -> Optional[Dict[str, Any]]:
    p = Path(model_dir) / "config.json"
    if not p.is_file():
        return None
    with p.open() as fh:
        return json.load(fh)


def _load_state_dict(model_dir: Path) -> Dict[str, np.ndarray]:
    """All tensors from safetensors shards (preferred) or a torch bin."""
    st_files = sorted(model_dir.glob("*.safetensors"))
    if st_files:
        from safetensors import safe_open
        out: Dict[str, np.ndarray] = {}
        for f in st_files:
            with safe_open(str(f), framework="np") as sf:
                for key in sf.keys():
                    out[key] = sf.get_tensor(key)
        return out
    bin_files = sorted(model_dir.glob("pytorch_model*.bin"))
    if bin_files:
        import torch
        out = {}
        for f in bin_files:
            sd = torch.load(str(f), map_location="cpu", weights_only=True)
            for k, v in sd.items():
                out[k] = v.float().numpy() if v.dtype == torch.bfloat16 \
                    else v.numpy()
        return out
    raise FileNotFoundError(
        f"No *.safetensors or pytorch_model*.bin under {model_dir}")


def import_hf_weights(model_dir, cfg: ModelConfig,
                      dtype: Optional[str] = None) -> Dict[str, Any]:
    """Local HF checkpoint dir -> dla_tpu param pytree (host numpy)."""
    model_dir = Path(model_dir)
    sd = _load_state_dict(model_dir)
    pdtype = np.dtype(dtype or cfg.param_dtype)
    pre = "model." if any(k.startswith("model.") for k in sd) else ""

    def take(name: str) -> np.ndarray:
        key = pre + name
        if key not in sd:
            raise KeyError(f"HF checkpoint missing tensor '{key}'")
        return np.asarray(sd[key])

    def linear(name: str) -> np.ndarray:
        return take(name).T.astype(pdtype)  # [out,in] -> [in,out]

    if cfg.arch == "phi":
        return _import_phi(sd, cfg, pdtype, take, linear)

    L = cfg.num_layers
    moe = cfg.num_experts > 0
    gemma2 = cfg.arch == "gemma2"
    stacked: Dict[str, list] = {k: [] for k in (
        "attn_norm", "wq", "wk", "wv", "wo",
        "mlp_norm", "w_gate", "w_up", "w_down")}
    if gemma2:
        stacked["attn_post_norm"] = []
        stacked["mlp_post_norm"] = []
    if moe:
        stacked["router"] = []
    if cfg.attention_bias:
        for k in ("wq_bias", "wk_bias", "wv_bias"):
            stacked[k] = []
    # phi-3 fuses q/k/v into qkv_proj and gate/up into gate_up_proj;
    # detect by key (the config maps to the plain llama block otherwise)
    fused_qkv = (pre + "layers.0.self_attn.qkv_proj.weight") in sd
    qd = cfg.num_heads * cfg.head_dim_
    kvd = cfg.num_kv_heads * cfg.head_dim_
    for i in range(L):
        p = f"layers.{i}."
        stacked["attn_norm"].append(take(p + "input_layernorm.weight").astype(pdtype))
        if fused_qkv:
            qkv = take(p + "self_attn.qkv_proj.weight")  # [(H+2K)dh, D]
            stacked["wq"].append(qkv[:qd].T.astype(pdtype))
            stacked["wk"].append(qkv[qd:qd + kvd].T.astype(pdtype))
            stacked["wv"].append(qkv[qd + kvd:].T.astype(pdtype))
        else:
            stacked["wq"].append(linear(p + "self_attn.q_proj.weight"))
            stacked["wk"].append(linear(p + "self_attn.k_proj.weight"))
            stacked["wv"].append(linear(p + "self_attn.v_proj.weight"))
        if cfg.attention_bias:
            stacked["wq_bias"].append(
                take(p + "self_attn.q_proj.bias").astype(pdtype))
            stacked["wk_bias"].append(
                take(p + "self_attn.k_proj.bias").astype(pdtype))
            stacked["wv_bias"].append(
                take(p + "self_attn.v_proj.bias").astype(pdtype))
        stacked["wo"].append(linear(p + "self_attn.o_proj.weight"))
        if gemma2:
            # gemma-2 norm names: post_attention_layernorm normalizes the
            # attention OUTPUT (pre-residual); the MLP's pre-norm is
            # pre_feedforward_layernorm
            stacked["attn_post_norm"].append(
                take(p + "post_attention_layernorm.weight").astype(pdtype))
            stacked["mlp_norm"].append(
                take(p + "pre_feedforward_layernorm.weight").astype(pdtype))
            stacked["mlp_post_norm"].append(
                take(p + "post_feedforward_layernorm.weight").astype(pdtype))
        else:
            stacked["mlp_norm"].append(
                take(p + "post_attention_layernorm.weight").astype(pdtype))
        if moe:
            # Mixtral MoE layout: block_sparse_moe.gate -> router,
            # experts.j.{w1,w3,w2} -> per-expert gate/up/down, stacked
            # along a leading [E] dim
            m = p + "block_sparse_moe."
            stacked["router"].append(linear(m + "gate.weight"))
            stacked["w_gate"].append(np.stack(
                [linear(m + f"experts.{j}.w1.weight")
                 for j in range(cfg.num_experts)]))
            stacked["w_up"].append(np.stack(
                [linear(m + f"experts.{j}.w3.weight")
                 for j in range(cfg.num_experts)]))
            stacked["w_down"].append(np.stack(
                [linear(m + f"experts.{j}.w2.weight")
                 for j in range(cfg.num_experts)]))
        elif fused_qkv:
            gu = take(p + "mlp.gate_up_proj.weight")      # [2F, D]
            f_dim = cfg.intermediate_size
            stacked["w_gate"].append(gu[:f_dim].T.astype(pdtype))
            stacked["w_up"].append(gu[f_dim:].T.astype(pdtype))
            stacked["w_down"].append(linear(p + "mlp.down_proj.weight"))
        else:
            stacked["w_gate"].append(linear(p + "mlp.gate_proj.weight"))
            stacked["w_up"].append(linear(p + "mlp.up_proj.weight"))
            stacked["w_down"].append(linear(p + "mlp.down_proj.weight"))

    params: Dict[str, Any] = {
        "embed": {"embedding": take("embed_tokens.weight").astype(pdtype)},
        "layers": {k: np.stack(v) for k, v in stacked.items()},
        "final_norm": take("norm.weight").astype(pdtype),
    }
    if cfg.arch in ("gemma", "gemma2"):
        # HF gemma RMSNorm computes x * (1 + w); fold the +1 here so the
        # model's shared rms_norm path needs no arch branch
        norm_keys = ("attn_norm", "mlp_norm") if cfg.arch == "gemma" else (
            "attn_norm", "mlp_norm", "attn_post_norm", "mlp_post_norm")
        for k in norm_keys:
            params["layers"][k] = params["layers"][k] + np.asarray(1, pdtype)
        params["final_norm"] = params["final_norm"] + np.asarray(1, pdtype)
    if not cfg.tie_embeddings:
        if "lm_head.weight" in sd:
            params["lm_head"] = np.asarray(sd["lm_head.weight"]).T.astype(pdtype)
        else:
            params["lm_head"] = params["embed"]["embedding"].T.copy()
    return params


def _import_phi(sd, cfg: ModelConfig, pdtype, take, linear
                ) -> Dict[str, Any]:
    """Phi weight layout (HF PhiForCausalLM): shared input_layernorm
    (weight+bias), q/k/v_proj + dense with biases, mlp.fc1/fc2 with
    biases, final_layernorm, biased lm_head."""
    L = cfg.num_layers
    names = {
        "ln": "input_layernorm.weight", "ln_bias": "input_layernorm.bias",
        "wq": "self_attn.q_proj.weight", "wq_bias": "self_attn.q_proj.bias",
        "wk": "self_attn.k_proj.weight", "wk_bias": "self_attn.k_proj.bias",
        "wv": "self_attn.v_proj.weight", "wv_bias": "self_attn.v_proj.bias",
        "wo": "self_attn.dense.weight", "wo_bias": "self_attn.dense.bias",
        "fc1": "mlp.fc1.weight", "fc1_bias": "mlp.fc1.bias",
        "fc2": "mlp.fc2.weight", "fc2_bias": "mlp.fc2.bias",
    }
    matrices = ("wq", "wk", "wv", "wo", "fc1", "fc2")
    stacked: Dict[str, list] = {k: [] for k in names}
    for i in range(L):
        p = f"layers.{i}."
        for ours, theirs in names.items():
            stacked[ours].append(
                linear(p + theirs) if ours in matrices
                else take(p + theirs).astype(pdtype))
    params: Dict[str, Any] = {
        "embed": {"embedding": take("embed_tokens.weight").astype(pdtype)},
        "layers": {k: np.stack(v) for k, v in stacked.items()},
        "final_norm": take("final_layernorm.weight").astype(pdtype),
        "final_norm_bias": take("final_layernorm.bias").astype(pdtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = np.asarray(sd["lm_head.weight"]).T.astype(pdtype)
        bias = sd.get("lm_head.bias")
        params["lm_head_bias"] = (
            np.asarray(bias).astype(pdtype) if bias is not None
            else np.zeros((cfg.vocab_size,), pdtype))
    return params
