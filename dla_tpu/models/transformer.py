"""Decoder-only transformer (Llama-2 family), pure JAX, TPU-first.

Replaces the reference's HF ``AutoModelForCausalLM`` wrapper
(src/models/base_model.py:17-42). Design points that matter on TPU:

- **scan-over-layers**: per-layer params are stacked with a leading [L]
  dim and the block is applied with ``lax.scan`` — compile time is O(1) in
  depth and XLA sees one block to optimize.
- **PartitionSpec-annotated params**: ``partition_specs()`` mirrors the
  param pytree. ZeRO-3-equivalent sharding = the ``fsdp`` axis on one dim
  of every matrix (GSPMD all-gathers per use, like DeepSpeed stage-3,
  config/deepspeed_zero3.json:6); tensor parallelism = the ``model`` axis
  on attention heads / MLP hidden (megatron layout, new capability —
  SURVEY.md sec 2.3).
- **remat**: ``jax.checkpoint`` around the block body replaces
  ``gradient_checkpointing_enable`` (base_model.py:36-37).
- **mixed precision**: bf16 activations, fp32 master params; params are
  cast to the activation dtype at use so the MXU runs bf16.
- **KV-cache decode**: ``prefill``/``decode_step`` give the jitted
  autoregressive path HF ``generate`` provided for the reference
  (train_rlhf.py:123-124).
"""
from __future__ import annotations

import sys
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dla_tpu.models.config import ModelConfig
from dla_tpu.parallel.mesh import auto_axes
from dla_tpu.ops.attention import (
    causal_attention,
    chunked_causal_attention,
    decode_attention,
)
from dla_tpu.ops.norms import layer_norm, rms_norm
from dla_tpu.ops.rotary import apply_rotary, rotary_angles

Params = Dict[str, Any]

# Activation sharding: batch over the two batch axes, sequence over the
# context-parallel axis, features replicated (TP slices live inside the block).
ACT_SPEC = P(("data", "fsdp"), "sequence", None)


# shapes for which the replicated-flash fallback was already reported —
# trace-time, so one line per compiled shape, not per step
_REPLICATED_FLASH_LOGGED: set = set()

def _flash_tileable(t: int) -> bool:
    """Whether the Pallas flash kernel may take sequence length T.

    On hardware, mosaic tiles 128-wide MXU blocks: require T % 128 == 0
    (VERDICT r2 weak-item 7 — ``t % min(128, t)`` was vacuously true for
    any T < 128, letting flash engage with degenerate blocks on TPU).
    CPU runs the kernel in interpret mode where any divisor-of-128 tile
    is fine — that keeps the small-shape parity tests cheap."""
    if jax.default_backend() == "cpu":
        return t % min(128, t) == 0
    return t >= 128 and t % 128 == 0


def _flash_mesh():
    """The ambient mesh when flash attention must be shard_map-wrapped:
    a pallas_call has no SPMD partitioning rule, so under a >1-device
    mesh GSPMD would otherwise fully replicate the attention inputs
    (observed: output sharding collapses to PartitionSpec()). Axes that
    an enclosing shard_map already made manual (the `stage` axis inside
    the pipeline schedule) don't count: the kernel nests as a
    partial-manual shard_map over the remaining auto axes. Returns None
    on single-device / no-mesh / all->1-axes-already-manual (plain
    pallas_call is fine)."""
    mesh = _ambient_mesh()
    if mesh is None:
        return None
    n = 1
    for name in auto_axes(mesh):  # any >1 AUTO axis replicates
        n *= mesh.shape[name]
    return mesh if n > 1 else None


def _constrain(x, spec):
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except (ValueError, RuntimeError):
        return x  # outside a mesh context (plain single-device use)


def _ambient_mesh():
    """The ambient mesh, or None when absent/empty/unavailable.

    Prefers ``jax.sharding.get_abstract_mesh`` (jax >= 0.5); on older
    jax — where that symbol is a deprecation stub or missing — the
    ``with mesh:`` context lives in ``thread_resources.env.physical_mesh``
    (a concrete Mesh, which every consumer here accepts: ``auto_axes``
    treats it as all-auto and shard_map takes it directly)."""
    try:
        mesh = jax.sharding.get_abstract_mesh()
    except (ValueError, RuntimeError, AttributeError):
        try:
            from jax._src.mesh import thread_resources
            mesh = thread_resources.env.physical_mesh
        except (ImportError, AttributeError, ValueError, RuntimeError):
            return None
    if mesh is None or mesh.empty:
        return None
    return mesh


def _sequence_axis_size() -> int:
    """Size of the `sequence` axis of the ambient mesh (1 if no mesh)."""
    mesh = _ambient_mesh()
    return mesh.shape.get("sequence", 1) if mesh is not None else 1


def _stage_axis_size() -> int:
    """Size of the `stage` (pipeline) axis of the ambient mesh."""
    mesh = _ambient_mesh()
    return mesh.shape.get("stage", 1) if mesh is not None else 1


class Transformer:
    """Functional model: a namespace of pure functions bound to a config."""

    def __init__(self, cfg: ModelConfig):
        self.cfg = cfg
        self.adtype = jnp.dtype(cfg.dtype)
        self.pdtype = jnp.dtype(cfg.param_dtype)
        if self._interleaved_storage and cfg.num_layers % (
                cfg.pipeline_stages * cfg.pipeline_interleave):
            raise ValueError(
                f"pipeline_stages={cfg.pipeline_stages} x "
                f"pipeline_interleave={cfg.pipeline_interleave} must divide "
                f"num_layers={cfg.num_layers}")
        # gemma-2 scales attention by query_pre_attn_scalar**-0.5 (which
        # differs from head_dim**-0.5 on the 27B); None = op default
        self._softmax_scale = (
            cfg.query_pre_attn_scalar ** -0.5
            if cfg.query_pre_attn_scalar else None)

    # ------------------------------------------------------- storage layout

    @property
    def _interleaved_storage(self) -> bool:
        """Whether stacked layer leaves are stored [V, S, c, ...] instead
        of [L, ...]. The circular/interleaved pipeline schedule assigns
        block b = p*S + s to stage s; with flat [L] storage sharded
        contiguously over `stage`, GSPMD must exchange ~(V-1)/V of every
        layer weight across the stage ring EVERY step (measured: one
        weight-shaped all-to-all per layer leaf per step, r5 HLO probe).
        Because block-major [V, S, c] is exactly the row-major reshape of
        the canonical [L] stack, storing that 3-D leading shape and
        sharding dim 1 over `stage` makes the round-robin ownership
        shard-local with ZERO data reordering — flattening back to [L]
        is a free reshape off-mesh. Enabled by cfg.pipeline_stages (set
        from hardware.mesh.stage by the config loader when
        pipeline_interleave > 1)."""
        return (self.cfg.pipeline_stages > 1
                and self.cfg.pipeline_interleave > 1)

    def _storage_lead(self) -> Tuple[int, int, int]:
        cfg = self.cfg
        v, s = cfg.pipeline_interleave, cfg.pipeline_stages
        return v, s, cfg.num_layers // (v * s)

    def _map_layer_stack(self, tree: Params, fn) -> Params:
        """Apply ``fn`` to every stacked leaf under tree["layers"]
        (shallow copy elsewhere). Trees without a "layers" key pass
        through unchanged."""
        if not isinstance(tree, dict) or "layers" not in tree:
            return tree
        return {**tree,
                "layers": {k: fn(v) for k, v in tree["layers"].items()}}

    def to_storage_layout(self, tree: Params) -> Params:
        """Canonical [L, ...] layer stacks -> the model's storage layout
        ([V, S, c, ...] when interleaved storage is on; identity
        otherwise). Idempotent: leaves already in storage shape pass
        through. Use after building canonical trees (HF import, external
        tools) before handing them to this model."""
        if not self._interleaved_storage:
            return tree
        v, s, c = self._storage_lead()

        def go(x):
            if x.shape[:3] == (v, s, c):
                return x
            return x.reshape((v, s, c) + x.shape[1:])
        return self._map_layer_stack(tree, go)

    def to_canonical_layout(self, tree: Params) -> Params:
        """Inverse of to_storage_layout (for export / plain-scan paths)."""
        if not self._interleaved_storage:
            return tree
        n = self.cfg.num_layers

        def go(x):
            if x.shape[0] == n:
                return x
            return x.reshape((n,) + x.shape[3:])
        return self._map_layer_stack(tree, go)

    def _flat_layers(self, layers: Params) -> Params:
        """Layer dict in canonical flat [L, ...] form for plain
        scan-over-layers paths (free reshape: block-major storage IS
        canonical row-major order)."""
        if not self._interleaved_storage:
            return layers
        n = self.cfg.num_layers
        return {k: (v.reshape((n,) + v.shape[3:])
                    if v.shape[0] != n else v)
                for k, v in layers.items()}

    def _storage_spec(self, spec: P) -> P:
        """Layer-stack PartitionSpec for the storage layout: the leading
        P("stage", *rest) becomes P(None, "stage", None, *rest) — the
        stage axis moves to the middle (block-index) dim."""
        if not self._interleaved_storage:
            return spec
        return P(None, "stage", None, *spec[1:])

    # ------------------------------------------------------------------ init

    def init(self, rng: jax.Array) -> Params:
        return self.to_storage_layout(self._init_canonical(rng))

    def _init_canonical(self, rng: jax.Array) -> Params:
        cfg = self.cfg
        dh = cfg.head_dim_
        qdim, kvdim = cfg.num_heads * dh, cfg.num_kv_heads * dh
        keys = jax.random.split(rng, 8)
        std = 0.02
        out_std = std / (2 * cfg.num_layers) ** 0.5  # gpt-2-style depth scaling

        def mat(key, shape, scale):
            return (jax.random.normal(key, shape, jnp.float32) * scale
                    ).astype(self.pdtype)

        L, D, F = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
        if cfg.arch == "phi":
            # parallel-residual block: one shared input LayerNorm, biased
            # projections, non-gated GELU MLP (fc1/fc2)
            params = {
                "embed": {"embedding": mat(keys[0], (cfg.vocab_size, D), std)},
                "layers": {
                    "ln": jnp.ones((L, D), self.pdtype),
                    "ln_bias": jnp.zeros((L, D), self.pdtype),
                    "wq": mat(keys[1], (L, D, qdim), std),
                    "wq_bias": jnp.zeros((L, qdim), self.pdtype),
                    "wk": mat(keys[2], (L, D, kvdim), std),
                    "wk_bias": jnp.zeros((L, kvdim), self.pdtype),
                    "wv": mat(keys[3], (L, D, kvdim), std),
                    "wv_bias": jnp.zeros((L, kvdim), self.pdtype),
                    "wo": mat(keys[4], (L, qdim, D), out_std),
                    "wo_bias": jnp.zeros((L, D), self.pdtype),
                    "fc1": mat(keys[5], (L, D, F), std),
                    "fc1_bias": jnp.zeros((L, F), self.pdtype),
                    "fc2": mat(keys[6], (L, F, D), out_std),
                    "fc2_bias": jnp.zeros((L, D), self.pdtype),
                },
                "final_norm": jnp.ones((D,), self.pdtype),
                "final_norm_bias": jnp.zeros((D,), self.pdtype),
            }
            if not cfg.tie_embeddings:
                params["lm_head"] = mat(
                    jax.random.fold_in(rng, 99), (D, cfg.vocab_size), std)
                params["lm_head_bias"] = jnp.zeros(
                    (cfg.vocab_size,), self.pdtype)
            return params
        if cfg.num_experts > 0:
            E = cfg.num_experts
            mlp = {
                "router": mat(jax.random.fold_in(rng, 7), (L, D, E), std),
                "w_gate": mat(keys[5], (L, E, D, F), std),
                "w_up": mat(keys[6], (L, E, D, F), std),
                "w_down": mat(keys[7], (L, E, F, D), out_std),
            }
        else:
            mlp = {
                "w_gate": mat(keys[5], (L, D, F), std),
                "w_up": mat(keys[6], (L, D, F), std),
                "w_down": mat(keys[7], (L, F, D), out_std),
            }
        params: Params = {
            "embed": {"embedding": mat(keys[0], (cfg.vocab_size, D), std)},
            "layers": {
                "attn_norm": jnp.ones((L, D), self.pdtype),
                "wq": mat(keys[1], (L, D, qdim), std),
                "wk": mat(keys[2], (L, D, kvdim), std),
                "wv": mat(keys[3], (L, D, kvdim), std),
                "wo": mat(keys[4], (L, qdim, D), out_std),
                "mlp_norm": jnp.ones((L, D), self.pdtype),
                **mlp,
            },
            "final_norm": jnp.ones((D,), self.pdtype),
        }
        if cfg.arch == "gemma2":  # post-attn / post-ffw norms (4 per block)
            params["layers"]["attn_post_norm"] = jnp.ones((L, D), self.pdtype)
            params["layers"]["mlp_post_norm"] = jnp.ones((L, D), self.pdtype)
        if cfg.attention_bias:  # qwen2-style q/k/v biases
            params["layers"]["wq_bias"] = jnp.zeros((L, qdim), self.pdtype)
            params["layers"]["wk_bias"] = jnp.zeros((L, kvdim), self.pdtype)
            params["layers"]["wv_bias"] = jnp.zeros((L, kvdim), self.pdtype)
        if not cfg.tie_embeddings:
            params["lm_head"] = mat(
                jax.random.fold_in(rng, 99), (D, cfg.vocab_size), std)
        return params

    # ----------------------------------------------------------------- LoRA

    # target -> (in-dim key, out-dim key) of the base matrix [L, in, out]
    _LORA_SHAPES = {
        "wq": ("hidden", "q"), "wk": ("hidden", "kv"), "wv": ("hidden", "kv"),
        "wo": ("q", "hidden"), "w_gate": ("hidden", "ffn"),
        "w_up": ("hidden", "ffn"), "w_down": ("ffn", "hidden"),
        "fc1": ("hidden", "ffn"), "fc2": ("ffn", "hidden"),  # phi MLP
    }

    def _lora_dims(self):
        cfg = self.cfg
        dh = cfg.head_dim_
        return {"hidden": cfg.hidden_size, "q": cfg.num_heads * dh,
                "kv": cfg.num_kv_heads * dh, "ffn": cfg.intermediate_size}

    def init_lora(self, rng: jax.Array) -> Params:
        """Adapter pytree for cfg.lora_targets: per target, A [L, in, r]
        (gaussian) and B [L, r, out] (zeros) — the functional version of the
        reference's dead ``freeze_except_lora``/``model.lora`` surface
        (reference base_model.py:45-49, config/distill_config.yaml:10-14)."""
        cfg = self.cfg
        if cfg.lora_r <= 0:
            raise ValueError("init_lora requires lora_r > 0")
        dims = self._lora_dims()
        layers: Params = {}
        for i, t in enumerate(cfg.lora_targets):
            din, dout = (dims[k] for k in self._LORA_SHAPES[t])
            key = jax.random.fold_in(rng, i)
            layers[f"{t}_lora_a"] = (
                jax.random.normal(key, (cfg.num_layers, din, cfg.lora_r),
                                  jnp.float32) * 0.02).astype(self.pdtype)
            layers[f"{t}_lora_b"] = jnp.zeros(
                (cfg.num_layers, cfg.lora_r, dout), self.pdtype)
        return self.to_storage_layout({"layers": layers})

    def lora_partition_specs(self) -> Params:
        """A shards its input dim like the base matrix; B its output dim."""
        base = {
            "wq": P(None, "fsdp", "model"), "wk": P(None, "fsdp", "model"),
            "wv": P(None, "fsdp", "model"), "wo": P(None, "model", "fsdp"),
            "w_gate": P(None, "fsdp", "model"),
            "w_up": P(None, "fsdp", "model"),
            "w_down": P(None, "model", "fsdp"),
            "fc1": P(None, "fsdp", "model"),     # phi MLP
            "fc2": P(None, "model", "fsdp"),
        }
        layers: Params = {}
        for t in self.cfg.lora_targets:
            spec = base[t]
            layers[f"{t}_lora_a"] = self._storage_spec(
                P("stage", spec[1], None))
            layers[f"{t}_lora_b"] = self._storage_spec(
                P("stage", None, spec[2]))
        return {"layers": layers}

    def merge_lora(self, params: Params, lora: Params) -> Params:
        """Fold adapters into a standalone param tree (for decode/export:
        the KV-cache generation path runs merged weights)."""
        cfg = self.cfg
        scale = cfg.lora_alpha / cfg.lora_r
        out = jax.tree.map(lambda x: x, params)  # shallow-ish copy
        new_layers = dict(out["layers"])
        for t in cfg.lora_targets:
            a = lora["layers"][f"{t}_lora_a"].astype(jnp.float32)
            b = lora["layers"][f"{t}_lora_b"].astype(jnp.float32)
            # "..." leading dims: [L] canonical or [V, S, c] storage
            delta = jnp.einsum("...ir,...ro->...io", a, b) * scale
            new_layers[t] = (new_layers[t].astype(jnp.float32) + delta
                             ).astype(new_layers[t].dtype)
        out["layers"] = new_layers
        return out

    def _lora_proj(self, layer: Params, name: str, x: jnp.ndarray,
                   base_out: jnp.ndarray,
                   dropout_key: Optional[jax.Array]) -> jnp.ndarray:
        """base_out + scale * dropout(x) @ A @ B when adapters are present."""
        a = layer.get(f"{name}_lora_a")
        if a is None:
            return base_out
        cfg = self.cfg
        b_ = layer[f"{name}_lora_b"]
        z = x
        if dropout_key is not None and cfg.lora_dropout > 0:
            idx = list(cfg.lora_targets).index(name)
            keep = jax.random.bernoulli(
                jax.random.fold_in(dropout_key, idx),
                1.0 - cfg.lora_dropout, z.shape)
            z = jnp.where(keep, z / (1.0 - cfg.lora_dropout), 0.0)
        scale = cfg.lora_alpha / cfg.lora_r
        return base_out + ((z @ a.astype(self.adtype))
                           @ b_.astype(self.adtype)) * scale

    def slot_lora_xs(self, adapters: Optional[Params]) -> Params:
        """Per-slot LoRA leaves for the paged decode scans: gather each
        batch row's adapter from the stacked ``[N, L, din, r]`` pools by
        ``adapters["idx"]`` ([B] int32) and move the layer axis leading
        ([L, B, din, r]) so the leaves ride the layer scan like
        ``swa_on``. Keys are renamed ``_lora_`` -> ``_slot_lora_`` so
        the training-path ``_lora_proj`` never sees them; pool B factors
        are expected pre-scaled by alpha/r (AdapterStore's publish
        contract), so the in-graph delta is a bare x@A@B. ``None``
        (tenancy off) contributes nothing — the decode graph is
        byte-identical to the adapter-free build."""
        if adapters is None:
            return {}
        idx = adapters["idx"]
        out: Params = {}
        for key, pool in adapters.items():
            if key == "idx":
                continue
            g = jnp.take(pool, idx, axis=0)        # [B, L, din, r]
            out[key.replace("_lora_", "_slot_lora_")] = \
                jnp.moveaxis(g, 0, 1)              # [L, B, din, r]
        return out

    # ------------------------------------------------------- partition specs

    def partition_specs(self) -> Params:
        specs = self._partition_specs_canonical()
        return self._map_layer_stack(
            specs, self._storage_spec) if self._interleaved_storage \
            else specs

    def _partition_specs_canonical(self) -> Params:
        """PartitionSpec pytree mirroring ``init``'s output.

        fsdp shards the embedding/hidden dim; model shards heads / MLP
        hidden / vocab (megatron). Stacked layer leaves lead with the
        ``stage`` axis — pipeline parallelism is "shard the layer stack":
        each stage owns a contiguous block of layers (no-op at stage=1,
        where the axis prunes away).

        The token-embedding table is deliberately NOT model-sharded: a
        gather whose operand is sharded on the indexed (vocab) dim forces
        the SPMD partitioner to rematerialize the full table on every
        forward ("involuntary full rematerialization"), paying a
        model-axis all-gather per step. P("fsdp", None) keeps the memory
        win (ZeRO-3 shard over fsdp, gathered at use like every other
        matrix) with zero TP-axis traffic on the embed path.
        """
        if self.cfg.arch == "phi":
            specs = {
                "embed": {"embedding": P("fsdp", None)},
                "layers": {
                    "ln": P("stage", None), "ln_bias": P("stage", None),
                    "wq": P("stage", "fsdp", "model"),
                    "wq_bias": P("stage", "model"),
                    "wk": P("stage", "fsdp", "model"),
                    "wk_bias": P("stage", "model"),
                    "wv": P("stage", "fsdp", "model"),
                    "wv_bias": P("stage", "model"),
                    "wo": P("stage", "model", "fsdp"),
                    "wo_bias": P("stage", None),
                    "fc1": P("stage", "fsdp", "model"),
                    "fc1_bias": P("stage", "model"),
                    "fc2": P("stage", "model", "fsdp"),
                    "fc2_bias": P("stage", None),
                },
                "final_norm": P(None),
                "final_norm_bias": P(None),
            }
            if not self.cfg.tie_embeddings:
                specs["lm_head"] = P("fsdp", "model")
                specs["lm_head_bias"] = P("model")
            return specs
        if self.cfg.num_experts > 0:
            mlp_specs = {
                "router": P("stage", "fsdp", None),
                "w_gate": P("stage", "expert", "fsdp", "model"),
                "w_up": P("stage", "expert", "fsdp", "model"),
                "w_down": P("stage", "expert", "model", "fsdp"),
            }
        else:
            mlp_specs = {
                "w_gate": P("stage", "fsdp", "model"),
                "w_up": P("stage", "fsdp", "model"),
                "w_down": P("stage", "model", "fsdp"),
            }
        specs: Params = {
            "embed": {"embedding": P("fsdp", None)},
            "layers": {
                "attn_norm": P("stage", None),
                "wq": P("stage", "fsdp", "model"),
                "wk": P("stage", "fsdp", "model"),
                "wv": P("stage", "fsdp", "model"),
                "wo": P("stage", "model", "fsdp"),
                "mlp_norm": P("stage", None),
                **mlp_specs,
            },
            "final_norm": P(None),
        }
        if self.cfg.arch == "gemma2":
            specs["layers"]["attn_post_norm"] = P("stage", None)
            specs["layers"]["mlp_post_norm"] = P("stage", None)
        if self.cfg.attention_bias:
            specs["layers"]["wq_bias"] = P("stage", "model")
            specs["layers"]["wk_bias"] = P("stage", "model")
            specs["layers"]["wv_bias"] = P("stage", "model")
        if not self.cfg.tie_embeddings:
            specs["lm_head"] = P("fsdp", "model")
        return specs

    # ---------------------------------------------------------------- block

    def _block(self, layer: Params, x: jnp.ndarray,
               cos: jnp.ndarray, sin: jnp.ndarray,
               kv_segment_mask: Optional[jnp.ndarray],
               q_positions: jnp.ndarray,
               kv_positions: jnp.ndarray,
               kv_override: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
               allow_flash: bool = False,
               flash_segs: Optional[jnp.ndarray] = None,
               cp: Optional[Tuple] = None,
               dropout_key: Optional[jax.Array] = None,
               token_valid: Optional[jnp.ndarray] = None,  # [B, T] for MoE
               factored_mask: Optional[Tuple] = None,  # (valid, segments)
               ) -> Tuple[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]:
        """One decoder block. Returns (output, (k, v)) — k/v before override,
        for cache writes. ``layer`` may carry LoRA leaves (merged upstream)."""
        cfg = self.cfg
        dh = cfg.head_dim_
        rd = cfg.rotary_dim_
        b, t, d = x.shape

        def cast(w):
            return w.astype(self.adtype)

        def proj(name, inp):
            out = self._dense(layer, name, inp)
            bias = layer.get(f"{name}_bias")
            if bias is not None:
                out = out + cast(bias)
            return self._lora_proj(layer, name, inp, out, dropout_key)

        if cfg.arch == "phi":
            h = layer_norm(x, layer["ln"], layer["ln_bias"],
                           cfg.rms_norm_eps)
        else:
            h = rms_norm(x, layer["attn_norm"], cfg.rms_norm_eps)
        q = proj("wq", h).reshape(b, t, cfg.num_heads, dh)
        k = proj("wk", h).reshape(b, t, cfg.num_kv_heads, dh)
        v = proj("wv", h).reshape(b, t, cfg.num_kv_heads, dh)
        q = _constrain(q, P(("data", "fsdp"), "sequence", "model", None))
        k = _constrain(k, P(("data", "fsdp"), "sequence", "model", None))
        q = apply_rotary(q, cos, sin, rotary_dim=rd)
        k = apply_rotary(k, cos, sin, rotary_dim=rd)
        new_kv = (k, v)
        if kv_override is not None:
            k, v = kv_override
        attn = self._attention(q, k, v, kv_segment_mask,
                               q_positions, kv_positions, allow_flash, cp,
                               flash_segs=flash_segs,
                               window=self._layer_window(layer),
                               factored_mask=factored_mask)
        attn = attn.reshape(b, t, cfg.num_heads * dh)

        if cfg.arch == "phi":
            # parallel residual: attention and MLP both read the shared h
            attn_out = _constrain(proj("wo", attn), ACT_SPEC)
            ff = _constrain(jax.nn.gelu(proj("fc1", h), approximate=True),
                            P(("data", "fsdp"), "sequence", "model"))
            mlp_out = _constrain(proj("fc2", ff), ACT_SPEC)
            return x + attn_out + mlp_out, new_kv, None

        attn_out = proj("wo", attn)
        if cfg.arch == "gemma2":  # post-attn norm BEFORE the residual add
            attn_out = rms_norm(attn_out, layer["attn_post_norm"],
                                cfg.rms_norm_eps)
        x = x + _constrain(attn_out, ACT_SPEC)
        h = rms_norm(x, layer["mlp_norm"], cfg.rms_norm_eps)
        mlp_out, moe_aux = self._mlp(layer, h, proj, token_valid)
        if cfg.arch == "gemma2":
            mlp_out = rms_norm(mlp_out, layer["mlp_post_norm"],
                               cfg.rms_norm_eps)
        x = x + _constrain(mlp_out, ACT_SPEC)
        return x, new_kv, moe_aux

    def _mlp(self, layer: Params, h: jnp.ndarray, proj,
             token_valid: Optional[jnp.ndarray] = None):
        """Dense gated-SiLU MLP, or the routed MoE variant when the layer
        carries a router (cfg.num_experts > 0). Returns (out, aux | None);
        aux is the (load_balance, router_z, dropped_frac) triple from
        ops.moe for the trainer to weight in. ``token_valid`` keeps pad
        tokens from claiming expert capacity or skewing router stats."""
        if "router" in layer:
            from dla_tpu.ops.moe import moe_mlp
            out, aux = moe_mlp(
                h, layer["router"], layer["w_gate"], layer["w_up"],
                layer["w_down"], k=self.cfg.num_experts_per_token,
                capacity_factor=self.cfg.moe_capacity_factor,
                valid=token_valid, group_size=self.cfg.moe_group_size)
            return out, aux
        if self.cfg.arch in ("gemma", "gemma2"):
            gate = jax.nn.gelu(proj("w_gate", h), approximate=True)
        else:
            gate = jax.nn.silu(proj("w_gate", h))
        up = proj("w_up", h)
        ff = _constrain(gate * up, P(("data", "fsdp"), "sequence", "model"))
        return proj("w_down", ff), None

    def _flash_eligible(self, t: int) -> bool:
        """Whether the Pallas flash kernel may serve a full-sequence
        forward of length t for THIS config: the kernel speaks neither
        softcapping, per-layer windows, nor a non-default softmax scale
        (gemma-2) — those take the XLA path. The scale gate compares the
        EFFECTIVE scale, not the knob: query_pre_attn_scalar == head_dim
        (gemma2-2b/9b) yields exactly the kernel's default head_dim**-0.5
        and must not disqualify. One predicate shared by apply() and
        prefill() so the two gates cannot diverge."""
        cfg = self.cfg
        return (cfg.attention == "flash" and _flash_tileable(t)
                and not cfg.attn_logit_softcap
                and cfg.sliding_window_pattern == 1
                and (cfg.query_pre_attn_scalar is None
                     or cfg.query_pre_attn_scalar == cfg.head_dim_))

    def _with_layer_windows(self, layers: Params,
                            storage: bool = False) -> Params:
        """Inject the per-layer SWA flag into the scan stream for
        alternating-window archs (gemma-2: layer l slides iff
        (l+1) % pattern != 0, HF Gemma2's is_sliding). Not a param —
        rides the scan xs like the LoRA dropout keys. ``storage``:
        shape the flag [V, S, c] to match interleaved-storage leaves
        (canonical index semantics survive the row-major reshape)."""
        cfg = self.cfg
        if not (cfg.sliding_window and cfg.sliding_window_pattern > 1):
            return layers
        win = ((jnp.arange(cfg.num_layers) + 1)
               % cfg.sliding_window_pattern != 0)
        if storage and self._interleaved_storage:
            win = win.reshape(self._storage_lead())
        return {**layers, "swa_on": win}

    def _weight(self, container: Params, name: str) -> jnp.ndarray:
        """The named weight matrix in activation dtype. int8 weight-only
        storage (``quantize_weights``) dequantizes on the fly via the
        ``<name>_wscale`` per-output-channel scales — XLA reads int8
        from HBM and fuses convert*scale into the consuming matmul, so
        the weight read traffic halves vs bf16 (the dominant bytes of
        the HBM-bound decode loop). Full-precision trees hit the plain
        astype path (dtype check is trace-time — zero runtime cost)."""
        w = container[name]
        if w.dtype == jnp.int8:
            # multiply in fp32, cast the PRODUCT: casting the scale to
            # bf16 first would add a correlated ~2^-9 relative error per
            # output channel on top of int8's inherent half-step error
            return (w.astype(jnp.float32)
                    * container[name + "_wscale"]).astype(self.adtype)
        return w.astype(self.adtype)

    def _dense(self, container: Params, name: str,
               inp: jnp.ndarray) -> jnp.ndarray:
        """``inp @ weight`` with int8 weight-only storage consumed through
        the fused Pallas kernel (ops.quant_matmul): the dequantization
        happens in VMEM, so HBM reads the int8 bytes and nothing else.
        The ``_weight`` convert*scale path relies on XLA fusing the
        dequant into the dot — measured on chip (r5 sweep_decode) it does
        NOT and materializes the bf16 matrix, making int8 rollout decode
        SLOWER than bf16 (b64 full stack 4.7x roofline). Under a >1-device
        auto mesh the kernel (no SPMD rule) would replicate the weight, so
        those contexts keep the XLA path — logged once per shape; the
        single-chip rollout/bench path is where the int8 bytes matter."""
        w = container[name]
        if w.dtype != jnp.int8:
            return inp @ w.astype(self.adtype)
        if _flash_mesh() is not None:
            key = ("int8_dense", name, inp.shape)
            if key not in _REPLICATED_FLASH_LOGGED and \
                    jax.process_index() == 0:
                _REPLICATED_FLASH_LOGGED.add(key)
                print(f"[dla_tpu][int8] {name} {inp.shape} consumed via "
                      "the XLA dequant path (multi-device auto mesh; the "
                      "fused kernel has no SPMD rule)",
                      file=sys.stderr, flush=True)
            return inp @ self._weight(container, name)
        from dla_tpu.ops.quant_matmul import int8_matmul
        return int8_matmul(inp, w, container[name + "_wscale"]
                           ).astype(self.adtype)

    _WEIGHT_ONLY_MATS = ("wq", "wk", "wv", "wo", "w_gate", "w_up",
                         "w_down", "fc1", "fc2")

    def quantize_weights(self, params: Params) -> Params:
        """Weight-only int8 copy of a param tree for ROLLOUT decode
        (RLHF's hot loop): each dense [L, in, out] matrix stores int8
        with symmetric per-(layer, out-channel) fp32 scales
        (absmax/127 over the in dim). Embeddings, norms, biases, the
        tied unembedding, and MoE expert stacks stay full precision.
        The update/scoring paths keep using the original tree — only
        the sampled tokens see quantization."""
        out_layers: Params = {}
        # dense [L, in, out] canonical or [V, S, c, in, out] storage
        mat_ndim = 5 if self._interleaved_storage else 3
        for key, val in params["layers"].items():
            if (key in self._WEIGHT_ONLY_MATS and val.ndim == mat_ndim
                    and val.dtype != jnp.int8):  # idempotent: re-apply
                # of an already-quantized tree must not re-scale
                q, scale = self._symmetric_int8(val, axis=val.ndim - 2)
                out_layers[key] = q            # scale [..., 1, out]
                out_layers[key + "_wscale"] = scale
            else:
                out_layers[key] = val
        new = {**params, "layers": out_layers}
        lm = params.get("lm_head")
        if lm is not None and lm.dtype != jnp.int8:      # [D, V]
            q, scale = self._symmetric_int8(lm, axis=0)  # [1, V]
            new["lm_head"] = q
            new["lm_head_wscale"] = scale
        return new

    def _layer_window(self, layer: Params):
        """Effective window for a layer: the static config window, or —
        when the per-layer ``swa_on`` flag rides the scan (gemma-2
        alternating SWA) — a TRACED scalar that is the window on sliding
        layers and an unreachable bound on full-attention layers (one
        code path, no lax.cond in the scan body)."""
        cfg = self.cfg
        swa_on = layer.get("swa_on") if isinstance(layer, dict) else None
        if swa_on is None:
            return cfg.sliding_window or None
        return jnp.where(swa_on, jnp.int32(cfg.sliding_window),
                         jnp.int32(2 ** 30))

    def _attention(self, q, k, v, kv_segment_mask, q_positions, kv_positions,
                   allow_flash: bool = False, cp: Optional[Tuple] = None,
                   flash_segs: Optional[jnp.ndarray] = None,
                   window=None, factored_mask: Optional[Tuple] = None):
        """Pick the attention backend. The pallas flash kernel handles the
        full-sequence causal path on contiguous right-padded batches whose
        length tiles its blocks — including packed batches, whose segment
        ids fold into the kernel's mask (``flash_segs``). Everything else
        (decode against a cache, gapped masks, odd lengths) takes the XLA
        path. When ``cp`` is set — a (mode, kv_valid, segment_ids,
        gapped) 4-tuple, ``gapped`` meaning positions carry no physical
        -contiguity guarantee (gapped mask or caller-supplied) — the
        sequence dim is sharded over the mesh and attention runs ring /
        ulysses context-parallel, with the windowed ring's scan
        truncation disabled for gapped positions."""
        t, s = q.shape[1], k.shape[1]
        if cp is not None:
            mode, kv_valid, seg, gapped = cp
            if mode == "ulysses":
                from dla_tpu.ops.ulysses import ulysses_causal_attention
                # window/softcap/query-scale fold into the per-head-slice
                # attention: the all-to-all hands each device the FULL
                # sequence (global positions via gather), so the same
                # window semantics ring implements by rotating metadata
                # apply directly (ops/ulysses.py _ulysses_local)
                return ulysses_causal_attention(
                    q, k, v, q_positions=q_positions,
                    kv_positions=kv_positions, kv_valid=kv_valid,
                    segment_ids=seg,
                    window=window,
                    contiguous=not gapped,
                    softmax_scale=self._softmax_scale,
                    logit_softcap=self.cfg.attn_logit_softcap,
                    use_flash=(self.cfg.attention == "flash"
                               and _flash_tileable(t)),
                    flash_block_q=self.cfg.flash_block_q,
                    flash_block_k=self.cfg.flash_block_k)
            from dla_tpu.ops.ring_attention import ring_causal_attention
            # `window` comes from _layer_window: a static int (uniform
            # SWA — enables ring truncation), a traced per-layer scalar
            # (gemma-2 alternating SWA — mask-only), or None
            return ring_causal_attention(
                q, k, v, q_positions=q_positions, kv_positions=kv_positions,
                kv_valid=kv_valid, segment_ids=seg,
                window=window,
                window_truncate=not gapped,
                softmax_scale=self._softmax_scale,
                logit_softcap=self.cfg.attn_logit_softcap)
        if (self.cfg.attention == "flash" and allow_flash and t == s
                and _flash_tileable(t)):
            return self._flash(q, k, v, flash_segs)
        kw = dict(
            kv_segment_mask=kv_segment_mask,
            q_positions=q_positions, kv_positions=kv_positions,
            window=window if window is not None
            else (self.cfg.sliding_window or None),
            softmax_scale=self._softmax_scale,
            logit_softcap=self.cfg.attn_logit_softcap)
        from dla_tpu.ops.attention import DEFAULT_Q_CHUNK
        if t == s and t > DEFAULT_Q_CHUNK:
            # flash-ineligible long sequences (gemma-2 softcap/per-layer
            # window, gapped masks): query-chunked to keep live scores
            # O(T * chunk), forward AND backward (checkpointed scan).
            # With factored_mask set, each chunk builds its own [B,C,S]
            # mask slab from the 1-D metadata — no [B,T,T] anywhere.
            if factored_mask is not None:
                valid, segs = factored_mask
                return chunked_causal_attention(
                    q, k, v, kv_valid=valid,
                    q_segments=segs, kv_segments=segs, **kw)
            return chunked_causal_attention(q, k, v, **kw)
        if factored_mask is not None and kw["kv_segment_mask"] is None:
            # safety net (callers only set factored_mask on the long
            # path above): chunked's t <= q_chunk branch builds the slab
            valid, segs = factored_mask
            return chunked_causal_attention(
                q, k, v, kv_valid=valid,
                q_segments=segs, kv_segments=segs, **kw)
        return causal_attention(q, k, v, **kw)

    def _flash(self, q, k, v, segs: Optional[Tuple]):
        """Invoke the pallas flash kernel, shard_map-wrapped when the
        ambient mesh spans >1 device: the kernel has no SPMD rule, so a
        bare pallas_call under GSPMD silently replicates its operands.
        Per-shard the kernel sees the local batch slice and local head
        group; GQA grouping survives because the model axis divides
        num_kv_heads in any valid TP layout. ``segs`` is the
        pre-broadcast (qseg, kseg) pair from broadcast_segment_ids."""
        from dla_tpu.ops.flash_attention import (
            DEFAULT_BLOCK_K,
            DEFAULT_BLOCK_Q,
            flash_causal_attention,
        )
        kw = dict(window=self.cfg.sliding_window or None,
                  block_q=self.cfg.flash_block_q or DEFAULT_BLOCK_Q,
                  block_k=self.cfg.flash_block_k or DEFAULT_BLOCK_K)
        mesh = _flash_mesh()
        if mesh is None:
            return flash_causal_attention(q, k, v, segs=segs, **kw)
        # wrap over the batch/head axes that are still GSPMD-auto; under
        # the pipeline's stage shard_map this nests partial-manual with
        # `stage` untouched (already manual in the enclosing scope)
        wrap_axes = {a for a in ("data", "fsdp", "model")
                     if a in auto_axes(mesh)}
        model_size = mesh.shape.get("model", 1) if "model" in wrap_axes \
            else 1
        batch_shards = 1
        for a in ("data", "fsdp"):
            if a in wrap_axes:
                batch_shards *= mesh.shape[a]
        if (q.shape[0] % batch_shards or self.cfg.num_heads % model_size
                or self.cfg.num_kv_heads % model_size):
            # shard_map needs even divisibility; odd shapes (a last partial
            # eval batch, B < dp shards in a rollout) take the bare
            # pallas_call, which GSPMD runs replicated — correct, just not
            # partitioned. Training batches are always divisible. Logged
            # once per shape at trace time so a misconfigured run (e.g. a
            # rollout batch smaller than the dp shard count every step) is
            # diagnosable from its logs (VERDICT r3 weak-item 4).
            key = (q.shape, batch_shards, model_size)
            if key not in _REPLICATED_FLASH_LOGGED and \
                    jax.process_index() == 0:
                _REPLICATED_FLASH_LOGGED.add(key)
                print(f"[dla_tpu][flash] batch {q.shape[0]} x heads "
                      f"{self.cfg.num_heads}/{self.cfg.num_kv_heads} does "
                      f"not divide mesh (batch shards {batch_shards}, "
                      f"model {model_size}); attention runs REPLICATED "
                      "across the mesh for this shape",
                      file=sys.stderr, flush=True)
            return flash_causal_attention(q, k, v, segs=segs, **kw)
        batch_axes = tuple(a for a in ("data", "fsdp") if a in wrap_axes)
        head_axis = "model" if "model" in wrap_axes else None
        bspec = P(batch_axes or None, None, head_axis, None)
        if segs is None:
            fn = jax.shard_map(
                lambda a, b, c: flash_causal_attention(a, b, c, **kw),
                mesh=mesh, in_specs=(bspec, bspec, bspec),
                out_specs=bspec, axis_names=wrap_axes, check_vma=False)
            return fn(q, k, v)
        sspec = P(batch_axes or None, None, None)
        fn = jax.shard_map(
            lambda a, b, c, s: flash_causal_attention(a, b, c, segs=s, **kw),
            mesh=mesh,
            in_specs=(bspec, bspec, bspec, (sspec, sspec)),
            out_specs=bspec, axis_names=wrap_axes, check_vma=False)
        return fn(q, k, v, segs)

    def _maybe_remat(self, fn):
        if self.cfg.remat == "none":
            return fn
        if self.cfg.remat == "dots":
            # matmul outputs + the flash kernel's (out, lse) residuals:
            # saving the named flash outputs keeps the backward from
            # replaying the pallas forward (measured ~25% of the step at
            # T=2048); elementwise glue (norms, rotary, silu) is still
            # recomputed, which is the cheap part
            policy = jax.checkpoint_policies.save_from_both_policies(
                jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
                jax.checkpoint_policies.save_only_these_names(
                    "flash_out", "flash_lse"))
            return jax.checkpoint(fn, policy=policy)
        return jax.checkpoint(fn)  # "full"

    # -------------------------------------------------------------- forward

    def hidden_states(
        self,
        params: Params,
        input_ids: jnp.ndarray,                 # [B, T]
        attention_mask: Optional[jnp.ndarray] = None,   # [B, T] 1 = real
        segment_ids: Optional[jnp.ndarray] = None,      # [B, T] for packing
        positions: Optional[jnp.ndarray] = None,        # [B, T]
        gapped_mask: bool = False,
        lora: Optional[Params] = None,                  # adapter pytree
        dropout_rng: Optional[jax.Array] = None,        # enables lora dropout
    ) -> jnp.ndarray:
        """Full-sequence forward up to the final norm. [B, T, D].
        (Aux-discarding wrapper — MoE models training through a CE loss
        should use hidden_states_with_aux to keep the router's
        load-balance loss.)"""
        return self.hidden_states_with_aux(
            params, input_ids, attention_mask, segment_ids, positions,
            gapped_mask=gapped_mask, lora=lora, dropout_rng=dropout_rng)[0]

    def hidden_states_with_aux(
        self,
        params: Params,
        input_ids: jnp.ndarray,                 # [B, T]
        attention_mask: Optional[jnp.ndarray] = None,   # [B, T] 1 = real
        segment_ids: Optional[jnp.ndarray] = None,      # [B, T] for packing
        positions: Optional[jnp.ndarray] = None,        # [B, T]
        gapped_mask: bool = False,
        lora: Optional[Params] = None,                  # adapter pytree
        dropout_rng: Optional[jax.Array] = None,        # enables lora dropout
    ) -> Tuple[jnp.ndarray, Optional[Any]]:
        """Full-sequence forward up to the final norm. Returns
        ([B, T, D], moe_aux) where moe_aux is an ops.moe.MoEAux of
        layer-mean scalars when cfg.num_experts > 0, else None.

        ``gapped_mask``: declare that attention_mask may have internal
        zero gaps (not plain right-padding). Gapped masks are handled
        correctly by the XLA attention path (cumsum positions + explicit
        kv mask) but NOT by the flash kernel, so setting this disables
        flash. All internal callers produce right-padded or compacted
        (left_align-ed) batches and keep the default.
        """
        cfg = self.cfg
        b, t = input_ids.shape
        # caller-supplied positions carry no contiguity guarantee — the
        # windowed ring must treat them like gapped-mask positions and
        # skip its scan truncation
        custom_positions = positions is not None
        if positions is None:
            if segment_ids is None and attention_mask is not None:
                # position = index among *real* tokens, so sequences with
                # masked gaps (e.g. prompt pad + generated tail) see the
                # same rotary phases as their contiguous equivalents
                positions = jnp.maximum(
                    jnp.cumsum(attention_mask.astype(jnp.int32), axis=1) - 1, 0)
            elif segment_ids is not None:
                # restart positions at each packed segment boundary
                seg_start = jnp.concatenate(
                    [jnp.ones((b, 1), bool),
                     segment_ids[:, 1:] != segment_ids[:, :-1]], axis=1)
                seg_idx = jnp.cumsum(seg_start.astype(jnp.int32), axis=1) - 1
                first_pos = jnp.where(
                    seg_start, jnp.arange(t)[None, :], 0)
                starts = jax.lax.cummax(first_pos, axis=1)
                positions = jnp.arange(t)[None, :] - starts
                del seg_idx
            else:
                positions = jnp.broadcast_to(jnp.arange(t)[None, :], (b, t))

        # Context parallelism: when the ambient mesh shards `sequence`,
        # attention runs ring/ulysses from 1-D metadata. Ring stays
        # blockwise (no [B, T, T] mask); ulysses routes its per-shard
        # full-sequence attention through the flash kernel when the
        # backend is on (O(T) memory) and only its XLA fallback
        # materializes full-length scores (dla_tpu/ops/ulysses.py).
        cp = None
        if cfg.context_parallel != "none" and _sequence_axis_size() > 1:
            kv_valid = (attention_mask if attention_mask is not None
                        else jnp.ones((b, t), jnp.int32))
            seg = (segment_ids if segment_ids is not None
                   else jnp.zeros((b, t), jnp.int32))
            # gapped masks derive positions from cumsum(mask) and custom
            # positions are arbitrary, so physical chunk distance no
            # longer bounds position distance — the windowed ring must
            # not truncate its scan then
            cp = (cfg.context_parallel, kv_valid, seg,
                  gapped_mask or custom_positions)

        # Flash eligibility decided up front so the packed path skips the
        # [B, T, T] mask materialization entirely (round-2 verdict item 1:
        # packing + flash now compose — segment ids go to the kernel).
        # Right-padding alone needs no mask at all under flash: pad keys
        # sit above every real query's causal diagonal. Under pipeline
        # parallelism the kernel nests inside the stage shard_map as a
        # partial-manual shard_map over the still-auto batch/head axes
        # (round-3 verdict item 5 — PP no longer forces XLA attention).
        n_stages = _stage_axis_size()
        allow_flash = (not gapped_mask and cp is None
                       and self._flash_eligible(t))
        flash_segs = None
        if allow_flash and segment_ids is not None:
            # broadcast to the kernel's tileable layouts ONCE, outside the
            # scan-over-layers: inside the body the [B,T,block_k] expansion
            # would be rebuilt per layer (and re-rebuilt per layer in the
            # remat'd backward)
            from dla_tpu.ops.flash_attention import (
                DEFAULT_BLOCK_K,
                broadcast_segment_ids,
            )
            flash_segs = broadcast_segment_ids(
                segment_ids,
                block_k=self.cfg.flash_block_k or DEFAULT_BLOCK_K)

        kv_mask = None
        factored = None
        if cp is None and not allow_flash:
            from dla_tpu.ops.attention import DEFAULT_Q_CHUNK
            if (t > DEFAULT_Q_CHUNK and n_stages == 1
                    and (attention_mask is not None
                         or segment_ids is not None)):
                # long flash-ineligible sequences route through the
                # query-chunked attention, which builds each chunk's
                # mask slab from this 1-D metadata — never materialize
                # the [B, T, T] mask here (at 32k that mask alone is
                # O(GB) before any score exists)
                factored = (attention_mask, segment_ids)
            else:
                if attention_mask is not None:
                    kv_mask = jnp.broadcast_to(
                        attention_mask[:, None, :].astype(bool), (b, t, t))
                if segment_ids is not None:
                    same_seg = (segment_ids[:, :, None]
                                == segment_ids[:, None, :])
                    kv_mask = (same_seg if kv_mask is None
                               else (kv_mask & same_seg))

        x = _constrain(self._embed(params, input_ids), ACT_SPEC)
        cos, sin = rotary_angles(positions, cfg.rotary_dim_, cfg.rope_theta,
                                 scaling=cfg.rope_scaling)

        layers = params["layers"]
        keys = None
        if lora is not None:
            layers = {**layers, **lora["layers"]}
            if dropout_rng is not None and cfg.lora_dropout > 0:
                keys = jax.random.split(dropout_rng, cfg.num_layers)
        # MoE routing must know which tokens are real: pads must not
        # claim expert capacity or skew the balance statistics (shared
        # by the pipeline and plain-scan paths)
        token_valid = None
        if cfg.num_experts > 0:
            if attention_mask is not None:
                token_valid = attention_mask
            elif segment_ids is not None:
                token_valid = (segment_ids > 0).astype(jnp.int32)

        # window flags join in the layout each path consumes: storage
        # shape under the pipeline (the [V,S,c] leaves go straight to the
        # stage schedule), flat [L] for the plain scan
        if n_stages > 1:
            layers = self._with_layer_windows(layers, storage=True)
        else:
            layers = self._with_layer_windows(self._flat_layers(layers))

        if n_stages > 1:
            # pipeline parallelism: layer stack sharded over `stage`,
            # GPipe microbatch schedule (ops.pipeline). LoRA leaves ride
            # in `layers` and reshape with everything else. Context
            # parallelism composes: the ring/ulysses shard_map nests
            # partial-manual over the still-auto `sequence` axis inside
            # the stage schedule (like _flash), with the CP metadata
            # (validity, segments) riding the aux shift register.
            if keys is not None:
                raise NotImplementedError(
                    "lora_dropout under pipeline parallelism is not "
                    "supported; set lora.dropout to 0")
            x, moe_aux = self._pipeline_forward(
                layers, x, cos, sin, kv_mask, positions, n_stages,
                allow_flash=allow_flash, flash_segs=flash_segs, cp=cp,
                token_valid=token_valid)
            return self._final_norm(params, x), moe_aux

        if keys is None:
            def body(carry, layer):
                h, _, aux = self._block(layer, carry, cos, sin, kv_mask,
                                        positions, positions,
                                        allow_flash=allow_flash,
                                        flash_segs=flash_segs, cp=cp,
                                        token_valid=token_valid,
                                        factored_mask=factored)
                return h, aux
        else:
            def body(carry, xs):
                layer, key = xs
                h, _, aux = self._block(layer, carry, cos, sin, kv_mask,
                                        positions, positions,
                                        allow_flash=allow_flash,
                                        flash_segs=flash_segs, cp=cp,
                                        dropout_key=key,
                                        token_valid=token_valid,
                                        factored_mask=factored)
                return h, aux
            layers = (layers, keys)

        x, auxs = jax.lax.scan(self._maybe_remat(body), x, layers)
        moe_aux = None
        if auxs is not None:
            moe_aux = type(auxs)(*(jnp.mean(a) for a in auxs))  # layer mean
        return self._final_norm(params, x), moe_aux

    def _pipeline_forward(self, layers: Params, x: jnp.ndarray,
                          cos: jnp.ndarray, sin: jnp.ndarray,
                          kv_mask: Optional[jnp.ndarray],
                          positions: jnp.ndarray,
                          n_stages: int, *,
                          allow_flash: bool = False,
                          flash_segs: Optional[Tuple] = None,
                          cp: Optional[Tuple] = None,
                          token_valid: Optional[jnp.ndarray] = None
                          ) -> Tuple[jnp.ndarray, Optional[Any]]:
        """GPipe over the `stage` mesh axis: reshape the [L, ...] layer
        stack to [S, L/S, ...] (shard-local — the stage axis owns
        contiguous layer blocks), microbatch the batch dim, and run the
        shift-register schedule from ops.pipeline. Flash attention stays
        engaged inside the stage shard_map: _flash nests partial-manual
        over the still-auto batch/head axes (`stage` stays manual in the
        enclosing scope), so the 70B PP path keeps the kernel that set
        the single-chip headline (round-3 verdict item 5)."""
        from dla_tpu.ops.pipeline import gpipe, microbatch, \
            resolve_microbatches
        cfg = self.cfg
        n_layers = cfg.num_layers
        v = max(1, cfg.pipeline_interleave)
        if n_layers % (n_stages * v):
            raise ValueError(
                f"pipeline needs num_layers ({n_layers}) divisible by "
                f"stage axis x interleave ({n_stages} x {v})")
        mesh = _ambient_mesh()
        dp_shards = 1
        if mesh is not None:
            for a in ("data", "fsdp"):
                if a in auto_axes(mesh):
                    dp_shards *= mesh.shape[a]
        if v > 1:
            # circular schedule: M pinned to the stage count; falls back
            # to plain GPipe when the batch can't split S ways. The
            # degradation announcements live in ops.pipeline, next to the
            # plain-path policy, so the two cannot drift.
            from dla_tpu.ops.pipeline import \
                resolve_interleaved_microbatches
            m, v = resolve_interleaved_microbatches(
                x.shape[0], n_stages, v, dp_shards,
                cfg.pipeline_microbatches)
        else:
            m = resolve_microbatches(x.shape[0], cfg.pipeline_microbatches,
                                     n_stages, dp_shards=dp_shards)
        # block b = p*S + s lives at stacked[s, p]: the schedule wants
        # [S, V, c] leaves with `stage` sharding dim 0.
        if self._interleaved_storage:
            if n_stages != cfg.pipeline_stages:
                raise ValueError(
                    f"model storage is laid out for pipeline_stages="
                    f"{cfg.pipeline_stages} but the mesh has a stage axis "
                    f"of {n_stages}; rebuild params via "
                    "to_canonical_layout/to_storage_layout")
            if v > 1:
                # storage leaves are already block-major [V, S, c, ...]
                # with `stage` sharding dim 1: the swap to [S, V, c] is a
                # shard-local transpose — NO cross-stage weight
                # collective per step (the (V-1)/V all-to-all reshard the
                # flat layout paid; docs/pp_bubble.md, r5)
                stage_layers = jax.tree.map(
                    lambda l: l.swapaxes(0, 1), layers)
            else:
                # degraded to plain GPipe (batch cannot split S ways —
                # already announced): contiguous stages need canonical
                # order, so this corner pays the reshard the main path
                # no longer does
                c = n_layers // n_stages
                stage_layers = jax.tree.map(
                    lambda l: l.reshape((n_layers,) + l.shape[3:]
                                        ).reshape((n_stages, 1, c)
                                                  + l.shape[3:]), layers)
        else:
            # flat [L] storage: [L] -> [V, S, c] (block-major) ->
            # transpose -> [S, V, c]. LAYOUT COST (v > 1 only): params
            # are stored contiguously over `stage` but the round-robin
            # schedule needs the strided blocks {p*S+s} — GSPMD inserts
            # a cross-stage reshard of ~(V-1)/V of the layer weights per
            # step. Set cfg.pipeline_stages (the config loader does it
            # from hardware.mesh.stage) to store block-major and make
            # the schedule shard-local.
            c = n_layers // (n_stages * v)
            stage_layers = jax.tree.map(
                lambda l: l.reshape((v, n_stages, c) + l.shape[1:]
                                    ).swapaxes(0, 1), layers)
        aux = {"cos": microbatch(cos, m), "sin": microbatch(sin, m),
               "positions": microbatch(positions, m)}
        if kv_mask is not None:
            aux["kv_mask"] = microbatch(kv_mask, m)
        if flash_segs is not None:
            aux["flash_segs"] = jax.tree.map(
                lambda a: microbatch(a, m), flash_segs)
        cp_mode = cp_gapped = None
        if cp is not None:
            # CP metadata microbatches with the activations; the static
            # parts (mode, gapped-positions flag) close over stage_fn
            cp_mode, cp_valid, cp_seg, cp_gapped = cp
            aux["cp_valid"] = microbatch(cp_valid, m)
            aux["cp_seg"] = microbatch(cp_seg, m)
        collect_aux = cfg.num_experts > 0
        if token_valid is not None:
            aux["token_valid"] = microbatch(token_valid, m)

        def stage_fn(stage_params, h, aux_t):
            cp_t = None
            if cp_mode is not None:
                cp_t = (cp_mode, aux_t["cp_valid"], aux_t["cp_seg"],
                        cp_gapped)

            def body(carry, layer):
                out, _, aux_l = self._block(
                    layer, carry, aux_t["cos"],
                    aux_t["sin"], aux_t.get("kv_mask"),
                    aux_t["positions"], aux_t["positions"],
                    allow_flash=allow_flash,
                    flash_segs=aux_t.get("flash_segs"), cp=cp_t,
                    token_valid=aux_t.get("token_valid"))
                return out, aux_l
            h, auxs = jax.lax.scan(self._maybe_remat(body), h,
                                   stage_params)
            if collect_aux:
                # sum this block's per-layer scalars; gpipe masks
                # garbage ticks, sums across ticks and psums across
                # stages — (1/(L*M))x that sum is the layer-and-
                # microbatch mean the plain scan path reports
                return h, jax.tree.map(
                    lambda a: jnp.sum(a.astype(jnp.float32), axis=0),
                    auxs)
            return h

        out = gpipe(stage_fn, stage_layers, microbatch(x, m), aux,
                    n_stages, passes=v, collect_aux=collect_aux)
        moe_aux = None
        if collect_aux:
            out, aux_sums = out
            moe_aux = type(aux_sums)(
                *(a / (n_layers * m) for a in aux_sums))
        return out.reshape(x.shape), moe_aux

    def _final_norm(self, params: Params, x: jnp.ndarray) -> jnp.ndarray:
        if self.cfg.arch == "phi":
            return layer_norm(x, params["final_norm"],
                              params["final_norm_bias"],
                              self.cfg.rms_norm_eps)
        return rms_norm(x, params["final_norm"], self.cfg.rms_norm_eps)

    def _embed(self, params: Params, ids: jnp.ndarray) -> jnp.ndarray:
        """Token embedding read in the activation dtype. Gemma scales the
        input embedding by sqrt(hidden) (normalizer cast to the activation
        dtype, matching HF GemmaModel's bf16-rounded multiplier); the tied
        unembedding stays unscaled."""
        x = jnp.take(params["embed"]["embedding"], ids, axis=0
                     ).astype(self.adtype)
        if self.cfg.arch in ("gemma", "gemma2"):
            x = x * jnp.asarray(self.cfg.hidden_size ** 0.5, self.adtype)
        return x

    def unembed_params(self, params: Params
                       ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
        """(w [D, V] in activation dtype, bias [V] or None) — the
        unembedding operands, for fused losses (ops.fused_ce) that
        contract hidden states against w chunk-by-chunk instead of
        materializing [B, T, V] logits."""
        if self.cfg.tie_embeddings:
            w = params["embed"]["embedding"].astype(self.adtype).T
        else:
            w = self._weight(params, "lm_head")
        bias = params.get("lm_head_bias")
        return w, None if bias is None else bias.astype(self.adtype)

    def unembed(self, params: Params, hidden: jnp.ndarray) -> jnp.ndarray:
        """[..., D] -> [..., V] logits (activation dtype; cast at the loss).
        gemma-2 softcaps final logits: cap * tanh(logits / cap) — applied
        here AND in the chunked fused-CE path (ops.fused_ce reads
        cfg.final_logit_softcap through model.cfg)."""
        lm = params.get("lm_head")
        if lm is not None and lm.dtype == jnp.int8:
            # quantized rollout tree: fused kernel path (the [D, V]
            # dequant would otherwise materialize 2x the int8 bytes
            # EVERY decode step)
            logits = self._dense(params, "lm_head", hidden)
            bias = params.get("lm_head_bias")
            bias = None if bias is None else bias.astype(logits.dtype)
        else:
            w, bias = self.unembed_params(params)
            logits = hidden @ w
        if bias is not None:
            logits = logits + bias
        cap = self.cfg.final_logit_softcap
        if cap:
            logits = (jnp.tanh(logits / jnp.asarray(cap, logits.dtype))
                      * jnp.asarray(cap, logits.dtype))
        return logits

    def apply(self, params: Params, input_ids: jnp.ndarray,
              attention_mask: Optional[jnp.ndarray] = None,
              segment_ids: Optional[jnp.ndarray] = None,
              positions: Optional[jnp.ndarray] = None,
              gapped_mask: bool = False,
              lora: Optional[Params] = None,
              dropout_rng: Optional[jax.Array] = None) -> jnp.ndarray:
        """Logits forward: [B, T] -> [B, T, V]."""
        h = self.hidden_states(params, input_ids, attention_mask,
                               segment_ids, positions,
                               gapped_mask=gapped_mask, lora=lora,
                               dropout_rng=dropout_rng)
        return self.unembed(params, h)

    __call__ = apply

    # ------------------------------------------------------------- KV cache

    @property
    def _kv_int8(self) -> bool:
        return self.cfg.kv_cache_dtype == "int8"

    @staticmethod
    def _symmetric_int8(x: jnp.ndarray, axis: int
                        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Symmetric int8 quantization along ``axis``: (int8 values,
        fp32 scale with keepdims). The one recipe shared by the KV cache
        and weight-only paths (absmax/127, round, clip)."""
        absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis,
                         keepdims=True)
        scale = absmax / 127.0 + 1e-12
        q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale),
                     -127, 127).astype(jnp.int8)
        return q, scale

    def _quantize_kv(self, x: jnp.ndarray
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """[..., D] -> (int8 values, fp32 scale [...]): symmetric
        per-position per-head quantization (scale = absmax/127 along the
        head dim). Dequantization (q * scale) fuses into the attention
        einsum, so the cache's HBM read traffic halves on the
        bandwidth-bound decode loop."""
        q, scale = self._symmetric_int8(x, axis=-1)
        return q, scale[..., 0]

    def _dequantize_kv(self, q: jnp.ndarray, scale: jnp.ndarray
                       ) -> jnp.ndarray:
        # fp32 multiply, cast the product (see _weight: a bf16-cast
        # scale would shift whole per-position head vectors coherently)
        return (q.astype(jnp.float32) * scale[..., None]
                ).astype(self.adtype)

    def init_cache(self, batch: int, max_len: int) -> Params:
        cfg = self.cfg
        shape = (cfg.num_layers, batch, max_len, cfg.num_kv_heads, cfg.head_dim_)
        kv_dtype = jnp.int8 if self._kv_int8 else self.adtype
        cache = {
            "k": jnp.zeros(shape, kv_dtype),
            "v": jnp.zeros(shape, kv_dtype),
            "valid": jnp.zeros((batch, max_len), bool),
            "lengths": jnp.zeros((batch,), jnp.int32),  # next position per seq
            "step": jnp.zeros((), jnp.int32),           # decode steps taken
        }
        if self._kv_int8:
            # scales are stored K-MAJOR [L, B, K, S] — the layout the
            # Pallas decode kernel consumes — so no [B, S, K] transpose
            # rides the per-layer decode hot loop (r5 review finding)
            sshape = (shape[0], batch, cfg.num_kv_heads, max_len)
            cache["k_scale"] = jnp.zeros(sshape, jnp.float32)
            cache["v_scale"] = jnp.zeros(sshape, jnp.float32)
        return cache

    def cache_partition_specs(self) -> Params:
        specs = {
            "k": P(None, ("data", "fsdp"), None, "model", None),
            "v": P(None, ("data", "fsdp"), None, "model", None),
            "valid": P(("data", "fsdp"), None),
            "lengths": P(("data", "fsdp")),
            "step": P(),
        }
        if self._kv_int8:
            specs["k_scale"] = P(None, ("data", "fsdp"), "model", None)
            specs["v_scale"] = P(None, ("data", "fsdp"), "model", None)
        return specs

    def prefill_external(self, params: Params, input_ids: jnp.ndarray,
                         attention_mask: jnp.ndarray,
                         ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """The cache-layout-agnostic half of prefill: run the prompt
        forward and hand back the raw KV columns instead of writing any
        particular cache. Returns (last-real-token logits [B, V],
        ks [L, B, T, KH, D], vs [L, B, T, KH, D]) in activation dtype.

        ``prefill`` packs these into the contiguous cache; the serving
        engine (dla_tpu/serving) scatters them into its block-paged
        pool — one forward, two cache layouts."""
        cfg = self.cfg
        b, t = input_ids.shape
        positions = jnp.broadcast_to(jnp.arange(t)[None, :], (b, t))
        flash_ok = self._flash_eligible(t)
        from dla_tpu.ops.attention import DEFAULT_Q_CHUNK
        kv_mask = None
        pre_factored = None
        if not flash_ok:
            if t > DEFAULT_Q_CHUNK:
                # long flash-ineligible prefill (gemma-2 32k rollouts):
                # factored validity through the chunked path, no [B,T,T]
                pre_factored = (attention_mask, None)
            else:
                kv_mask = jnp.broadcast_to(
                    attention_mask[:, None, :].astype(bool), (b, t, t))
        x = self._embed(params, input_ids)
        cos, sin = rotary_angles(positions, cfg.rotary_dim_, cfg.rope_theta,
                                 scaling=cfg.rope_scaling)

        def body(carry, layer):
            h, kv, _ = self._block(layer, carry, cos, sin, kv_mask,
                                   positions, positions,
                                   allow_flash=flash_ok,
                                   token_valid=attention_mask,
                                   factored_mask=pre_factored)
            return h, kv

        x, (ks, vs) = jax.lax.scan(
            body, x,
            self._with_layer_windows(self._flat_layers(params["layers"])))
        h = self._final_norm(params, x)

        lengths = attention_mask.astype(jnp.int32).sum(axis=1)
        last_idx = jnp.maximum(lengths - 1, 0)
        last_h = jnp.take_along_axis(h, last_idx[:, None, None], axis=1)[:, 0]
        logits = self.unembed(params, last_h)
        return logits, ks, vs

    def prefill(self, params: Params, cache: Params,
                input_ids: jnp.ndarray, attention_mask: jnp.ndarray,
                ) -> Tuple[jnp.ndarray, Params]:
        """Run the prompt through the model, writing the cache at [0, T).

        Prompts are right-padded to T; pad positions are marked invalid in
        the cache and the returned logits come from the last *real* token.
        Returns (last-real-token logits [B, V], cache).

        When the flash backend is on and T tiles its blocks, prefill runs
        the blockwise kernel with NO [B, T, T] mask materialization —
        right padding makes the causal structure sufficient: every pad key
        sits above the causal diagonal of every real query, and pad-query
        rows are garbage nothing consumes (VERDICT round-1 item 6; the 32k
        long-context rollout path stays O(T) HBM like training).
        """
        b, t = input_ids.shape
        logits, ks, vs = self.prefill_external(
            params, input_ids, attention_mask)
        lengths = attention_mask.astype(jnp.int32).sum(axis=1)
        max_len = cache["k"].shape[2]
        pad = max_len - t
        pad5 = ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0))
        new_cache = {
            "valid": jnp.pad(attention_mask.astype(bool), ((0, 0), (0, pad))),
            "lengths": lengths,
            "step": jnp.zeros((), jnp.int32),
        }
        if self._kv_int8:
            kq, k_s = self._quantize_kv(ks)
            vq, v_s = self._quantize_kv(vs)
            new_cache["k"] = jnp.pad(kq, pad5)
            new_cache["v"] = jnp.pad(vq, pad5)
            # [L, B, T, K] -> K-major [L, B, K, S] (one transpose at
            # prefill; decode reads it transpose-free every step)
            pads = ((0, 0), (0, 0), (0, 0), (0, pad))
            new_cache["k_scale"] = jnp.pad(k_s.transpose(0, 1, 3, 2), pads)
            new_cache["v_scale"] = jnp.pad(v_s.transpose(0, 1, 3, 2), pads)
        else:
            new_cache["k"] = jnp.pad(ks, pad5)
            new_cache["v"] = jnp.pad(vs, pad5)
        return logits, new_cache

    def _unpack_decode_xs(self, xs, dequantize: bool):
        """Unstack one decode-scan slice: (layer, k_cache, v_cache,
        k_scale, v_scale); int8 caches optionally dequantized here (the
        XLA path — the Pallas kernel takes raw int8 + scales)."""
        k_s = v_s = None
        if self._kv_int8:
            layer, k_cache, v_cache, k_s, v_s = xs
            if dequantize:
                # K-major [B, K, S] storage -> positional [B, S, K]
                k_cache = self._dequantize_kv(
                    k_cache, k_s.transpose(0, 2, 1))
                v_cache = self._dequantize_kv(
                    v_cache, v_s.transpose(0, 2, 1))
        else:
            layer, k_cache, v_cache = xs
        return layer, k_cache, v_cache, k_s, v_s

    def _decode_layer(self, layer: Params, h_in: jnp.ndarray,
                      cos, sin, attend):
        """The per-layer decode computation SHARED by decode_step (one
        token) and decode_block (G tokens): norms, projections, MLP,
        and every arch branch — only the attention backend differs, and
        ``attend(q, k, v) -> [B, T, H, D]`` supplies it. Keeping this
        single ensures a new arch branch lands in both paths (the
        'G == 1 is semantically decode_step' contract)."""
        cfg = self.cfg
        b, t, _ = h_in.shape
        dh = cfg.head_dim_

        def cast(w):
            return w.astype(self.adtype)

        def proj(name, inp):
            out = self._dense(layer, name, inp)
            sa = layer.get(f"{name}_slot_lora_a")
            if sa is not None:
                # per-slot low-rank delta around the (possibly int8)
                # base matmul: inp [B,T,din] x A [B,din,r] x B [B,r,out]
                # — B pre-scaled by alpha/r at publish, rank-padded with
                # zeros so every slot shares one static shape
                sb = layer[f"{name}_slot_lora_b"]
                z = jnp.einsum("btd,bdr->btr", inp, sa.astype(self.adtype))
                out = out + jnp.einsum("btr,bro->bto", z,
                                       sb.astype(self.adtype))
            bias = layer.get(f"{name}_bias")
            return out if bias is None else out + cast(bias)

        if cfg.arch == "phi":
            hn = layer_norm(h_in, layer["ln"], layer["ln_bias"],
                            cfg.rms_norm_eps)
        else:
            hn = rms_norm(h_in, layer["attn_norm"], cfg.rms_norm_eps)
        q = proj("wq", hn).reshape(b, t, cfg.num_heads, dh)
        k = proj("wk", hn).reshape(b, t, cfg.num_kv_heads, dh)
        v = proj("wv", hn).reshape(b, t, cfg.num_kv_heads, dh)
        q = apply_rotary(q, cos, sin, rotary_dim=cfg.rotary_dim_)
        k = apply_rotary(k, cos, sin, rotary_dim=cfg.rotary_dim_)
        attn = attend(q, k, v).reshape(b, t, cfg.num_heads * dh)
        if cfg.arch == "phi":
            ff = jax.nn.gelu(proj("fc1", hn), approximate=True)
            return h_in + proj("wo", attn) + proj("fc2", ff), (k, v)
        attn_out = proj("wo", attn)
        if cfg.arch == "gemma2":
            attn_out = rms_norm(attn_out, layer["attn_post_norm"],
                                cfg.rms_norm_eps)
        x1 = h_in + attn_out
        hn2 = rms_norm(x1, layer["mlp_norm"], cfg.rms_norm_eps)
        mlp_out = self._mlp(layer, hn2, proj)[0]  # aux unused at decode
        if cfg.arch == "gemma2":
            mlp_out = rms_norm(mlp_out, layer["mlp_post_norm"],
                               cfg.rms_norm_eps)
        return x1 + mlp_out, (k, v)

    def decode_step(self, params: Params, cache: Params,
                    tokens: jnp.ndarray,  # [B] the tokens just sampled
                    ) -> Tuple[jnp.ndarray, Params]:
        """One decode step: write `tokens` at slot prompt_T + step, return
        logits for the next token. Static shapes; position per example is
        its true length (pads skipped via the cache valid mask)."""
        cfg = self.cfg
        b = tokens.shape[0]
        max_len = cache["k"].shape[2]
        if "prompt_width" not in cache:
            raise ValueError(
                "decode_step requires a cache produced by start_decode()")
        write_idx = cache["lengths"]                       # [B] logical position

        positions = write_idx[:, None]                     # [B, 1]
        x = self._embed(params, tokens[:, None])
        cos, sin = rotary_angles(positions, cfg.rotary_dim_, cfg.rope_theta,
                                 scaling=cfg.rope_scaling)

        # Physical write slot: prompts are right-padded to a uniform width T,
        # so every row writes decode step s at the same column T + s. Rotary
        # is applied with the *logical* position at write time, and
        # cache["pos"] records each column's logical position so the causal
        # mask stays correct even though pad columns sit mid-cache.
        col = cache["prompt_width"] + cache["step"]
        kv_pos = cache["pos"]

        # Attend over the UN-updated cache plus this token's fresh k/v via
        # decode_attention (score concatenation — no [B,S,K,D] copy inside
        # the layer loop); the scan emits only the new [B,1,K,D] columns,
        # written into the cache ONCE below. The round-3 path re-emitted
        # the full [L,B,S,K,D] cache through the scan each step, ~4x the
        # necessary HBM traffic on the decode hot loop (the PPO bottleneck,
        # reference src/training/train_rlhf.py:123-124).
        # int8 caches route through the Pallas decode kernel (dequant in
        # VMEM): the XLA `_dequantize_kv` path materializes a bf16 copy
        # of the cache per layer per step — measured on chip (r5
        # sweep_decode) that made int8 KV a REGRESSION vs bf16 (b64:
        # 3.77 vs 2.71 ms/token). Kernel gates: lane-aligned head_dim,
        # GQA group <= 8, and no >1-device auto mesh (pallas has no SPMD
        # rule; replicating the cache would be worse than the dequant
        # copy). Softcap is a static kernel param; gemma-2's alternating
        # per-layer windows become a two-bias select below.
        from dla_tpu.ops.decode_kernel import GP as _KGP
        kernel_eligible = (
            cfg.decode_kernel != "off"
            and cfg.head_dim_ % 128 == 0
            and cfg.num_heads // cfg.num_kv_heads <= _KGP
            and _flash_mesh() is None)
        # "auto": int8 caches only (in-VMEM dequant is the measured
        # win); "on": bf16 caches too (fill-bounded reads vs the XLA
        # einsum's full-S reads)
        use_decode_kernel = kernel_eligible and (
            self._kv_int8 or cfg.decode_kernel == "on")
        if cfg.decode_kernel == "on" and not kernel_eligible:
            # an EXPLICIT kernel request degrading to the XLA path must
            # not be silent: a sweep recording "kernel" numbers would
            # actually measure the einsum (same one-time-per-shape
            # discipline as the int8-dense fallback log above)
            key = ("decode_kernel_on", cfg.head_dim_, cfg.num_heads,
                   tokens.shape)
            if key not in _REPLICATED_FLASH_LOGGED and \
                    jax.process_index() == 0:
                _REPLICATED_FLASH_LOGGED.add(key)
                print("[dla_tpu][decode] decode_kernel: 'on' requested "
                      "but ineligible (head_dim % 128 != 0, GQA group "
                      f"> {_KGP}, or multi-device auto mesh) — decoding "
                      "via the XLA path", file=sys.stderr, flush=True)
        if (cfg.decode_kernel == "auto" and self._kv_int8
                and not kernel_eligible):
            # 'auto' + int8 KV exists to dequantize in VMEM; an
            # ineligible model silently pays the per-layer-per-step
            # bf16 materialization the kernel was chosen to avoid —
            # the exact regression the r5 sweep measured
            key = ("decode_kernel_auto_int8", cfg.head_dim_,
                   cfg.num_heads, tokens.shape)
            if key not in _REPLICATED_FLASH_LOGGED and \
                    jax.process_index() == 0:
                _REPLICATED_FLASH_LOGGED.add(key)
                print("[dla_tpu][decode] decode_kernel: 'auto' with an "
                      "int8 KV cache but the fused kernel is ineligible "
                      "(head_dim % 128 != 0, GQA group "
                      f"> {_KGP}, or multi-device auto mesh) — each "
                      "decode step dequantizes the full cache via XLA; "
                      "expect int8 KV to run SLOWER than bf16 here",
                      file=sys.stderr, flush=True)

        attn_bias = attn_bias_win = None
        if use_decode_kernel:
            # validity+causality(+window) as additive biases built ONCE
            # per step. Uniform-window models (mistral: pattern == 1)
            # fold the window into the single shared bias; alternating-
            # window models (gemma-2: pattern > 1) get BOTH biases, and
            # each layer's traced swa_on flag picks one inside the scan
            # (a [B, S] select per layer — nothing quadratic, no
            # re-derivation of the mask from positions).
            from dla_tpu.ops.decode_kernel import NEG_INF as _KNEG
            delta = positions - kv_pos                       # [B, S]
            bmask = cache["valid"] & (delta >= 0)
            if cfg.sliding_window:
                wmask = bmask & (delta < cfg.sliding_window)
                if cfg.sliding_window_pattern > 1:
                    attn_bias_win = jnp.where(
                        wmask, 0.0, _KNEG).astype(jnp.float32)
                else:
                    bmask = wmask
            attn_bias = jnp.where(bmask, 0.0, _KNEG).astype(jnp.float32)

        def body2(carry, xs):
            layer, k_cache, v_cache, k_s, v_s = self._unpack_decode_xs(
                xs, dequantize=not use_decode_kernel)

            def attend(q, k, v):
                if use_decode_kernel:
                    from dla_tpu.ops.decode_kernel import (
                        flash_decode_attention,
                    )
                    bias_l = attn_bias
                    if attn_bias_win is not None:
                        # gemma-2 alternating SWA: the layer's traced
                        # flag picks the windowed or full bias
                        bias_l = jnp.where(layer["swa_on"],
                                           attn_bias_win, attn_bias)
                    return flash_decode_attention(
                        q, k_cache, v_cache, k, v,
                        bias=bias_l, k_scale=k_s, v_scale=v_s,
                        kv_fill=col,  # no valid col at/after write slot
                        softmax_scale=self._softmax_scale,
                        logit_softcap=cfg.attn_logit_softcap)
                return decode_attention(
                    q, k_cache, v_cache, k, v,
                    kv_valid=cache["valid"],
                    q_positions=positions, kv_positions=kv_pos,
                    window=self._layer_window(layer),
                    softmax_scale=self._softmax_scale,
                    logit_softcap=cfg.attn_logit_softcap)

            return self._decode_layer(layer, carry, cos, sin, attend)

        xs = (self._with_layer_windows(self._flat_layers(params["layers"])),
              cache["k"], cache["v"])
        if self._kv_int8:
            xs = xs + (cache["k_scale"], cache["v_scale"])
        x, (k_cols, v_cols) = jax.lax.scan(body2, x, xs)
        h = self._final_norm(params, x)
        logits = self.unembed(params, h[:, 0])

        # Single cache write for the whole step: the stacked [L,B,1,K,D]
        # new columns land at physical column `col`. Inside the decode
        # scan/while carry XLA aliases the cache buffers, so this is an
        # in-place column write, not a cache copy.
        zero = jnp.zeros((), jnp.int32)

        def write_col(buf, cols, rank5=True):
            # rank5: KV [L, B, S, K, D], column dim 2; rank4: K-major
            # scales [L, B, K, S], column is the LAST dim
            idx = (zero, zero, col, zero, zero) if rank5 else \
                (zero, zero, zero, col)
            return jax.lax.dynamic_update_slice(buf, cols, idx)

        # validity/positions after writing this token
        onehot_col = jax.nn.one_hot(col, max_len, dtype=jnp.int32)[None, :]
        valid_next = cache["valid"] | (onehot_col > 0)
        kv_pos_next = jnp.where(onehot_col > 0, write_idx[:, None], kv_pos)

        new_cache = {
            "valid": valid_next,
            "lengths": cache["lengths"] + 1,
            "step": cache["step"] + 1,
            "prompt_width": cache["prompt_width"],
            "pos": kv_pos_next,
        }
        if self._kv_int8:
            kq, k_s = self._quantize_kv(k_cols)
            vq, v_s = self._quantize_kv(v_cols)
            new_cache["k"] = write_col(cache["k"], kq)
            new_cache["v"] = write_col(cache["v"], vq)
            # K-major scale storage [L, B, K, S]: the new column
            # [L, B, 1, K] transposes to [L, B, K, 1], lands at col
            new_cache["k_scale"] = write_col(
                cache["k_scale"], k_s.transpose(0, 1, 3, 2), rank5=False)
            new_cache["v_scale"] = write_col(
                cache["v_scale"], v_s.transpose(0, 1, 3, 2), rank5=False)
        else:
            new_cache["k"] = write_col(cache["k"], k_cols)
            new_cache["v"] = write_col(cache["v"], v_cols)
        return logits, new_cache

    def decode_step_paged(self, params: Params, view: Params,
                          tokens: jnp.ndarray,  # [B] the tokens just sampled
                          adapters: Optional[Params] = None,
                          ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """One decode step against an EXTERNALLY-gathered KV view — the
        cache-layout-agnostic sibling of ``decode_step``. The serving
        engine's block-paged pool (dla_tpu/serving/kv_blocks.py) gathers
        each sequence's pages into a [B, S] window via its block table and
        hands the result here; this method never writes a cache — it
        returns the step's fresh KV columns for the caller to scatter
        back into whatever layout it owns.

        ``view``:
          k, v     [L, B, S, KH, D]  gathered cache (activation dtype)
          valid    [B, S]            columns that may be attended
          pos      [B, S]            logical position per column
          lengths  [B]               true tokens so far = this query's pos

        Returns (logits [B, V], k_cols [L, B, 1, KH, D], v_cols). Rows
        whose view is garbage (freed serving slots) compute garbage that
        the caller masks — static shapes, no recompilation as requests
        come and go. int8 KV paging is not plumbed yet: serving pages
        store the activation dtype."""
        cfg = self.cfg
        if self._kv_int8:
            raise NotImplementedError(
                "decode_step_paged serves activation-dtype pages; "
                "kv_cache_dtype=int8 is only wired into the contiguous "
                "decode_step path")
        positions = view["lengths"][:, None]               # [B, 1]
        x = self._embed(params, tokens[:, None])
        cos, sin = rotary_angles(positions, cfg.rotary_dim_, cfg.rope_theta,
                                 scaling=cfg.rope_scaling)

        def body(carry, xs):
            layer, k_cache, v_cache = xs

            def attend(q, k, v):
                return decode_attention(
                    q, k_cache, v_cache, k, v,
                    kv_valid=view["valid"],
                    q_positions=positions, kv_positions=view["pos"],
                    window=self._layer_window(layer),
                    softmax_scale=self._softmax_scale,
                    logit_softcap=cfg.attn_logit_softcap)

            return self._decode_layer(layer, carry, cos, sin, attend)

        layers = self._with_layer_windows(self._flat_layers(params["layers"]))
        xs = ({**layers, **self.slot_lora_xs(adapters)},
              view["k"], view["v"])
        x, (k_cols, v_cols) = jax.lax.scan(body, x, xs)
        h = self._final_norm(params, x)
        logits = self.unembed(params, h[:, 0])
        return logits, k_cols, v_cols

    def prefill_step_paged(self, params: Params, view: Params,
                           tokens: jnp.ndarray,     # [B, C] chunk tokens
                           positions: jnp.ndarray,  # [B, C] absolute pos
                           last_index: jnp.ndarray,  # [B] last real token
                           adapters: Optional[Params] = None,
                           ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """One fixed-width prefill CHUNK against an externally-gathered
        KV view — the chunked-prefill sibling of ``decode_step_paged``.
        The chunk's C queries attend jointly over (a) the already-
        computed prefix held in the paged pool, gathered into the view
        with ``valid`` marking exactly the columns BEFORE this chunk,
        and (b) the chunk's own fresh keys, causally by absolute
        position (pad tokens carry later positions than every real
        query, so they mask themselves out). Returns
        (logits [B, V] — the next-token distribution after the token at
        ``last_index``, only meaningful on the FINAL chunk —
        k_cols/v_cols [L, B, C, KH, D] for the caller to scatter into
        the pool; pad columns route to the trash page)."""
        cfg = self.cfg
        if self._kv_int8:
            raise NotImplementedError(
                "prefill_step_paged serves activation-dtype pages; "
                "kv_cache_dtype=int8 is only wired into the contiguous "
                "path")
        b, c = tokens.shape
        x = self._embed(params, tokens)
        cos, sin = rotary_angles(positions, cfg.rotary_dim_, cfg.rope_theta,
                                 scaling=cfg.rope_scaling)
        from dla_tpu.ops.attention import block_decode_attention

        def body(carry, xs):
            layer, k_cache, v_cache = xs

            def attend(q, k, v):
                return block_decode_attention(
                    q, k_cache, v_cache, k, v,
                    kv_valid=view["valid"],
                    q_positions=positions, kv_positions=view["pos"],
                    window=self._layer_window(layer),
                    softmax_scale=self._softmax_scale,
                    logit_softcap=cfg.attn_logit_softcap)

            return self._decode_layer(layer, carry, cos, sin, attend)

        layers = self._with_layer_windows(self._flat_layers(params["layers"]))
        xs = ({**layers, **self.slot_lora_xs(adapters)},
              view["k"], view["v"])
        x, (k_cols, v_cols) = jax.lax.scan(body, x, xs)
        h = self._final_norm(params, x)                     # [B, C, H]
        last = h[jnp.arange(b), last_index]                 # [B, H]
        logits = self.unembed(params, last)
        return logits, k_cols, v_cols

    def decode_block_paged(self, params: Params, view: Params,
                           tokens: jnp.ndarray,  # [B, G] token block
                           adapters: Optional[Params] = None,
                           ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """Verify a G-token block against an externally-gathered KV view
        — the speculative-verify sibling of ``decode_step_paged``. Row
        b's block occupies absolute positions lengths[b]..lengths[b]+G-1;
        query g attends over (a) the committed prefix in the view
        (``valid`` marks exactly the columns BEFORE the block — draft
        columns must NOT be valid, the in-block keys supply them fresh)
        and (b) the block's own keys, causally by position. Returns
        (logits [B, G, V] — one next-token distribution per block
        position — and k_cols/v_cols [L, B, G, KH, D] for the caller to
        scatter; rejected columns are the caller's rollback problem)."""
        cfg = self.cfg
        if self._kv_int8:
            raise NotImplementedError(
                "decode_block_paged serves activation-dtype pages; "
                "kv_cache_dtype=int8 is only wired into the contiguous "
                "path")
        b, g = tokens.shape
        positions = view["lengths"][:, None] + \
            jnp.arange(g, dtype=jnp.int32)[None, :]          # [B, G]
        x = self._embed(params, tokens)
        cos, sin = rotary_angles(positions, cfg.rotary_dim_, cfg.rope_theta,
                                 scaling=cfg.rope_scaling)
        from dla_tpu.ops.attention import block_decode_attention

        def body(carry, xs):
            layer, k_cache, v_cache = xs

            def attend(q, k, v):
                return block_decode_attention(
                    q, k_cache, v_cache, k, v,
                    kv_valid=view["valid"],
                    q_positions=positions, kv_positions=view["pos"],
                    window=self._layer_window(layer),
                    softmax_scale=self._softmax_scale,
                    logit_softcap=cfg.attn_logit_softcap)

            return self._decode_layer(layer, carry, cos, sin, attend)

        layers = self._with_layer_windows(self._flat_layers(params["layers"]))
        xs = ({**layers, **self.slot_lora_xs(adapters)},
              view["k"], view["v"])
        x, (k_cols, v_cols) = jax.lax.scan(body, x, xs)
        h = self._final_norm(params, x)                      # [B, G, H]
        logits = self.unembed(params, h)                     # [B, G, V]
        return logits, k_cols, v_cols

    def start_decode(self, params: Params, input_ids: jnp.ndarray,
                     attention_mask: jnp.ndarray, max_new_tokens: int,
                     ) -> Tuple[jnp.ndarray, Params]:
        """Prefill + set up decode bookkeeping. Returns (first logits, cache)."""
        b, t = input_ids.shape
        cache0 = self.init_cache(b, t + max_new_tokens)
        logits, cache = self.prefill(params, cache0, input_ids, attention_mask)
        max_len = t + max_new_tokens
        cache["prompt_width"] = jnp.asarray(t, jnp.int32)
        cache["pos"] = jnp.broadcast_to(
            jnp.arange(max_len)[None, :], (b, max_len)).astype(jnp.int32)
        return logits, cache

    def decode_block(self, params: Params, cache: Params,
                     tokens: jnp.ndarray,  # [B, G] a block of tokens
                     ) -> Tuple[jnp.ndarray, Params]:
        """Multi-token decode step: score a block of G tokens in ONE
        forward against the cache (intra-block causal via
        ops.attention.block_decode_attention), writing all G KV columns
        once. Returns (logits [B, G, V], cache) where logits[:, i] is
        the next-token distribution AFTER tokens[:, :i+1] — the
        verification forward of speculative decoding. The write is
        TENTATIVE: every new column is marked valid and lengths advance
        by G; a caller that rejects a per-row suffix retracts it with
        ``retract_block`` (columns invalidated, lengths corrected).
        G == 1 is semantically decode_step."""
        cfg = self.cfg
        b, g = tokens.shape
        if "prompt_width" not in cache:
            raise ValueError(
                "decode_block requires a cache produced by start_decode()")
        lengths0 = cache["lengths"]                        # [B]
        positions = lengths0[:, None] + jnp.arange(g)[None, :]  # [B, G]
        x = self._embed(params, tokens)
        cos, sin = rotary_angles(positions, cfg.rotary_dim_, cfg.rope_theta,
                                 scaling=cfg.rope_scaling)
        col0 = cache["prompt_width"] + cache["step"]
        kv_pos = cache["pos"]
        from dla_tpu.ops.attention import block_decode_attention
        if self._kv_int8:
            # block verify dequantizes via the XLA path (the Pallas
            # decode kernel is single-token); speculative decoding with
            # an int8 cache pays the materialization decode_step's
            # kernel exists to avoid — say so once rather than letting
            # a benchmark silently measure the slow path
            key = ("decode_block_int8", tokens.shape)
            if key not in _REPLICATED_FLASH_LOGGED and \
                    jax.process_index() == 0:
                _REPLICATED_FLASH_LOGGED.add(key)
                print("[dla_tpu][decode] decode_block with an int8 KV "
                      "cache uses the XLA dequant path (the fused "
                      "kernel is single-token); prefer bf16 caches for "
                      "speculative decoding", file=sys.stderr, flush=True)

        def body(carry, xs):
            layer, k_cache, v_cache, _, _ = self._unpack_decode_xs(
                xs, dequantize=True)

            def attend(q, k, v):
                return block_decode_attention(
                    q, k_cache, v_cache, k, v,
                    kv_valid=cache["valid"],
                    q_positions=positions, kv_positions=kv_pos,
                    window=self._layer_window(layer),
                    softmax_scale=self._softmax_scale,
                    logit_softcap=cfg.attn_logit_softcap)

            return self._decode_layer(layer, carry, cos, sin, attend)

        xs = (self._with_layer_windows(self._flat_layers(params["layers"])),
              cache["k"], cache["v"])
        if self._kv_int8:
            xs = xs + (cache["k_scale"], cache["v_scale"])
        x, (k_cols, v_cols) = jax.lax.scan(body, x, xs)
        h = self._final_norm(params, x)
        logits = self.unembed(params, h)                   # [B, G, V]

        zero = jnp.zeros((), jnp.int32)
        max_len = cache["k"].shape[2]

        def write_cols(buf, cols, rank5=True):
            idx = (zero, zero, col0, zero, zero) if rank5 else \
                (zero, zero, zero, col0)
            return jax.lax.dynamic_update_slice(buf, cols, idx)

        colmask = jax.nn.one_hot(  # [B?, S] no: [S] per col block
            col0 + jnp.arange(g), max_len, dtype=jnp.int32).sum(0)[None, :]
        valid_next = cache["valid"] | (colmask > 0)
        # logical position of physical col col0+i for row b is
        # lengths0[b] + i: scatter the block's positions in
        block_pos = jnp.zeros_like(kv_pos)
        block_pos = jax.lax.dynamic_update_slice(
            block_pos, positions, (zero, col0))
        kv_pos_next = jnp.where(colmask > 0, block_pos, kv_pos)

        new_cache = {
            "valid": valid_next,
            "lengths": lengths0 + g,
            "step": cache["step"] + g,
            "prompt_width": cache["prompt_width"],
            "pos": kv_pos_next,
        }
        if self._kv_int8:
            kq, k_s = self._quantize_kv(k_cols)
            vq, v_s = self._quantize_kv(v_cols)
            new_cache["k"] = write_cols(cache["k"], kq)
            new_cache["v"] = write_cols(cache["v"], vq)
            new_cache["k_scale"] = write_cols(
                cache["k_scale"], k_s.transpose(0, 1, 3, 2), rank5=False)
            new_cache["v_scale"] = write_cols(
                cache["v_scale"], v_s.transpose(0, 1, 3, 2), rank5=False)
        else:
            new_cache["k"] = write_cols(cache["k"], k_cols)
            new_cache["v"] = write_cols(cache["v"], v_cols)
        return logits, new_cache

    @staticmethod
    def retract_block(cache: Params, keep: jnp.ndarray,  # [B] 0..G
                      g: int) -> Params:
        """Undo the tentative acceptance of the LAST decode_block: per
        row, only the first ``keep[b]`` of its G columns stay valid;
        lengths roll back to pre-block + keep. The KV bytes of rejected
        columns stay in place (invalid, never attended) and are
        overwritten by... nothing — speculative decoding advances the
        physical cursor by G every round, trading cache columns for
        fewer serial steps."""
        col0 = cache["prompt_width"] + cache["step"] - g
        max_len = cache["valid"].shape[1]
        off = jnp.arange(max_len)[None, :] - col0          # [1, S]
        in_block = (off >= 0) & (off < g)
        keep_mask = off < keep[:, None]                    # [B, S]
        valid = jnp.where(in_block, cache["valid"] & keep_mask,
                          cache["valid"])
        return {**cache, "valid": valid,
                "lengths": cache["lengths"] - g + keep}
