from dla_tpu.models.config import ModelConfig, get_model_config, known_models, register_model
from dla_tpu.models.transformer import Transformer
from dla_tpu.models.reward import RewardModel

__all__ = [
    "ModelConfig",
    "get_model_config",
    "known_models",
    "register_model",
    "Transformer",
    "RewardModel",
]
