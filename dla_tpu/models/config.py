"""Model hyperparameter schema + registry of presets.

Replaces the reference's reliance on HF ``AutoConfig``/``AutoModel``
(src/models/base_model.py:17-42): model architecture is explicit data here,
so the same transformer code serves Llama-2 7B/13B/70B, Mistral-7B, phi-2
-class students, and tiny test models.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    vocab_size: int
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: Optional[int] = None      # defaults to hidden_size // num_heads
    rope_theta: float = 10000.0
    # HF ``rope_scaling`` dict for extended-context checkpoints:
    # {"rope_type": "llama3", factor, low_freq_factor, high_freq_factor,
    #  original_max_position_embeddings} (llama-3.1/3.2) or
    # {"rope_type": "linear", factor} — ops/rotary.py:_scale_inv_freq.
    rope_scaling: Optional[Dict[str, Any]] = None
    rms_norm_eps: float = 1e-5
    tie_embeddings: bool = False
    max_seq_length: int = 2048
    # architecture family:
    #   "llama"  pre-RMSNorm sequential block, gated-SiLU MLP, full RoPE
    #            (llama-2/-3, mistral)
    #   "phi"    parallel residual block (shared input LayerNorm feeding
    #            both attention and MLP), biased projections, GELU MLP,
    #            partial RoPE (phi-2 / phi-1.5)
    #   "gemma"  llama block shape with gated GELU-tanh MLP, embeddings
    #            scaled by sqrt(hidden) on read, tied unembedding, and
    #            (1+w) RMSNorm — the +1 folds into the stored weights at
    #            import/init so the norm path stays shared (gemma-1)
    #   "gemma2" gemma plus: post-attention and post-feedforward norms
    #            (four RMSNorms per block), attention-score and final
    #            -logit softcapping, query_pre_attn_scalar softmax scale,
    #            and alternating-layer sliding window (pattern 2)
    arch: str = "llama"
    # fraction of head_dim that rotates (phi-2: 0.4); 1.0 = full RoPE
    rotary_pct: float = 1.0
    # biases on the q/k/v projections within the llama block layout —
    # the qwen2 family (phi carries biases on every projection already)
    attention_bias: bool = False
    # mistral-style sliding-window attention (HF ``sliding_window``):
    # each token attends kv positions in (pos - window, pos]. None/0 =
    # full causal. Applies to every attention path: flash, xla, decode,
    # and ring context parallelism (absolute-position mask term rotates
    # with kv). Ulysses CP is the one refusal — Transformer.__init__
    # raises when both are set under an active sequence mesh (the mesh
    # isn't known here, and context_parallel is a harmless default
    # otherwise).
    sliding_window: Optional[int] = None
    # Alternating-layer SWA (gemma-2/-3): layer l uses the sliding
    # window iff (l + 1) % pattern != 0 — pattern 2 = every other layer
    # windowed starting at layer 0 (HF Gemma2's is_sliding), pattern 1 =
    # uniform (every layer windowed when sliding_window is set).
    sliding_window_pattern: int = 1
    # gemma-2 softcaps: scores <- cap * tanh(scores / cap) before the
    # softmax (attn) / at the unembedding (final). 0 = off.
    attn_logit_softcap: float = 0.0
    final_logit_softcap: float = 0.0
    # gemma-2 attention scale: softmax scale = query_pre_attn_scalar
    # ** -0.5 (HF Gemma2Config; 27B uses hidden/num_heads != head_dim).
    # None = the usual head_dim ** -0.5.
    query_pre_attn_scalar: Optional[int] = None
    # numerics
    dtype: str = "bfloat16"             # activation dtype
    param_dtype: str = "float32"        # master param dtype
    # remat: "none" | "full" | "dots"  (jax.checkpoint policy per block)
    remat: str = "full"
    # attention backend: "xla" (fused einsum) | "flash" (pallas kernel,
    # used on the full-sequence path when shapes allow; decode/packed
    # paths always use xla)
    attention: str = "xla"
    # KV-cache storage dtype for autoregressive decode: "bfloat16"
    # (stores in the activation dtype) | "int8" (per-position per-head
    # symmetric quantization with fp scales — halves the cache's HBM
    # traffic on the bandwidth-bound decode loop; dequantize fuses into
    # the attention einsum). Training/prefill attention is unaffected.
    kv_cache_dtype: str = "bfloat16"
    # Pallas decode-attention kernel selection: "auto" engages it for
    # int8 caches (where in-VMEM dequant is the measured win); "on"
    # additionally routes bf16 caches through it (fill-bounded reads vs
    # the XLA einsum's full-S reads — sweepable per chip); "off" forces
    # the XLA decode_attention path everywhere.
    # Eligibility (transformer.decode_step): head_dim % 128 == 0 (lane
    # alignment), num_heads/num_kv_heads <= the kernel's GQA group cap,
    # and no multi-device auto mesh. An ineligible model falls back to
    # the XLA path — with int8 KV that path re-materializes a bf16 cache
    # copy per layer per step, so int8 + ineligible is SLOWER than bf16
    # (logged once per shape at decode time).
    decode_kernel: str = "auto"
    # flash kernel tile sizes (0 = the kernel's measured default, 512).
    # 512-wide blocks measured ~1.8x faster than 128 on v5e; exposed so
    # new chip generations / unusual shapes can retune without a fork.
    flash_block_q: int = 0
    flash_block_k: int = 0
    # context parallelism over the `sequence` mesh axis (long-context):
    # "ring" (ppermute KV rotation, any head count) | "ulysses" (head
    # all-to-all, needs kv_heads % seq_axis == 0). Active only when the
    # ambient mesh has sequence > 1; decode paths always run unsharded.
    context_parallel: str = "ring"
    # GPipe microbatch count when the mesh has stage > 1 (pipeline
    # parallelism). 0 = auto (targets 4x the stage count, see
    # ops.pipeline.resolve_microbatches). More microbatches shrink the
    # (S-1)/(M+S-1) bubble at the cost of smaller per-stage matmuls;
    # batch must be divisible by it.
    pipeline_microbatches: int = 0
    # Interleaved/circular pipeline (virtual stages): each physical
    # stage owns V round-robin layer blocks and microbatches traverse
    # the ring V times — bubble (S-1)/(V*S + S - 1) with only S
    # microbatches of activation in flight (vs needing M = V*S
    # microbatches for the same bubble under plain GPipe). Requires
    # num_layers % (stage * V) == 0; M is pinned to the stage count.
    pipeline_interleave: int = 1
    # storage hint for the interleaved schedule: when > 1 (and
    # pipeline_interleave > 1) the stacked layer dim of every layer/LoRA
    # leaf is stored block-major [V, S, L/(S*V), ...] — a row-major
    # reshape of the canonical [L] stack — so the circular schedule's
    # round-robin block ownership is stage-shard-local (no per-step
    # cross-stage weight reshard). The config loader sets this from
    # hardware.mesh.stage; couples param storage SHAPE (not order) to
    # the stage count — cross-topology moves are a free reshape via
    # Transformer.to_canonical_layout/to_storage_layout.
    pipeline_stages: int = 0
    # Mixture-of-Experts (beyond-reference capability; makes the
    # reserved `expert` mesh axis real — ops/moe.py). 0 = dense MLP.
    # llama arch only; top-k routing with GShard capacity dispatch.
    num_experts: int = 0
    num_experts_per_token: int = 2
    moe_capacity_factor: float = 1.25
    # GShard token-group size: capacity is enforced per group of this
    # many tokens, keeping dispatch memory/FLOPs O(T) at long context
    moe_group_size: int = 512
    moe_aux_weight: float = 0.01      # switch load-balance loss weight
    moe_z_weight: float = 0.001       # router z-loss weight
    # LoRA (the reference's model.lora block, advertised but never wired —
    # reference base_model.py:45-49 dead code, SURVEY.md sec 2.5; here it
    # is functional). lora_r == 0 disables. Adapters are a separate
    # trainable pytree (Transformer.init_lora); base params stay frozen.
    lora_r: int = 0
    lora_alpha: float = 32.0
    lora_dropout: float = 0.0
    lora_targets: tuple = ("wq", "wk", "wv", "wo")

    def __post_init__(self):
        if self.kv_cache_dtype not in ("bfloat16", "int8"):
            raise ValueError(
                f"kv_cache_dtype must be 'bfloat16' or 'int8', got "
                f"{self.kv_cache_dtype!r} — a typo here would silently "
                "run the full-precision cache")
        if self.decode_kernel not in ("auto", "on", "off"):
            raise ValueError(
                f"decode_kernel must be 'auto', 'on' or 'off', got "
                f"{self.decode_kernel!r} — a typo here would silently "
                "fall back to the XLA decode path")
        if self.num_experts > 0:
            if self.arch != "llama":
                raise ValueError(
                    f"MoE (num_experts={self.num_experts}) is implemented "
                    f"for the llama block only, not arch='{self.arch}'")
            if self.lora_r > 0:
                ffn = {"w_gate", "w_up", "w_down", "fc1", "fc2"}
                bad = ffn & set(self.lora_targets)
                if bad:
                    raise ValueError(
                        f"LoRA targets {sorted(bad)} are dense-MLP "
                        f"matrices; with num_experts > 0 restrict "
                        f"lora_targets to attention projections")

    @property
    def head_dim_(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @property
    def rotary_dim_(self) -> int:
        """Rotated slice of each head; even, as rotate_half requires."""
        rd = int(self.head_dim_ * self.rotary_pct)
        rd -= rd % 2
        if rd <= 0:
            raise ValueError(
                f"rotary_pct {self.rotary_pct} rotates {rd} of "
                f"{self.head_dim_} head dims; needs at least 2")
        return rd

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ModelConfig":
        fields = {f.name for f in dataclasses.fields(cls)}
        d = {k: v for k, v in d.items() if k in fields}
        if "lora_targets" in d:
            d["lora_targets"] = tuple(d["lora_targets"])
        return cls(**d)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# Registry: name -> ModelConfig. Names accepted anywhere the reference
# accepts an HF repo id (model_name_or_path config keys).
# ---------------------------------------------------------------------------

_REGISTRY: Dict[str, ModelConfig] = {}


def register_model(name: str, cfg: ModelConfig) -> None:
    _REGISTRY[name.lower()] = cfg


def get_model_config(name: str, **overrides: Any) -> ModelConfig:
    key = name.lower()
    if key not in _REGISTRY:
        raise KeyError(
            f"Unknown model preset '{name}'. Known: {sorted(_REGISTRY)}")
    cfg = _REGISTRY[key]
    return dataclasses.replace(cfg, **overrides) if overrides else cfg


def known_models() -> Dict[str, ModelConfig]:
    return dict(_REGISTRY)


register_model("llama2-7b", ModelConfig(
    vocab_size=32000, hidden_size=4096, intermediate_size=11008,
    num_layers=32, num_heads=32, num_kv_heads=32, max_seq_length=4096))
register_model("llama2-13b", ModelConfig(
    vocab_size=32000, hidden_size=5120, intermediate_size=13824,
    num_layers=40, num_heads=40, num_kv_heads=40, max_seq_length=4096))
register_model("llama2-70b", ModelConfig(
    vocab_size=32000, hidden_size=8192, intermediate_size=28672,
    num_layers=80, num_heads=64, num_kv_heads=8, max_seq_length=4096))
register_model("mistral-7b", ModelConfig(
    vocab_size=32000, hidden_size=4096, intermediate_size=14336,
    num_layers=32, num_heads=32, num_kv_heads=8, max_seq_length=8192,
    sliding_window=4096))  # HF config.json sliding_window (mistral v0.1)
register_model("gemma-2b", ModelConfig(
    vocab_size=256000, hidden_size=2048, intermediate_size=16384,
    num_layers=18, num_heads=8, num_kv_heads=1, head_dim=256,
    rms_norm_eps=1e-6, tie_embeddings=True, max_seq_length=8192,
    arch="gemma"))  # HF google/gemma-2b config.json (MQA)
register_model("gemma-7b", ModelConfig(
    vocab_size=256000, hidden_size=3072, intermediate_size=24576,
    num_layers=28, num_heads=16, num_kv_heads=16, head_dim=256,
    rms_norm_eps=1e-6, tie_embeddings=True, max_seq_length=8192,
    arch="gemma"))
register_model("gemma2-2b", ModelConfig(
    vocab_size=256000, hidden_size=2304, intermediate_size=9216,
    num_layers=26, num_heads=8, num_kv_heads=4, head_dim=256,
    rms_norm_eps=1e-6, tie_embeddings=True, max_seq_length=8192,
    arch="gemma2", sliding_window=4096, sliding_window_pattern=2,
    attn_logit_softcap=50.0, final_logit_softcap=30.0,
    query_pre_attn_scalar=256))  # HF google/gemma-2-2b config.json
register_model("gemma2-9b", ModelConfig(
    vocab_size=256000, hidden_size=3584, intermediate_size=14336,
    num_layers=42, num_heads=16, num_kv_heads=8, head_dim=256,
    rms_norm_eps=1e-6, tie_embeddings=True, max_seq_length=8192,
    arch="gemma2", sliding_window=4096, sliding_window_pattern=2,
    attn_logit_softcap=50.0, final_logit_softcap=30.0,
    query_pre_attn_scalar=256))
register_model("llama3-8b", ModelConfig(
    vocab_size=128256, hidden_size=4096, intermediate_size=14336,
    num_layers=32, num_heads=32, num_kv_heads=8, rope_theta=500000.0,
    max_seq_length=8192))  # HF meta-llama/Meta-Llama-3-8B config.json
register_model("llama3.1-8b", ModelConfig(
    vocab_size=128256, hidden_size=4096, intermediate_size=14336,
    num_layers=32, num_heads=32, num_kv_heads=8, rope_theta=500000.0,
    max_seq_length=131072,
    rope_scaling={"rope_type": "llama3", "factor": 8.0,
                  "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                  "original_max_position_embeddings": 8192}))
register_model("llama3-70b", ModelConfig(
    vocab_size=128256, hidden_size=8192, intermediate_size=28672,
    num_layers=80, num_heads=64, num_kv_heads=8, rope_theta=500000.0,
    max_seq_length=8192))
register_model("llama3.2-1b", ModelConfig(
    vocab_size=128256, hidden_size=2048, intermediate_size=8192,
    num_layers=16, num_heads=32, num_kv_heads=8, rope_theta=500000.0,
    tie_embeddings=True, max_seq_length=131072,
    rope_scaling={"rope_type": "llama3", "factor": 32.0,
                  "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                  "original_max_position_embeddings": 8192}))
register_model("llama3.2-3b", ModelConfig(
    vocab_size=128256, hidden_size=3072, intermediate_size=8192,
    num_layers=28, num_heads=24, num_kv_heads=8, rope_theta=500000.0,
    tie_embeddings=True, max_seq_length=131072,
    rope_scaling={"rope_type": "llama3", "factor": 32.0,
                  "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                  "original_max_position_embeddings": 8192}))
register_model("phi3-mini", ModelConfig(
    vocab_size=32064, hidden_size=3072, intermediate_size=8192,
    num_layers=32, num_heads=32, num_kv_heads=32, rope_theta=10000.0,
    max_seq_length=4096, sliding_window=2047,
    # llama block shape: HF Phi3 fuses qkv/gate_up in storage only
    # (hf_import splits them); microsoft/Phi-3-mini-4k-instruct
    ))
register_model("qwen2-7b", ModelConfig(
    vocab_size=152064, hidden_size=3584, intermediate_size=18944,
    num_layers=28, num_heads=28, num_kv_heads=4, rope_theta=1e6,
    rms_norm_eps=1e-6, max_seq_length=131072, attention_bias=True))
# phi-2 (2.7B): true architecture — parallel residual block, partial
# rotary (0.4), LayerNorm, biased projections, GELU MLP (HF
# microsoft/phi-2 config.json values; weight import in models/hf_import)
register_model("phi-2", ModelConfig(
    vocab_size=51200, hidden_size=2560, intermediate_size=10240,
    num_layers=32, num_heads=32, num_kv_heads=32, max_seq_length=2048,
    arch="phi", rotary_pct=0.4, rms_norm_eps=1e-5))
# mixtral 8x7B (MoE): 8 experts, top-2 routing — beyond-reference
# capability exercising the `expert` mesh axis. HF mixtral checkpoints
# import via models/hf_import (block_sparse_moe mapping,
# logits-parity-tested against transformers).
register_model("mixtral-8x7b", ModelConfig(
    vocab_size=32000, hidden_size=4096, intermediate_size=14336,
    num_layers=32, num_heads=32, num_kv_heads=8, rope_theta=1e6,
    max_seq_length=32768, num_experts=8, num_experts_per_token=2))
# tiny models for tests / smoke runs
register_model("tiny", ModelConfig(
    vocab_size=512, hidden_size=64, intermediate_size=192,
    num_layers=2, num_heads=4, num_kv_heads=2, max_seq_length=256,
    param_dtype="float32", dtype="float32", remat="none"))
register_model("tiny-gqa", ModelConfig(
    vocab_size=512, hidden_size=128, intermediate_size=384,
    num_layers=4, num_heads=8, num_kv_heads=4, max_seq_length=512,
    param_dtype="float32", dtype="float32", remat="none"))
register_model("tiny-moe", ModelConfig(
    vocab_size=512, hidden_size=64, intermediate_size=128,
    num_layers=2, num_heads=4, num_kv_heads=2, max_seq_length=256,
    num_experts=4, num_experts_per_token=2,
    param_dtype="float32", dtype="float32", remat="none"))

# HF repo-id aliases so reference configs keep working verbatim
register_model("google/gemma-2b", _REGISTRY["gemma-2b"])
register_model("google/gemma-7b", _REGISTRY["gemma-7b"])
register_model("google/gemma-2-2b", _REGISTRY["gemma2-2b"])
register_model("google/gemma-2-9b", _REGISTRY["gemma2-9b"])
register_model("meta-llama/Meta-Llama-3-8B", _REGISTRY["llama3-8b"])
register_model("meta-llama/Llama-3.1-8B", _REGISTRY["llama3.1-8b"])
register_model("meta-llama/Meta-Llama-3-70B", _REGISTRY["llama3-70b"])
register_model("meta-llama/Llama-3.2-1B", _REGISTRY["llama3.2-1b"])
register_model("meta-llama/Llama-3.2-3B", _REGISTRY["llama3.2-3b"])
register_model("microsoft/Phi-3-mini-4k-instruct", _REGISTRY["phi3-mini"])
register_model("meta-llama/Llama-2-7b-hf", _REGISTRY["llama2-7b"])
register_model("meta-llama/Llama-2-13b-hf", _REGISTRY["llama2-13b"])
register_model("meta-llama/Llama-2-70b-hf", _REGISTRY["llama2-70b"])
register_model("mistralai/Mistral-7B-v0.1", _REGISTRY["mistral-7b"])
register_model("Qwen/Qwen2-7B", _REGISTRY["qwen2-7b"])
# qwen2.5 shares the qwen2 architecture and the 7B's exact dims
# (config.json differs only in sliding-window metadata, which HF
# defaults to off — hf_import handles real config.json files directly)
register_model("qwen2.5-7b", _REGISTRY["qwen2-7b"])
register_model("Qwen/Qwen2.5-7B", _REGISTRY["qwen2-7b"])
register_model("microsoft/phi-2", _REGISTRY["phi-2"])
register_model("mistralai/Mixtral-8x7B-v0.1", _REGISTRY["mixtral-8x7b"])
