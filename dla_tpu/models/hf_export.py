"""Export dla_tpu weights to a HuggingFace checkpoint directory.

The inverse of models/hf_import: the reference's sixth phase is
"Packaging" (reference README.md:46 — collect artifacts for downstream
use); the strongest packaging for a trained model is the interchange
format everything else can load. Writes ``config.json`` +
``model.safetensors`` in the Llama-family layout (llama / mistral /
qwen2 / mixtral), so a model trained in this framework loads straight
into ``transformers`` (or any safetensors consumer), and round-trips
through models/hf_import.

Layout inversions mirror the importer exactly: our ``x @ w`` [in, out]
matrices transpose back to HF's [out, in] Linear layout, and the
scan-over-layers leading [L] dim unstacks into ``model.layers.{i}.*``
keys. MoE expert stacks [L, E, ...] expand to
``block_sparse_moe.experts.{j}.{w1,w3,w2}``.

CLI:
    python -m dla_tpu.models.hf_export \
        --checkpoint checkpoints/sft/latest --output export/sft_hf
"""
from __future__ import annotations

import argparse
import json
from pathlib import Path
from typing import Any, Dict, Optional

import numpy as np

from dla_tpu.models.config import ModelConfig


def _hf_model_type(cfg: ModelConfig) -> str:
    if cfg.arch == "phi":
        return "phi"
    if cfg.arch == "gemma":
        return "gemma"
    if cfg.arch == "gemma2":
        return "gemma2"
    if cfg.num_experts > 0:
        return "mixtral"
    # attention_bias wins over sliding_window: MistralForCausalLM defines
    # no q/k/v bias tensors, so a biased windowed model must be qwen2
    # (which supports both) or the biases would be silently dropped
    if cfg.attention_bias:
        return "qwen2"
    if cfg.sliding_window:
        return "mistral"
    return "llama"


def model_config_to_hf(cfg: ModelConfig) -> Dict[str, Any]:
    """ModelConfig -> HF config.json dict (inverse of
    hf_config_to_model_config for the llama family)."""
    if cfg.arch == "phi":
        raise NotImplementedError(
            "phi export is not implemented (import-only architecture); "
            "export llama-family models")
    out: Dict[str, Any] = {
        "architectures": [{"mixtral": "MixtralForCausalLM",
                           "mistral": "MistralForCausalLM",
                           "qwen2": "Qwen2ForCausalLM",
                           "gemma": "GemmaForCausalLM",
                           "gemma2": "Gemma2ForCausalLM",
                           "llama": "LlamaForCausalLM"}[_hf_model_type(cfg)]],
        "model_type": _hf_model_type(cfg),
        "vocab_size": cfg.vocab_size,
        "hidden_size": cfg.hidden_size,
        "intermediate_size": cfg.intermediate_size,
        "num_hidden_layers": cfg.num_layers,
        "num_attention_heads": cfg.num_heads,
        "num_key_value_heads": cfg.num_kv_heads,
        "head_dim": cfg.head_dim_,
        "rope_theta": cfg.rope_theta,
        "rms_norm_eps": cfg.rms_norm_eps,
        "tie_word_embeddings": cfg.tie_embeddings,
        "max_position_embeddings": cfg.max_seq_length,
        "hidden_act": ("gelu_pytorch_tanh"
                       if cfg.arch in ("gemma", "gemma2") else "silu"),
        "torch_dtype": "float32",
    }
    if cfg.arch == "gemma2":
        # Gemma2Config has no sliding_window_pattern knob — alternation
        # (pattern 2) is implicit in the architecture. A model overridden
        # to any other pattern cannot be represented as gemma2; refuse
        # rather than silently round-tripping to different logits
        # (import hard-codes pattern 2 back).
        if cfg.sliding_window and cfg.sliding_window_pattern != 2:
            raise ValueError(
                f"gemma2 export requires sliding_window_pattern == 2 "
                f"(HF Gemma2's implicit alternation); this model uses "
                f"pattern {cfg.sliding_window_pattern}, which a gemma2 "
                "config.json cannot express")
        # Gemma2Config reads hidden_activation (hidden_act is the
        # legacy key other families use)
        out["hidden_activation"] = "gelu_pytorch_tanh"
        out["attn_logit_softcapping"] = cfg.attn_logit_softcap or None
        out["final_logit_softcapping"] = cfg.final_logit_softcap or None
        if cfg.query_pre_attn_scalar:
            out["query_pre_attn_scalar"] = int(cfg.query_pre_attn_scalar)
    if cfg.attention_bias:
        out["attention_bias"] = True
    if cfg.rope_scaling:
        # the importer folds top-level config.json fallbacks INTO the
        # dict so ops/rotary needs no config back-reference; exporting
        # those copies verbatim would persist values HF configs leave
        # implicit, so strip any key that re-derives to the same value
        # on the way back through hf_import._validated_rope_scaling
        rs = dict(cfg.rope_scaling)
        rope_type = rs.get("rope_type")
        if (rope_type == "yarn"
                and rs.get("original_max_position_embeddings")
                == cfg.max_seq_length):
            rs.pop("original_max_position_embeddings")
        if (rope_type == "dynamic"
                and rs.get("max_position_embeddings")
                == cfg.max_seq_length):
            rs.pop("max_position_embeddings")
        if rope_type == "longrope":
            orig = rs.get("original_max_position_embeddings")
            if orig and int(orig) != int(cfg.max_seq_length):
                # transformers reads the short/long switch point and the
                # derived attention factor from the TOP-LEVEL attribute
                # only (verified 4.57: a dict-level value is ignored) — a
                # reload that missed this would silently use max_position_
                # embeddings as the switch and never apply long_factor
                out["original_max_position_embeddings"] = int(orig)
                if rs.get("factor") == (float(cfg.max_seq_length)
                                        / float(orig)):
                    rs.pop("factor")
            # dict-level copy is an importer artifact either way: the
            # real switch point now lives at the top level, and an
            # orig == max_seq_length value was the importer's own
            # max_position_embeddings fallback
            rs.pop("original_max_position_embeddings", None)
        out["rope_scaling"] = rs
    if cfg.sliding_window:
        out["sliding_window"] = int(cfg.sliding_window)
        if _hf_model_type(cfg) == "qwen2":
            # HF qwen2: the first max_window_layers layers run FULL
            # attention; 0 means SWA on every layer — which is what this
            # framework's global window does
            out["use_sliding_window"] = True
            out["max_window_layers"] = 0
    if cfg.num_experts > 0:
        out["num_local_experts"] = cfg.num_experts
        out["num_experts_per_tok"] = cfg.num_experts_per_token
    return out


def export_hf_weights(params: Dict[str, Any], cfg: ModelConfig,
                      out_dir) -> Path:
    """Write ``config.json`` + ``model.safetensors`` (fp32) to out_dir.
    ``params`` is the dla_tpu pytree (host numpy or device arrays)."""
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    if cfg.arch == "phi":
        raise NotImplementedError(
            "phi export is not implemented (import-only architecture)")

    def host(x) -> np.ndarray:
        return np.asarray(x, dtype=np.float32)

    def linear(x) -> np.ndarray:
        return host(x).T.copy()  # [in, out] -> HF [out, in]

    # interleaved-PP storage layout ([V, S, c, ...] leaves) back to the
    # canonical [L, ...] stack HF expects — a no-op for flat storage,
    # with the enable predicate owned by the model, not duplicated here
    from dla_tpu.models.transformer import Transformer
    params = Transformer(cfg).to_canonical_layout(params)
    layers = params["layers"]
    L = cfg.num_layers
    moe = cfg.num_experts > 0
    # gemma stores norms centered at 0 (runtime computes x * (1 + w));
    # this framework folds the +1 into the weights at import/init, so
    # export subtracts it back out
    off = np.float32(1.0) if cfg.arch in ("gemma", "gemma2") \
        else np.float32(0.0)

    def norm(x) -> np.ndarray:
        return host(x) - off

    sd: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": host(params["embed"]["embedding"]),
        "model.norm.weight": norm(params["final_norm"]),
    }
    for i in range(L):
        p = f"model.layers.{i}."
        sd[p + "input_layernorm.weight"] = norm(layers["attn_norm"][i])
        sd[p + "self_attn.q_proj.weight"] = linear(layers["wq"][i])
        sd[p + "self_attn.k_proj.weight"] = linear(layers["wk"][i])
        sd[p + "self_attn.v_proj.weight"] = linear(layers["wv"][i])
        sd[p + "self_attn.o_proj.weight"] = linear(layers["wo"][i])
        if cfg.attention_bias:
            sd[p + "self_attn.q_proj.bias"] = host(layers["wq_bias"][i])
            sd[p + "self_attn.k_proj.bias"] = host(layers["wk_bias"][i])
            sd[p + "self_attn.v_proj.bias"] = host(layers["wv_bias"][i])
        if cfg.arch == "gemma2":
            sd[p + "post_attention_layernorm.weight"] = norm(
                layers["attn_post_norm"][i])
            sd[p + "pre_feedforward_layernorm.weight"] = norm(
                layers["mlp_norm"][i])
            sd[p + "post_feedforward_layernorm.weight"] = norm(
                layers["mlp_post_norm"][i])
        else:
            sd[p + "post_attention_layernorm.weight"] = norm(
                layers["mlp_norm"][i])
        if moe:
            m = p + "block_sparse_moe."
            sd[m + "gate.weight"] = linear(layers["router"][i])
            for j in range(cfg.num_experts):
                sd[m + f"experts.{j}.w1.weight"] = linear(
                    layers["w_gate"][i][j])
                sd[m + f"experts.{j}.w3.weight"] = linear(
                    layers["w_up"][i][j])
                sd[m + f"experts.{j}.w2.weight"] = linear(
                    layers["w_down"][i][j])
        else:
            sd[p + "mlp.gate_proj.weight"] = linear(layers["w_gate"][i])
            sd[p + "mlp.up_proj.weight"] = linear(layers["w_up"][i])
            sd[p + "mlp.down_proj.weight"] = linear(layers["w_down"][i])
    if not cfg.tie_embeddings and "lm_head" in params:
        sd["lm_head.weight"] = linear(params["lm_head"])

    from safetensors.numpy import save_file
    save_file(sd, str(out_dir / "model.safetensors"))
    with (out_dir / "config.json").open("w") as fh:
        json.dump(model_config_to_hf(cfg), fh, indent=1)
    return out_dir


def export_checkpoint(checkpoint_path, out_dir) -> Path:
    """dla_tpu checkpoint dir (or its ``latest`` pointer) -> HF dir.
    Checkpoints store ``model_config`` aux, so the export is
    self-describing. LoRA checkpoints must be saved ``merged`` (the
    trainers' default final save) — raw adapter trees are refused, never
    silently dropped."""
    from dla_tpu.checkpoint.checkpointer import load_tree_numpy
    params, aux = load_tree_numpy(checkpoint_path, prefix="params")
    mc = aux.get("model_config")
    if mc is None:
        raise ValueError(
            f"checkpoint {checkpoint_path} lacks model_config aux; "
            "cannot derive the HF config")
    layer_keys = params.get("layers", {})
    if "embed" not in params or any(
            k.endswith(("_lora_a", "_lora_b")) for k in layer_keys):
        # a LoRA run's step/`final` checkpoints hold the ADAPTER tree
        # ({'layers': {'wq_lora_a': ...}}); only the `merged` tag holds
        # the folded base weights this exporter needs
        raise ValueError(
            "checkpoint holds unmerged LoRA adapters (or no base "
            "weights); export the `merged` checkpoint the trainers "
            "write (checkpoints/<phase>/merged)")
    return export_hf_weights(params, ModelConfig.from_dict(mc), out_dir)


def main(argv: Optional[list] = None) -> None:
    ap = argparse.ArgumentParser(
        description="Export a dla_tpu checkpoint to HF safetensors")
    ap.add_argument("--checkpoint", required=True,
                    help="dla_tpu checkpoint dir or its latest pointer")
    ap.add_argument("--output", required=True, help="output directory")
    args = ap.parse_args(argv)
    out = export_checkpoint(args.checkpoint, args.output)
    print(f"[dla_tpu] exported HF checkpoint to {out}")


if __name__ == "__main__":
    main()
