"""Continuous-batching serving engine: the host loop that drives jitted
prefill/decode steps over the block-paged KV pool.

Execution model — the three invariants everything else hangs off:

1. **Static decode shapes.** The decode step always runs the full
   ``num_slots``-row batch over the full per-slot page window. Requests
   entering and leaving only change the *data* (block tables, validity,
   the active mask) — never a shape — so XLA compiles the decode step
   exactly once per engine lifetime (asserted by test).
2. **Bucketed prefill.** Prompts pad to power-of-two page-count buckets,
   so prefill compiles once per bucket width ever used, not per prompt
   length.
3. **Host-mirrored metadata.** Slot metadata (block tables, valid, pos,
   lengths, last tokens) is authoritative on the host as numpy; the
   jitted steps receive it as inputs and the host re-applies the
   deterministic updates itself instead of fetching arrays back. Only
   sampled tokens and prefill logits cross device->host per step.

Backpressure: admission needs every prompt page plus a decode reserve up
front; mid-decode page exhaustion preempts the youngest request (freed
pages go to older ones; the victim recomputes its prefix on
re-admission). The same engine is the intended async rollout backend for
PPO (docs/SERVING.md): rollouts are just requests whose consumer is the
trainer.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dla_tpu.generation.engine import GenerationConfig
from dla_tpu.generation.speculative import accept_prefix_len
from dla_tpu.models.transformer import Transformer
from dla_tpu.ops.sampling import (SamplingParams, derive_request_seed,
                                  sample_token_block,
                                  sample_token_per_row)
from dla_tpu.resilience.faults import FaultPlan
from dla_tpu.serving.kv_blocks import (
    PagedKVCache,
    PageGeometry,
    PrefixCache,
)
from dla_tpu.serving.metrics import ServingMetrics
from dla_tpu.serving.migration import MigrationError, MigrationTicket
from dla_tpu.serving.resilience import (
    AdmissionController,
    DegradationLadder,
    DeviceStepError,
    NaNLogitsError,
    ShedConfig,
)
from dla_tpu.serving.scheduler import (
    TERMINAL_STATES,
    Request,
    RequestState,
    Scheduler,
    SchedulerConfig,
)
from dla_tpu.serving.tenancy import (
    AdapterStore,
    TenancyConfig,
    TenantPolicy,
)
from dla_tpu.telemetry.anomaly import AnomalyConfig, AnomalyMonitor
from dla_tpu.telemetry.exporter import MetricsHTTPServer, ReadinessProbe
from dla_tpu.telemetry.flight_recorder import FlightRecorder
from dla_tpu.telemetry.mfu import MFUCalculator
from dla_tpu.telemetry.slo import SLOWatch
from dla_tpu.telemetry.trace import (
    Tracer,
    get_tracer,
    install_tracer,
    register_trace_gauges,
)
from dla_tpu.telemetry.xla_introspect import (
    IntrospectedFunction,
    register_live_bytes_gauge,
)
from dla_tpu.utils.profiling import ProfileWindow, annotate, step_annotation


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Geometry + policy of one serving engine instance."""
    page_size: int = 16
    num_pages: int = 64          # pool size (page 0 reserved for trash)
    num_slots: int = 4           # static decode batch rows
    max_model_len: int = 128     # per-slot logical window (prompt + new)
    max_prefill_batch: int = 2
    lookahead: int = 16
    decode_reserve_pages: int = 1
    seed: int = 0
    # chunked prefill: tokens per fixed-shape prefill chunk (must be a
    # multiple of page_size); 0 keeps PR-1's monolithic bucketed prefill
    prefill_chunk: int = 0
    # co-scheduling cap: a prefill chunk is deferred while the running
    # decode batch plus the chunk would exceed this many tokens per
    # engine step (0 = no cap; a chunk always runs when nothing decodes,
    # so the budget can't livelock prefill)
    prefill_token_budget: int = 0
    # share full pages of identical token prefixes across requests via
    # block-table aliasing (requires prefill_chunk > 0: cache hits are
    # chunk-granular so the fixed chunk schedule stays compile-stable)
    prefix_cache: bool = False
    # LRU cap on stored exact-full-prompt logits entries (each pins its
    # partial tail page in the cache)
    cached_logits_capacity: int = 128
    # same {trace_dir, start_step, num_steps} dict the trainer's
    # logging.profile takes: an xplane trace of a serving run is one
    # config flag away (windows count ENGINE steps, not tokens)
    profile: Optional[Dict] = None
    # Prometheus scrape endpoint (telemetry.exporter); 0 = ephemeral
    metrics_port: Optional[int] = None
    # host tracing (telemetry.trace): same {enabled, capacity, path}
    # block as the trainer's logging.telemetry.trace. When enabled the
    # engine emits one async span tree per request (enqueue -> admitted
    # -> first token -> per-decode instants -> finish), timestamped with
    # the engine's own clock so trace durations equal recorded TTFT/ITL.
    trace: Optional[Dict] = None
    # SLO watch (telemetry.slo): {objectives: [...], check_every: N}
    # evaluated against the metrics snapshot every N engine steps
    slo: Optional[Dict] = None
    # /healthz flips to 503 when no engine step completed for this long
    readiness_timeout_s: float = 600.0
    # admission control / load shedding / degradation ladder: the
    # serving.resilience ShedConfig fields as a dict (None or
    # {enabled: false} = no gate, PR-1 behavior)
    shed: Optional[Dict] = None
    # serving-scoped fault injection: an explicit plan spec string
    # ("engine_step=3:wedge;engine_step=6:nan_logits"); None falls back
    # to $DLA_FAULT_PLAN — only engine_step= entries fire here
    fault_plan: Optional[str] = None
    # flight-recorder postmortem directory (None = in-memory ring only)
    postmortem_dir: Optional[str] = None
    # XLA introspection (telemetry.xla_introspect): the three jitted
    # entry points dispatch through IntrospectedFunction for retrace
    # attribution + per-fn cost/memory/roofline gauges.
    # {enabled: bool (default true), max_entries: int}
    xla_introspect: Optional[Dict] = None
    # anomaly auto-triage (telemetry.anomaly.AnomalyConfig fields as a
    # dict) over inter-token latency and unattributed recompiles; the
    # capture dumps land in postmortem_dir. None = off.
    anomaly: Optional[Dict] = None
    # blockwise speculative decoding over the paged pool:
    # {enabled: bool (default true when the block is present),
    #  k: int draft tokens per round (default 4),
    #  draft: "int8" (weight-only int8 self-draft via quantize_weights)
    #         | "self" (full-precision self-draft — a correctness/bench
    #           reference with ~100% acceptance)}.
    # Greedy AND per-request-seeded sampled outputs stay bit-identical
    # to the non-speculative engine: the verify step samples the target
    # tokens itself at the request's fold_in(seed, k) stream positions
    # and accepts a draft token only when it EQUALS the target's sample.
    speculative: Optional[Dict] = None
    # multi-tenant LoRA serving (serving.tenancy TenancyConfig fields as
    # a dict): a device-resident pool of per-tenant adapters gathered
    # per-slot inside the ONE compiled decode step, plus per-tenant
    # quotas/SLOs/metrics. Requires prefill_chunk > 0 (tenant KV is
    # namespaced in the prefix cache at chunk granularity, and the
    # monolithic prefill path has no per-slot adapter plumbing).
    # None or {enabled: false} = single-tenant, PR-1 behavior.
    tenancy: Optional[Dict] = None
    # disaggregation role of this engine within a fleet:
    #   "mixed"   — prefill + decode co-scheduled (the default; a
    #               standalone engine is always mixed)
    #   "prefill" — runs chunked prefill only; the fleet ships each
    #               finished prefix to a decode engine as a
    #               MigrationTicket (requires prefill_chunk > 0)
    #   "decode"  — admission is handoff-only: submit() refuses, work
    #               arrives via import_request / restore
    role: str = "mixed"

    @property
    def pages_per_slot(self) -> int:
        return -(-self.max_model_len // self.page_size)


class ServingEngine:
    """Continuous-batching engine over one model + params.

    >>> eng = ServingEngine(model, params, GenerationConfig(...), cfg)
    >>> rid = eng.submit([1, 2, 3], max_new_tokens=16)
    >>> while eng.has_work():
    ...     for rid, tok in eng.step():
    ...         ...                      # stream tokens out per request
    >>> eng.result(rid).generated
    """

    def __init__(self, model: Transformer, params, gen: GenerationConfig,
                 cfg: ServingConfig,
                 now: Callable[[], float] = time.perf_counter):
        if cfg.page_size < 1 or cfg.max_model_len % cfg.page_size:
            raise ValueError(
                f"max_model_len ({cfg.max_model_len}) must be a positive "
                f"multiple of page_size ({cfg.page_size})")
        if cfg.prefill_chunk:
            if cfg.prefill_chunk % cfg.page_size:
                raise ValueError(
                    f"prefill_chunk ({cfg.prefill_chunk}) must be a "
                    f"multiple of page_size ({cfg.page_size}): chunk "
                    "boundaries must land on page boundaries so cached "
                    "prefixes alias whole pages")
            if cfg.prefill_chunk > cfg.max_model_len:
                raise ValueError(
                    f"prefill_chunk ({cfg.prefill_chunk}) exceeds "
                    f"max_model_len ({cfg.max_model_len})")
        elif cfg.prefix_cache:
            raise ValueError(
                "prefix_cache requires prefill_chunk > 0: cache hits "
                "are chunk-granular, so the monolithic prefill path "
                "cannot consume them")
        if cfg.role not in ("prefill", "decode", "mixed"):
            raise ValueError(
                f"role must be 'prefill', 'decode' or 'mixed', got "
                f"{cfg.role!r}")
        if cfg.role == "prefill" and not cfg.prefill_chunk:
            raise ValueError(
                "role 'prefill' requires prefill_chunk > 0: a prefill "
                "engine ships chunk-aligned prefixes, and only chunked "
                "prefill lands page-aligned committed state to export")
        ten_cfg = TenancyConfig.from_config(cfg.tenancy)
        if ten_cfg is not None and not cfg.prefill_chunk:
            raise ValueError(
                "tenancy requires prefill_chunk > 0: tenant KV is "
                "namespaced in the prefix cache at chunk granularity and "
                "only the chunked prefill path carries per-slot adapters")
        spec = dict(cfg.speculative or {})
        if spec and not spec.get("enabled", True):
            spec = {}
        if spec:
            unknown = set(spec) - {"enabled", "k", "draft"}
            if unknown:
                raise ValueError(
                    f"unknown speculative config keys: {sorted(unknown)}")
        self._spec_k = int(spec.get("k", 4)) if spec else 0
        self._spec_draft_kind = str(spec.get("draft", "int8"))
        if spec:
            if self._spec_k < 1:
                raise ValueError(
                    f"speculative.k must be >= 1, got {self._spec_k}")
            if self._spec_draft_kind not in ("int8", "self"):
                raise ValueError(
                    "speculative.draft must be 'int8' or 'self', got "
                    f"{self._spec_draft_kind!r}")
        self.model = model
        self.params = params
        self.gen = gen
        self.cfg = cfg
        self.now = now
        geom = PageGeometry(
            page_size=cfg.page_size, num_pages=cfg.num_pages,
            num_slots=cfg.num_slots, pages_per_slot=cfg.pages_per_slot)
        self.cache = PagedKVCache(model, geom)
        self.prefix_cache: Optional[PrefixCache] = None
        if cfg.prefix_cache:
            self.prefix_cache = PrefixCache(
                self.cache.allocator, cfg.page_size,
                logits_capacity=cfg.cached_logits_capacity)
        self.scheduler = Scheduler(
            self.cache,
            SchedulerConfig(max_prefill_batch=cfg.max_prefill_batch,
                            lookahead=cfg.lookahead,
                            decode_reserve_pages=cfg.decode_reserve_pages,
                            prefill_chunk=cfg.prefill_chunk,
                            prefill_token_budget=cfg.prefill_token_budget),
            bucket_widths=self._bucket_widths(geom),
            prefix_cache=self.prefix_cache)
        self.metrics = ServingMetrics()
        self._pc_mirrored = {"lookups": 0, "hit_tokens": 0,
                             "evictions": 0}
        # speculative round accounting lives in plain engine ints and is
        # delta-mirrored into the registry each step (same idiom as the
        # prefix-cache counters): a harness swapping in a fresh
        # ServingMetrics sees only post-swap activity, and the
        # Supervisor re-seeds cumulative totals across rebuilds
        self._spec_stats = {"rounds": 0, "proposed": 0, "accepted": 0,
                            "rollbacks": 0}
        self._spec_mirrored = dict(self._spec_stats)
        # KV migration accounting: same delta-mirror idiom. Export
        # failures count on the source engine; imports, page counts and
        # host-bounce bytes on the target.
        self._mig_stats = {"migrations": 0, "migrated_pages": 0,
                           "host_bounce_bytes": 0, "failed_migrations": 0}
        self._mig_mirrored = dict(self._mig_stats)
        # the draft tree: int8 weight-only self-draft (quantize_weights
        # adds _wscale leaves, so this is a DIFFERENT treedef from the
        # target and rides the spec fns as its own jit argument) or the
        # target tree itself ("self")
        self.draft_params = (self._derive_draft(params)
                             if self._spec_k else None)
        self._results: Dict[int, Request] = {}
        # per-slot sampling state shipped into the jitted decode each
        # step ([num_slots] host mirrors, like the cache metadata): every
        # request carries its own traced (temperature, top_p, top_k,
        # seed), and gen_pos is the generated-token index keying the
        # per-request PRNG stream — fold_in(PRNGKey(seed), gen_pos).
        # There is NO sequential engine rng: sampling is a pure function
        # of (request seed, token index), so sampled requests replay
        # bit-identically after eviction or a supervisor restart.
        ns = cfg.num_slots
        self.samp_temp = np.zeros((ns,), np.float32)
        self.samp_top_p = np.ones((ns,), np.float32)
        self.samp_top_k = np.zeros((ns,), np.int32)
        self.samp_seed = np.zeros((ns,), np.uint32)
        self.gen_pos = np.zeros((ns,), np.int32)
        # per-slot adapter pool row ([num_slots] host mirror like the
        # sampling state): row 0 is the all-zeros base identity, so free
        # slots and base-model requests gather an exact +0.0 delta
        self.adapter_idx = np.zeros((ns,), np.int32)
        self._draining = False
        self._old_handlers: Optional[dict] = None
        # engine-step counter drives the profiling window (the serving
        # analog of the trainer's step number)
        self.engine_steps = 0
        self.profile = ProfileWindow(cfg.profile)
        # host tracer: an engine-local one from cfg.trace (built on the
        # engine's OWN clock so request timestamps pass straight in and
        # trace durations equal recorded TTFT/ITL), installed process-
        # wide so annotate/step_annotation land on the same timeline;
        # otherwise whatever tracer is already installed (a co-located
        # trainer's) — or the disabled default, costing nothing.
        trace_cfg = dict(cfg.trace or {})
        self._installed_tracer = False
        if trace_cfg.get("enabled"):
            self.tracer = Tracer(
                enabled=True,
                capacity=int(trace_cfg.get("capacity", 65536)),
                now=now, path=trace_cfg.get("path"))
            install_tracer(self.tracer)
            self._installed_tracer = True
        else:
            self.tracer = get_tracer()
        # ring/spool accounting for THIS engine's tracer, mirrored into
        # the engine registry (the trainer tracer's contract — drops
        # are a /metrics number, not a silent eviction)
        register_trace_gauges(self.metrics.registry, self.tracer)
        # resilience surface: flight recorder for postmortems, the
        # admission gate + degradation ladder (both off unless cfg.shed
        # enables them), and the serving-scoped fault plan
        self.recorder = FlightRecorder(capacity=256,
                                       out_dir=cfg.postmortem_dir)
        shed_cfg = ShedConfig.from_config(cfg.shed)
        self.admission = (AdmissionController(shed_cfg)
                          if shed_cfg is not None else None)
        self.ladder = (DegradationLadder(shed_cfg, recorder=self.recorder)
                       if shed_cfg is not None else None)
        self._applied_level = 0
        self.faults = (FaultPlan.parse(cfg.fault_plan)
                       if cfg.fault_plan is not None
                       else FaultPlan.from_env())
        # armed by _poll_faults, consumed by the next decode dispatch
        self._fault_device_error = False
        self._fault_nan_logits = False
        # SLO watch over the serving snapshot (TTFT p95 etc.), checked
        # every `check_every` engine steps; /healthz readiness heartbeat
        self.slo = SLOWatch.from_config(cfg.slo,
                                        registry=self.metrics.registry,
                                        recorder=self.recorder)
        self._slo_every = max(1, int((cfg.slo or {}).get("check_every",
                                                         100)))
        # multi-tenant plane: the adapter pool the jitted steps gather
        # from, the per-tenant quota/SLO/metrics policy, and the
        # delta-mirror marks for the pool counters. The scheduler's
        # release hook pairs with _bind_adapter's acquire so adapter
        # refcounts track slot residency exactly (finish, evict, cancel
        # — every release path funnels through _release_resources).
        self.adapter_store: Optional[AdapterStore] = None
        self.tenants: Optional[TenantPolicy] = None
        self._bound_tenants: Dict[int, str] = {}    # rid -> acquired
        self._adapter_mirrored = {"publishes": 0, "loads": 0, "spills": 0}
        if ten_cfg is not None:
            self.adapter_store = AdapterStore(model, ten_cfg.adapter_pool)
            self.tenants = TenantPolicy(
                ten_cfg, registry=self.metrics.registry,
                recorder=self.recorder, now=now)
            self.scheduler.release_hook = self._release_adapter
        self.readiness = ReadinessProbe(
            threshold_s=float(cfg.readiness_timeout_s))
        self.metrics_server: Optional[MetricsHTTPServer] = None
        if cfg.metrics_port is not None:
            self.start_metrics_server(cfg.metrics_port)
        # trace-time counters: the function bodies run once per XLA
        # compile, so these ARE the compile counts the no-recompilation
        # test asserts on
        self.decode_compiles = 0
        self.prefill_compiles = 0
        self.prefill_chunk_compiles = 0
        self.spec_draft_compiles = 0
        self.spec_verify_compiles = 0
        self.export_compiles = 0
        self.import_compiles = 0
        self._decode = jax.jit(self._decode_fn)
        self._prefill = jax.jit(self._prefill_fn)
        self._prefill_chunk = jax.jit(self._prefill_chunk_fn)
        self._spec_draft = (jax.jit(self._spec_draft_fn)
                            if self._spec_k else None)
        self._spec_verify = (jax.jit(self._spec_verify_fn)
                             if self._spec_k else None)
        self._export_kv = jax.jit(self._export_kv_fn)
        self._import_kv = jax.jit(self._import_kv_fn)
        # anomaly auto-triage over inter-token latency + unattributed
        # recompiles; captures land next to the other postmortems
        anomaly_cfg = AnomalyConfig.from_config(cfg.anomaly)
        self.anomaly = None
        if anomaly_cfg is not None:
            self.anomaly = AnomalyMonitor(
                anomaly_cfg, recorder=self.recorder, tracer=self.tracer,
                registry=self.metrics.registry, out_dir=cfg.postmortem_dir)
        # XLA introspection: the wrappers OWN dispatch via the AOT path,
        # so the trace-time counters above still tick exactly once per
        # compile (the serving compile-once pins are unchanged). Rooflines
        # use the 2N inference cost model. First compiles never reach
        # on_compile, so every event it forwards is a true recompile.
        xi_cfg = dict(cfg.xla_introspect or {})
        self.xla_introspect_enabled = bool(xi_cfg.get("enabled", True))
        if self.xla_introspect_enabled:
            n_params = sum(int(np.prod(x.shape))
                           for x in jax.tree_util.tree_leaves(params))
            dev = jax.devices()[0]
            self.mfu_calc = MFUCalculator(
                n_params, device_kind=getattr(dev, "device_kind", "cpu"),
                platform=dev.platform, training=False)
            register_live_bytes_gauge(self.metrics.registry)
            max_entries = int(xi_cfg.get("max_entries", 16))
            named = [("decode", self._decode),
                     ("prefill", self._prefill),
                     ("prefill_chunk", self._prefill_chunk)]
            if self._spec_k:
                named += [("spec_draft", self._spec_draft),
                          ("spec_verify", self._spec_verify)]
            named += [("kv_export", self._export_kv),
                      ("kv_import", self._import_kv)]
            wrapped = [
                IntrospectedFunction(
                    name, fn, registry=self.metrics.registry,
                    recorder=self.recorder, mfu_calc=self.mfu_calc,
                    on_compile=self._on_recompile,
                    max_entries=max_entries)
                for name, fn in named]
            self._decode, self._prefill, self._prefill_chunk = wrapped[:3]
            if self._spec_k:
                self._spec_draft, self._spec_verify = wrapped[3:5]
            self._export_kv, self._import_kv = wrapped[-2:]
        else:
            self.mfu_calc = None

    def _derive_draft(self, params):
        """Build the draft tree from the (current) target tree. ``int8``
        re-quantizes (cheap relative to a refit's weight transfer);
        ``self`` aliases the target — zero extra memory, ~100%
        acceptance, the bench/correctness reference arm."""
        if self._spec_draft_kind == "self":
            return params
        return self.model.quantize_weights(params)

    def _on_recompile(self, event: Dict) -> None:
        """Recompile-event feed from the introspection wrappers: an
        UNattributed one (nothing in the fingerprint changed, yet XLA
        compiled) is an anomaly trigger after warmup."""
        if self.anomaly is not None:
            self.anomaly.note_recompile(
                int(event.get("step") or self.engine_steps), event["fn"],
                attributed=bool(event.get("attributed")))

    @staticmethod
    def _bucket_widths(geom: PageGeometry) -> List[int]:
        """Power-of-two page counts up to the slot window: one compiled
        prefill per bucket ever used."""
        widths, n = [], 1
        while n < geom.pages_per_slot:
            widths.append(n * geom.page_size)
            n *= 2
        widths.append(geom.slot_window)
        return widths

    @staticmethod
    def _dev(x: np.ndarray) -> jnp.ndarray:
        """Device-put host scheduler metadata BY VALUE.

        jnp.asarray on suitably-aligned host numpy memory may alias it
        zero-copy, and the engine mutates these arrays in place (e.g.
        mark_computed flips `valid` bits right after a chunk dispatch)
        while the async computation may not have executed yet — an
        aliased buffer makes the jitted step read torn state. Copying
        first pins the dispatched values.
        """
        # dla: disable=host-sync-in-hot-loop -- host->host copy of tiny scheduler metadata (no device fetch); the copy is the race fix
        return jnp.asarray(np.array(x))

    # -------------------------------------------------------- jitted steps

    def _prefill_fn(self, params, k_pages, v_pages, ids, mask, page_rows):
        """Prefill a padded bucket batch and scatter its KV into the
        pool. ids/mask [PB, W]; page_rows [PB, W/page_size] physical page
        ids (dummy rows -> trash page 0). Returns (k_pages, v_pages,
        last-real-token logits [PB, V])."""
        self.prefill_compiles += 1  # dla: disable=trace-side-effect -- deliberate trace-time compile counter, pinned by the serving compile-once tests
        ps = self.cfg.page_size
        logits, ks, vs = self.model.prefill_external(params, ids, mask)
        l, pb, w, kh, dh = ks.shape
        ks = ks.reshape(l, pb, w // ps, ps, kh, dh)
        vs = vs.reshape(l, pb, w // ps, ps, kh, dh)
        k_pages = k_pages.at[:, page_rows].set(ks)
        v_pages = v_pages.at[:, page_rows].set(vs)
        return k_pages, v_pages, logits

    def _prefill_chunk_fn(self, params, k_pages, v_pages, btab, valid,
                          pos, ids, start, nvalid, adapters=None):
        """One FIXED-SHAPE prefill chunk for a single slot: gather the
        slot's pages (the already-computed prefix — cached hit pages and
        earlier chunks — with ``valid`` marking exactly the columns
        before this chunk), run the chunk forward, scatter its C fresh
        KV columns into the pool. ``btab`` [1, pages/slot]; ``valid``/
        ``pos`` [1, S]; ``ids`` [1, C]; ``start``/``nvalid`` traced
        scalars (chunk's absolute start column / real-token count), so
        every chunk of every request reuses ONE compile. Returns
        (k_pages, v_pages, logits [1, V]) — logits are the next-token
        distribution after the chunk's last real token, meaningful only
        on a request's final chunk (the only one whose logits the host
        fetches)."""
        self.prefill_chunk_compiles += 1  # dla: disable=trace-side-effect -- deliberate trace-time compile counter, pinned by the serving compile-once tests
        geom = self.cache.geom
        ps = geom.page_size
        l = self.model.cfg.num_layers
        c = self.cfg.prefill_chunk
        k_view = k_pages[:, btab].reshape(
            l, 1, geom.slot_window, *k_pages.shape[3:])
        v_view = v_pages[:, btab].reshape(
            l, 1, geom.slot_window, *v_pages.shape[3:])
        view = {"k": k_view, "v": v_view, "valid": valid, "pos": pos}
        # absolute chunk schedule: positions are fixed by `start`, so a
        # cache hit changes WHICH chunks run, never the math inside one
        positions = start + jnp.arange(c, dtype=jnp.int32)[None, :]
        last_index = jnp.maximum(nvalid - 1, 0)[None]
        logits, k_cols, v_cols = self.model.prefill_step_paged(
            params, view, ids, positions, last_index, adapters=adapters)
        # scatter the chunk's columns at their physical (page, offset);
        # pad columns (index >= nvalid) route to the trash page
        cols = start + jnp.arange(c, dtype=jnp.int32)
        page_ids = btab[0, cols // ps]
        offs = cols % ps
        real = jnp.arange(c) < nvalid
        page_ids = jnp.where(real, page_ids, 0)
        offs = jnp.where(real, offs, 0)
        k_pages = k_pages.at[:, page_ids, offs].set(k_cols[:, 0])
        v_pages = v_pages.at[:, page_ids, offs].set(v_cols[:, 0])
        return k_pages, v_pages, logits

    def _export_kv_fn(self, k_pages, v_pages, page_ids):
        """Gather one request's ordered pages out of the pool into a
        migration payload. ``page_ids`` [pages_per_slot] physical page
        ids with pad entries routed to trash page 0 — the shape is fixed
        by engine geometry, so every export of every request reuses ONE
        compile. Returns (k_payload, v_payload)
        [L, pages_per_slot, page_size, KH, D]; the payload stays on
        device (the migrator decides whether it ever touches the host).
        """
        self.export_compiles += 1  # dla: disable=trace-side-effect -- deliberate trace-time compile counter, pinned by the migration compile-once tests
        return k_pages[:, page_ids], v_pages[:, page_ids]

    def _import_kv_fn(self, k_pages, v_pages, k_payload, v_payload,
                      page_ids):
        """Scatter a migration payload onto freshly allocated pages in
        ONE fixed-shape call — the install half of the KV handoff.
        ``page_ids`` [pages_per_slot] with pad entries routed to trash
        page 0 (pad payload rows carry the source's trash contents, so
        the duplicate page-0 writes are garbage-onto-garbage by the
        trash-page convention). Same one-compile-per-engine contract as
        the export gather."""
        self.import_compiles += 1  # dla: disable=trace-side-effect -- deliberate trace-time compile counter, pinned by the migration compile-once tests
        k_pages = k_pages.at[:, page_ids].set(k_payload)
        v_pages = v_pages.at[:, page_ids].set(v_payload)
        return k_pages, v_pages

    def _decode_fn(self, params, k_pages, v_pages, block_tables, valid,
                   pos, lengths, tokens, active, temps, top_ps, top_ks,
                   seeds, gen_pos, adapters=None):
        """One static-shape decode step over every slot: gather each
        slot's pages into its [S] window, run the layout-agnostic decode
        step, sample PER-ROW (each slot's traced temperature/top_p/top_k/
        seed, keyed by the slot's generated-token index), scatter the
        fresh KV column back. Free slots compute garbage routed to the
        trash page. Returns the fresh KV pools plus a packed [2, B] f32
        array — row 0 the sampled tokens bitcast to f32, row 1 their
        chosen-token logprobs — so the host still performs exactly ONE
        D2H fetch per decode step (the execution-model invariant)."""
        self.decode_compiles += 1  # dla: disable=trace-side-effect -- deliberate trace-time compile counter, pinned by the serving compile-once tests
        geom = self.cache.geom
        ps = geom.page_size
        l = self.model.cfg.num_layers
        b = geom.num_slots
        # in-graph block-table gather: [L, B, pages/slot, ps, KH, D]
        k_view = k_pages[:, block_tables].reshape(
            l, b, geom.slot_window, *k_pages.shape[3:])
        v_view = v_pages[:, block_tables].reshape(
            l, b, geom.slot_window, *v_pages.shape[3:])
        view = {"k": k_view, "v": v_view, "valid": valid, "pos": pos,
                "lengths": lengths}
        logits, k_cols, v_cols = self.model.decode_step_paged(
            params, view, tokens, adapters=adapters)
        new_tok, logp = sample_token_per_row(
            seeds, gen_pos, logits, temps, top_ps, top_ks)
        new_tok = jnp.where(active, new_tok, 0)
        logp = jnp.where(active, logp, 0.0)
        # scatter this step's KV column: physical (page, offset) of each
        # slot's write column; inactive slots write the trash page
        col = lengths
        page_ids = jnp.take_along_axis(
            block_tables, (col // ps)[:, None], axis=1)[:, 0]
        offs = col % ps
        page_ids = jnp.where(active, page_ids, 0)
        offs = jnp.where(active, offs, 0)
        k_pages = k_pages.at[:, page_ids, offs].set(k_cols[:, :, 0])
        v_pages = v_pages.at[:, page_ids, offs].set(v_cols[:, :, 0])
        packed = jnp.stack(
            [jax.lax.bitcast_convert_type(new_tok, jnp.float32), logp])
        return k_pages, v_pages, packed

    def _spec_draft_fn(self, draft_params, k_pages, v_pages, block_tables,
                       valid, pos, lengths, tokens, active, temps,
                       top_ps, top_ks, seeds, gen_pos, adapters=None):
        """The speculative DRAFT phase: K sequential fixed-shape decode
        steps with the draft tree over the shared paged pool. Step i
        feeds the previous proposal (the pending token at i=0), writes
        its KV column at ``lengths + i``, marks it valid in the TRACED
        metadata copy only (the host mirrors are authoritative and never
        see draft columns — that asymmetry is the free rollback), and
        samples proposal d_{i+1} on the request's own seeded stream at
        generated-token index ``gen_pos + i`` — so a perfect draft
        proposes exactly the tokens the target will sample, and the
        token-matching verify accepts the whole block. Columns beyond
        the slot window or the allocated pages route to the trash page.
        Returns (k_pages, v_pages, proposals [B, K]); the proposals stay
        on device and flow straight into the verify dispatch — no D2H.
        """
        self.spec_draft_compiles += 1  # dla: disable=trace-side-effect -- deliberate trace-time compile counter, pinned by the speculative compile-once tests
        geom = self.cache.geom
        ps = geom.page_size
        l = self.model.cfg.num_layers
        b = geom.num_slots
        sw = geom.slot_window
        col_ids = jnp.arange(sw, dtype=jnp.int32)[None, :]

        def draft_step(carry, i):
            cur, valid_c, pos_c, kp, vp = carry
            k_view = kp[:, block_tables].reshape(l, b, sw, *kp.shape[3:])
            v_view = vp[:, block_tables].reshape(l, b, sw, *vp.shape[3:])
            lens_i = lengths + i
            view = {"k": k_view, "v": v_view, "valid": valid_c,
                    "pos": pos_c, "lengths": lens_i}
            logits, k_cols, v_cols = self.model.decode_step_paged(
                draft_params, view, cur, adapters=adapters)
            nxt, _ = sample_token_per_row(
                seeds, gen_pos + i, logits, temps, top_ps, top_ks)
            nxt = jnp.where(active, nxt, 0)
            col = lens_i
            in_win = (col < sw) & active
            page_ids = jnp.take_along_axis(
                block_tables,
                jnp.minimum(col // ps, geom.pages_per_slot - 1)[:, None],
                axis=1)[:, 0]
            offs = col % ps
            page_ids = jnp.where(in_win, page_ids, 0)
            offs = jnp.where(in_win, offs, 0)
            kp = kp.at[:, page_ids, offs].set(k_cols[:, :, 0])
            vp = vp.at[:, page_ids, offs].set(v_cols[:, :, 0])
            written = (col_ids == col[:, None]) & in_win[:, None]
            valid_c = valid_c | written
            pos_c = jnp.where(written, col[:, None], pos_c)
            return (nxt, valid_c, pos_c, kp, vp), nxt

        (_, _, _, k_pages, v_pages), props = jax.lax.scan(
            draft_step, (tokens, valid, pos, k_pages, v_pages),
            jnp.arange(self._spec_k, dtype=jnp.int32))
        return k_pages, v_pages, jnp.moveaxis(props, 0, 1)

    def _spec_verify_fn(self, params, k_pages, v_pages, block_tables,
                        valid, pos, lengths, tokens, proposals, active,
                        temps, top_ps, top_ks, seeds, gen_pos,
                        adapters=None):
        """The speculative VERIFY phase: one multi-token target forward
        over the block [pending, d_1 .. d_K] at columns
        ``lengths .. lengths + K``. ``valid`` is the COMMITTED-ONLY host
        mirror — the draft's columns must not be valid here, or the
        block attention would double-count keys its in-block causal term
        already supplies. The target then samples its OWN next token at
        every block position on the request's fold_in(seed, gen_pos + i)
        stream — these samples ARE the emitted tokens, which is why
        greedy and sampled outputs are bit-identical to the
        non-speculative engine — and draft token d_{i+1} is accepted iff
        it equals target sample s_i (so position i+1's KV was computed
        from the right input). All K+1 target KV columns scatter over
        the draft's (same pages, CO-written/private by
        ensure_decode_pages' span guard); the host commits only the
        accepted prefix, so rejected columns are never marked valid —
        rollback costs nothing and rejected tokens can never reach the
        PrefixCache index (only prefill registers pages). Returns a
        packed [3, B, K+1] f32 array — tokens bitcast / chosen-token
        logps / accept-count bitcast broadcast — ONE D2H per round."""
        self.spec_verify_compiles += 1  # dla: disable=trace-side-effect -- deliberate trace-time compile counter, pinned by the speculative compile-once tests
        geom = self.cache.geom
        ps = geom.page_size
        l = self.model.cfg.num_layers
        b = geom.num_slots
        sw = geom.slot_window
        g = self._spec_k + 1
        k_view = k_pages[:, block_tables].reshape(
            l, b, sw, *k_pages.shape[3:])
        v_view = v_pages[:, block_tables].reshape(
            l, b, sw, *v_pages.shape[3:])
        view = {"k": k_view, "v": v_view, "valid": valid, "pos": pos,
                "lengths": lengths}
        block = jnp.concatenate([tokens[:, None], proposals], axis=1)
        logits, k_cols, v_cols = self.model.decode_block_paged(
            params, view, block, adapters=adapters)
        toks, logps = sample_token_block(
            seeds, gen_pos, logits, temps, top_ps, top_ks)
        toks = jnp.where(active[:, None], toks, 0)
        logps = jnp.where(active[:, None], logps, 0.0)
        accept = toks[:, :self._spec_k] == proposals
        acc = accept_prefix_len(accept)                    # [B] 0..K
        cols = lengths[:, None] + jnp.arange(g, dtype=jnp.int32)[None, :]
        in_win = (cols < sw) & active[:, None]
        page_ids = jnp.take_along_axis(
            block_tables,
            jnp.minimum(cols // ps, geom.pages_per_slot - 1), axis=1)
        offs = cols % ps
        page_ids = jnp.where(in_win, page_ids, 0)
        offs = jnp.where(in_win, offs, 0)
        k_pages = k_pages.at[:, page_ids, offs].set(k_cols)
        v_pages = v_pages.at[:, page_ids, offs].set(v_cols)
        packed = jnp.stack([
            jax.lax.bitcast_convert_type(toks, jnp.float32),
            logps,
            jax.lax.bitcast_convert_type(
                jnp.broadcast_to(acc[:, None], (b, g)), jnp.float32)])
        return k_pages, v_pages, packed

    # ------------------------------------------------------------- intake

    def submit(self, prompt_tokens: List[int], max_new_tokens: int,
               arrival_time: Optional[float] = None,
               deadline_s: Optional[float] = None,
               priority: int = 0,
               sampling: Optional[SamplingParams] = None,
               tenant: Optional[str] = None) -> int:
        """Queue a request; returns its id. Guards that the request can
        EVER fit: its worst-case page demand (re-admission prefix padded
        to a bucket, plus the decode reserve) within pool capacity.

        ``deadline_s`` is a per-request latency budget relative to
        arrival: past it the scheduler finishes the request with TIMEOUT
        status at the next engine step, whether it is still queued or
        mid-decode (generated-so-far tokens are kept).

        ``sampling`` overrides the engine-global ``gen.*`` knobs for this
        request (temperature/top_p/top_k/seed); None uses the engine
        defaults with a seed derived from (engine seed, rid). Either way
        the request's token stream is a pure function of its seed and
        token index — deterministic under eviction and supervisor
        replay. Per-token chosen-token logprobs accumulate on
        ``result(rid).generated_logprobs``.

        With admission control on (cfg.shed) the request may come back
        already terminal: SHED at the gate (bucket empty, or it is the
        worst of a full queue) — or it may displace a lower-priority
        queued request, which is shed instead. Check
        ``result(rid).state``.

        ``tenant`` (requires cfg.tenancy) runs the request under that
        tenant's published LoRA adapter, quota bucket, SLO accounting
        and prefix-cache namespace; None serves the base weights. A
        tenant whose own token bucket is empty has THIS request shed
        (``at="tenant_quota"``) before the shared gate is consulted —
        per-tenant isolation, other tenants unaffected."""
        if self._draining:
            raise RuntimeError(
                "engine is draining (SIGTERM received): admission closed")
        if self.cfg.role == "decode":
            raise RuntimeError(
                "engine role is 'decode': admission is handoff-only "
                "(import_request / restore)")
        if tenant is not None:
            self._check_tenant(tenant)
        geom = self.cache.geom
        req = Request(prompt_tokens=list(prompt_tokens),
                      max_new_tokens=int(max_new_tokens),
                      arrival_time=(self.now() if arrival_time is None
                                    else arrival_time),
                      priority=int(priority),
                      sampling=sampling,
                      tenant=tenant)
        if deadline_s is not None:
            req.deadline = req.arrival_time + float(deadline_s)
        worst = len(req.prompt_tokens) + req.max_new_tokens
        worst_pages = min(
            geom.pages_for(self.scheduler.bucket_width(min(
                worst, geom.slot_window)))
            + self.cfg.decode_reserve_pages,
            geom.pages_per_slot)
        if worst_pages > self.cache.allocator.capacity:
            raise ValueError(
                f"request {req.rid} can never be served: needs up to "
                f"{worst_pages} pages, pool capacity is "
                f"{self.cache.allocator.capacity}")
        self.scheduler.submit(req)
        self._results[req.rid] = req
        self.metrics.requests_submitted.inc()
        if self.tracer.enabled:
            # root of the request's async span tree, keyed by rid and
            # opened at the recorded arrival time — so the tree's span
            # durations are exactly the recorded latency metrics
            self.tracer.async_begin(
                "request", "request", req.rid, t=req.arrival_time,
                prompt_tokens=len(req.prompt_tokens),
                max_new_tokens=req.max_new_tokens)
        if tenant is not None and self.tenants is not None:
            self.tenants.on_submit(tenant)
            if not self.tenants.gate(tenant, req.arrival_time):
                # the tenant exhausted ITS OWN bucket: shed this arrival
                # and nothing else — the shared gate below never sees it
                self._shed(req, at="tenant_quota")
                return req.rid
        if self.admission is not None:
            _, victims = self.admission.on_submit(
                self.scheduler, req, req.arrival_time)
            for victim in victims:
                self._shed(victim, at="gate")
        return req.rid

    def result(self, rid: int) -> Request:
        return self._results[rid]

    def cancel(self, rid: int, reason: str = "cancelled") -> Request:
        """Client-initiated terminal cancellation — the gateway's
        broken-pipe-on-write path. Wherever the request currently lives
        (queued, prefilling, or mid-decode) its resources go back to
        the pool; generated-so-far tokens stay on the result. A no-op
        on already-terminal requests."""
        req = self._results[rid]
        if req.state in TERMINAL_STATES:
            return req
        self.scheduler.cancel(req, reason)
        self.metrics.requests_cancelled.inc()
        self.recorder.record("request_cancelled",
                             step=self.engine_steps, rid=rid,
                             reason=reason)
        if self.tracer.enabled:
            self.tracer.async_end("request", "request", req.rid,
                                  status="cancelled",
                                  tokens=len(req.generated))
        return req

    def publish_params(self, new_params, donate: bool = False) -> None:
        """In-place weight refit: swap the param tree the jitted steps
        read. The new tree must match the old one's structure, shapes
        and dtypes exactly — same jit fingerprint, so the decode/prefill
        compile counters stay pinned (enforced here rather than
        discovered as a silent retrace). With ``donate=True`` the OLD
        tree's device buffers are freed eagerly (the rollout refitter's
        donation contract) — only safe when the caller owns the old tree
        exclusively; never donate params shared with a trainer.

        For an ADAPTER-ONLY change (one tenant's LoRA factors moved, the
        base weights didn't) use :meth:`publish_adapter` instead: it
        swaps just that tenant's pool row, never retransfers the base
        tree, and leaves every other tenant untouched."""
        old = self.params
        old_def = jax.tree_util.tree_structure(old)
        new_def = jax.tree_util.tree_structure(new_params)
        if old_def != new_def:
            raise ValueError(
                "refit params tree structure mismatch: "
                f"{new_def} vs engine {old_def} (an adapter-only tree "
                "belongs to publish_adapter, not a full-tree refit)")
        for o, n_ in zip(jax.tree_util.tree_leaves(old),
                         jax.tree_util.tree_leaves(new_params)):
            if o.shape != n_.shape or o.dtype != n_.dtype:
                raise ValueError(
                    "refit params leaf mismatch (would retrace): "
                    f"{n_.shape}/{n_.dtype} vs engine {o.shape}/{o.dtype}")
        self.params = new_params
        if self._spec_k:
            # draft refit rides the target refit: re-derive BEFORE any
            # donation frees the old leaves ("self" would otherwise
            # alias deleted buffers). Same structure in -> same
            # structure out, so the spec-fn jit fingerprints hold and
            # the draft/verify compile counters stay pinned.
            self.draft_params = self._derive_draft(new_params)
        if donate and old is not new_params:
            keep = {id(leaf) for leaf
                    in jax.tree_util.tree_leaves(new_params)}
            for leaf in jax.tree_util.tree_leaves(old):
                if id(leaf) not in keep and hasattr(leaf, "delete"):
                    try:
                        leaf.delete()
                    except Exception:
                        pass  # already deleted / externally owned

    def publish_adapter(self, tenant: str, tree, *,
                        alpha: Optional[float] = None,
                        rank: Optional[int] = None) -> None:
        """Install (or hot-swap) one tenant's LoRA adapter — the
        adapter-only sibling of :meth:`publish_params`. The tree is the
        adapter pytree ``init_lora`` produces for the pool's targets
        (treedef-validated the same way a refit is); a resident tenant's
        pool row is rewritten in place with identical shapes and dtypes,
        so the decode jit fingerprint — and the compile counters the
        compile-once tests pin — never move. Requests already decoding
        under this tenant pick the new factors up on their next step."""
        if self.adapter_store is None:
            raise RuntimeError(
                "publish_adapter requires cfg.tenancy (the engine was "
                "built without an adapter pool)")
        self.adapter_store.publish(tenant, tree, alpha=alpha, rank=rank)
        if self.tenants is not None:
            self.tenants.ensure(tenant)

    def _check_tenant(self, tenant: str) -> None:
        if self.adapter_store is None:
            raise ValueError(
                "tenant-scoped request requires cfg.tenancy")
        if not (self.adapter_store.has(tenant)
                or self.tenants.configured(tenant)):
            raise ValueError(
                f"unknown tenant {tenant!r}: publish_adapter first, or "
                "list it under tenancy.quotas for base-weight serving")

    def restore(self, prompt_tokens: List[int], max_new_tokens: int, *,
                generated: List[int], arrival_time: float,
                deadline: Optional[float] = None, priority: int = 0,
                rid: Optional[int] = None,
                sampling: Optional[SamplingParams] = None,
                generated_logprobs: Optional[List[float]] = None,
                tenant: Optional[str] = None
                ) -> Request:
        """Re-enter a journaled in-flight request after a supervisor
        rebuild: the eviction deterministic-recompute contract taken
        cross-engine. ``generated`` pre-seeds the tokens the client
        already streamed, so ``prefix_tokens`` is prompt + streamed —
        the engine re-prefills that prefix and continues from the next
        token. Nothing is re-emitted, and the continuation is
        bit-identical to the fault-free run — greedy AND sampled, since
        the sampling stream is keyed by (seed, token index) and the
        continuation resumes at index ``len(generated)``. ``rid`` (and
        ``sampling``) must be preserved for that determinism when the
        request used the rid-derived default seed. Bypasses the
        admission gate and the drain closure: replayed requests ARE the
        in-flight work a drain exists to finish.

        When the prefix cache already holds EVERY page of the committed
        prefix (the usual case on supervisor replay — the crashed
        engine's registrations are gone, but fleet rebalance hands the
        request to an engine that often served the same prompt), the
        request adopts those pages straight into a decode slot and
        resumes with ZERO prefill; otherwise it queues for the normal
        re-prefill."""
        if tenant is not None:
            # a rebuilt engine must have the adapter republished by its
            # factory before replay reaches it — fail loudly, not with
            # silently-base-weight decoding
            self._check_tenant(tenant)
        req = Request(prompt_tokens=list(prompt_tokens),
                      max_new_tokens=int(max_new_tokens),
                      arrival_time=arrival_time,
                      priority=int(priority),
                      sampling=sampling,
                      tenant=tenant)
        if rid is not None:
            req.rid = rid
        req.deadline = deadline
        req.generated = list(generated)
        req.generated_logprobs = (
            list(generated_logprobs) if generated_logprobs is not None
            else [0.0] * len(req.generated))
        if req.remaining_new_tokens <= 0:
            # every token already streamed before the failure: nothing
            # left to recompute
            self.scheduler.submit(req)
            self.scheduler.cancel(req, "length")
            self.metrics.requests_finished.inc()
        elif not self._try_adopt_cached(req):
            self.scheduler.submit(req)
        self._results[req.rid] = req
        return req

    def _try_adopt_cached(self, req: Request) -> bool:
        """Restore fast path: when the prefix cache holds every page of
        the request's COMMITTED prefix (``prefix_tokens[:-1]`` — the
        last generated token is the next decode input, its column not
        yet written), alias them into a free decode slot and resume
        decode directly, skipping prefill entirely. Only a page-aligned
        committed length qualifies: partial tail columns are never
        indexed, so an unaligned prefix always needs at least one chunk
        recomputed and takes the normal queue path. References taken
        here are unwound completely on any refusal — the fallback is
        indistinguishable from never having tried."""
        if self.prefix_cache is None or not req.generated:
            return False
        ps = self.cfg.page_size
        committed = len(req.prefix_tokens) - 1
        if committed < ps or committed % ps:
            return False
        geom = self.cache.geom
        if len(req.prompt_tokens) + req.max_new_tokens > geom.slot_window:
            return False     # let submit() raise its precise error
        if not self.scheduler.free_slots:
            return False
        if self.scheduler._admission_headroom() == 0:
            return False
        pages = self.prefix_cache.acquire_pages(
            req.prefix_tokens[:committed], namespace=req.tenant)
        if pages is None:
            return False
        n_extra = min(self.cfg.decode_reserve_pages,
                      geom.pages_per_slot - len(pages))
        extra = self.cache.allocator.alloc(n_extra) if n_extra > 0 else []
        if extra is None:
            for p in pages:
                self.cache.allocator.decref(p)
            return False
        self._adopt_committed(req, pages + extra, committed)
        self.metrics.prefill_tokens_saved.inc(committed)
        return True

    def _adopt_committed(self, req: Request, pages: List[int],
                         committed: int) -> None:
        """Shared tail of the two no-prefill entry paths (cache-alias
        restore and KV import): bind the request into a decode slot over
        ``pages`` whose first ``ceil(committed/ps)`` entries hold its
        committed KV, and enter the decode batch with the last generated
        token as the next input."""
        slot = self.scheduler.adopt(req, pages)
        self.cache.open_slot_prefill(slot, req.pages, committed)
        self.cache.begin_decode(slot, committed, req.generated[-1])
        self._bind_adapter(req)
        self._bind_slot_sampling(req)

    # ------------------------------------------------------- KV migration

    def export_request(self, rid: int) -> MigrationTicket:
        """Serialize a mid-decode request's committed state into a
        :class:`MigrationTicket` (the extract half of the KV handoff —
        usually reached via ``KVMigrator``). The request itself is NOT
        released: it keeps decoding here until ``release_migrated``,
        so a failed install downstream loses nothing.

        Refuses (``MigrationError``, counted on
        ``serving/migration/failed_migrations``) requests that are not
        resumable in place: unknown, queued/prefilling/terminal, or with
        an eviction hole — block-table pages no longer covering the
        committed columns."""
        req = self._results.get(rid)
        if req is None:
            return self._export_refuse(f"unknown rid {rid}")
        if req.state is not RequestState.DECODE or req.slot is None \
                or self.scheduler.running.get(req.slot) is not req:
            return self._export_refuse(
                f"request {rid} is {req.state.value}, not mid-decode: "
                "only requests with committed KV in the pool can "
                "migrate (eviction hole — queued work just re-routes)")
        committed = len(req.prefix_tokens) - 1
        if committed < 1:
            return self._export_refuse(
                f"request {rid} has no committed columns yet")
        geom = self.cache.geom
        needed = geom.pages_for(committed)
        btab = self.cache.block_tables[req.slot]
        if len(req.pages) < needed or not all(
                int(btab[i]) == req.pages[i] and req.pages[i] != 0
                for i in range(needed)):
            return self._export_refuse(
                f"request {rid}: block table does not cover its "
                f"committed prefix (eviction hole)")
        if not bool(self.cache.valid[req.slot, :committed].all()):
            return self._export_refuse(
                f"request {rid}: uncomputed committed columns")
        ids = np.zeros((geom.pages_per_slot,), np.int32)
        ids[:needed] = req.pages[:needed]
        with annotate("serve_kv_export"):
            k_payload, v_payload = self._export_kv(
                self.cache.k_pages, self.cache.v_pages, self._dev(ids))
        return MigrationTicket(
            rid=req.rid,
            prompt_tokens=list(req.prompt_tokens),
            max_new_tokens=req.max_new_tokens,
            generated=list(req.generated),
            generated_logprobs=list(req.generated_logprobs),
            sampling=req.sampling,
            arrival_time=req.arrival_time,
            deadline=req.deadline,
            priority=req.priority,
            committed_len=committed,
            page_size=self.cfg.page_size,
            n_pages=needed,
            k_payload=k_payload,
            v_payload=v_payload,
            admitted_time=req.admitted_time,
            first_token_time=req.first_token_time,
            last_token_time=req.last_token_time,
            tenant=req.tenant)

    def _export_refuse(self, msg: str):
        self._mig_stats["failed_migrations"] += 1
        raise MigrationError(msg)

    def import_request(self, ticket: MigrationTicket) -> Request:
        """Install a migrated request (the install half of the KV
        handoff): allocate pages, scatter the payload in ONE jitted
        fixed-shape call, register the committed FULL pages into the
        prefix cache (tail columns of a partial page stay private), and
        resume decode mid-stream — the request decodes on the very next
        engine step, bit-identically to never having moved.

        The source clocks ride the ticket, so TTFT is never re-recorded
        and the first post-handoff ITL sample honestly includes the
        handoff wait (also recorded on
        ``serving/migration/handoff_wait_ms``). Refuses geometry
        mismatches, window overflows, slot/page exhaustion
        (``MigrationError``, counted on failed_migrations) — the caller
        keeps the source copy running."""
        t_start = self.now()
        if ticket.page_size != self.cfg.page_size:
            return self._import_refuse(
                f"page_size mismatch: ticket {ticket.page_size}, "
                f"engine {self.cfg.page_size}")
        if not ticket.generated:
            return self._import_refuse(
                f"ticket {ticket.rid} carries no generated tokens")
        committed = len(ticket.prompt_tokens) + len(ticket.generated) - 1
        if committed != ticket.committed_len:
            return self._import_refuse(
                f"ticket {ticket.rid}: committed_len "
                f"{ticket.committed_len} != prefix-1 ({committed})")
        geom = self.cache.geom
        needed = geom.pages_for(committed)
        if ticket.n_pages != needed:
            return self._import_refuse(
                f"ticket {ticket.rid}: n_pages {ticket.n_pages} != "
                f"{needed} for {committed} committed columns")
        kshape = tuple(getattr(ticket.k_payload, "shape", ()))
        if len(kshape) < 2 or kshape[1] != geom.pages_per_slot:
            return self._import_refuse(
                f"ticket {ticket.rid}: payload geometry {kshape} does "
                f"not match pages_per_slot {geom.pages_per_slot}")
        if len(ticket.prompt_tokens) + ticket.max_new_tokens \
                > geom.slot_window:
            return self._import_refuse(
                f"ticket {ticket.rid} cannot fit the slot window "
                f"({geom.slot_window})")
        if not self.scheduler.free_slots \
                or self.scheduler._admission_headroom() == 0:
            return self._import_refuse(
                f"ticket {ticket.rid}: no free decode slot")
        if ticket.tenant is not None:
            try:
                self._check_tenant(ticket.tenant)
            except ValueError as e:
                # counted like any other refused install: the source
                # keeps the request, nothing decodes under wrong weights
                return self._import_refuse(
                    f"ticket {ticket.rid}: {e}")
        n_alloc = min(needed + self.cfg.decode_reserve_pages,
                      geom.pages_per_slot)
        pages = self.cache.allocator.alloc(n_alloc)
        if pages is None:
            return self._import_refuse(
                f"ticket {ticket.rid}: page pool cannot supply "
                f"{n_alloc} pages")
        ids = np.zeros((geom.pages_per_slot,), np.int32)
        ids[:needed] = pages[:needed]
        with annotate("serve_kv_import"):
            self.cache.k_pages, self.cache.v_pages = self._import_kv(
                self.cache.k_pages, self.cache.v_pages,
                ticket.k_payload, ticket.v_payload, self._dev(ids))
        req = Request(prompt_tokens=list(ticket.prompt_tokens),
                      max_new_tokens=int(ticket.max_new_tokens),
                      arrival_time=ticket.arrival_time,
                      priority=int(ticket.priority),
                      sampling=ticket.sampling,
                      tenant=ticket.tenant)
        req.rid = ticket.rid
        req.deadline = ticket.deadline
        req.generated = list(ticket.generated)
        req.generated_logprobs = list(ticket.generated_logprobs)
        req.admitted_time = ticket.admitted_time
        req.first_token_time = ticket.first_token_time
        req.last_token_time = ticket.last_token_time
        self._adopt_committed(req, pages, committed)
        if self.prefix_cache is not None:
            # index the committed FULL pages so later identical prompts
            # (and future migrations back) alias them; no logits entry —
            # the request resumes decode, there are no prefill logits
            self.prefix_cache.register(
                req.prefix_tokens[:committed], pages,
                namespace=req.tenant)
        self._results[req.rid] = req
        self._mig_stats["migrations"] += 1
        self._mig_stats["migrated_pages"] += needed
        if ticket.transport == "host":
            self._mig_stats["host_bounce_bytes"] += ticket.payload_bytes
        if ticket.last_token_time is not None:
            self.metrics.handoff_wait_ms.record(
                (t_start - ticket.last_token_time) * 1000.0)
        if self.tracer.enabled:
            self.tracer.async_begin(
                "request", "request", req.rid, t=req.arrival_time,
                prompt_tokens=len(req.prompt_tokens),
                max_new_tokens=req.max_new_tokens)
        return req

    def _import_refuse(self, msg: str):
        self._mig_stats["failed_migrations"] += 1
        raise MigrationError(msg)

    def release_migrated(self, rid: int) -> None:
        """Drop the SOURCE copy of a request that a target engine has
        successfully imported: free its slot and page references and
        forget it from the result surface (its live state — and final
        result — now belong to the target). Called only after the
        install committed, so the request exists on exactly one engine
        at every step boundary."""
        req = self._results.pop(rid, None)
        if req is None:
            return
        if req.state is RequestState.DECODE:
            self.scheduler.cancel(req, "migrated")
        if self.tracer.enabled:
            self.tracer.async_end("request", "request", req.rid,
                                  status="migrated",
                                  tokens=len(req.generated))

    def has_work(self) -> bool:
        return bool(self.scheduler.queue or self.scheduler.running
                    or self.scheduler.prefilling)

    # --------------------------------------------------------- engine step

    def step(self) -> List[Tuple[int, int]]:
        """One engine iteration: ensure pages for running requests (may
        preempt) -> admit into leftovers -> decode. Page growth runs
        first so in-flight requests outrank new admissions for the pool;
        a fresh admission always carries its decode reserve, so it never
        needs a page in the same step. Returns the (rid, token) pairs
        emitted this step, in slot order — the streaming surface."""
        self.profile.on_step(self.engine_steps)
        if self.xla_introspect_enabled:
            # stamp compile events from this step's dispatches
            self._decode.step = self.engine_steps
            self._prefill.step = self.engine_steps
            self._prefill_chunk.step = self.engine_steps
            if self._spec_k:
                self._spec_draft.step = self.engine_steps
                self._spec_verify.step = self.engine_steps
            self._export_kv.step = self.engine_steps
            self._import_kv.step = self.engine_steps
        emitted: List[Tuple[int, int]] = []
        # a speculative round may COMMIT up to K+1 columns per slot, so
        # page headroom / copy-on-write cover the whole write span
        span = self._spec_k + 1
        with step_annotation(self.engine_steps, name="serve"):
            self._poll_faults()
            self._expire(self.now())
            self._resilience_pass()
            for req in self.scheduler.ensure_decode_pages(span=span):
                self.metrics.preemptions.inc()
            if self.cfg.prefill_chunk:
                self._admit_chunked(emitted)
                self._chunk_step(emitted)
                # second page-safety pass: requests admitted ABOVE (via
                # cache hit or final chunk) decode THIS step, and their
                # first write may land in a shared/indexed tail page —
                # copy-on-write must run before the decode, not next step
                for req in self.scheduler.ensure_decode_pages(span=span):
                    self.metrics.preemptions.inc()
            else:
                self._admit(emitted)
                # same second pass for the one-shot prefill path: an
                # admission's decode reserve guarantees ONE column, but
                # a speculative round commits up to span columns in the
                # admission step itself — grow (or preempt) before the
                # round, or commits could advance past allocated pages
                if self._spec_k:
                    for req in self.scheduler.ensure_decode_pages(
                            span=span):
                        self.metrics.preemptions.inc()
            if self.scheduler.running:
                emitted.extend(self._spec_decode_step() if self._spec_k
                               else self._decode_step())
        self.engine_steps += 1
        self.readiness.beat()
        if self.anomaly is not None:
            self.anomaly.on_step(self.engine_steps)
        self._mirror_cache_counters()
        self._mirror_spec_counters()
        self._mirror_migration_counters()
        self._mirror_adapter_counters()
        m = self.metrics
        m.queue_depth.set(self.scheduler.queue_depth)
        m.active_requests.set(self.scheduler.active_count)
        m.page_occupancy.set(self.cache.allocator.occupancy)
        if self.slo is not None \
                and self.engine_steps % self._slo_every == 0:
            self.slo.observe(m.snapshot(), step=self.engine_steps)
        if self.tenants is not None \
                and self.engine_steps % self._slo_every == 0:
            # per-tenant burn over each tenant's OWN panel; any tenant
            # past the (opt-in) burn threshold sheds ONLY its own queue
            self.tenants.observe(step=self.engine_steps)
            for victim in self.tenants.shed_pass(self.scheduler):
                self._shed(victim, at="tenant_slo")
        return emitted

    def run_until_drained(self, max_steps: int = 100000,
                          on_cap: str = "raise") -> Dict[int, Request]:
        """Step until ``has_work()`` is false. Hitting ``max_steps`` with
        work still in flight is a wedge, and the two dispositions are
        both terminal — a drain NEVER silently returns live requests:

        - ``on_cap="raise"`` (default): RuntimeError, matching the
          Supervisor's run cap.
        - ``on_cap="shed"``: resolve every straggler as SHED with a
          ``drain_cap`` flight-recorder event and return normally — the
          fleet scale-down path, where the caller must reclaim the
          engine but may not leak a request without a terminal status.
        """
        try:
            for _ in range(max_steps):
                if not self.has_work():
                    return dict(self._results)
                self.step()
        finally:
            # an open trace window must flush even on an early exit
            self.profile.close()
        if on_cap == "shed":
            self._shed_stragglers()
            return dict(self._results)
        raise RuntimeError(f"serving loop did not drain in {max_steps} steps")

    def _shed_stragglers(self) -> None:
        """Terminal SHED for every request still queued or in flight —
        the drain-cap escape hatch. Running/prefilling work gives its
        slot and pages back through the scheduler's cancel path, so the
        engine is fully reclaimable afterwards."""
        stragglers = (list(self.scheduler.queue)
                      + list(self.scheduler.running.values())
                      + list(self.scheduler.prefilling.values()))
        self.recorder.record("drain_cap", step=self.engine_steps,
                             stragglers=len(stragglers))
        for req in stragglers:
            self.scheduler.cancel(req, "shed", RequestState.SHED)
            self.metrics.requests_shed.inc()
            self.recorder.record("request_shed", step=self.engine_steps,
                                 rid=req.rid, priority=req.priority,
                                 at="drain_cap")
            if self.tracer.enabled:
                self.tracer.async_end("request", "request", req.rid,
                                      status="shed",
                                      tokens=len(req.generated))

    # -------------------------------------------------------- observability

    def start_metrics_server(self, port: int = 0) -> MetricsHTTPServer:
        """Expose this engine's registry at ``GET /metrics`` (Prometheus
        text format) on a background thread; idempotent. ``port=0``
        binds an ephemeral port — read it back from ``.port``."""
        if self.metrics_server is None:
            self.metrics_server = MetricsHTTPServer(
                self.metrics.registry, port=port,
                readiness=self.readiness)
        return self.metrics_server

    def close(self) -> None:
        """Release host-side resources (trace window, host tracer,
        metrics endpoint). Device state is dropped with the object as
        usual."""
        self.profile.close()
        if self.anomaly is not None:
            self.anomaly.close()
        if self._installed_tracer:
            self.tracer.dump()
            install_tracer(None)     # don't leak into the next engine
            self._installed_tracer = False
        if self.metrics_server is not None:
            self.metrics_server.stop()
            self.metrics_server = None

    # ------------------------------------------------------ graceful drain

    def begin_drain(self) -> None:
        """Stop admission and shed work that never started: queued
        requests with no generated tokens are cancelled; evicted
        in-flight requests (they hold generated tokens and sunk compute)
        stay queued for re-admission, and running decodes run to
        completion. Safe to call from a signal handler's flag path —
        it only mutates host state."""
        if self._draining:
            return
        self._draining = True
        # /healthz answers 503 body "draining" from here on (load
        # balancers stop routing before admission starts rejecting);
        # the tripped-circuit-breaker path flips the same switch
        self.readiness.set_draining("draining")
        for req in [r for r in self.scheduler.queue if not r.generated]:
            self.scheduler.cancel(req, "cancelled")
            self.metrics.requests_cancelled.inc()
            if self.tracer.enabled:
                self.tracer.async_end("request", "request", req.rid,
                                      status="cancelled", tokens=0)

    @property
    def draining(self) -> bool:
        return self._draining

    def install_drain_handler(self) -> None:
        """SIGTERM -> begin_drain(): the serving analog of the trainer's
        preemption handling. The engine loop keeps stepping until
        ``has_work()`` is false, then the caller flushes metrics and
        exits — in-flight decodes finish, nothing is dropped mid-token."""
        from dla_tpu.resilience.preemption import install_sigterm_flag
        self._old_handlers = install_sigterm_flag(self.begin_drain)

    def drain(self, logger=None, max_steps: int = 100000,
              on_cap: str = "raise") -> Dict[int, Request]:
        """Begin (or continue) a drain, run it to empty, flush metrics.
        ``on_cap`` picks the straggler disposition at the step cap (see
        ``run_until_drained``); either way no request is left without a
        terminal status."""
        self.begin_drain()
        results = self.run_until_drained(max_steps, on_cap=on_cap)
        self.metrics.report(logger, self.metrics.decode_steps.value)
        return results

    def _expire(self, now: float) -> None:
        """Finish every queued or running request past its deadline with
        TIMEOUT status. Queued requests simply leave the queue; a running
        one gives its slot and pages back, so the timeout of a stuck-long
        request is itself a backpressure release valve."""
        for req in self.scheduler.expired(now):
            self.scheduler.cancel(req, "timeout", RequestState.TIMEOUT)
            self.metrics.requests_timed_out.inc()
            if req.admitted_time is None:
                # expired straight out of the queue, never admitted:
                # queue wait alone blew the deadline — the admission-
                # pressure signal, distinct from slow decode
                self.metrics.queue_timeouts.inc()
            if self.tracer.enabled:
                self.tracer.async_end(
                    "request", "request", req.rid, t=now,
                    status="timeout", tokens=len(req.generated))

    # ----------------------------------------------------------- resilience

    def _shed(self, req: Request, at: str = "queue") -> None:
        """Terminal SHED for one queued request: cancel out of the
        queue, count, record, close the trace span. Only never-started
        requests are ever shed (``sheddable_queued`` guarantees it), so
        beyond the scheduler's cancel path there is no slot or page
        state to unwind."""
        self.scheduler.cancel(req, "shed", RequestState.SHED)
        self.metrics.requests_shed.inc()
        if self.tenants is not None and req.tenant is not None:
            self.tenants.on_shed(req.tenant)
        self.recorder.record("request_shed", step=self.engine_steps,
                             rid=req.rid, priority=req.priority, at=at,
                             tenant=req.tenant)
        if self.tracer.enabled:
            self.tracer.async_end("request", "request", req.rid,
                                  status="shed", tokens=0)

    def _resilience_pass(self) -> None:
        """Once per step, after deadline expiry and before scheduling:
        feed the pressure signal (max of page occupancy and queue-depth
        fraction) to the degradation ladder, apply its rungs, and run
        the SLO-aware shed pass over the queue."""
        if self.admission is None:
            return
        shed_cfg = self.admission.cfg
        qfrac = self.scheduler.queue_depth / max(1,
                                                 shed_cfg.max_queue_depth)
        pressure = max(self.cache.allocator.occupancy, min(1.0, qfrac))
        prev = self._applied_level
        level = self.ladder.update(pressure, step=self.engine_steps)
        self.metrics.degradation_level.set(level)
        if level != prev:
            if prev == 0 and level >= 1 and self.prefix_cache is not None:
                # rung 1 entry: give cached-but-unreferenced prefix
                # pages back to the free pool (throughput optimization
                # goes first, requests go last)
                n_pages = self.cache.allocator.reclaim_cached()
                self.recorder.record("degradation_cache_flush",
                                     step=self.engine_steps,
                                     pages=n_pages)
            self._applied_level = level
        # rung 3: halve the concurrent-request ceiling so queue wait
        # trades against decode interference under pressure
        self.scheduler.max_active = (
            None if not self.ladder.shrink_batch
            else max(1, self.cfg.num_slots // 2))
        burn = 0.0
        if self.slo is not None:
            for objective in self.slo.slos:
                rate = self.slo.burn_rate(objective)
                if rate > burn:
                    burn = rate
        for victim in self.admission.shed_pass(self.scheduler, burn,
                                               level):
            self._shed(victim, at="slo" if burn else "ladder")

    def _poll_faults(self) -> None:
        """Fire any serving-scoped (``engine_step=``) fault-plan entries
        due this step. ``wedge`` sleeps right here — inside the step, so
        a supervising watchdog sees it; ``device_error``/``nan_logits``
        arm a flag the next decode dispatch consumes. ``burst`` is the
        Supervisor's to consume (it owns intake); the engine ignores
        it."""
        if not self.faults:
            return
        f = self.faults.take("wedge", self.engine_steps,
                             site="engine_step")
        if f is not None:
            self.recorder.record("fault_injected", step=self.engine_steps,
                                 fault="wedge")
            time.sleep(0.3 if f.arg is None else f.arg)
        f = self.faults.take("device_error", self.engine_steps,
                             site="engine_step")
        if f is not None:
            self.recorder.record("fault_injected", step=self.engine_steps,
                                 fault="device_error")
            self._fault_device_error = True
        f = self.faults.take("nan_logits", self.engine_steps,
                             site="engine_step")
        if f is not None:
            self.recorder.record("fault_injected", step=self.engine_steps,
                                 fault="nan_logits")
            self._fault_nan_logits = True

    # ------------------------------------------------------------ internals

    def _effective_sampling(self, req: Request) -> SamplingParams:
        """The request's sampling knobs: its explicit override, or the
        engine-global gen.* defaults with a (engine seed, rid)-derived
        seed — deterministic across restarts since restore() preserves
        rids."""
        if req.sampling is not None:
            return req.sampling
        return SamplingParams.from_gen(
            self.gen, derive_request_seed(self.cfg.seed, req.rid))

    def _bind_slot_sampling(self, req: Request) -> None:
        """Mirror the request's sampling knobs into its slot's row of the
        per-slot arrays the decode step ships to device."""
        sp = self._effective_sampling(req)
        s = req.slot
        self.samp_temp[s] = sp.effective_temperature
        self.samp_top_p[s] = sp.top_p
        self.samp_top_k[s] = sp.top_k
        self.samp_seed[s] = np.uint32(sp.seed & 0xFFFFFFFF)

    def _bind_adapter(self, req: Request) -> None:
        """Pin the request's tenant adapter for its freshly assigned
        slot and mirror the pool row into ``adapter_idx`` (row 0 — the
        zero identity — for base requests, and always rewritten so a
        reused slot never inherits the previous tenant's adapter).
        Called exactly once per slot assignment, BEFORE the slot's first
        dispatch; the paired release rides the scheduler's
        ``release_hook``, so every release path (finish, evict, cancel,
        shed, drain) unpins it. Load-on-admission lives here: acquire
        reloads a spilled adapter from its host copy."""
        if self.adapter_store is None or req.slot is None:
            return
        idx = 0
        if req.tenant is not None and self.adapter_store.has(req.tenant):
            idx = self.adapter_store.acquire(req.tenant)
            self._bound_tenants[req.rid] = req.tenant
        self.adapter_idx[req.slot] = idx

    def _release_adapter(self, req: Request) -> None:
        """Scheduler release hook: unpin whatever _bind_adapter acquired
        for this request (a no-op for base requests — the _bound_tenants
        record keeps acquire/release exactly paired even if an adapter
        appears for the tenant mid-flight)."""
        tenant = self._bound_tenants.pop(req.rid, None)
        if tenant is not None:
            self.adapter_store.release(tenant)

    def _adapters_args(self, rows=None):
        """The gathered-adapter argument for one jitted dispatch: the
        per-slot pool rows (every slot, or ``rows`` for a single-slot
        prefill chunk) plus the stacked A/B pools. None when tenancy is
        off — an empty pytree, so the dispatch signature and jit
        fingerprint are byte-identical to an adapter-free build."""
        if self.adapter_store is None:
            return None
        idx = (self.adapter_idx if rows is None
               else self.adapter_idx[rows])
        return {"idx": self._dev(idx), **self.adapter_store.pools}

    def _mirror_adapter_counters(self) -> None:
        """Delta-mirror the AdapterStore's plain-int counters into the
        registry (the prefix-cache/speculative mirror contract: a fresh
        ServingMetrics swap sees only post-swap activity; the Supervisor
        re-seeds cumulative totals into rebuilt engines)."""
        st = self.adapter_store
        if st is None:
            return
        m, seen = self.metrics, self._adapter_mirrored
        m.adapter_publishes.inc(st.publishes - seen["publishes"])
        m.adapter_loads.inc(st.loads - seen["loads"])
        m.adapter_spills.inc(st.spills - seen["spills"])
        seen.update(publishes=st.publishes, loads=st.loads,
                    spills=st.spills)
        m.adapter_resident.set(st.resident_count)

    def _admit(self, emitted: List[Tuple[int, int]]) -> None:
        """Drain as many bucketed prefill batches as slots/pages allow."""
        while True:
            batch = self.scheduler.next_prefill_batch()
            if not batch:
                return
            self._run_prefill(batch, emitted)

    def _run_prefill(self, batch: List[Request],
                     emitted: List[Tuple[int, int]]) -> None:
        geom = self.cache.geom
        ps, pb = self.cfg.page_size, self.cfg.max_prefill_batch
        width = self.scheduler.bucket_width(len(batch[0].prefix_tokens))
        n_prompt_pages = geom.pages_for(width)
        ids = np.zeros((pb, width), np.int32)
        mask = np.zeros((pb, width), np.int32)
        page_rows = np.zeros((pb, n_prompt_pages), np.int32)
        for i, req in enumerate(batch):
            toks = req.prefix_tokens
            ids[i, :len(toks)] = toks
            mask[i, :len(toks)] = 1
            page_rows[i] = req.pages[:n_prompt_pages]
        for i in range(len(batch), pb):
            mask[i, 0] = 1   # dummy rows: one valid token, trash pages
        with annotate("serve_prefill"):
            self.cache.k_pages, self.cache.v_pages, logits = self._prefill(
                self.params, self.cache.k_pages, self.cache.v_pages,
                jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(page_rows))
            # dla: disable=host-sync-in-hot-loop -- designed prefill D2H: one logits fetch per admitted batch, not per token
            logits_np = np.asarray(logits)
        t_done = self.now()
        self.metrics.prefill_batches.inc()
        first, first_lps = self._sample_host(logits_np[:len(batch)], batch)
        for i, req in enumerate(batch):
            tok = int(first[i])
            if req.admitted_time is None:
                # queue wait = arrival -> first admission (re-prefills
                # after eviction are decode-path stalls, not queue time)
                req.admitted_time = t_done
                self.metrics.queue_wait_ms.record(
                    (t_done - req.arrival_time) * 1000.0)
                if self.tracer.enabled:
                    self.tracer.async_instant(
                        "request", "admitted", req.rid, t=t_done,
                        queue_wait_ms=(t_done - req.arrival_time)
                        * 1000.0)
            self.cache.open_slot(req.slot, req.pages,
                                 len(req.prefix_tokens), width, tok)
            self.scheduler.activate(req)
            self._bind_slot_sampling(req)
            self._emit(req, tok, t_done, emitted, first_of_prefill=True,
                       logp=float(first_lps[i]))  # dla: disable=host-sync-in-hot-loop -- host numpy scalar; rode the prefill batch fetch above

    def _admit_chunked(self, emitted: List[Tuple[int, int]]) -> None:
        """Strict-FCFS chunked admission. Exact-full-prompt cache hits
        skip prefill entirely (stored logits -> first token now) and
        keep admitting behind them; a partial admission occupies the
        single mid-prefill seat and stops the loop."""
        while True:
            req = self.scheduler.admit_chunk_prefill()
            if req is None:
                return
            # adapter rides every chunk of the prefill, so it binds at
            # slot assignment — before the first chunk dispatch, not at
            # activation (this is also where a cold adapter loads)
            self._bind_adapter(req)
            t = self.now()
            if req.admitted_time is None:
                req.admitted_time = t
                self.metrics.queue_wait_ms.record(
                    (t - req.arrival_time) * 1000.0)
                if self.tracer.enabled:
                    self.tracer.async_instant(
                        "request", "admitted", req.rid, t=t,
                        queue_wait_ms=(t - req.arrival_time) * 1000.0)
            n = len(req.prefix_tokens)
            self.metrics.prefill_tokens_saved.inc(req.prefill_pos)
            if req.prefill_pos >= n:
                # full hit: every prompt page aliased, first-token
                # logits served from the cache — zero prefill FLOPs
                # dla: disable=host-sync-in-hot-loop -- cached_logits is already host numpy (stored by register); no device fetch happens
                logits_row = np.asarray(req.cached_logits)[None, :]
                toks, lps = self._sample_host(logits_row, [req])
                tok = int(toks[0])
                req.cached_logits = None
                self.cache.begin_decode(req.slot, n, tok)
                self.scheduler.activate(req)
                self._bind_slot_sampling(req)
                self._emit(req, tok, t, emitted, first_of_prefill=True,
                           logp=float(lps[0]))  # dla: disable=host-sync-in-hot-loop -- host numpy scalar from the cached-logits sample

    def _chunk_step(self, emitted: List[Tuple[int, int]]) -> None:
        """Advance the (single) mid-prefill request by one fixed-shape
        chunk, co-scheduled with the running decode batch under the
        token budget. Only the FINAL chunk's logits cross device->host
        (the decode step's single-D2H discipline extends to prefill)."""
        sched = self.scheduler
        if not sched.prefilling:
            return
        if self.ladder is not None and self.ladder.no_coschedule \
                and sched.running:
            # degradation rung 2: never co-schedule a chunk with a live
            # decode batch. Same no-livelock shape as the budget below —
            # with nothing decoding the chunk always runs.
            return
        budget = self.cfg.prefill_token_budget
        if budget and sched.running and \
                len(sched.running) + self.cfg.prefill_chunk > budget:
            # decode batch fills the budget: the chunk waits a step.
            # With no running decodes the chunk ALWAYS runs, so an
            # undersized budget can't livelock prefill.
            return
        slot, req = next(iter(sched.prefilling.items()))
        prefix = req.prefix_tokens
        n = len(prefix)
        start = req.prefill_pos
        nvalid = min(self.cfg.prefill_chunk, n - start)
        ids = np.zeros((1, self.cfg.prefill_chunk), np.int32)
        ids[0, :nvalid] = prefix[start:start + nvalid]
        c = self.cache
        with annotate("serve_prefill_chunk"):
            c.k_pages, c.v_pages, logits = self._prefill_chunk(
                self.params, c.k_pages, c.v_pages,
                self._dev(c.block_tables[slot:slot + 1]),
                self._dev(c.valid[slot:slot + 1]),
                self._dev(c.pos[slot:slot + 1]),
                jnp.asarray(ids),
                jnp.asarray(start, jnp.int32),
                jnp.asarray(nvalid, jnp.int32),
                self._adapters_args(slice(slot, slot + 1)))
        self.metrics.prefill_chunks.inc()
        c.mark_computed(slot, start, nvalid)
        req.prefill_pos = start + nvalid
        if req.prefill_pos < n:
            return
        # dla: disable=host-sync-in-hot-loop -- designed prefill D2H: one logits fetch per REQUEST (final chunk only), not per chunk
        logits_np = np.asarray(logits)
        t_done = self.now()
        self.metrics.prefill_batches.inc()
        toks, lps = self._sample_host(logits_np, [req])
        tok = int(toks[0])
        self.cache.begin_decode(slot, n, tok)
        if self.prefix_cache is not None:
            # first-writer-wins: later identical prompts alias these
            # pages; the stored logits make the NEXT identical prompt a
            # zero-prefill full hit
            self.prefix_cache.register(prefix, req.pages, logits_np[0],
                                       namespace=req.tenant)
        self.scheduler.activate(req)
        self._bind_slot_sampling(req)
        self._emit(req, tok, t_done, emitted, first_of_prefill=True,
                   logp=float(lps[0]))  # dla: disable=host-sync-in-hot-loop -- host numpy scalar; rode the final-chunk logits fetch

    def _mirror_cache_counters(self) -> None:
        """Mirror the PrefixCache's plain-int counters into the metrics
        registry, delta-based with engine-side marks — so a harness that
        swaps in a fresh ServingMetrics (eval_latency does, to shed
        warmup) sees only post-swap activity."""
        pc = self.prefix_cache
        if pc is None:
            return
        m, seen = self.metrics, self._pc_mirrored
        m.prefix_lookups.inc(pc.lookups - seen["lookups"])
        m.prefix_hit_tokens.inc(pc.hit_tokens - seen["hit_tokens"])
        m.prefix_evictions.inc(pc.evictions - seen["evictions"])
        seen.update(lookups=pc.lookups, hit_tokens=pc.hit_tokens,
                    evictions=pc.evictions)

    def _sample_host(self, logits: np.ndarray, reqs: List[Request]):
        """Sample each request's next token from its prefill logits row —
        the EXACT per-row rule the decode step runs (same fold_in(seed,
        token-index) keying, same filters), eager jax once per prefill
        batch, off the hot loop. The token index is len(generated), so
        an eviction/replay re-prefill resumes the same stream. Returns
        (tokens, logps) host arrays."""
        if np.isnan(logits).any():
            # real detection on the only logits the host ever sees: the
            # serving analog of the trainer's NaN guard. The supervisor
            # turns this into a rebuild-and-replay.
            raise NaNLogitsError("non-finite prefill logits")
        sps = [self._effective_sampling(r) for r in reqs]
        # python-list -> numpy marshalling of per-request sampling
        # params (host-only, no device fetch on these lines)
        seeds = np.array([sp.seed & 0xFFFFFFFF for sp in sps], np.uint32)  # dla: disable=host-sync-in-hot-loop -- host list->numpy marshalling, no device fetch
        gpos = np.array([len(r.generated) for r in reqs], np.int32)  # dla: disable=host-sync-in-hot-loop -- host list->numpy marshalling, no device fetch
        temps = np.array([sp.effective_temperature for sp in sps], np.float32)  # dla: disable=host-sync-in-hot-loop -- host list->numpy marshalling, no device fetch
        top_ps = np.array([sp.top_p for sp in sps], np.float32)  # dla: disable=host-sync-in-hot-loop -- host list->numpy marshalling, no device fetch
        top_ks = np.array([sp.top_k for sp in sps], np.int32)  # dla: disable=host-sync-in-hot-loop -- host list->numpy marshalling, no device fetch
        toks, lps = sample_token_per_row(
            jnp.asarray(seeds), jnp.asarray(gpos), jnp.asarray(logits),
            jnp.asarray(temps), jnp.asarray(top_ps), jnp.asarray(top_ks))
        # dla: disable=host-sync-in-hot-loop -- prefill sample fetch: one D2H per admitted batch
        return np.asarray(toks), np.asarray(lps)

    def _decode_step(self) -> List[Tuple[int, int]]:
        c = self.cache
        active_slots = sorted(self.scheduler.running)
        active = np.zeros((c.geom.num_slots,), bool)
        active[active_slots] = True
        for slot in active_slots:
            # the PRNG position of the token this step samples: the
            # request's generated-token index (re-binds every step so
            # evicted/re-admitted requests resume their stream exactly)
            self.gen_pos[slot] = len(self.scheduler.running[slot].generated)
        if self._fault_device_error:
            # injected BEFORE dispatch: no KV column was written, no
            # token sampled — exactly the state a real dispatch failure
            # leaves behind, so supervisor replay recomputes cleanly
            self._fault_device_error = False
            raise DeviceStepError(
                "injected device error (fault plan engine_step)")
        with annotate("serve_decode"):
            self.cache.k_pages, self.cache.v_pages, packed = self._decode(
                self.params, c.k_pages, c.v_pages,
                self._dev(c.block_tables), self._dev(c.valid),
                self._dev(c.pos), self._dev(c.lengths),
                self._dev(c.tokens), jnp.asarray(active),
                self._dev(self.samp_temp), self._dev(self.samp_top_p),
                self._dev(self.samp_top_k), self._dev(self.samp_seed),
                self._dev(self.gen_pos), self._adapters_args())
            # dla: disable=host-sync-in-hot-loop -- the designed single D2H per decode step (execution-model invariant)
            packed_np = np.asarray(packed)
        toks_np = packed_np[0].view(np.int32)
        logps_np = packed_np[1]
        if self._fault_nan_logits:
            # injected AFTER the fetch, where the real NaN guard below
            # (_sample_host) and a device-side check would trip: the
            # sampled tokens are garbage, so nothing is committed
            self._fault_nan_logits = False
            raise NaNLogitsError(
                "injected non-finite logits (fault plan engine_step)")
        t_done = self.now()
        self.metrics.decode_steps.inc()
        emitted: List[Tuple[int, int]] = []
        for slot in active_slots:
            req = self.scheduler.running[slot]
            tok = int(toks_np[slot])
            c.advance_slot(slot, tok)
            self._emit(req, tok, t_done, emitted,
                       logp=float(logps_np[slot]))  # dla: disable=host-sync-in-hot-loop -- host numpy scalar; rode the packed decode fetch
        return emitted

    def _spec_decode_step(self) -> List[Tuple[int, int]]:
        """One speculative ROUND for the whole decode batch: draft
        dispatch -> verify dispatch -> one packed D2H -> per-slot
        variable commit. The host metadata (valid/pos/lengths/tokens
        mirrors) is authoritative and only ever advances by the ACCEPTED
        prefix — rejected draft columns exist solely in device pages
        that the next round's verify overwrites, so rollback is a no-op
        and an eviction/replay re-prefill never sees speculative
        residue. Both dispatches read the same host-metadata snapshot;
        the draft extends its own traced copy of ``valid``/``pos`` while
        the verify attends committed-only (draft keys arrive via the
        in-block causal term instead)."""
        c = self.cache
        k = self._spec_k
        active_slots = sorted(self.scheduler.running)
        active = np.zeros((c.geom.num_slots,), bool)
        active[active_slots] = True
        for slot in active_slots:
            # the PRNG position of the FIRST token this round samples:
            # the request's generated-token index (re-binds every round
            # so evicted/re-admitted requests resume their stream, and
            # in-round positions advance as gen_pos + i in-graph)
            self.gen_pos[slot] = len(self.scheduler.running[slot].generated)
        if self._fault_device_error:
            # injected BEFORE dispatch: no KV column written, no token
            # sampled — the state a real dispatch failure leaves behind
            self._fault_device_error = False
            raise DeviceStepError(
                "injected device error (fault plan engine_step)")
        with annotate("serve_spec_decode"):
            btab = self._dev(c.block_tables)
            valid = self._dev(c.valid)
            pos = self._dev(c.pos)
            lengths = self._dev(c.lengths)
            tokens = self._dev(c.tokens)
            active_d = jnp.asarray(active)
            temps = self._dev(self.samp_temp)
            top_ps = self._dev(self.samp_top_p)
            top_ks = self._dev(self.samp_top_k)
            seeds = self._dev(self.samp_seed)
            gpos = self._dev(self.gen_pos)
            # draft and verify share one adapter view: the draft
            # proposes under the SAME per-slot deltas the target
            # verifies with, so per-tenant acceptance stays high
            adapters = self._adapters_args()
            c.k_pages, c.v_pages, proposals = self._spec_draft(
                self.draft_params, c.k_pages, c.v_pages, btab, valid,
                pos, lengths, tokens, active_d, temps, top_ps, top_ks,
                seeds, gpos, adapters)
            c.k_pages, c.v_pages, packed = self._spec_verify(
                self.params, c.k_pages, c.v_pages, btab, valid, pos,
                lengths, tokens, proposals, active_d, temps, top_ps,
                top_ks, seeds, gpos, adapters)
            # dla: disable=host-sync-in-hot-loop -- the designed single D2H per speculative round (proposals never leave the device)
            packed_np = np.asarray(packed)
        toks_np = packed_np[0].view(np.int32)         # [B, K+1]
        logps_np = packed_np[1]
        acc_np = packed_np[2].view(np.int32)[:, 0]    # [B] accepts 0..K
        if self._fault_nan_logits:
            # injected AFTER the fetch, where a real device-side NaN
            # would surface: nothing was committed, replay is clean
            self._fault_nan_logits = False
            raise NaNLogitsError(
                "injected non-finite logits (fault plan engine_step)")
        t_done = self.now()
        self.metrics.decode_steps.inc()
        emitted: List[Tuple[int, int]] = []
        for slot in active_slots:
            req = self.scheduler.running[slot]
            a = int(acc_np[slot])
            self._spec_stats["rounds"] += 1
            self._spec_stats["proposed"] += k
            self._spec_stats["accepted"] += a
            if a < k:
                self._spec_stats["rollbacks"] += 1
            # commit the accepted prefix: a+1 target samples (column
            # lengths+j holds block token j's target KV; the emitted
            # token becomes the next pending). EOS/length may finish
            # the request mid-block — the tail accepts are dropped,
            # exactly as the non-speculative engine would never have
            # sampled past the terminal token.
            for j in range(a + 1):
                tok = int(toks_np[slot, j])
                c.advance_slot(slot, tok)
                self._emit(req, tok, t_done, emitted,
                           logp=float(logps_np[slot, j]))  # dla: disable=host-sync-in-hot-loop -- host numpy scalar; rode the packed round fetch
                if self.scheduler.running.get(slot) is not req:
                    break
        return emitted

    def _mirror_spec_counters(self) -> None:
        """Delta-mirror the speculative round stats into the registry
        (same contract as the prefix-cache mirror: a fresh
        ServingMetrics swap sees only post-swap activity; the Supervisor
        re-seeds cumulative totals into rebuilt engines)."""
        if not self._spec_k:
            return
        m, s, seen = self.metrics, self._spec_stats, self._spec_mirrored
        m.spec_rounds.inc(s["rounds"] - seen["rounds"])
        m.spec_proposed.inc(s["proposed"] - seen["proposed"])
        m.spec_accepted.inc(s["accepted"] - seen["accepted"])
        m.spec_rollbacks.inc(s["rollbacks"] - seen["rollbacks"])
        seen.update(s)
        if m.spec_proposed.value > 0:
            m.spec_acceptance_rate.set(
                m.spec_accepted.value / m.spec_proposed.value)

    def _mirror_migration_counters(self) -> None:
        """Delta-mirror the KV migration stats into the registry (the
        prefix-cache/speculative mirror contract: a fresh ServingMetrics
        swap sees only post-swap activity; the Supervisor re-seeds
        cumulative totals into rebuilt engines so the counters stay
        monotone across restarts)."""
        m, s, seen = self.metrics, self._mig_stats, self._mig_mirrored
        m.migrations.inc(s["migrations"] - seen["migrations"])
        m.migrated_pages.inc(
            s["migrated_pages"] - seen["migrated_pages"])
        m.host_bounce_bytes.inc(
            s["host_bounce_bytes"] - seen["host_bounce_bytes"])
        m.failed_migrations.inc(
            s["failed_migrations"] - seen["failed_migrations"])
        seen.update(s)

    def _emit(self, req: Request, tok: int, t: float,
              emitted: List[Tuple[int, int]],
              first_of_prefill: bool = False,
              logp: float = 0.0) -> None:
        """Record one generated token: stream it, time it, finish the
        request on EOS or length. ``logp`` is the token's chosen-token
        logprob (raw model distribution), kept parallel to
        ``generated`` on the request's result surface."""
        req.generated.append(tok)
        req.generated_logprobs.append(float(logp))  # dla: disable=host-sync-in-hot-loop -- float coercion of an already-host scalar
        emitted.append((req.rid, tok))
        self.metrics.tokens_generated.inc()
        # per-tenant panel: same samples as the engine-wide instruments,
        # attributed — the surface the tenant SLO watches burn against
        ten = (self.tenants if req.tenant is not None else None)
        if ten is not None:
            ten.on_token(req.tenant)
        traced = self.tracer.enabled
        if req.first_token_time is None:
            req.first_token_time = t
            self.metrics.ttft_ms.record((t - req.arrival_time) * 1000.0)
            if ten is not None:
                ten.on_ttft(req.tenant, (t - req.arrival_time) * 1000.0)
            if traced:
                self.tracer.async_instant(
                    "request", "first_token", req.rid, t=t,
                    ttft_ms=(t - req.arrival_time) * 1000.0)
        elif not first_of_prefill and req.last_token_time is not None:
            # inter-token latency only between consecutive decode steps
            # (a re-prefill after eviction restarts the clock)
            itl_ms = (t - req.last_token_time) * 1000.0
            self.metrics.itl_ms.record(itl_ms)
            if ten is not None:
                ten.on_itl(req.tenant, itl_ms)
            if self.anomaly is not None:
                self.anomaly.observe("itl_ms", itl_ms, self.engine_steps)
            if traced:
                self.tracer.async_instant(
                    "request", "decode", req.rid, t=t,
                    n=len(req.generated),
                    itl_ms=(t - req.last_token_time) * 1000.0)
        req.last_token_time = t
        eos = self.gen.eos_token_id
        status = None
        if eos is not None and eos >= 0 and tok == eos:
            self.scheduler.finish(req, "eos")
            self.metrics.requests_finished.inc()
            status = "eos"
        elif len(req.generated) >= req.max_new_tokens:
            self.scheduler.finish(req, "length")
            self.metrics.requests_finished.inc()
            status = "length"
        if ten is not None and status is not None:
            ten.on_finish(req.tenant)
        if traced and status is not None:
            self.tracer.async_end("request", "request", req.rid, t=t,
                                  status=status,
                                  tokens=len(req.generated))
