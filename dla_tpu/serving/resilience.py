"""Serving resilience: admission control + load shedding, the graceful
degradation ladder, and the engine Supervisor with deterministic
request replay.

Production serving treats overload and crash recovery as first-class:
one bad request, one device error, or one burst must never wedge the
engine or silently drop work. This module composes two primitives the
stack already has — the deterministic-recompute contract of eviction
(``Request.prefix_tokens``: a greedy re-prefill of prompt + generated
reproduces the continuation bit-identically) and the ``DLA_FAULT_PLAN``
injection harness — into a self-healing layer:

**Admission control / shedding** (:class:`AdmissionController`): a
token-bucket + bounded-wait-queue gate consulted by
``ServingEngine.submit``, plus a per-step SLO-aware shed pass that
drops the lowest-priority queued requests (terminal ``SHED`` status)
when the :mod:`~dla_tpu.telemetry.slo` burn rate says queue wait would
only blow their deadlines. Only never-started requests are sheddable;
in-flight work (including evicted requests holding generated tokens)
is never dropped.

**Degradation ladder** (:class:`DegradationLadder`): under sustained
pressure the engine gives up throughput optimizations before it gives
up requests — rung 1 flushes prefix-cache pages, rung 2 stops
co-scheduling prefill chunks with decode, rung 3 halves the admission
batch, rung 4 sheds. Every rung change is a flight-recorder event and
moves the ``serving/degradation_level`` gauge.

**Supervision** (:class:`Supervisor`): wraps ``ServingEngine.step``
with a Watchdog (armed only *inside* the step — idle gaps between
open-loop arrivals are not hangs), catches device errors and NaN
logits, then tears the engine down, rebuilds it via the caller's
factory, and replays every in-flight request from its journaled prompt
+ streamed tokens. Replay reuses the eviction recompute path, so
already-streamed tokens are never re-emitted and greedy outputs stay
bit-identical to a fault-free run. Restarts are bounded by a
:class:`CircuitBreaker`; when it trips, ``/healthz`` flips to 503
(body ``draining``) and the engine drains.

Everything here is host-side Python — no jitted code, no device state
of its own — so the whole ladder is CPU-testable through the
``engine_step=`` fault-plan grammar (see resilience/faults.py).
"""
from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from dla_tpu.resilience.watchdog import Watchdog
from dla_tpu.serving.scheduler import (
    Request,
    RequestState,
    TERMINAL_STATES,
)


class DeviceStepError(RuntimeError):
    """A jitted serving step failed at the device/runtime layer (the
    CPU-testable stand-in for XlaRuntimeError and friends, raised by
    ``engine_step=N:device_error`` injection)."""


class NaNLogitsError(RuntimeError):
    """Non-finite logits came back from the model — the serving analog
    of the trainer's NaN-guard trip. Raised by real detection on the
    host-visible prefill logits and by ``engine_step=N:nan_logits``
    injection on the decode path."""


# ----------------------------------------------------------------- shedding


@dataclasses.dataclass(frozen=True)
class ShedConfig:
    """Admission-control + degradation policy (the serving ``shed:``
    config block; ``ShedSchema`` in training/config.py mirrors it)."""
    max_queue_depth: int = 64      # bounded wait queue (excess sheds)
    rate: float = 0.0              # token-bucket refill, requests/s; 0 = off
    burst: int = 0                 # bucket capacity; 0 -> max_queue_depth
    slo_burn_threshold: float = 1.0  # shed queued work at/above this burn
    # degradation ladder hysteresis: escalate after `patience` steps at
    # or above `high` pressure, de-escalate after `patience` below `low`
    degrade_high: float = 0.85
    degrade_low: float = 0.5
    degrade_patience: int = 3

    @classmethod
    def from_config(cls, cfg: Optional[Dict]) -> Optional["ShedConfig"]:
        """Build from a config dict; None (or ``enabled: false``)
        disables admission control entirely."""
        if not cfg:
            return None
        cfg = dict(cfg)
        if not cfg.pop("enabled", True):
            return None
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(cfg) - known)
        if unknown:
            raise ValueError(f"unknown shed config keys: {unknown}")
        return cls(**cfg)


class TokenBucket:
    """Classic request-rate gate: ``rate`` tokens/s refill up to
    ``burst`` capacity; each admission takes one. Clock comes in as an
    argument so tests drive it deterministically."""

    def __init__(self, rate: float, burst: float):
        self.rate = rate
        self.burst = burst
        self.tokens = burst          # starts full: bursts up to capacity
        self._t: Optional[float] = None

    def try_take(self, now: float) -> bool:
        if self._t is not None:
            self.tokens = min(self.burst,
                              self.tokens + (now - self._t) * self.rate)
        self._t = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class AdmissionController:
    """Token-bucket / bounded-queue admission gate with per-request
    priority and SLO-aware queue shedding. Pure decision logic: the
    engine owns the terminal-SHED bookkeeping (metrics, trace spans,
    flight-recorder events)."""

    def __init__(self, cfg: ShedConfig):
        self.cfg = cfg
        self.bucket: Optional[TokenBucket] = None
        if cfg.rate > 0:
            self.bucket = TokenBucket(
                cfg.rate, cfg.burst if cfg.burst > 0 else cfg.max_queue_depth)

    def on_submit(self, sched, req: Request,
                  now: float) -> Tuple[bool, List[Request]]:
        """Gate one JUST-QUEUED arrival. Returns ``(admitted, victims)``
        where victims are the requests to shed: the arrival itself
        (bucket empty, or it is the worst of a full queue), or the
        lowest-priority queued request it displaces."""
        if self.bucket is not None and not self.bucket.try_take(now):
            return False, [req]
        if sched.queue_depth > self.cfg.max_queue_depth:
            cands = sched.sheddable_queued()
            worst = cands[0] if cands else req
            return worst.rid != req.rid, [worst]
        return True, []

    def shed_pass(self, sched, burn: float, level: int) -> List[Request]:
        """Per-step shed decision: enforce the queue bound, and — when
        the SLO burn rate is at/over threshold or the ladder reached its
        shed rung — trim the queue down to what the decode slots can
        absorb promptly, lowest-priority first. Returns the victims
        (not yet cancelled)."""
        victims: List[Request] = []
        cands = sched.sheddable_queued()
        keep = sched.queue_depth
        while keep > self.cfg.max_queue_depth and cands:
            victims.append(cands.pop(0))
            keep -= 1
        if burn >= self.cfg.slo_burn_threshold or level >= SHED_LEVEL:
            target = sched.cache.geom.num_slots
            while keep > target and cands:
                victims.append(cands.pop(0))
                keep -= 1
        return victims


# -------------------------------------------------------- degradation ladder

#: Rung names, in escalation order. Each rung keeps every lower rung's
#: effect: at level 3 the cache is flushed AND co-scheduling is off AND
#: the batch is shrunk.
LADDER_RUNGS = ("none", "flush_prefix_cache", "no_coschedule",
                "shrink_batch", "shed")
SHED_LEVEL = len(LADDER_RUNGS) - 1


class DegradationLadder:
    """Hysteresis controller over a scalar pressure signal (max of page
    occupancy and queue-depth fraction). Sustained pressure climbs one
    rung per ``degrade_patience`` window; sustained calm climbs back
    down. The engine applies the rung effects; the ladder owns the
    level, the flight-recorder events, and nothing else."""

    def __init__(self, cfg: ShedConfig, recorder=None):
        self.cfg = cfg
        self.recorder = recorder
        self.level = 0
        self._over = 0
        self._under = 0

    @property
    def no_coschedule(self) -> bool:
        return self.level >= 2

    @property
    def shrink_batch(self) -> bool:
        return self.level >= 3

    def update(self, pressure: float, step: Optional[int] = None) -> int:
        cfg = self.cfg
        if pressure >= cfg.degrade_high:
            self._under = 0
            self._over += 1
            if self._over >= cfg.degrade_patience and \
                    self.level < SHED_LEVEL:
                self._over = 0
                self._move(self.level + 1, pressure, step)
        elif pressure < cfg.degrade_low:
            self._over = 0
            self._under += 1
            if self._under >= cfg.degrade_patience and self.level > 0:
                self._under = 0
                self._move(self.level - 1, pressure, step)
        else:
            self._over = 0
            self._under = 0
        return self.level

    def _move(self, level: int, pressure: float,
              step: Optional[int]) -> None:
        prev, self.level = self.level, level
        if self.recorder is not None:
            self.recorder.record(
                "degradation", step=step, level=level,
                rung=LADDER_RUNGS[level], prev_level=prev,
                pressure=round(pressure, 4))


# ------------------------------------------------------------- supervision


@dataclasses.dataclass(frozen=True)
class SupervisorConfig:
    """Supervisor policy (the serving ``supervisor:`` config block;
    ``SupervisorSchema`` in training/config.py mirrors it)."""
    watchdog_timeout_s: float = 60.0   # wedged-step threshold
    watchdog_poll_s: Optional[float] = None  # default: timeout/4
    max_restarts: int = 3              # breaker budget per window
    restart_window_s: float = 600.0

    @classmethod
    def from_config(cls, cfg: Optional[Dict]
                    ) -> Optional["SupervisorConfig"]:
        if not cfg:
            return None
        cfg = dict(cfg)
        if not cfg.pop("enabled", True):
            return None
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(cfg) - known)
        if unknown:
            raise ValueError(f"unknown supervisor config keys: {unknown}")
        return cls(**cfg)


class CircuitBreaker:
    """Sliding-window restart budget: more than ``max_restarts``
    restarts inside ``window_s`` trips the breaker. A tripped breaker
    never closes again for the supervisor's lifetime — a restart loop
    is an operator page, not something to ride out."""

    def __init__(self, max_restarts: int, window_s: float,
                 now: Callable[[], float] = time.monotonic):
        self.max_restarts = int(max_restarts)
        self.window_s = window_s
        self.now = now
        self._events: deque = deque()

    def record(self, t: Optional[float] = None) -> None:
        t = self.now() if t is None else t
        self._events.append(t)
        self._prune(t)

    def _prune(self, t: float) -> None:
        while self._events and t - self._events[0] > self.window_s:
            self._events.popleft()

    @property
    def tripped(self) -> bool:
        self._prune(self.now())
        return len(self._events) > self.max_restarts


@dataclasses.dataclass
class JournalEntry:
    """Everything needed to replay one request deterministically on a
    rebuilt engine: the immutable submission plus the tokens the client
    has already seen. Sampling state is (seed, token index) — the
    engine's per-request PRNG keying is history-free like argmax — so
    prompt + streamed + sampling IS the state the replay resumes from,
    greedy and sampled alike. ``streamed_logps`` mirrors ``streamed``
    so the replayed request's logprob surface is also seamless."""
    prompt_tokens: List[int]
    max_new_tokens: int
    priority: int
    arrival_time: float
    deadline: Optional[float]
    streamed: List[int]
    done: bool
    request: Request      # live request object on the CURRENT engine
    sampling: Optional[object] = None          # SamplingParams override
    streamed_logps: List[float] = dataclasses.field(default_factory=list)
    # owning tenant (multi-tenant serving): replay re-binds the adapter
    # and KV namespace from this — the rebuilt engine's factory must
    # republish the tenant's adapter (engine.restore fails loudly if not)
    tenant: Optional[str] = None
    # migration provenance: the fleet moves a journal entry to the
    # TARGET member's supervisor atomically with the KV install (popped
    # from the source first), so replay after a mid-handoff crash lands
    # the request on exactly one engine. These fields record where it
    # came from and how many hops it has taken — postmortem breadcrumbs,
    # not replay inputs.
    migrated_from: Optional[int] = None        # source fleet slot
    migrations: int = 0                        # completed handoffs


class Supervisor:
    """Supervises a ServingEngine: journaled intake, failure detection
    around every step, bounded teardown/rebuild with deterministic
    replay of in-flight work.

    ``factory`` builds a fresh engine (same model/params/config); the
    supervisor owns the engine's lifecycle from then on. Drive it like
    the engine itself::

        sup = Supervisor(lambda: ServingEngine(...), SupervisorConfig())
        rid = sup.submit(prompt, max_new_tokens=32)
        results = sup.run()          # step() in a loop, self-healing
        sup.close()

    Failure kinds and their detection sites:

    - ``wedge``: the Watchdog (armed only while ``engine.step`` runs)
      fires; the step eventually returned, so journaled state is
      consistent — rebuild to shed whatever latency debt built up.
    - ``device_error``: any non-NaN exception out of ``engine.step``.
    - ``nan_logits``: :class:`NaNLogitsError` out of the step.

    Every restart rebuilds the engine (compile counters restart at
    zero and pin at one per build — the static-shape invariant is per
    engine) and replays all non-terminal journal entries via
    ``engine.restore``; tokens emitted by a failed step were never
    committed to the journal, so the replay recomputes them — greedy
    outputs stay bit-identical. When the breaker trips, the rebuilt
    engine comes up draining (``/healthz`` 503 ``draining``); a
    further failure past that point resolves all remaining in-flight
    requests as SHED rather than restarting forever.
    """

    def __init__(self, factory: Callable[[], object],
                 cfg: Optional[SupervisorConfig] = None,
                 now: Callable[[], float] = time.monotonic,
                 on_burst: Optional[Callable[[int], None]] = None):
        self.factory = factory
        self.cfg = cfg or SupervisorConfig()
        self.now = now
        # burst-fault hook: called with K when an engine_step=N:burst=K
        # entry fires; None submits K synthetic low-priority requests
        self.on_burst = on_burst
        self.journal: Dict[int, JournalEntry] = {}
        self.restarts = 0
        self.replayed = 0
        # cumulative speculative-round totals captured from each dying
        # engine and re-seeded into its replacement, so serving/spec/*
        # stay monotonic across rebuilds like the supervisor counters
        self._spec_totals = {"rounds": 0, "proposed": 0, "accepted": 0,
                             "rollbacks": 0}
        # same carry for the KV-migration counters: serving/migration/*
        # totals survive rebuilds of the engine that earned them
        self._mig_totals = {"migrations": 0, "migrated_pages": 0,
                            "host_bounce_bytes": 0,
                            "failed_migrations": 0}
        # and for the adapter-pool counters: serving/adapter_pool/*
        # (a rebuilt engine's AdapterStore restarts at zero; republishes
        # by the factory then count on top of the carried totals)
        self._adapter_totals = {"publishes": 0, "loads": 0, "spills": 0}
        self.failures: List[str] = []     # restart kinds, in order
        self.tripped = False
        self.breaker = CircuitBreaker(
            self.cfg.max_restarts, self.cfg.restart_window_s, now=now)
        self._hang = threading.Event()
        self._watchdog: Optional[Watchdog] = None
        # one fault plan for the supervised run, carried across engine
        # generations: a rebuilt engine re-parses its config plan with
        # fresh consumed-state and a reset step counter, so without
        # this the same injected fault re-fires after every rebuild
        # and no plan ever drains
        self._fault_plan = None
        self.engine = None
        self._build_engine()

    # ----------------------------------------------------------- lifecycle

    def _build_engine(self) -> None:
        self.engine = self.factory()
        if self._fault_plan is None:
            self._fault_plan = getattr(self.engine, "faults", None)
        else:
            self.engine.faults = self._fault_plan
        m = self.engine.metrics
        # supervisor totals outlive engine rebuilds: re-seed the fresh
        # registry so /metrics stays monotonic across restarts
        m.supervisor_restarts.inc(self.restarts)
        m.replayed_requests.inc(self.replayed)
        m.breaker_open.set(1.0 if self.tripped else 0.0)
        t = self._spec_totals
        if any(t.values()):
            m.spec_rounds.inc(t["rounds"])
            m.spec_proposed.inc(t["proposed"])
            m.spec_accepted.inc(t["accepted"])
            m.spec_rollbacks.inc(t["rollbacks"])
            if t["proposed"]:
                m.spec_acceptance_rate.set(t["accepted"] / t["proposed"])
        mt = self._mig_totals
        if any(mt.values()):
            m.migrations.inc(mt["migrations"])
            m.migrated_pages.inc(mt["migrated_pages"])
            m.host_bounce_bytes.inc(mt["host_bounce_bytes"])
            m.failed_migrations.inc(mt["failed_migrations"])
        at = self._adapter_totals
        if any(at.values()):
            m.adapter_publishes.inc(at["publishes"])
            m.adapter_loads.inc(at["loads"])
            m.adapter_spills.inc(at["spills"])
        self._arm_watchdog()
        if self.tripped:
            self.engine.begin_drain()

    def _arm_watchdog(self) -> None:
        if self._watchdog is not None:
            self._watchdog.stop()
        self._hang.clear()
        wd = Watchdog(timeout_s=self.cfg.watchdog_timeout_s,
                      poll_s=self.cfg.watchdog_poll_s,
                      on_hang=lambda dump: self._hang.set(),
                      abort=False,
                      recorder=getattr(self.engine, "recorder", None))
        wd.pause()                 # armed only inside engine.step
        wd.start()
        self._watchdog = wd

    def close(self) -> None:
        if self._watchdog is not None:
            self._watchdog.stop()
            self._watchdog = None
        if self.engine is not None:
            self.engine.close()

    # -------------------------------------------------------------- intake

    def submit(self, prompt_tokens: List[int], max_new_tokens: int,
               arrival_time: Optional[float] = None,
               deadline_s: Optional[float] = None,
               priority: int = 0, sampling=None,
               tenant: Optional[str] = None) -> int:
        rid = self.engine.submit(
            prompt_tokens, max_new_tokens, arrival_time=arrival_time,
            deadline_s=deadline_s, priority=priority, sampling=sampling,
            tenant=tenant)
        req = self.engine.result(rid)
        self.journal[rid] = JournalEntry(
            prompt_tokens=list(prompt_tokens),
            max_new_tokens=int(max_new_tokens),
            priority=priority,
            arrival_time=req.arrival_time,
            deadline=req.deadline,
            streamed=[],
            done=req.state in TERMINAL_STATES,   # shed at the gate
            request=req,
            sampling=sampling,
            tenant=tenant)
        return rid

    def result(self, rid: int) -> Request:
        return self.journal[rid].request

    def cancel(self, rid: int, reason: str = "cancelled") -> Request:
        """Client-initiated cancellation through the journal: the entry
        is marked done so a later engine rebuild does NOT replay the
        request the client already walked away from."""
        req = self.engine.cancel(rid, reason)
        entry = self.journal.get(rid)
        if entry is not None:
            entry.done = True
        return req

    def results(self) -> Dict[int, Request]:
        return {rid: e.request for rid, e in self.journal.items()}

    def has_work(self) -> bool:
        return self.engine.has_work()

    @property
    def draining(self) -> bool:
        return self.engine.draining

    # --------------------------------------------------------- supervision

    def step(self) -> List[Tuple[int, int]]:
        """One supervised engine step: poll the burst fault, run the
        step under the watchdog, commit emitted tokens to the journal,
        restart on failure. Returns the step's (rid, token) stream —
        empty on a failed step (its tokens were never streamed and the
        replay recomputes them)."""
        self._poll_burst()
        eng = self.engine
        compile_mark = (eng.decode_compiles, eng.prefill_compiles,
                        eng.prefill_chunk_compiles,
                        eng.spec_draft_compiles, eng.spec_verify_compiles,
                        eng.export_compiles, eng.import_compiles)
        wd = self._watchdog
        wd.resume()
        try:
            emitted = eng.step()
        except Exception as exc:  # noqa: BLE001 — every step failure
            wd.pause()            # routes through the restart path
            kind = ("nan_logits" if isinstance(exc, NaNLogitsError)
                    else "device_error")
            self._restart(kind, repr(exc))
            return []
        wd.pause()
        self._commit(emitted)
        if self._hang.is_set():
            if (eng.decode_compiles, eng.prefill_compiles,
                    eng.prefill_chunk_compiles, eng.spec_draft_compiles,
                    eng.spec_verify_compiles, eng.export_compiles,
                    eng.import_compiles) != compile_mark:
                # an XLA compile landed in this step: tracing/lowering
                # legitimately blows any serving latency budget (and
                # recurs on every rebuilt engine), so it is a known
                # outlier, not a wedge. The fired watchdog is spent —
                # arm a fresh one and move on.
                self._arm_watchdog()
            else:
                # the step DID return (an injected wedge sleeps; a
                # truly never-returning step is the process watchdog's
                # job) but blew the budget: state is consistent and
                # committed, so the emitted tokens are real — journal
                # first, then rebuild
                self._restart("wedge", None)
        return emitted

    # dla: hot-loop-root
    def run(self, max_steps: int = 100000) -> Dict[int, Request]:
        """Drive the supervised engine until drained; the self-healing
        analog of ``ServingEngine.run_until_drained``."""
        for _ in range(max_steps):
            if not self.has_work():
                return self.results()
            self.step()
        raise RuntimeError(
            f"supervised serving loop did not drain in {max_steps} steps")

    # ----------------------------------------------------------- internals

    def _commit(self, emitted: List[Tuple[int, int]]) -> None:
        for rid, tok in emitted:
            e = self.journal.get(rid)
            if e is not None and not e.done:
                e.streamed.append(tok)
                # the request's logprob list advances in lockstep with
                # its generated tokens (failed steps never commit), so
                # the committed token's logp is at the same index
                lps = e.request.generated_logprobs
                e.streamed_logps.append(
                    float(lps[len(e.streamed) - 1])
                    if len(lps) >= len(e.streamed) else 0.0)
        for e in self.journal.values():
            if not e.done and e.request.state in TERMINAL_STATES:
                e.done = True

    def _poll_burst(self) -> None:
        plan = getattr(self.engine, "faults", None)
        if not plan or self.engine.draining:
            return
        f = plan.take("burst", self.engine.engine_steps,
                      site="engine_step")
        if f is None:
            return
        k = 8 if f.arg is None else int(f.arg)
        rec = getattr(self.engine, "recorder", None)
        if rec is not None:
            rec.record("fault_injected", step=self.engine.engine_steps,
                       fault="burst", count=k)
        if self.on_burst is not None:
            self.on_burst(k)
            return
        ps = self.engine.cfg.page_size
        for i in range(k):
            self.submit([2 + (i % 7)] * ps, 4, priority=-1)

    def _restart(self, kind: str, detail: Optional[str]) -> None:
        eng = self.engine
        rec = getattr(eng, "recorder", None)
        if rec is not None:
            rec.record("engine_restart", step=eng.engine_steps,
                       failure=kind, detail=detail)
            rec.dump(f"engine_restart_{kind}")
        self.restarts += 1
        self.failures.append(kind)
        stats = getattr(eng, "_spec_stats", None)
        if stats:
            # fold the dying engine's speculative totals into the carry
            # before teardown; _build_engine re-seeds them
            for key in self._spec_totals:
                self._spec_totals[key] += int(stats.get(key, 0))
        mig = getattr(eng, "_mig_stats", None)
        if mig:
            for key in self._mig_totals:
                self._mig_totals[key] += int(mig.get(key, 0))
        store = getattr(eng, "adapter_store", None)
        if store is not None:
            for key in self._adapter_totals:
                self._adapter_totals[key] += int(getattr(store, key, 0))
        self.breaker.record(self.now())
        out_of_budget = self.tripped   # tripped BEFORE this failure
        self.tripped = self.tripped or self.breaker.tripped
        try:
            eng.close()
        except Exception:  # noqa: BLE001 — teardown of a failed engine
            pass
        if out_of_budget:
            # the post-trip drain engine failed too: stop restarting.
            # Everything still in flight resolves terminally as SHED —
            # the client sees a final status, never a hang.
            for e in self.journal.values():
                if not e.done:
                    e.request.finish_reason = "shed"
                    e.request.state = RequestState.SHED
                    e.done = True
        self._build_engine()
        rec = getattr(self.engine, "recorder", None)
        if self.tripped and not out_of_budget and rec is not None:
            rec.record("breaker_open", restarts=self.restarts)
            rec.dump("breaker_open")
        if not out_of_budget:
            self._replay()

    def _replay(self) -> None:
        pending = [e for e in self.journal.values() if not e.done]
        pending.sort(key=lambda e: e.request.rid)
        m = self.engine.metrics
        for e in pending:
            req = self.engine.restore(
                e.prompt_tokens, e.max_new_tokens,
                generated=list(e.streamed),
                arrival_time=e.arrival_time,
                deadline=e.deadline, priority=e.priority,
                rid=e.request.rid, sampling=e.sampling,
                generated_logprobs=list(e.streamed_logps),
                tenant=e.tenant)
            e.request = req
            self.replayed += 1
            m.replayed_requests.inc()
