"""Serving metrics surface: counters, gauges, and bounded histograms for
the quantities that tell you whether a serving deployment is healthy —
queue depth, time-to-first-token, inter-token latency, queue wait,
page-pool occupancy, preemption count.

The instrument classes live in ``dla_tpu.telemetry.registry`` (re-
exported here for back-compat) and every instrument registers into a
shared :class:`~dla_tpu.telemetry.MetricRegistry`, so the same numbers
export two ways: ``snapshot()`` returns the flat dict a
``MetricsLogger`` writes as one JSONL row, and the registry's
``prometheus_text()`` backs the engine's HTTP ``/metrics`` endpoint.
Percentiles come from ``utils.logging.percentile`` so serving and
eval_latency report the same statistic.
"""
from __future__ import annotations

from typing import Dict, Optional

from dla_tpu.telemetry.registry import (  # noqa: F401 — re-exported
    Counter,
    Gauge,
    Histogram,
    MetricRegistry,
)
from dla_tpu.utils.logging import MetricsLogger


class ServingMetrics:
    """The serving engine's instrument panel. The engine records; anyone
    (CLI harness, bench, tests, a Prometheus scraper) reads
    ``snapshot()``, streams rows through ``report()``, or scrapes the
    registry."""

    def __init__(self, registry: Optional[MetricRegistry] = None):
        r = self.registry = registry or MetricRegistry()
        self.queue_depth = r.gauge("serving/queue_depth")
        self.active_requests = r.gauge("serving/active_requests")
        self.page_occupancy = r.gauge("serving/page_occupancy")
        self.ttft_ms = r.histogram("serving/ttft_ms")
        self.itl_ms = r.histogram("serving/itl_ms")
        self.queue_wait_ms = r.histogram("serving/queue_wait_ms")
        self.requests_submitted = r.counter("serving/requests_submitted")
        self.requests_finished = r.counter("serving/requests_finished")
        self.requests_timed_out = r.counter("serving/requests_timed_out")
        self.requests_cancelled = r.counter("serving/requests_cancelled")
        self.preemptions = r.counter("serving/preemptions")
        self.decode_steps = r.counter("serving/decode_steps")
        self.prefill_batches = r.counter("serving/prefill_batches")
        self.tokens_generated = r.counter("serving/tokens_generated")
        self.prefix_lookups = r.counter("serving/prefix_cache/lookups")
        self.prefix_hit_tokens = r.counter(
            "serving/prefix_cache/hit_tokens")
        self.prefix_evictions = r.counter(
            "serving/prefix_cache/evictions")
        self.prefill_chunks = r.counter("serving/prefill/chunks")
        self.prefill_tokens_saved = r.counter(
            "serving/prefill/tokens_saved")
        self.requests_shed = r.counter("serving/requests_shed")
        self.queue_timeouts = r.counter("serving/queue_timeouts")
        self.degradation_level = r.gauge("serving/degradation_level")
        self.supervisor_restarts = r.counter(
            "serving/supervisor/restarts")
        self.replayed_requests = r.counter(
            "serving/supervisor/replayed_requests")
        self.breaker_open = r.gauge("serving/supervisor/breaker_open")
        self.spec_rounds = r.counter("serving/spec/rounds")
        self.spec_proposed = r.counter("serving/spec/proposed_tokens")
        self.spec_accepted = r.counter("serving/spec/accepted_tokens")
        self.spec_rollbacks = r.counter("serving/spec/rollbacks")
        self.spec_acceptance_rate = r.gauge("serving/spec/acceptance_rate")
        self.migrations = r.counter("serving/migration/migrations")
        self.migrated_pages = r.counter(
            "serving/migration/migrated_pages")
        self.host_bounce_bytes = r.counter(
            "serving/migration/host_bounce_bytes")
        self.failed_migrations = r.counter(
            "serving/migration/failed_migrations")
        self.handoff_wait_ms = r.histogram(
            "serving/migration/handoff_wait_ms")
        self.adapter_resident = r.gauge("serving/adapter_pool/resident")
        self.adapter_publishes = r.counter(
            "serving/adapter_pool/publishes")
        self.adapter_loads = r.counter("serving/adapter_pool/loads")
        self.adapter_spills = r.counter("serving/adapter_pool/spills")

    def snapshot(self) -> Dict[str, float]:
        out: Dict[str, float] = {
            "serving/queue_depth": self.queue_depth.value,
            "serving/queue_depth_peak": self.queue_depth.peak,
            "serving/active_requests": self.active_requests.value,
            "serving/page_occupancy": self.page_occupancy.value,
            "serving/page_occupancy_peak": self.page_occupancy.peak,
            "serving/requests_submitted": float(
                self.requests_submitted.value),
            "serving/requests_finished": float(self.requests_finished.value),
            "serving/requests_timed_out": float(
                self.requests_timed_out.value),
            "serving/requests_cancelled": float(
                self.requests_cancelled.value),
            "serving/preemptions": float(self.preemptions.value),
            "serving/decode_steps": float(self.decode_steps.value),
            "serving/prefill_batches": float(self.prefill_batches.value),
            "serving/tokens_generated": float(self.tokens_generated.value),
            "serving/prefix_cache/lookups": float(
                self.prefix_lookups.value),
            "serving/prefix_cache/hit_tokens": float(
                self.prefix_hit_tokens.value),
            "serving/prefix_cache/evictions": float(
                self.prefix_evictions.value),
            "serving/prefill/chunks": float(self.prefill_chunks.value),
            "serving/prefill/tokens_saved": float(
                self.prefill_tokens_saved.value),
            "serving/requests_shed": float(self.requests_shed.value),
            "serving/queue_timeouts": float(self.queue_timeouts.value),
            "serving/degradation_level": self.degradation_level.value,
            "serving/supervisor/restarts": float(
                self.supervisor_restarts.value),
            "serving/supervisor/replayed_requests": float(
                self.replayed_requests.value),
            "serving/supervisor/breaker_open": self.breaker_open.value,
            "serving/spec/rounds": float(self.spec_rounds.value),
            "serving/spec/proposed_tokens": float(
                self.spec_proposed.value),
            "serving/spec/accepted_tokens": float(
                self.spec_accepted.value),
            "serving/spec/rollbacks": float(self.spec_rollbacks.value),
            "serving/spec/acceptance_rate":
                self.spec_acceptance_rate.value,
            "serving/migration/migrations": float(self.migrations.value),
            "serving/migration/migrated_pages": float(
                self.migrated_pages.value),
            "serving/migration/host_bounce_bytes": float(
                self.host_bounce_bytes.value),
            "serving/migration/failed_migrations": float(
                self.failed_migrations.value),
            "serving/adapter_pool/resident": self.adapter_resident.value,
            "serving/adapter_pool/publishes": float(
                self.adapter_publishes.value),
            "serving/adapter_pool/loads": float(self.adapter_loads.value),
            "serving/adapter_pool/spills": float(
                self.adapter_spills.value),
        }
        out.update(self.ttft_ms.summary("serving/ttft_ms_"))
        out.update(self.itl_ms.summary("serving/itl_ms_"))
        out.update(self.queue_wait_ms.summary("serving/queue_wait_ms_"))
        out.update(self.handoff_wait_ms.summary(
            "serving/migration/handoff_wait_ms_"))
        return out

    def report(self, logger: Optional[MetricsLogger], step: int) -> None:
        if logger is not None:
            logger.log(self.snapshot(), step)
