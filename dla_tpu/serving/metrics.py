"""Serving metrics surface: counters, gauges, and bounded histograms for
the quantities that tell you whether a serving deployment is healthy —
queue depth, time-to-first-token, inter-token latency, page-pool
occupancy, preemption count.

Everything exports through dla_tpu/utils/logging.py: ``snapshot()``
returns a flat dict a ``MetricsLogger`` writes as one JSONL row (and to
wandb when enabled); percentiles come from ``utils.logging.percentile``
so serving and eval_latency report the same statistic.
"""
from __future__ import annotations

from collections import deque
from typing import Dict, Optional

from dla_tpu.utils.logging import MetricsLogger, latency_summary


class Counter:
    """Monotonic event count."""

    def __init__(self):
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    """Last-set value plus the observed peak (peak matters for capacity
    questions like "did the page pool ever fill?")."""

    def __init__(self):
        self.value = 0.0
        self.peak = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)
        self.peak = max(self.peak, self.value)


class Histogram:
    """Windowed latency sample store (last ``window`` observations) with
    p50/p95/mean via the shared percentile helper. A serving process
    runs indefinitely; the bound keeps the store O(1) while the window
    is wide enough that percentiles track current behavior."""

    def __init__(self, window: int = 4096):
        self.samples: deque = deque(maxlen=window)
        self.total_count = 0

    def record(self, v: float) -> None:
        self.samples.append(float(v))
        self.total_count += 1

    def summary(self, prefix: str = "") -> Dict[str, float]:
        return latency_summary(self.samples, prefix)


class ServingMetrics:
    """The serving engine's instrument panel. The engine records; anyone
    (CLI harness, bench, tests) reads ``snapshot()`` or streams rows
    through ``report()``."""

    def __init__(self):
        self.queue_depth = Gauge()
        self.active_requests = Gauge()
        self.page_occupancy = Gauge()
        self.ttft_ms = Histogram()
        self.itl_ms = Histogram()
        self.requests_submitted = Counter()
        self.requests_finished = Counter()
        self.requests_timed_out = Counter()
        self.requests_cancelled = Counter()
        self.preemptions = Counter()
        self.decode_steps = Counter()
        self.prefill_batches = Counter()
        self.tokens_generated = Counter()

    def snapshot(self) -> Dict[str, float]:
        out: Dict[str, float] = {
            "serving/queue_depth": self.queue_depth.value,
            "serving/queue_depth_peak": self.queue_depth.peak,
            "serving/active_requests": self.active_requests.value,
            "serving/page_occupancy": self.page_occupancy.value,
            "serving/page_occupancy_peak": self.page_occupancy.peak,
            "serving/requests_submitted": float(
                self.requests_submitted.value),
            "serving/requests_finished": float(self.requests_finished.value),
            "serving/requests_timed_out": float(
                self.requests_timed_out.value),
            "serving/requests_cancelled": float(
                self.requests_cancelled.value),
            "serving/preemptions": float(self.preemptions.value),
            "serving/decode_steps": float(self.decode_steps.value),
            "serving/prefill_batches": float(self.prefill_batches.value),
            "serving/tokens_generated": float(self.tokens_generated.value),
        }
        out.update(self.ttft_ms.summary("serving/ttft_ms_"))
        out.update(self.itl_ms.summary("serving/itl_ms_"))
        return out

    def report(self, logger: Optional[MetricsLogger], step: int) -> None:
        if logger is not None:
            logger.log(self.snapshot(), step)
