"""Cross-host fleet federation: place requests onto N remote
gateway-fronted fleets with the same cache-aware score ``FleetRouter``
uses locally.

Topology: each serving pod runs a ``ServingGateway`` (serving.gateway)
plus a :class:`GossipBeater` that heartbeats into a shared gossip
directory — the lease-file idiom from ``resilience/elastic.py``
(write-aside + atomic rename, monotone sequence numbers). A
:class:`FederatedRouter` on any host scans the directory to discover
peers, treats a peer whose beat went quiet past the TTL as stale
(counted on ``serving/federation/stale_peers``, never placed on), and
scores live peers per request with the SAME inputs the in-process
router uses: peeked prefix-cache hit fraction (``POST /v1/peek``, the
per-prompt signal gossip cannot ship) and pressure, combined as
``prefix_weight * hit - load_weight * pressure`` with sticky family
affinity.

Zero-loss contract: the router journals every submission (prompt +
sampling — exactly the Supervisor's replay state, because per-request
``fold_in(seed, k)`` sampling is history-free). A fleet that dies
mid-stream just costs a replay: the request is re-placed on a live
peer and regenerates the IDENTICAL token stream, so nothing a client
was promised is ever lost. Mid-decode requests can also move without
recompute: ``migrate()`` ships the serialized ``MigrationTicket``
(``/v1/migrate_out`` -> ``/v1/migrate_in``, counted on
``serving/federation/handoff_bytes``) and the stream re-attaches on
the target, bit-identical.

Fault injection: every wire operation polls the ``net=`` scope of a
:class:`~dla_tpu.resilience.faults.FaultPlan` (drop / delay /
disconnect) against a monotone wire-op counter, so chaos benches and
tests drive the replay machinery deterministically.
"""
from __future__ import annotations

import dataclasses
import http.client
import json
import os
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional, Tuple
from urllib.parse import urlparse

from dla_tpu.resilience.faults import FaultPlan
from dla_tpu.telemetry.aggregate import FleetMetricsAggregator
from dla_tpu.telemetry.registry import MetricRegistry
from dla_tpu.telemetry.trace import get_tracer, register_trace_gauges
from dla_tpu.telemetry.trace_context import TRACEPARENT_HEADER, TraceContext


class FederationError(RuntimeError):
    """A wire operation against a peer fleet failed or was refused."""


@dataclasses.dataclass(frozen=True)
class FederationConfig:
    """Cross-fleet routing knobs. The score weights default to the
    in-process ``FleetConfig`` values — federation is the same policy
    one network hop up."""

    prefix_weight: float = 2.0
    load_weight: float = 1.0
    sticky_bonus: float = 0.5
    lease_ttl_s: float = 3.0           # beat older than this -> stale
    beat_interval_s: float = 0.25
    wire_timeout_s: float = 120.0      # per-op socket timeout
    place_timeout_s: float = 60.0      # total wait for any live peer
    max_replays: int = 4               # per-request re-placements


class FederationMetrics:
    """The ``serving/federation/*`` panel, owned by the router's own
    registry (which outlives every remote fleet)."""

    #: RTT histogram families — one fleet-wide + one per peer each, so
    #: a slow peer is attributable before it goes stale.
    RTT_KINDS = ("peek", "place", "stream")

    def __init__(self, registry: Optional[MetricRegistry] = None):
        r = self.registry = registry or MetricRegistry()
        self.gossip_beats = r.counter("serving/federation/gossip_beats")
        self.routed_remote = r.counter(
            "serving/federation/routed_remote")
        self.handoff_bytes = r.counter(
            "serving/federation/handoff_bytes")
        self.stale_peers = r.counter("serving/federation/stale_peers")
        self._rtt = {
            "peek": r.histogram("serving/federation/peek_rtt_ms"),
            "place": r.histogram("serving/federation/place_rtt_ms"),
            "stream": r.histogram("serving/federation/stream_rtt_ms"),
        }
        # the router process's tracer ring/spool accounting (the
        # trainer tracer's contract, extended to every tracer ring)
        register_trace_gauges(r)

    def rtt(self, kind: str, peer: str, ms: float) -> None:
        """Observe one wire round trip on the fleet-wide histogram AND
        the per-peer one (``serving/federation/peer/<name>/...``, a
        dynamic-prefix family like ``serving/fleet/engine/``)."""
        self._rtt[kind].record(ms)
        key = (kind, peer)
        hist = self._rtt.get(key)
        if hist is None:
            hist = self._rtt[key] = self.registry.histogram(
                f"serving/federation/peer/{peer}/{kind}_rtt_ms")
        hist.record(ms)

    def snapshot(self) -> Dict[str, float]:
        return self.registry.snapshot()


def write_beat(gossip_dir, name: str, url: str, seq: int,
               pressure: float, draining: bool,
               metrics: Optional[Dict[str, float]] = None) -> None:
    """One gossip heartbeat, atomically (write-aside + ``os.replace``,
    the elastic lease idiom): readers never see a torn beat.
    ``metrics`` is the writer's numeric health digest
    (``ServingGateway.metrics_digest``) that ``FleetMetricsAggregator``
    rolls into the reader-side ``fleet/*`` panel."""
    gossip_dir = Path(gossip_dir)
    gossip_dir.mkdir(parents=True, exist_ok=True)
    path = gossip_dir / f"peer_{name}.json"
    tmp = gossip_dir / f".peer_{name}.tmp"
    doc = {"name": name, "url": url, "seq": int(seq),
           "time": time.time(), "pressure": float(pressure),
           "draining": bool(draining)}
    if metrics:
        doc["metrics"] = {str(k): float(v) for k, v in metrics.items()}
    tmp.write_text(json.dumps(doc))
    os.replace(tmp, path)


class GossipBeater:
    """Background heartbeat for one gateway: advertises its URL and
    pressure into the gossip directory every ``beat_interval_s`` until
    stopped (or the process dies — which is exactly what the TTL
    detects on the reader side)."""

    def __init__(self, gateway, gossip_dir, name: str,
                 cfg: Optional[FederationConfig] = None):
        self.gateway = gateway
        self.gossip_dir = Path(gossip_dir)
        self.name = name
        self.cfg = cfg or FederationConfig()
        self._seq = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="dla-federation-beat", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            gw = self.gateway
            try:
                with gw._lock:
                    _, pressure = gw.peek([])
                digest = None
                digest_fn = getattr(gw, "metrics_digest", None)
                if digest_fn is not None:
                    digest = digest_fn()
                self._seq += 1
                write_beat(self.gossip_dir, self.name, gw.url,
                           self._seq, pressure, gw.draining,
                           metrics=digest)
                # stamp the send on the span spool: matched with the
                # observer's beat_seen stamp, this pair is what lets
                # trace_merge align the two processes' clocks
                spool = get_tracer().spool
                if spool is not None:
                    spool.beat_sent(self.name, self._seq)
            except Exception:  # noqa: BLE001 — a failed beat is a
                pass           # missed heartbeat, not a crash
            self._stop.wait(self.cfg.beat_interval_s)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)


@dataclasses.dataclass
class FedRequest:
    """One federated request: the journaled replay state plus the
    stream collected so far."""
    fid: int
    prompt_tokens: List[int]
    max_new_tokens: int
    sampling: Optional[dict]           # SamplingParams fields or None
    priority: int
    tokens: List[int] = dataclasses.field(default_factory=list)
    logprobs: List[float] = dataclasses.field(default_factory=list)
    state: str = "pending"
    peer: Optional[str] = None         # current serving peer name
    remote_rid: Optional[int] = None
    replays: int = 0
    handoff: Optional[Tuple[str, int]] = None   # (peer name, new rid)
    trace: Optional[TraceContext] = None        # root context (origin)
    handoff_event: threading.Event = dataclasses.field(
        default_factory=threading.Event)


class FederatedRouter:
    """Top-level request placement across gateway-fronted fleets.

    Each ``submit`` runs on its own reader thread: place -> stream ->
    (replay on wire failure | re-attach after a migrate) -> terminal.
    ``results()`` joins every thread and returns the collected
    streams. ``requests_lost`` MUST be 0 after any chaos run — that is
    the acceptance bar this class exists to clear."""

    def __init__(self, gossip_dir,
                 cfg: Optional[FederationConfig] = None,
                 registry: Optional[MetricRegistry] = None,
                 fault_plan: Optional[FaultPlan] = None):
        self.gossip_dir = Path(gossip_dir)
        self.cfg = cfg or FederationConfig()
        self.metrics = FederationMetrics(registry)
        # gossip metric digests rolled into the fleet/* panel, served
        # from this router's /metrics (serve_metrics)
        self.fleet = FleetMetricsAggregator(self.metrics.registry)
        self.plan = fault_plan or FaultPlan()
        self.replayed = 0
        self._lock = threading.Lock()
        self._op_lock = threading.Lock()
        self._wire_ops = 0
        self._peers: Dict[str, dict] = {}
        self._affinity: Dict[Tuple[int, ...], str] = {}
        self._requests: Dict[int, FedRequest] = {}
        self._threads: Dict[int, threading.Thread] = {}
        self._next_fid = 0

    # ------------------------------------------------------------- gossip

    def refresh_peers(self) -> None:
        """Scan the gossip directory; a beat with a new sequence number
        re-stamps the peer's local freshness clock (cross-process wall
        clocks are not comparable; monotone seqs + a local monotonic
        stamp are)."""
        now = time.monotonic()
        docs = []                          # read beats OUTSIDE the lock
        for path in sorted(self.gossip_dir.glob("peer_*.json")):
            try:
                docs.append(json.loads(path.read_text()))
            except (OSError, ValueError):
                pass                       # torn/unlinked beat: skip
        fresh = []                         # (name, seq) newly observed
        with self._lock:
            for doc in docs:
                name = doc.get("name")
                if not name:
                    continue
                prev = self._peers.get(name)
                if prev is None or doc["seq"] > prev["seq"]:
                    doc["_seen"] = now
                    self._peers[name] = doc
                    self.metrics.gossip_beats.inc()
                    fresh.append((name, int(doc["seq"])))
            digests = {name: dict(doc.get("metrics") or {})
                       for name, doc in self._peers.items()
                       if now - doc["_seen"] <= self.cfg.lease_ttl_s}
        # spool first-observation stamps OUTSIDE the lock (file I/O):
        # matched with the writers' beat_sent stamps they bound the
        # cross-process clock offset for trace_merge
        spool = get_tracer().spool
        if spool is not None:
            for name, seq in fresh:
                spool.beat_seen(name, seq)
        self.fleet.update(digests)

    def live_peers(self) -> List[dict]:
        """Fresh, non-draining peers; stale ones are counted and
        skipped (never placed on)."""
        self.refresh_peers()
        now = time.monotonic()
        out = []
        with self._lock:
            for name in sorted(self._peers):
                doc = self._peers[name]
                if now - doc["_seen"] > self.cfg.lease_ttl_s:
                    self.metrics.stale_peers.inc()
                    continue
                if doc.get("draining"):
                    continue
                out.append(dict(doc))
        return out

    # --------------------------------------------------------- wire layer

    def _net_op(self) -> int:
        """One wire operation: poll the ``net=`` fault scope against
        the monotone op counter (drop raises here; delay sleeps;
        disconnect is polled separately mid-stream). Returns the op
        number so callers never re-read the counter unsynchronized."""
        with self._op_lock:
            self._wire_ops += 1
            op = self._wire_ops
        if self.plan.take("drop", op, site="net") is not None:
            raise FederationError(f"injected net drop at op {op}")
        delay = self.plan.take("delay", op, site="net")
        if delay is not None:
            time.sleep(delay.arg if delay.arg is not None else 0.05)
        return op

    def _connect(self, url: str) -> http.client.HTTPConnection:
        u = urlparse(url)
        return http.client.HTTPConnection(
            u.hostname, u.port, timeout=self.cfg.wire_timeout_s)

    def _post_json(self, url: str, path: str, obj,
                   headers: Optional[Dict[str, str]] = None) -> dict:
        self._net_op()
        conn = self._connect(url)
        try:
            hdrs = {"Content-Type": "application/json"}
            if headers:
                hdrs.update(headers)
            conn.request("POST", path, json.dumps(obj).encode(), hdrs)
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                raise FederationError(
                    f"POST {path} -> {resp.status}: {body[:200]!r}")
            return json.loads(body)
        finally:
            conn.close()

    def _post_raw(self, url: str, path: str, obj,
                  headers: Optional[Dict[str, str]] = None) -> bytes:
        self._net_op()
        conn = self._connect(url)
        try:
            body = (obj if isinstance(obj, (bytes, bytearray))
                    else json.dumps(obj).encode())
            ctype = ("application/octet-stream"
                     if isinstance(obj, (bytes, bytearray))
                     else "application/json")
            hdrs = {"Content-Type": ctype}
            if headers:
                hdrs.update(headers)
            conn.request("POST", path, body, hdrs)
            resp = conn.getresponse()
            raw = resp.read()
            if resp.status != 200:
                raise FederationError(
                    f"POST {path} -> {resp.status}: {raw[:200]!r}")
            return raw
        finally:
            conn.close()

    # ---------------------------------------------------------- placement

    def _family(self, prompt: List[int]) -> Tuple[int, ...]:
        return tuple(prompt[:16])

    def _place(self, fr: FedRequest) -> Optional[dict]:
        """Best live peer for this prompt: the FleetRouter score over
        peeked hit-frac and pressure, sticky family affinity, name
        tie-break. None when no live peer answers."""
        tracer = get_tracer()
        t_place = tracer.now()
        peers = self.live_peers()
        with self._lock:
            sticky = self._affinity.get(self._family(fr.prompt_tokens))
        scored = []
        for doc in peers:
            headers = None
            pk_ctx = None
            if fr.trace is not None:
                # the peek rides the request's trace: the peer's gateway
                # emits a child span under this hop
                pk_ctx = fr.trace.child()
                headers = {TRACEPARENT_HEADER: pk_ctx.to_header()}
            t0 = tracer.now()
            try:
                pk = self._post_json(doc["url"], "/v1/peek",
                                     {"prompt": fr.prompt_tokens},
                                     headers=headers)
            except (OSError, http.client.HTTPException,
                    FederationError):
                continue               # unreachable despite a fresh
            t1 = tracer.now()
            self.metrics.rtt("peek", doc["name"], (t1 - t0) * 1e3)
            if pk_ctx is not None:
                tracer.complete("peek", t0, t1, cat="federation",
                                args=dict(peer=doc["name"],
                                          **pk_ctx.tags(fr.trace)))
            if pk.get("draining"):     # beat: treat as dead this pass
                continue
            hit = float(pk.get("hit_frac") or 0.0)
            if doc["name"] == sticky:
                hit = max(hit, self.cfg.sticky_bonus)
            score = (self.cfg.prefix_weight * hit
                     - self.cfg.load_weight
                     * float(pk.get("pressure") or 0.0))
            scored.append((score, doc))
        if not scored:
            return None
        scored.sort(key=lambda t: (-t[0], t[1]["name"]))
        best = scored[0][1]
        with self._lock:
            self._affinity[self._family(fr.prompt_tokens)] = \
                best["name"]
        self.metrics.routed_remote.inc()
        t_done = tracer.now()
        self.metrics.rtt("place", best["name"], (t_done - t_place) * 1e3)
        if fr.trace is not None:
            ctx = fr.trace.child()
            tracer.complete("place", t_place, t_done, cat="federation",
                            args=dict(peer=best["name"], fid=fr.fid,
                                      **ctx.tags(fr.trace)))
        return best

    # ------------------------------------------------------------- intake

    def submit(self, prompt_tokens: List[int], max_new_tokens: int,
               sampling: Optional[dict] = None,
               priority: int = 0) -> int:
        """Journal + launch one federated request; returns its fid.
        ``sampling`` is the SamplingParams field dict (an explicit seed
        makes the stream peer-independent; greedy always is)."""
        with self._lock:
            fid = self._next_fid
            self._next_fid += 1
            fr = FedRequest(
                fid=fid, prompt_tokens=[int(t) for t in prompt_tokens],
                max_new_tokens=int(max_new_tokens),
                sampling=dict(sampling) if sampling else None,
                priority=int(priority),
                # the router is this request's ORIGIN: mint the root
                # trace context every downstream hop parents onto
                trace=TraceContext.mint())
            self._requests[fid] = fr
            t = threading.Thread(target=self._serve_request, args=(fr,),
                                 name=f"dla-federation-req-{fid}",
                                 daemon=True)
            self._threads[fid] = t
        get_tracer().async_begin("federation", "federated_request", fid,
                                 **fr.trace.tags())
        t.start()
        return fid

    # --------------------------------------------------------- the reader

    def _serve_request(self, fr: FedRequest) -> None:
        try:
            self._serve_request_inner(fr)
        finally:
            get_tracer().async_end(
                "federation", "federated_request", fr.fid,
                state=fr.state, replays=fr.replays, **fr.trace.tags())

    def _serve_request_inner(self, fr: FedRequest) -> None:
        deadline = time.monotonic() + self.cfg.place_timeout_s
        while True:
            peer = self._place(fr)
            if peer is None:
                if time.monotonic() > deadline:
                    fr.state = "lost"
                    return
                time.sleep(0.1)
                continue
            try:
                final = self._stream_generate(peer, fr)
                while final == "migrated":
                    final = self._resume_after_handoff(fr)
            except (OSError, http.client.HTTPException,
                    FederationError):
                # the peer died (or chaos said it did) mid-request:
                # drop the partial stream and replay from the journal —
                # fold_in(seed, k) sampling regenerates the identical
                # tokens on any peer
                with self._lock:
                    fr.tokens, fr.logprobs = [], []
                    fr.peer = fr.remote_rid = None
                    fr.replays += 1
                    self.replayed += 1
                if fr.replays > self.cfg.max_replays:
                    fr.state = "lost"
                    return
                deadline = time.monotonic() + self.cfg.place_timeout_s
                continue
            fr.state = final
            return

    def _read_events(self, resp, fr: FedRequest,
                     disconnect_after: Optional[int]) -> str:
        """Append streamed token events to ``fr`` until the done event;
        returns its state. A closed/injured socket raises."""
        n_events = 0
        while True:
            line = resp.readline()
            if not line:
                raise FederationError("stream closed before done event")
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            try:
                ev = json.loads(line[len(b"data: "):])
            except ValueError as exc:   # half-written line: the peer
                raise FederationError(  # died mid-event -> replay
                    f"torn event line: {exc}") from exc
            if ev.get("done"):
                return str(ev.get("state"))
            with self._lock:
                fr.tokens.append(int(ev["token"]))
                fr.logprobs.append(float(ev["logprob"]))
            n_events += 1
            if disconnect_after is not None \
                    and n_events >= disconnect_after:
                raise FederationError(
                    "injected net disconnect mid-stream")

    def _stream_generate(self, peer: dict, fr: FedRequest) -> str:
        op = self._net_op()
        disconnect = self.plan.take("disconnect", op, site="net")
        tracer = get_tracer()
        hop = fr.trace.child() if fr.trace is not None else None
        t0 = tracer.now()
        conn = self._connect(peer["url"])
        try:
            headers = {"Content-Type": "application/json"}
            if hop is not None:
                # the remote gateway's wire_request span parents onto
                # this hop's span id
                headers[TRACEPARENT_HEADER] = hop.to_header()
            conn.request("POST", "/v1/generate", json.dumps({
                "prompt": fr.prompt_tokens,
                "max_new_tokens": fr.max_new_tokens,
                "sampling": fr.sampling,
                "priority": fr.priority,
            }).encode(), headers)
            resp = conn.getresponse()
            self.metrics.rtt("stream", peer["name"],
                             (tracer.now() - t0) * 1e3)
            if resp.status != 200:
                raise FederationError(
                    f"generate on {peer['name']} -> {resp.status}: "
                    f"{resp.read()[:200]!r}")
            with self._lock:
                fr.peer = peer["name"]
                rid = resp.headers.get("X-DLA-Rid")
                fr.remote_rid = int(rid) if rid is not None else None
            return self._read_events(
                resp, fr,
                disconnect_after=1 if disconnect is not None else None)
        finally:
            conn.close()
            if hop is not None:
                tracer.complete(
                    "stream_generate", t0, tracer.now(),
                    cat="federation",
                    args=dict(peer=peer["name"], fid=fr.fid,
                              **hop.tags(fr.trace)))

    def _resume_after_handoff(self, fr: FedRequest) -> str:
        """The source stream ended with ``migrated``: wait for
        ``migrate()`` to publish the target, then re-attach with a
        catch-up from the tokens we already hold."""
        if not fr.handoff_event.wait(timeout=self.cfg.wire_timeout_s):
            raise FederationError(
                f"fid {fr.fid}: stream migrated away but no handoff "
                "target was published")
        with self._lock:
            peer_name, rid = fr.handoff
            fr.handoff = None
            fr.handoff_event.clear()
            fr.peer, fr.remote_rid = peer_name, rid
            have = len(fr.tokens)
            url = self._peers[peer_name]["url"]
        self._net_op()
        tracer = get_tracer()
        hop = fr.trace.child() if fr.trace is not None else None
        t0 = tracer.now()
        conn = self._connect(url)
        try:
            conn.request("GET", f"/v1/stream?rid={rid}&have={have}")
            resp = conn.getresponse()
            if resp.status != 200:
                raise FederationError(
                    f"stream attach on {peer_name} -> {resp.status}")
            return self._read_events(resp, fr, disconnect_after=None)
        finally:
            conn.close()
            if hop is not None:
                tracer.complete(
                    "resume_after_handoff", t0, tracer.now(),
                    cat="federation",
                    args=dict(peer=peer_name, fid=fr.fid,
                              **hop.tags(fr.trace)))

    # ------------------------------------------------------------ handoff

    def migrate(self, fid: int, target_name: str) -> int:
        """Move a mid-decode request to ``target_name`` via the
        serialized MigrationTicket wire format; the reader thread
        re-attaches on the target. Returns the new remote rid."""
        with self._lock:
            fr = self._requests[fid]
            src_name, rid = fr.peer, fr.remote_rid
            if src_name is None or rid is None:
                raise FederationError(f"fid {fid} is not streaming yet")
            src_url = self._peers[src_name]["url"]
            dst_url = self._peers[target_name]["url"]
        tracer = get_tracer()
        hop = fr.trace.child() if fr.trace is not None else None
        headers = ({TRACEPARENT_HEADER: hop.to_header()}
                   if hop is not None else None)
        t0 = tracer.now()
        blob = self._post_raw(src_url, "/v1/migrate_out", {"rid": rid},
                              headers=headers)
        self.metrics.handoff_bytes.inc(len(blob))
        ack = json.loads(self._post_raw(dst_url, "/v1/migrate_in", blob))
        if hop is not None:
            tracer.complete(
                "migrate", t0, tracer.now(), cat="federation",
                args=dict(src=src_name, dst=target_name, fid=fid,
                          **hop.tags(fr.trace)))
        with self._lock:
            fr.handoff = (target_name, int(ack["rid"]))
            fr.handoff_event.set()
        return int(ack["rid"])

    # ------------------------------------------------------------ results

    @property
    def requests_lost(self) -> int:
        with self._lock:
            return sum(1 for fr in self._requests.values()
                       if fr.state == "lost")

    def results(self, timeout_s: float = 600.0) -> Dict[int, FedRequest]:
        """Join every reader thread; returns fid -> FedRequest."""
        deadline = time.monotonic() + timeout_s
        for fid, t in list(self._threads.items()):
            t.join(timeout=max(0.0, deadline - time.monotonic()))
            if t.is_alive():
                raise FederationError(
                    f"fid {fid} still streaming after {timeout_s}s")
        with self._lock:
            return dict(self._requests)

    def drain_peer(self, name: str) -> None:
        """Ask one peer to drain (its /healthz flips to 503 and its
        gossip beats start carrying draining=True)."""
        with self._lock:
            url = self._peers[name]["url"]
        self._post_json(url, "/admin/drain", {})

    def serve_metrics(self, port: int = 0, host: str = "127.0.0.1"):
        """Expose this router's registry — the ``serving/federation/*``
        counters/RTT histograms plus the gossip-fed ``fleet/*`` panel —
        on a ``/metrics`` endpoint (the exporter idiom). Returns the
        started :class:`~dla_tpu.telemetry.exporter.MetricsHTTPServer`;
        the caller owns ``stop()``."""
        from dla_tpu.telemetry.exporter import MetricsHTTPServer
        return MetricsHTTPServer(self.metrics.registry, port=port,
                                 host=host)
