"""Serving fleet: a multi-engine router with cache-aware placement,
SLO-driven autoscaling, and fleet-wide draining.

One ``ServingEngine`` is a hard throughput ceiling; the ``FleetRouter``
fronts N of them — each member on its own worker thread with its own
scheduler, page pool, prefix cache, metrics registry, and per-member
``Supervisor`` (a wedged member rebuilds and replays while the router
keeps steering new arrivals elsewhere). The router exposes the same
``submit / step / has_work / result / drain`` surface as a single
engine, so ``eval_latency``'s open-loop driver, ``RolloutEngine``, and
the Supervisor factory pattern work unchanged on top of a fleet.

Placement scores every live member by:

- **prefix affinity** — the longest-prefix-cache match length via the
  read-only ``PrefixCache.peek()`` (no increfs, no LRU touch: the N-1
  losing candidates must be left exactly as found), plus a sticky
  family map that keeps a request family on the member that owns its
  pages even before the first member's prefix registers;
- **load** — page-pool occupancy plus normalized queue depth (and the
  admission controller's configured bound when shedding is on);
- **draining state** — members answering ``/healthz`` 503 ``draining``
  (supervisor breaker trip, scale-down, or fleet drain) take no new
  placements.

The ``Autoscaler`` consumes the SLO burn-rate signal ``telemetry/slo``
already computes plus fleet pressure, spawns members through the same
engine factory the supervisors rebuild with, and retires members
through the existing draining contract: queued requests are
redistributed to peers FIRST (rid, sampling params, and streamed
tokens preserved through ``engine.restore`` — the supervisor-replay
idiom), in-flight decodes run to completion, and the member is
reclaimed only after its last request resolves. Zero lost requests,
ever.

``FleetConfig.roles`` disaggregates the fleet into prefill and decode
members: prefill-role members run chunked prefill only, and after every
router step the handoff pass exports each freshly-prefilled request's
committed KV pages as a :class:`~dla_tpu.serving.migration
.MigrationTicket` and installs it on the least-pressured decode-capable
member (``KVMigrator`` device-to-device transfer, one jitted gather on
the source and one jitted scatter on the target). The journal entry
moves between supervisors atomically with the install — popped from the
source before, re-inserted on failure — so a request lands exactly once
even when the source dies mid-handoff. Scale-down migrates committed KV
the same way instead of re-prefilling on a peer.

Outputs are placement-independent by construction: generated token k
of a request is sampled with ``fold_in(PRNGKey(seed), k)`` where the
seed depends only on (engine config seed, rid) or on explicit
``SamplingParams`` — never on slot, batch, or member — so a routed
fleet reproduces a single engine's tokens bit-for-bit on the same
trace. Fleet metrics live in the ROUTER's registry, not a member's,
so ``serving/fleet/*`` totals are monotone across member rebuilds by
construction.
"""
from __future__ import annotations

import functools
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, fields
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from dla_tpu.serving.migration import (TRANSPORTS, KVMigrator,
                                       MigrationConfig, MigrationError)
from dla_tpu.serving.scheduler import TERMINAL_STATES, Request, RequestState
from dla_tpu.serving.resilience import Supervisor, SupervisorConfig
from dla_tpu.telemetry.registry import MetricRegistry

PLACEMENTS = ("cache_aware", "random", "round_robin")
ROLES = ("prefill", "decode", "mixed")


def broadcast_waves(n: int, branch: int) -> List[List[int]]:
    """Partition member indices ``0..n-1`` into broadcast-tree waves:
    the root (the caller — learner or router) sends to ``branch``
    members in wave 0, then every member that already holds the payload
    forwards to ``branch`` more per wave, so coverage multiplies by
    ``1 + branch`` each wave and the wave count — the wall-clock bound
    when each wave runs concurrently on the target members' executors —
    is ``ceil(log_{1+branch}(n/branch + 1))``, not ``n``. Shared by the
    sampler-fleet refit fanout (rollout.actor_fleet) and
    :meth:`FleetRouter.publish_params`."""
    if branch < 1:
        raise ValueError(f"broadcast branch must be >= 1, got {branch}")
    waves: List[List[int]] = []
    holders = 1                     # the root already has the payload
    nxt = 0
    while nxt < n:
        wave = list(range(nxt, min(n, nxt + holders * branch)))
        waves.append(wave)
        nxt += len(wave)
        holders += len(wave)
    return waves


@dataclass(frozen=True)
class FleetConfig:
    """Router + autoscaler knobs (``latency.serving.fleet`` in config).

    ``placement`` picks the routing policy: ``cache_aware`` (peek +
    load + affinity, the default), ``random`` (seeded — the A/B
    baseline that destroys cross-request prefix locality), or
    ``round_robin``. Autoscaling is off unless ``autoscale`` is set;
    scale decisions need ``patience`` consecutive over/under-threshold
    checks, one check every ``check_every`` router steps.

    ``roles`` disaggregates the fleet: one role per startup member
    (``prefill`` members run chunked prefill only and hand finished
    prefixes to the least-pressured ``decode``/``mixed`` member as KV
    migration tickets after every router step; ``decode`` members take
    no router admissions). None keeps every member ``mixed`` — the
    co-scheduled default. Explicit roles pin the topology, so they are
    mutually exclusive with ``autoscale``. ``migration_transport`` is
    the :class:`~dla_tpu.serving.migration.MigrationConfig` transport
    the handoff path uses. ``max_handoff_retries`` bounds how many
    times one request's decode handoff may be refused (page exhaustion,
    geometry mismatch) before the router gives up on migrating it:
    the request then finishes decoding on its prefill member, or is
    shed if that member is draining — never an unbounded
    refuse/re-insert cycle."""

    engines: int = 2                   # members at startup
    min_engines: int = 1
    max_engines: int = 4
    placement: str = "cache_aware"
    prefix_weight: float = 2.0         # score weight of peek hit frac
    load_weight: float = 1.0           # score weight of member pressure
    sticky_bonus: float = 0.5          # hit-frac stand-in for a sticky
                                       # family whose pages are not yet
                                       # registered (in-flight prefill)
    adapter_weight: float = 1.0        # score weight of the tenant's
                                       # adapter residency (device-hot
                                       # 1.0, published-but-spilled 0.5)
    autoscale: bool = False
    scale_up_burn: float = 1.0         # max member SLO burn rate >= this
    scale_up_pressure: float = 0.85    # mean member pressure >= this
    scale_down_pressure: float = 0.25  # mean member pressure <= this
    patience: int = 3                  # consecutive checks before acting
    check_every: int = 10              # router steps between checks
    seed: int = 0                      # random-placement stream
    roles: Optional[Tuple[str, ...]] = None  # per-slot disaggregation
    migration_transport: str = "auto"  # handoff KV transport
    max_handoff_retries: int = 8       # refusals before decoding at home

    def __post_init__(self):
        if self.placement not in PLACEMENTS:
            raise ValueError(
                f"fleet placement must be one of {PLACEMENTS}, "
                f"got {self.placement!r}")
        if self.engines < 1:
            raise ValueError("fleet needs engines >= 1")
        if not (1 <= self.min_engines <= self.max_engines):
            raise ValueError("fleet wants 1 <= min_engines <= max_engines")
        if not (self.min_engines <= self.engines <= self.max_engines):
            raise ValueError(
                "fleet wants min_engines <= engines <= max_engines")
        if self.max_handoff_retries < 1:
            raise ValueError("fleet needs max_handoff_retries >= 1")
        if self.migration_transport not in TRANSPORTS:
            raise ValueError(
                f"fleet migration_transport must be one of {TRANSPORTS}, "
                f"got {self.migration_transport!r}")
        if self.roles is not None:
            if len(self.roles) != self.engines:
                raise ValueError(
                    f"fleet roles must name every startup member: got "
                    f"{len(self.roles)} roles for {self.engines} engines")
            bad = sorted(set(self.roles) - set(ROLES))
            if bad:
                raise ValueError(
                    f"fleet roles must be drawn from {ROLES}, got {bad}")
            if all(r == "prefill" for r in self.roles):
                raise ValueError(
                    "fleet roles need at least one decode-capable "
                    "(decode/mixed) member to land handoffs on")
            if self.autoscale:
                raise ValueError(
                    "explicit fleet roles pin the topology and cannot "
                    "be combined with autoscale")

    def role_for(self, slot: int) -> str:
        """Slot -> role, defaulting to ``mixed`` past the pinned list
        (slots recycled by a future scale cycle stay co-scheduled)."""
        if self.roles is not None and 0 <= slot < len(self.roles):
            return self.roles[slot]
        return "mixed"

    @classmethod
    def from_config(cls, cfg: Optional[Dict]) -> Optional["FleetConfig"]:
        """None/falsy or ``enabled: false`` -> None (no fleet); unknown
        keys raise — config drift surfaces at startup, not at 3am."""
        if not cfg:
            return None
        cfg = dict(cfg)
        if not cfg.pop("enabled", True):
            return None
        known = {f.name for f in fields(cls)}
        unknown = set(cfg) - known
        if unknown:
            raise ValueError(f"unknown fleet config keys: {sorted(unknown)}")
        if isinstance(cfg.get("roles"), list):
            cfg["roles"] = tuple(cfg["roles"])
        return cls(**cfg)


class FleetMetrics:
    """The ``serving/fleet/*`` panel. Instruments are owned by the
    router's registry, which outlives every member engine (and its
    per-rebuild registries) — monotonicity across rebuilds needs no
    re-seeding here, unlike the supervisor counters."""

    def __init__(self, registry: Optional[MetricRegistry] = None):
        self.registry = registry or MetricRegistry()
        r = self.registry
        self.engines_active = r.gauge("serving/fleet/engines_active")
        self.routed_by_prefix = r.counter("serving/fleet/routed_by_prefix")
        self.routed_by_load = r.counter("serving/fleet/routed_by_load")
        self.scale_ups = r.counter("serving/fleet/scale_ups")
        self.scale_downs = r.counter("serving/fleet/scale_downs")
        self.rebalanced_requests = r.counter(
            "serving/fleet/rebalanced_requests")
        self.failed_handoffs = r.counter(
            "serving/migration/failed_handoffs")
        self._slot_gauges: set = set()

    def ensure_slot_gauge(self, slot: int,
                          fn: Callable[[], float]) -> None:
        """Per-member occupancy FuncGauge, registered once per slot
        (slots are reused across scale cycles; the read-through closure
        resolves the CURRENT occupant, 0.0 when the slot is empty)."""
        if slot in self._slot_gauges:
            return
        self._slot_gauges.add(slot)
        self.registry.func_gauge(
            f"serving/fleet/engine/{slot}/page_occupancy", fn)

    def snapshot(self) -> Dict[str, float]:
        return self.registry.snapshot()


class _Member:
    """One fleet slot: a supervised engine pinned to its own worker
    thread (a single-thread executor keeps the thread persistent and
    the member's JAX dispatch serialized)."""

    def __init__(self, slot: int, sup: Supervisor, role: str = "mixed"):
        self.slot = slot
        self.sup = sup
        self.role = role               # prefill | decode | mixed
        self.pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"dla-fleet-engine-{slot}")
        self.retiring = False          # scale-down in progress

    @property
    def engine(self):
        return self.sup.engine

    def accepting(self) -> bool:
        return not self.retiring and not self.sup.draining

    def close(self) -> None:
        self.sup.close()
        self.pool.shutdown(wait=True)


class Autoscaler:
    """SLO-burn + pressure driven member count. Pure decision logic —
    the router owns spawn/retire mechanics; this just watches the
    signals ``_resilience_pass`` already trusts (max member burn rate,
    mean of max(occupancy, queue fraction)) and debounces with
    ``patience`` so one hot check never flaps the fleet."""

    def __init__(self, router: "FleetRouter", cfg: FleetConfig):
        self.router = router
        self.cfg = cfg
        self._up_streak = 0
        self._down_streak = 0

    def evaluate(self) -> None:
        r, cfg = self.router, self.cfg
        active = [m for m in r.members() if not m.retiring]
        if not active:
            return
        pressure = float(np.mean([r.member_pressure(m) for m in active]))
        burn = max(r.member_burn(m) for m in active)
        want_up = (pressure >= cfg.scale_up_pressure
                   or burn >= cfg.scale_up_burn)
        want_down = (pressure <= cfg.scale_down_pressure
                     and burn < cfg.scale_up_burn)
        self._up_streak = self._up_streak + 1 if want_up else 0
        self._down_streak = self._down_streak + 1 if want_down else 0
        if self._up_streak >= cfg.patience and len(active) < cfg.max_engines:
            self._up_streak = 0
            r.scale_up()
        elif (self._down_streak >= cfg.patience
              and len(active) > cfg.min_engines):
            self._down_streak = 0
            r.scale_down()


class FleetRouter:
    """N supervised ``ServingEngine`` members behind one engine-shaped
    front end (see the module docstring for the architecture).

    ``factory(slot)`` builds a fresh engine for fleet slot ``slot`` —
    the same callable serves initial spawn, supervisor rebuild after a
    fault, and autoscaler scale-up, so every generation of a slot's
    engine shares its config (including ``cfg.seed``, which is what
    keeps default-seeded sampling placement-independent)."""

    def __init__(self, factory: Callable[[int], object],
                 cfg: Optional[FleetConfig] = None,
                 supervisor: Optional[SupervisorConfig] = None,
                 registry: Optional[MetricRegistry] = None):
        self.factory = factory
        self.cfg = cfg or FleetConfig()
        self.sup_cfg = supervisor
        self.metrics = FleetMetrics(registry)
        self._slots: Dict[int, _Member] = {}
        self._placement: Dict[int, _Member] = {}       # rid -> member
        self._affinity: Dict[Tuple[int, ...], int] = {}  # family -> slot
        self._archive: Dict[int, Request] = {}  # results of retired slots
        self._handoff_fails: Dict[int, int] = {}  # rid -> refusal count
        self._handoff_pinned: set = set()  # rids decoding at home for good
        self._rs = np.random.RandomState(self.cfg.seed)
        self._rr = 0                   # round-robin cursor
        self._steps = 0
        self._draining = False
        self.autoscaler = Autoscaler(self, self.cfg)
        self.migrator = KVMigrator(MigrationConfig(
            transport=self.cfg.migration_transport))
        for _ in range(self.cfg.engines):
            self._spawn()

    # ------------------------------------------------------------ members

    def members(self) -> List[_Member]:
        return [self._slots[s] for s in sorted(self._slots)]

    @property
    def num_engines(self) -> int:
        return len([m for m in self._slots.values() if not m.retiring])

    def member_pressure(self, member: _Member) -> float:
        """The scalar ``_resilience_pass`` steers by: max of page-pool
        occupancy and queue depth over its bound."""
        eng = member.engine
        occ = eng.cache.allocator.occupancy
        qcap = (eng.admission.cfg.max_queue_depth
                if eng.admission is not None
                else max(8, 2 * eng.cfg.num_slots))
        return max(occ, eng.scheduler.queue_depth / max(1, qcap))

    def member_burn(self, member: _Member) -> float:
        eng = member.engine
        slo = eng.slo
        burn = (max(slo.burn_rate(obj) for obj in slo.slos)
                if slo is not None and slo.slos else 0.0)
        # per-tenant SLOs feed the same autoscale signal: one tenant
        # burning its budget scales the fleet even when the aggregate
        # latency surface looks healthy
        tenants = getattr(eng, "tenants", None)
        if tenants is not None:
            burn = max(burn, tenants.max_burn())
        return burn

    def _spawn(self) -> _Member:
        slot = next(i for i in range(len(self._slots) + 1)
                    if i not in self._slots)
        sup = Supervisor(functools.partial(self.factory, slot),
                         self.sup_cfg)
        role = self.cfg.role_for(slot)
        if role == "mixed":
            # a factory may disaggregate on its own (per-slot engine
            # configs) — honor the engine's declared role in that case
            role = getattr(sup.engine.cfg, "role", "mixed")
        if role == "prefill" and sup.engine.cfg.prefill_chunk <= 0:
            raise ValueError(
                f"fleet slot {slot} is prefill-role but its engine has "
                "prefill_chunk=0: chunked prefill is the whole job")
        member = _Member(slot, sup, role)
        self._slots[slot] = member
        self.metrics.ensure_slot_gauge(slot, functools.partial(
            self._slot_occupancy, slot))
        self.metrics.engines_active.set(self.num_engines)
        return member

    def _slot_occupancy(self, slot: int) -> float:
        member = self._slots.get(slot)
        if member is None:
            return 0.0
        return float(member.engine.cache.allocator.occupancy)

    # ------------------------------------------------------------- intake

    def submit(self, prompt_tokens: List[int], max_new_tokens: int,
               arrival_time: Optional[float] = None,
               deadline_s: Optional[float] = None,
               priority: int = 0, sampling=None,
               tenant: Optional[str] = None) -> int:
        candidates = [m for m in self.members()
                      if m.accepting() and m.role != "decode"]
        if self._draining or not candidates:
            raise RuntimeError(
                "fleet is draining: no member accepts admissions")
        member, by_prefix = self._choose(prompt_tokens, candidates,
                                         tenant=tenant)
        rid = member.sup.submit(
            prompt_tokens, max_new_tokens, arrival_time=arrival_time,
            deadline_s=deadline_s, priority=priority, sampling=sampling,
            tenant=tenant)
        self._placement[rid] = member
        self._affinity[self._family(prompt_tokens)] = member.slot
        if by_prefix:
            self.metrics.routed_by_prefix.inc()
        else:
            self.metrics.routed_by_load.inc()
        return rid

    def _family(self, prompt_tokens: List[int]) -> Tuple[int, ...]:
        ps = self.members()[0].engine.cfg.page_size if self._slots else 16
        return tuple(prompt_tokens[:ps])

    def _peek(self, member: _Member, prompt_tokens: List[int],
              tenant: Optional[str] = None) -> int:
        eng = member.engine
        if eng.prefix_cache is None:
            return 0
        return eng.prefix_cache.peek(prompt_tokens, eng.cfg.prefill_chunk,
                                     namespace=tenant)

    def _adapter_heat(self, member: _Member,
                      tenant: Optional[str]) -> float:
        """Adapter residency scored like prefix-cache heat: a member
        whose pool already holds the tenant's adapter on device serves
        its first token without a host->device load (1.0); a member
        holding only the spilled host copy avoids a publish but pays
        the load (0.5); anywhere else the adapter is absent (0.0)."""
        if tenant is None:
            return 0.0
        store = getattr(member.engine, "adapter_store", None)
        if store is None or not store.has(tenant):
            return 0.0
        return 1.0 if store.resident(tenant) else 0.5

    def _choose(self, prompt_tokens: List[int],
                candidates: List[_Member],
                tenant: Optional[str] = None) -> Tuple[_Member, bool]:
        """-> (member, routed_by_prefix). Deterministic: score ties
        break toward the sticky-affinity slot, then the lowest slot."""
        if self.cfg.placement == "random":
            return candidates[self._rs.randint(len(candidates))], False
        if self.cfg.placement == "round_robin":
            member = candidates[self._rr % len(candidates)]
            self._rr += 1
            return member, False
        n = max(1, len(prompt_tokens))
        sticky = self._affinity.get(self._family(prompt_tokens))
        best, best_key, best_hit = None, None, 0.0
        for m in candidates:
            # affinity covers the registration gap: the family owner's
            # first prefill may still be in flight, so peek reads 0
            # there — score it as if the expected shared prefix were
            # already cached, or placement scatters a family submitted
            # in one burst across the whole fleet
            hit = self._peek(m, prompt_tokens, tenant) / n
            if m.slot == sticky:
                hit = max(hit, self.cfg.sticky_bonus)
            score = (self.cfg.prefix_weight * hit
                     + self.cfg.adapter_weight * self._adapter_heat(
                         m, tenant)
                     - self.cfg.load_weight * self.member_pressure(m))
            key = (score, -m.slot)
            if best is None or key > best_key:
                best, best_key, best_hit = m, key, hit
        return best, best_hit > 0

    # ----------------------------------------------------------- stepping

    def step(self) -> List[Tuple[int, int]]:
        """One fleet step: every member advances one supervised engine
        step on its own thread; emitted (rid, token) streams merge in
        slot order (deterministic — member states are independent, so
        thread completion order cannot change any token)."""
        members = self.members()
        futures = [(m, m.pool.submit(m.sup.step)) for m in members
                   if m.sup.has_work() or not m.retiring]
        emitted: List[Tuple[int, int]] = []
        for _, fut in futures:
            emitted.extend(fut.result())
        self._steps += 1
        self._handoff_pass()
        self._finalize_retired()
        if self.cfg.autoscale and not self._draining \
                and self._steps % self.cfg.check_every == 0:
            self.autoscaler.evaluate()
        return emitted

    # ``poll`` is the streaming-consumer name for the same operation
    poll = step

    def publish_params(self, params, donate: bool = False,
                       branch: int = 2) -> None:
        """Fleet-wide weight refit: publish ``params`` into every live
        member's engine via the broadcast-tree wave schedule
        (:func:`broadcast_waves`) — each wave's publishes run
        concurrently on the target members' own executors, so wall time
        is bounded by the tree depth, not the member count. The swap is
        the usual zero-recompile pointer update per member. Note:
        publishes reach the LIVE engines only; a later supervisor
        rebuild re-reads the caller's factory tree, so callers that
        refit must also update whatever their factory closes over (the
        RolloutEngine-per-member sampler fleet does; see
        rollout.actor_fleet)."""
        members = self.members()
        for wave in broadcast_waves(len(members), branch):
            futures = [members[i].pool.submit(
                members[i].engine.publish_params, params, donate=donate)
                for i in wave]
            for fut in futures:
                fut.result()

    def publish_adapter(self, tenant: str, tree, *, alpha=None,
                        rank=None, branch: int = 2) -> None:
        """Fleet-wide adapter refit: publish ``tenant``'s LoRA tree into
        every live member's AdapterStore on the same broadcast-tree wave
        schedule as :meth:`publish_params` — every member can then land
        the tenant's requests (placement still prefers members where the
        adapter is device-resident, see ``adapter_weight``). Same
        caveat: a supervisor rebuild re-runs the factory, which must
        republish adapters it wants the rebuilt engine to serve."""
        members = self.members()
        for wave in broadcast_waves(len(members), branch):
            futures = [members[i].pool.submit(
                members[i].engine.publish_adapter, tenant, tree,
                alpha=alpha, rank=rank)
                for i in wave]
            for fut in futures:
                fut.result()

    def has_work(self) -> bool:
        return any(m.sup.has_work() for m in self.members())

    def result(self, rid: int) -> Request:
        member = self._placement.get(rid)
        if member is not None and rid in member.sup.journal:
            return member.sup.result(rid)
        for m in self.members():       # burst-synthetic intake
            if rid in m.sup.journal:
                return m.sup.result(rid)
        return self._archive[rid]

    def cancel(self, rid: int, reason: str = "cancelled") -> Request:
        """Client-initiated cancellation, routed to whichever member
        currently owns the request (handoffs move ownership)."""
        member = self._placement.get(rid)
        if member is None or rid not in member.sup.journal:
            member = next((m for m in self.members()
                           if rid in m.sup.journal), None)
        if member is None:
            return self._archive[rid]
        return member.sup.cancel(rid, reason)

    def export_request(self, rid: int):
        """Export ``rid``'s resumable state for a CROSS-FLEET handoff
        (the gateway's ``/v1/migrate_out``): the owning member's journal
        entry is popped and its engine copy released — from here the
        serialized ticket IS the request, and the shipper owns replay
        if the remote install fails (FederatedRouter journals prompts
        for exactly that). Raises :class:`MigrationError` when the
        request is not resumable in place."""
        member = self._placement.get(rid)
        if member is None or rid not in member.sup.journal:
            member = next((m for m in self.members()
                           if rid in m.sup.journal), None)
        if member is None:
            raise MigrationError(f"request {rid} is not on this fleet")
        ticket = self.migrator.export_ticket(
            member.engine, rid, src_slot=member.slot)
        entry = member.sup.journal.pop(rid, None)
        member.engine.release_migrated(rid)
        if entry is not None:
            self._archive[rid] = entry.request
        self._placement.pop(rid, None)
        return ticket

    def import_request(self, ticket) -> Request:
        """Install a cross-fleet ticket onto the least-pressured
        decode-capable member, journaled for replay like any local
        submission (the exactly-once discipline of
        ``_migrate_request``, with the source on another host)."""
        from dla_tpu.serving.resilience import JournalEntry
        candidates = [m for m in self.members()
                      if m.accepting() and m.role != "prefill"]
        if self._draining or not candidates:
            raise MigrationError(
                "fleet is draining: no member accepts an import")
        dst = min(candidates,
                  key=lambda m: (self.member_pressure(m), m.slot))
        req = self.migrator.install(dst.engine, ticket)
        dst.sup.journal[req.rid] = JournalEntry(
            prompt_tokens=list(req.prompt_tokens),
            max_new_tokens=int(req.max_new_tokens),
            priority=req.priority, arrival_time=req.arrival_time,
            deadline=req.deadline, streamed=list(req.generated),
            done=req.state in TERMINAL_STATES, request=req,
            sampling=req.sampling,
            streamed_logps=list(req.generated_logprobs),
            tenant=req.tenant,
            migrated_from=ticket.src_slot, migrations=1)
        self._placement[req.rid] = dst
        self._affinity[self._family(list(req.prompt_tokens))] = dst.slot
        return req

    def peek_score(self, prompt_tokens: List[int],
                   tenant: Optional[str] = None) -> Tuple[float, float]:
        """-> (best peeked hit-frac, mean member pressure) over the
        accepting members — the gateway's ``/v1/peek`` surface, so a
        FederatedRouter scores this fleet with the same inputs
        ``_choose`` uses locally."""
        candidates = [m for m in self.members()
                      if m.accepting() and m.role != "decode"]
        if self._draining or not candidates:
            return 0.0, 1.0
        n = max(1, len(prompt_tokens))
        hit = max(self._peek(m, prompt_tokens, tenant) / n
                  for m in candidates)
        pressure = float(np.mean(
            [self.member_pressure(m) for m in candidates]))
        return hit, pressure

    def results(self) -> Dict[int, Request]:
        out = dict(self._archive)
        for m in self.members():
            out.update(m.sup.results())
        return out

    def run_until_drained(self, max_steps: int = 100000,
                          on_cap: str = "raise") -> Dict[int, Request]:
        for _ in range(max_steps):
            if not self.has_work():
                return self.results()
            self.step()
        if on_cap == "shed":
            for m in self.members():
                if m.sup.has_work():
                    m.engine._shed_stragglers()
            return self.results()
        raise RuntimeError(
            f"fleet did not drain in {max_steps} steps")

    # ----------------------------------------------------------- handoffs

    def _handoff_pass(self) -> None:
        """Ship every freshly-prefilled request off prefill-role members
        to the least-pressured decode-capable member. Runs synchronously
        between fleet steps — member faults only surface inside
        ``engine.step()``, so nothing can interrupt a handoff halfway.

        Refusals (page exhaustion, geometry mismatch) are retried on
        later passes at most ``max_handoff_retries`` times per request;
        past the bound the request is pinned to finish decoding on its
        prefill member (the engine is decode-capable, the role is router
        policy) — or shed if that member is draining — and
        ``serving/migration/failed_handoffs`` ticks once."""
        sources = [m for m in self.members() if m.role == "prefill"]
        if not sources:
            return
        if self._handoff_pinned or self._handoff_fails:
            # retire bookkeeping only for requests the source scheduler
            # no longer tracks (terminal): an evicted-but-live request
            # keeps its refusal count and its pin across re-admission
            live = {req.rid for m in sources
                    for req in (*m.engine.scheduler.queue,
                                *m.engine.scheduler.prefilling.values(),
                                *m.engine.scheduler.running.values())}
            self._handoff_pinned &= live
            self._handoff_fails = {r: c for r, c in
                                   self._handoff_fails.items() if r in live}
        for src in sources:
            for req in list(src.engine.scheduler.running.values()):
                if not req.generated:
                    continue           # prefill not finished this step
                if req.rid in self._handoff_pinned:
                    continue           # gave up: decoding at home
                sinks = [m for m in self.members()
                         if m is not src and m.accepting()
                         and m.role != "prefill"]
                dedicated = [m for m in sinks if m.role == "decode"]
                if dedicated:
                    sinks = dedicated
                if not sinks:
                    return             # decode locally; retry next step
                dst = min(sinks, key=lambda m: (
                    self.member_pressure(m), m.slot))
                if self._migrate_request(src, req, dst):
                    self._handoff_fails.pop(req.rid, None)
                else:
                    self._note_handoff_failure(src, req)

    def _note_handoff_failure(self, src: _Member, req: Request) -> None:
        """One refused handoff attempt; enforce the retry bound."""
        fails = self._handoff_fails.get(req.rid, 0) + 1
        if fails < self.cfg.max_handoff_retries:
            self._handoff_fails[req.rid] = fails
            return
        self._handoff_fails.pop(req.rid, None)
        self.metrics.failed_handoffs.inc()
        if src.accepting():
            self._handoff_pinned.add(req.rid)
            return
        # a draining/retiring source cannot keep the decode: terminal shed
        # (tokens-so-far preserved on the request, journal entry closed)
        src.engine.scheduler.cancel(req, "handoff_failed",
                                    RequestState.SHED)
        entry = src.sup.journal.get(req.rid)
        if entry is not None:
            entry.request = req
            entry.done = True

    def _migrate_request(self, src: _Member, req: Request,
                         dst: _Member) -> bool:
        """Move one mid-decode request ``src`` -> ``dst`` by KV page
        migration, exactly once: the journal entry is popped from the
        source supervisor BEFORE the install (a source crash after a
        successful install must not replay the request there) and
        re-inserted on failure (the request keeps decoding at home, a
        later pass retries). Refusals are already counted on the
        refusing engine's ``serving/migration/failed_migrations``."""
        try:
            ticket = self.migrator.export_ticket(
                src.engine, req.rid, src_slot=src.slot)
        except MigrationError:
            return False
        entry = src.sup.journal.pop(req.rid, None)
        try:
            moved = self.migrator.install(dst.engine, ticket)
        except MigrationError:
            if entry is not None:
                src.sup.journal[req.rid] = entry
            return False
        src.engine.release_migrated(req.rid)
        if entry is not None:
            entry.request = moved
            entry.done = moved.state in TERMINAL_STATES
            entry.migrated_from = src.slot
            entry.migrations += 1
            dst.sup.journal[req.rid] = entry
        self._placement[req.rid] = dst
        self._affinity[self._family(list(req.prompt_tokens))] = dst.slot
        return True

    def _migrate_running(self, member: _Member) -> int:
        """Scale-down path: migrate the member's mid-decode requests to
        the least-pressured decode-capable peer instead of letting them
        run out on the retiring member (frees the slot sooner) or
        re-prefilling elsewhere (wastes the committed KV)."""
        peers = [m for m in self.members()
                 if m is not member and m.accepting()
                 and m.role != "prefill"]
        if not peers:
            return 0
        moved = 0
        for req in list(member.engine.scheduler.running.values()):
            if not req.generated:
                continue
            dst = min(peers, key=lambda m: (
                self.member_pressure(m), m.slot))
            if self._migrate_request(member, req, dst):
                moved += 1
        return moved

    # ------------------------------------------------------------ scaling

    def scale_up(self) -> _Member:
        member = self._spawn()
        self.metrics.scale_ups.inc()
        return member

    def scale_down(self, member: Optional[_Member] = None) -> None:
        """Retire one member through the draining contract: queued work
        moves to peers first (rid/sampling/streamed preserved), the
        member stops admitting, in-flight decodes run to completion
        under ``step()``, and the slot is reclaimed by
        ``_finalize_retired`` after the last request resolves."""
        active = [m for m in self.members() if not m.retiring]
        if len(active) <= 1:
            raise RuntimeError("cannot scale down the last fleet member")
        if member is None:
            # least sunk work: emptiest queue, fewest active slots
            member = min(active, key=lambda m: (
                m.engine.scheduler.queue_depth,
                m.engine.scheduler.active_count, m.slot))
        if member.role != "prefill" and not any(
                m.role != "prefill" for m in active if m is not member):
            raise RuntimeError(
                "cannot retire the last decode-capable fleet member")
        moved = self._rebalance_queued(member)
        moved += self._migrate_running(member)
        member.retiring = True
        member.engine.begin_drain()
        self.metrics.scale_downs.inc()
        self.metrics.rebalanced_requests.inc(moved)
        self.metrics.engines_active.set(self.num_engines)

    def _rebalance_queued(self, member: _Member) -> int:
        """Move every queued request off ``member`` onto a scoring peer
        via ``engine.restore`` — the supervisor-replay idiom, so rid,
        sampling params, streamed tokens, and journal entry all carry
        over and a later peer rebuild still replays the moved work."""
        peers = [m for m in self.members()
                 if m is not member and m.accepting()]
        # restore re-runs prefill on the peer, so prefer prefill-capable
        # members; a decode-only fleet remnant still beats losing work
        non_decode = [m for m in peers if m.role != "decode"]
        if non_decode:
            peers = non_decode
        if not peers:
            return 0
        src = member.sup
        moved = 0
        for req in list(member.engine.scheduler.queue):
            entry = src.journal.get(req.rid)
            member.engine.scheduler.cancel(req, "rebalanced")
            if entry is None or entry.done:
                continue
            dst, _ = self._choose(entry.prompt_tokens, peers,
                                  tenant=entry.tenant)
            restored = dst.engine.restore(
                entry.prompt_tokens, entry.max_new_tokens,
                generated=list(entry.streamed),
                arrival_time=entry.arrival_time,
                deadline=entry.deadline, priority=entry.priority,
                rid=req.rid, sampling=entry.sampling,
                generated_logprobs=list(entry.streamed_logps),
                tenant=entry.tenant)
            entry.request = restored
            entry.done = restored.state in TERMINAL_STATES
            del src.journal[req.rid]
            dst.sup.journal[req.rid] = entry
            self._placement[req.rid] = dst
            self._affinity[self._family(entry.prompt_tokens)] = dst.slot
            moved += 1
        return moved

    def _finalize_retired(self) -> None:
        """Reclaim retired members whose last in-flight request has
        resolved: archive their terminal results, drop their affinity
        entries, close the supervised engine, release the thread."""
        for member in [m for m in self.members()
                       if m.retiring and not m.sup.has_work()]:
            for rid, req in member.sup.results().items():
                self._archive[rid] = req
                self._placement.pop(rid, None)
            for fam in [k for k, s in self._affinity.items()
                        if s == member.slot]:
                del self._affinity[fam]
            del self._slots[member.slot]
            member.close()
        self.metrics.engines_active.set(self.num_engines)

    # ------------------------------------------------------------- drain

    def begin_drain(self) -> None:
        """Fleet-wide drain: every member enters the single-engine
        draining contract (healthz 503, queued-never-started cancelled,
        in-flight runs out); admission closes at the router."""
        self._draining = True
        for m in self.members():
            m.engine.begin_drain()

    @property
    def draining(self) -> bool:
        return self._draining

    def drain(self, logger=None, max_steps: int = 100000,
              on_cap: str = "raise") -> Dict[int, Request]:
        self.begin_drain()
        return self.run_until_drained(max_steps, on_cap=on_cap)

    def close(self) -> None:
        for m in self.members():
            m.close()
        self._slots.clear()

    # ------------------------------------------------------ observability

    def fleet_snapshot(self) -> Dict[str, float]:
        return self.metrics.snapshot()

    def engine_snapshots(self) -> List[Dict[str, float]]:
        return [m.engine.metrics.snapshot() for m in self.members()]
