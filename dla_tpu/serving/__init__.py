"""Continuous-batching inference core (dla_tpu/serving).

The serving layer decouples REQUEST admission from STEP execution — the
property that lets a static-shape, never-recompiled decode loop serve
requests that arrive, finish, and get evicted at arbitrary times
(Podracer-style decoupling, arxiv 2104.06272; vLLM-style paged KV).

Modules:
  kv_blocks  block-paged KV cache: fixed-size page pool + host-side
             allocator + the in-graph block-table gather/scatter
  scheduler  request lifecycle state machine (WAITING -> PREFILL ->
             DECODE -> FINISHED/EVICTED), FCFS + longest-prefix
             bucketing, eviction-on-OOM
  server     the host engine loop driving jitted prefill/decode steps
  metrics    queue depth, TTFT, inter-token latency, page occupancy,
             preemption counters
  resilience admission control + load shedding, degradation ladder,
             engine Supervisor (watchdog/rebuild/deterministic replay),
             circuit breaker
  fleet      multi-engine FleetRouter (cache-aware placement via
             PrefixCache.peek, sticky-prefix affinity, per-member
             supervisors) + SLO-driven Autoscaler with zero-loss
             scale-down (docs/SERVING.md "Fleet")
  migration  KVMigrator: prefill/decode disaggregation — export a
             mid-decode request's committed KV pages as a
             MigrationTicket, install on another engine, resume
             bit-identically (docs/SERVING.md "Disaggregated
             prefill/decode")
  gateway    ServingGateway: stdlib HTTP front door — POST /v1/generate
             with per-token SSE streaming, disconnect -> cancel,
             shed -> 429 / deadline -> 408 / draining -> 503
             (docs/SERVING.md "Gateway & federation")
  federation GossipBeater + FederatedRouter: cross-host placement over
             N gateway-fronted fleets with the FleetRouter score,
             replay-on-failure zero loss, MigrationTicket wire handoff
"""
from dla_tpu.serving.federation import (
    FederatedRouter,
    FederationConfig,
    FederationError,
    FederationMetrics,
    GossipBeater,
)
from dla_tpu.serving.fleet import (
    Autoscaler,
    FleetConfig,
    FleetMetrics,
    FleetRouter,
)
from dla_tpu.serving.gateway import (
    GatewayConfig,
    GatewayMetrics,
    ServingGateway,
)
from dla_tpu.serving.kv_blocks import (
    PageAllocator,
    PagedKVCache,
    PageGeometry,
    PrefixCache,
)
from dla_tpu.serving.metrics import ServingMetrics
from dla_tpu.serving.migration import (
    KVMigrator,
    MigrationConfig,
    MigrationError,
    MigrationTicket,
)
from dla_tpu.serving.resilience import (
    AdmissionController,
    CircuitBreaker,
    DegradationLadder,
    DeviceStepError,
    NaNLogitsError,
    ShedConfig,
    Supervisor,
    SupervisorConfig,
)
from dla_tpu.serving.scheduler import (
    TERMINAL_STATES,
    Request,
    RequestState,
    Scheduler,
    SchedulerConfig,
)
from dla_tpu.serving.server import ServingConfig, ServingEngine
# per-request sampling contract lives in ops.sampling (shared with the
# batch generate fn); re-exported here because submit() speaks it
from dla_tpu.ops.sampling import SamplingParams

__all__ = [
    "SamplingParams",
    "AdmissionController",
    "Autoscaler",
    "CircuitBreaker",
    "DegradationLadder",
    "DeviceStepError",
    "FederatedRouter",
    "FederationConfig",
    "FederationError",
    "FederationMetrics",
    "FleetConfig",
    "FleetMetrics",
    "FleetRouter",
    "GatewayConfig",
    "GatewayMetrics",
    "GossipBeater",
    "KVMigrator",
    "MigrationConfig",
    "MigrationError",
    "MigrationTicket",
    "NaNLogitsError",
    "PageAllocator",
    "PagedKVCache",
    "PageGeometry",
    "PrefixCache",
    "Request",
    "RequestState",
    "Scheduler",
    "SchedulerConfig",
    "ServingConfig",
    "ServingEngine",
    "ServingGateway",
    "ServingMetrics",
    "ShedConfig",
    "Supervisor",
    "SupervisorConfig",
    "TERMINAL_STATES",
]
