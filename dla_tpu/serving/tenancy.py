"""Multi-tenant LoRA serving: the adapter registry + tenant policy plane.

One base model serves N fine-tuned variants through ONE jitted decode
step — the train->serve closure for the framework's own outputs
(``init_lora`` adapters from SFT/DPO/PPO). The static-shape discipline
is the design constraint throughout: heterogeneous adapters must batch
into the engine's single decode compile with zero retraces.

**AdapterStore** — a registry of LoRA adapter trees keyed by
``tenant_id`` over a fixed-capacity device-resident pool of
``[n_adapters, L, ...]`` stacked A/B matrices. Every adapter is
rank-padded (with zeros — mathematically exact) to the configured
``max_rank`` and its B factor pre-scaled by ``alpha/r`` at publish, so
the pool's shapes and the in-graph delta (``x @ A @ B``) are static
across every tenant mix. Pool row 0 is the all-zeros base identity:
requests without a tenant gather it and add an exact ``+0.0``.
``publish_adapter`` is a hot-swap following the ``publish_params``
treedef-validation idiom (same shapes in -> same jit fingerprint, no
recompile); the host-side fp32 copy is always the source of truth, so
cold adapters LRU-spill to host-only and reload on admission
bit-identically.

**TenantPolicy** — per-tenant token buckets gating ``submit`` ahead of
the global :class:`~dla_tpu.serving.resilience.AdmissionController`
(a noisy tenant exhausting its bucket sheds only its own arrivals),
per-tenant metric panels on the engine registry
(``serving/tenant/<id>/...`` — a dynamic catalog prefix), and
per-tenant :class:`~dla_tpu.telemetry.slo.SLOWatch` instances whose
gauges land under ``serving/tenant/<id>/slo/``. Per-tenant SLO burn is
evaluated against the tenant's OWN latency panel, never the engine-wide
snapshot, so one tenant's burn cannot shed another's work.

The engine-facing counters (``publishes``/``loads``/``spills``) are
plain host ints delta-mirrored into the registry by the engine each
step (the speculative-counter idiom), so totals survive supervisor
rebuilds.

Declared in config as the serving ``tenancy:`` block
(``TenancySchema``/``AdapterPoolSchema`` in training/config.py)::

    tenancy:
      adapter_pool:
        max_adapters: 8
        max_rank: 8
        targets: [wq, wv]      # default: the model's lora_targets
      quotas:
        acme: {rate: 50.0, burst: 8}
      slo:
        objectives:
          - name: ttft
            metric: ttft_ms_p95      # relative to the tenant panel
            objective: 500.0
        shed_burn_threshold: 0.0     # 0 = quota-gate isolation only
"""
from __future__ import annotations

import dataclasses
import json
import os
import re
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dla_tpu.serving.resilience import TokenBucket
from dla_tpu.telemetry.slo import SLOWatch

__all__ = [
    "AdapterPoolConfig",
    "TenancyConfig",
    "AdapterStore",
    "TenantPolicy",
    "export_adapter_tree",
    "load_adapter_tree",
]

#: tenant ids become metric-name path segments (serving/tenant/<id>/...)
#: and filesystem-safe manifest fields — keep them to a sane charset
_TENANT_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.-]*$")

ADAPTER_FORMAT = "adapter_store/v1"
ADAPTER_MANIFEST = "manifest.json"
ADAPTER_WEIGHTS = "adapter.npz"


def _check_tenant_id(tenant: str) -> str:
    if not isinstance(tenant, str) or not _TENANT_RE.match(tenant):
        raise ValueError(
            f"invalid tenant id {tenant!r}: must match "
            f"{_TENANT_RE.pattern} (it names metric series and "
            "manifest entries)")
    return tenant


# ------------------------------------------------------------------- config


@dataclasses.dataclass(frozen=True)
class AdapterPoolConfig:
    """Device-resident adapter pool geometry (the ``adapter_pool:``
    sub-block; ``AdapterPoolSchema`` in training/config.py mirrors it).
    The pool allocates ``max_adapters + 1`` rows — row 0 is reserved for
    the all-zeros base identity."""
    max_adapters: int = 8          # concurrent device-resident tenants
    max_rank: int = 8              # adapters rank-pad up to this
    targets: Optional[Tuple[str, ...]] = None  # None -> model lora_targets

    @classmethod
    def from_config(cls, cfg: Optional[Dict]) -> "AdapterPoolConfig":
        cfg = dict(cfg or {})
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(cfg) - known)
        if unknown:
            raise ValueError(f"unknown adapter_pool config keys: {unknown}")
        if "targets" in cfg and cfg["targets"] is not None:
            cfg["targets"] = tuple(cfg["targets"])
        out = cls(**cfg)
        if out.max_adapters < 1:
            raise ValueError(
                f"adapter_pool.max_adapters must be >= 1, got "
                f"{out.max_adapters}")
        if out.max_rank < 1:
            raise ValueError(
                f"adapter_pool.max_rank must be >= 1, got {out.max_rank}")
        return out


@dataclasses.dataclass(frozen=True)
class TenancyConfig:
    """The serving ``tenancy:`` config block (``TenancySchema`` in
    training/config.py mirrors it)."""
    adapter_pool: AdapterPoolConfig = AdapterPoolConfig()
    quotas: Optional[Dict[str, Dict]] = None   # tenant -> {rate, burst}
    slo: Optional[Dict] = None   # per-tenant objectives (panel-relative)

    @classmethod
    def from_config(cls, cfg: Optional[Dict]) -> Optional["TenancyConfig"]:
        """Build from a config dict; None (or ``enabled: false``)
        disables multi-tenancy entirely."""
        if not cfg:
            return None
        cfg = dict(cfg)
        if not cfg.pop("enabled", True):
            return None
        pool = AdapterPoolConfig.from_config(cfg.pop("adapter_pool", None))
        known = {"quotas", "slo"}
        unknown = sorted(set(cfg) - known)
        if unknown:
            raise ValueError(f"unknown tenancy config keys: {unknown}")
        quotas = cfg.get("quotas")
        if quotas:
            for tenant, q in quotas.items():
                _check_tenant_id(tenant)
                bad = sorted(set(q or {}) - {"rate", "burst"})
                if bad:
                    raise ValueError(
                        f"unknown quota keys for tenant {tenant!r}: {bad}")
        return cls(adapter_pool=pool, quotas=quotas, slo=cfg.get("slo"))


# ------------------------------------------------------------ adapter store


class AdapterStore:
    """Fixed-capacity device pool of stacked per-tenant LoRA factors.

    ``pools`` maps ``f"{target}_lora_a"`` -> ``[N, L, din, max_rank]``
    and ``f"{target}_lora_b"`` -> ``[N, L, max_rank, dout]`` device
    arrays (activation-param dtype). The jitted steps gather per-slot
    rows by ``adapter_idx`` (``Transformer.slot_lora_xs``); publishes
    and residency loads are ``.at[idx].set`` writes — same shapes and
    dtypes, so the decode jit fingerprint never changes.

    Residency protocol: ``acquire(tenant)`` on slot bind (refcounted,
    loading the adapter from its host copy if it was spilled),
    ``release(tenant)`` when the scheduler releases the slot. Only
    refcount-0 residents are LRU-spillable; a pool full of pinned
    adapters is a capacity config error and raises.
    """

    def __init__(self, model, cfg: AdapterPoolConfig):
        self.model = model
        self.cfg = cfg
        targets = (tuple(cfg.targets) if cfg.targets
                   else tuple(model.cfg.lora_targets))
        if not targets:
            raise ValueError(
                "adapter pool has no targets: set "
                "tenancy.adapter_pool.targets or the model's lora_targets")
        unknown = [t for t in targets if t not in model._LORA_SHAPES]
        if unknown:
            raise ValueError(
                f"unknown adapter targets {unknown}: known targets are "
                f"{sorted(model._LORA_SHAPES)}")
        self.targets = targets
        self.n_rows = int(cfg.max_adapters) + 1   # row 0 = base identity
        self.max_rank = int(cfg.max_rank)
        dims = model._lora_dims()
        L = model.cfg.num_layers
        self._num_layers = L
        self._shapes: Dict[str, Tuple[int, ...]] = {}
        self.pools: Dict[str, jnp.ndarray] = {}
        for t in targets:
            din, dout = (dims[k] for k in model._LORA_SHAPES[t])
            self._shapes[t] = (din, dout)
            self.pools[f"{t}_lora_a"] = jnp.zeros(
                (self.n_rows, L, din, self.max_rank), model.pdtype)
            self.pools[f"{t}_lora_b"] = jnp.zeros(
                (self.n_rows, L, self.max_rank, dout), model.pdtype)
        # host fp32 padded/pre-scaled copies: ALWAYS the source of truth
        # (spill = drop device residency; reload casts fp32 -> pool
        # dtype exactly as the original publish did, so a reloaded
        # adapter decodes bit-identically)
        self._host: Dict[str, Dict[str, np.ndarray]] = {}
        self._resident: Dict[str, int] = {}
        self._refs: Dict[str, int] = {}
        self._free: List[int] = list(range(1, self.n_rows))
        self._lru: List[str] = []     # refcount-0 residents, oldest first
        # plain ints, delta-mirrored by the engine (speculative-counter
        # idiom) so serving/adapter_pool/* stay monotone across rebuilds
        self.publishes = 0
        self.loads = 0
        self.spills = 0

    # ------------------------------------------------------------ publish

    def _expected_treedef(self):
        layers = {}
        for t in self.targets:
            layers[f"{t}_lora_a"] = 0
            layers[f"{t}_lora_b"] = 0
        return jax.tree_util.tree_structure({"layers": layers})

    def publish(self, tenant: str, tree, *, alpha: Optional[float] = None,
                rank: Optional[int] = None) -> None:
        """Install (or hot-swap) one tenant's adapter tree.

        The tree must be the adapter-only pytree ``init_lora`` produces
        for this pool's targets — treedef-validated like
        ``ServingEngine.publish_params`` validates a full refit, and
        for the same reason: a mismatch would silently retrace. The B
        factor is pre-scaled by ``alpha / r`` here (r inferred from the
        A leaves unless given), so the jitted delta is a bare
        ``x @ A @ B``. A resident tenant's pool row is rewritten in
        place — a recompile-free, donation-safe hot swap (the pool
        update is functional; nothing aliases the caller's leaves)."""
        _check_tenant_id(tenant)
        tree = self._canonical(tree)
        exp_def = self._expected_treedef()
        got_def = jax.tree_util.tree_structure(
            jax.tree_util.tree_map(lambda _: 0, tree))
        if got_def != exp_def:
            raise ValueError(
                f"publish_adapter tree structure mismatch: {got_def} vs "
                f"expected {exp_def} (adapter-only tree over targets "
                f"{list(self.targets)}; a full-weight republish belongs "
                "to ServingEngine.publish_params)")
        L = self._num_layers
        r_seen: Optional[int] = None
        for t in self.targets:
            din, dout = self._shapes[t]
            a = tree["layers"][f"{t}_lora_a"]
            b = tree["layers"][f"{t}_lora_b"]
            if a.ndim != 3 or a.shape[0] != L or a.shape[1] != din:
                raise ValueError(
                    f"adapter leaf {t}_lora_a shape {tuple(a.shape)}: "
                    f"expected [L={L}, {din}, r]")
            r = int(a.shape[2])
            if b.ndim != 3 or tuple(b.shape) != (L, r, dout):
                raise ValueError(
                    f"adapter leaf {t}_lora_b shape {tuple(b.shape)}: "
                    f"expected [L={L}, r={r}, {dout}]")
            if r_seen is None:
                r_seen = r
            elif r != r_seen:
                raise ValueError(
                    f"adapter rank mismatch across targets: {t} has r={r}"
                    f", earlier targets r={r_seen}")
        if rank is not None and int(rank) != r_seen:
            raise ValueError(
                f"declared rank {rank} != adapter leaves' rank {r_seen}")
        if r_seen > self.max_rank:
            raise ValueError(
                f"adapter rank {r_seen} exceeds the pool's max_rank "
                f"{self.max_rank} (rank-padding only goes up): raise "
                "tenancy.adapter_pool.max_rank")
        mcfg = self.model.cfg
        eff_alpha = float(alpha) if alpha is not None else float(
            mcfg.lora_alpha)
        scale = eff_alpha / r_seen
        host: Dict[str, np.ndarray] = {}
        for t in self.targets:
            din, dout = self._shapes[t]
            a = np.asarray(jax.device_get(
                tree["layers"][f"{t}_lora_a"].astype(jnp.float32)))
            b = np.asarray(jax.device_get(
                tree["layers"][f"{t}_lora_b"].astype(jnp.float32)))
            pad_r = self.max_rank - r_seen
            host[f"{t}_lora_a"] = np.pad(
                a, ((0, 0), (0, 0), (0, pad_r)))
            host[f"{t}_lora_b"] = np.pad(
                b * scale, ((0, 0), (0, pad_r), (0, 0)))
        self._host[tenant] = host
        self.publishes += 1
        idx = self._resident.get(tenant)
        if idx is not None:
            self._write(idx, host)   # hot swap in place, no recompile

    def _canonical(self, tree):
        """Accept interleaved-storage adapter leaves ([V, S, c, ...],
        what ``init_lora`` emits under pipeline configs) by flattening
        the layer stack back to canonical [L, ...]."""
        L = self._num_layers

        def go(x):
            if getattr(x, "ndim", 0) == 5 and x.shape[0] != L:
                return x.reshape((L,) + x.shape[3:])
            return x
        return jax.tree_util.tree_map(go, tree)

    def _write(self, idx: int, host: Dict[str, np.ndarray]) -> None:
        for key, arr in host.items():
            pool = self.pools[key]
            self.pools[key] = pool.at[idx].set(
                jnp.asarray(arr, pool.dtype))

    # ---------------------------------------------------------- residency

    def has(self, tenant: str) -> bool:
        return tenant in self._host

    def resident(self, tenant: str) -> bool:
        return tenant in self._resident

    @property
    def tenants(self) -> List[str]:
        return sorted(self._host)

    @property
    def resident_count(self) -> int:
        return len(self._resident)

    def ensure_resident(self, tenant: str) -> int:
        """The tenant's pool row, loading its host copy into a free (or
        LRU-spilled) row first when it is not resident."""
        if tenant not in self._host:
            raise KeyError(
                f"unknown tenant {tenant!r}: publish_adapter first "
                f"(known: {self.tenants})")
        idx = self._resident.get(tenant)
        if idx is not None:
            return idx
        if self._free:
            idx = self._free.pop(0)
        elif self._lru:
            cold = self._lru.pop(0)
            idx = self._resident.pop(cold)
            self.spills += 1   # host copy stays authoritative
        else:
            raise RuntimeError(
                "adapter pool exhausted: every resident adapter is "
                "pinned by a bound decode slot — raise "
                "tenancy.adapter_pool.max_adapters above the engine's "
                "concurrent-tenant working set")
        self._write(idx, self._host[tenant])
        self._resident[tenant] = idx
        self.loads += 1
        return idx

    def acquire(self, tenant: str) -> int:
        """Pin the tenant's adapter for one bound slot; returns its pool
        row for the slot's ``adapter_idx`` mirror."""
        idx = self.ensure_resident(tenant)
        self._refs[tenant] = self._refs.get(tenant, 0) + 1
        if tenant in self._lru:
            self._lru.remove(tenant)
        return idx

    def release(self, tenant: str) -> None:
        """Drop one slot's pin; refcount-0 residents become LRU-spill
        candidates (they stay resident — and warm — until capacity
        actually needs the row)."""
        n = self._refs.get(tenant, 0) - 1
        if n < 0:
            raise RuntimeError(
                f"adapter release underflow for tenant {tenant!r}")
        self._refs[tenant] = n
        if n == 0 and tenant in self._resident \
                and tenant not in self._lru:
            self._lru.append(tenant)


# ------------------------------------------------------------ tenant policy


class _TenantPanel:
    """One tenant's instrument panel on the engine registry. Series ride
    the ``serving/tenant/`` dynamic catalog prefix; the panel also
    renders its own snapshot dict because per-tenant SLO watches consume
    tenant-local values, never the engine-wide snapshot."""

    def __init__(self, registry, tenant: str):
        self.prefix = p = f"serving/tenant/{tenant}/"
        self.submitted = registry.counter(p + "requests_submitted")
        self.finished = registry.counter(p + "requests_finished")
        self.shed = registry.counter(p + "requests_shed")
        self.tokens = registry.counter(p + "tokens_generated")
        self.ttft_ms = registry.histogram(p + "ttft_ms")
        self.itl_ms = registry.histogram(p + "itl_ms")

    def snapshot(self) -> Dict[str, float]:
        p = self.prefix
        out = {
            p + "requests_submitted": float(self.submitted.value),
            p + "requests_finished": float(self.finished.value),
            p + "requests_shed": float(self.shed.value),
            p + "tokens_generated": float(self.tokens.value),
        }
        out.update(self.ttft_ms.summary(p + "ttft_ms_"))
        out.update(self.itl_ms.summary(p + "itl_ms_"))
        return out


class TenantPolicy:
    """Per-tenant quotas, metrics, and SLO burn — the policy plane the
    engine consults around the shared decode step.

    Quota gate: ``gate(tenant, now)`` is a per-tenant
    :class:`TokenBucket` consulted by ``submit`` BEFORE the global
    admission controller, so a tenant that exhausts its own bucket
    sheds only its own arrivals (``at="tenant_quota"``) and never
    touches the shared queue bound or another tenant's SLO burn.

    SLO rows in the ``tenancy.slo.objectives`` block name metrics
    RELATIVE to the tenant panel (``ttft_ms_p95``, ``itl_ms_p99``,
    ``requests_shed`` ...); each tenant gets its own
    :class:`SLOWatch` over its own panel snapshot with gauges under
    ``serving/tenant/<id>/slo/``. With ``shed_burn_threshold > 0`` the
    per-step ``shed_pass`` trims ONLY the burning tenant's queued,
    never-started requests."""

    def __init__(self, cfg: TenancyConfig, registry, recorder=None,
                 now=time.monotonic):
        self.cfg = cfg
        self.registry = registry
        self.recorder = recorder
        self.now = now
        self._quotas: Dict[str, Dict] = dict(cfg.quotas or {})
        slo_block = dict(cfg.slo or {})
        self._slo_rows = list(slo_block.get("objectives") or [])
        self._slo_defaults = {k: v for k, v in slo_block.items()
                              if k not in ("objectives",
                                           "shed_burn_threshold")}
        self.shed_burn_threshold = float(
            slo_block.get("shed_burn_threshold", 0.0))
        self._panels: Dict[str, _TenantPanel] = {}
        self._buckets: Dict[str, TokenBucket] = {}
        self._watches: Dict[str, SLOWatch] = {}
        for tenant in self._quotas:
            self.ensure(tenant)

    def configured(self, tenant: str) -> bool:
        return tenant in self._quotas

    @property
    def tenants(self) -> List[str]:
        return sorted(self._panels)

    def ensure(self, tenant: str) -> _TenantPanel:
        """The tenant's panel, lazily creating panel + bucket + SLO
        watch on first sight (adapters may be published mid-run)."""
        panel = self._panels.get(tenant)
        if panel is not None:
            return panel
        _check_tenant_id(tenant)
        panel = _TenantPanel(self.registry, tenant)
        self._panels[tenant] = panel
        q = dict(self._quotas.get(tenant) or {})
        rate = float(q.get("rate", 0.0))
        if rate > 0:
            self._buckets[tenant] = TokenBucket(
                rate, float(q.get("burst", 1.0)))
        if self._slo_rows:
            rows = []
            for row in self._slo_rows:
                row = dict(row)
                row["metric"] = panel.prefix + str(row["metric"])
                rows.append(row)
            block = dict(self._slo_defaults)
            block["objectives"] = rows
            self._watches[tenant] = SLOWatch.from_config(
                block, registry=self.registry, recorder=self.recorder,
                prefix=panel.prefix + "slo/")
        return panel

    # -------------------------------------------------------------- gates

    def gate(self, tenant: str, now: float) -> bool:
        """One quota-bucket take for an arriving request; True admits.
        Tenants without a configured rate are never quota-gated."""
        bucket = self._buckets.get(tenant)
        return bucket is None or bucket.try_take(now)

    def burn(self, tenant: str) -> float:
        watch = self._watches.get(tenant)
        if watch is None:
            return 0.0
        return max((watch.burn_rate(s) for s in watch.slos), default=0.0)

    def max_burn(self) -> float:
        """Hottest tenant's burn rate — the fleet autoscaler's
        per-tenant pressure signal (a single tenant blowing its SLO
        scales the fleet even when aggregate latency looks fine)."""
        return max((self.burn(t) for t in self._watches), default=0.0)

    def shed_pass(self, sched) -> List:
        """Tenant-scoped burn shedding: victims are queued, never-
        started requests OF THE BURNING TENANT only — other tenants'
        queues are structurally untouchable from here."""
        thr = self.shed_burn_threshold
        if thr <= 0 or not self._watches:
            return []
        victims = []
        burning = {t for t in self._watches if self.burn(t) >= thr}
        if burning:
            victims = [r for r in sched.sheddable_queued()
                       if r.tenant in burning]
        return victims

    # ---------------------------------------------------------- recording

    def on_submit(self, tenant: str) -> None:
        self.ensure(tenant).submitted.inc()

    def on_finish(self, tenant: str) -> None:
        self.ensure(tenant).finished.inc()

    def on_shed(self, tenant: str) -> None:
        self.ensure(tenant).shed.inc()

    def on_token(self, tenant: str) -> None:
        self.ensure(tenant).tokens.inc()

    def on_ttft(self, tenant: str, ms: float) -> None:
        self.ensure(tenant).ttft_ms.record(ms)

    def on_itl(self, tenant: str, ms: float) -> None:
        self.ensure(tenant).itl_ms.record(ms)

    def observe(self, step: Optional[int] = None) -> None:
        """Feed each tenant watch its OWN panel snapshot (the engine
        snapshot is an explicit hand-built dict that never carries
        per-tenant series)."""
        for tenant, watch in self._watches.items():
            watch.observe(self._panels[tenant].snapshot(), step=step)


# --------------------------------------------------------- servable export


def export_adapter_tree(out_dir: str, tree, *, targets, rank: int,
                        alpha: float, num_layers: int,
                        tenant: Optional[str] = None) -> str:
    """Write an adapter-only tree in the AdapterStore servable format:
    ``manifest.json`` (format/targets/rank/alpha/num_layers/tenant) +
    ``adapter.npz`` holding fp32 canonical ``[L, ...]`` leaves under
    ``layers.<target>_lora_{a,b}`` keys. The RAW (unscaled, unpadded)
    factors are stored; ``publish_adapter`` applies ``alpha/r`` scaling
    and rank-padding at publish time, so a finished RLHF run's export
    round-trips into serving without re-deriving from checkpoints.
    Returns ``out_dir``."""
    os.makedirs(out_dir, exist_ok=True)
    arrays: Dict[str, np.ndarray] = {}
    for t in targets:
        for suffix in ("_lora_a", "_lora_b"):
            key = f"{t}{suffix}"
            leaf = tree["layers"][key]
            if getattr(leaf, "ndim", 0) == 5 \
                    and leaf.shape[0] != num_layers:
                # interleaved-storage [V, S, c, ...] -> canonical [L, ...]
                leaf = leaf.reshape((num_layers,) + leaf.shape[3:])
            arrays[f"layers.{key}"] = np.asarray(
                jax.device_get(leaf.astype(jnp.float32)))
    manifest = {
        "format": ADAPTER_FORMAT,
        "tenant": tenant,
        "targets": list(targets),
        "rank": int(rank),
        "alpha": float(alpha),
        "num_layers": int(num_layers),
    }
    with open(os.path.join(out_dir, ADAPTER_MANIFEST), "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
    np.savez(os.path.join(out_dir, ADAPTER_WEIGHTS), **arrays)
    return out_dir


def load_adapter_tree(path: str) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Load an :func:`export_adapter_tree` directory back into the
    ``(tree, manifest)`` pair ``publish_adapter`` consumes::

        tree, meta = load_adapter_tree(run_dir)
        engine.publish_adapter("acme", tree,
                               alpha=meta["alpha"], rank=meta["rank"])
    """
    with open(os.path.join(path, ADAPTER_MANIFEST)) as f:
        manifest = json.load(f)
    if manifest.get("format") != ADAPTER_FORMAT:
        raise ValueError(
            f"{path}: manifest format {manifest.get('format')!r} is not "
            f"{ADAPTER_FORMAT!r}")
    data = np.load(os.path.join(path, ADAPTER_WEIGHTS))
    layers = {}
    for key in data.files:
        if not key.startswith("layers."):
            raise ValueError(f"{path}: unexpected npz entry {key!r}")
        layers[key[len("layers."):]] = jnp.asarray(data[key])
    return {"layers": layers}, manifest
