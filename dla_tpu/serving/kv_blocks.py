"""Block-paged KV cache: a fixed page pool shared by every in-flight
sequence, so sequences of wildly different lengths never reserve
worst-case contiguous cache.

Layout: one preallocated pool ``k_pages``/``v_pages`` of shape
[L, num_pages, page_size, KH, D]. Each decode SLOT (a row of the
static-shape decode batch) owns a block table row — ``pages_per_slot``
physical page ids — and the in-graph gather

    k_view = k_pages[:, block_table]           # [L, B, P/slot, ps, KH, D]
             .reshape(L, B, S, KH, D)          # S = pages_per_slot * ps

rebuilds the contiguous [B, S] window ``Transformer.decode_step_paged``
consumes. The gather is the whole trick: attention math stays
layout-agnostic, the pool stays fixed-size, and page ownership is pure
host-side bookkeeping (PageAllocator) that never touches the graph.

Physical page 0 is RESERVED as the trash page: free slots' block tables
point at it, so the static-shape decode step can let inactive rows
write/read garbage there without branching. The allocator never hands
page 0 out and the prefix cache never indexes it.

THE POOL DOUBLES AS A PREFIX CACHE. Pages are refcounted: several block
tables may alias one physical page when their requests share a token
prefix (KV content is position-dependent but prefix-determined, so equal
prefixes mean bit-equal pages). When the last reference drops, a page
that the :class:`PrefixCache` still indexes is RETAINED on an LRU list
instead of freed — zero extra memory, the cache simply delays reuse.
Allocation under pressure reclaims retained pages LRU-first, unindexing
them as it goes, so a busy pool degrades gracefully to the uncached
behavior. Every page is always in exactly one of three states: free,
used (refcount >= 1), or cached (refcount 0, content retained).
"""
from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import (Callable, Dict, List, Optional, Sequence, Tuple)

import jax
import jax.numpy as jnp
import numpy as np


class PageAllocator:
    """Host-side refcounted free-list allocator over the fixed page pool.

    Pages are fixed-size, so there is no external fragmentation — any
    interleaving of alloc/free keeps every free page usable. Allocation
    is all-or-nothing: a request that cannot get ALL ``n`` pages gets
    none (no partial reservations to unwind on admission failure).

    ``alloc`` hands out pages at refcount 1; ``incref`` lets another
    block table alias a page (prefix sharing); ``decref``/``free`` drop
    references. A page reaching refcount 0 normally returns to the free
    list, but when ``retain_hook`` claims it (the prefix cache still
    indexes its content) it parks on an LRU cached list instead —
    revivable by ``incref`` (a cache hit) and reclaimable by ``alloc``
    under pressure (``evict_hook`` fires so the index forgets it).
    """

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is the trash page)")
        self.num_pages = num_pages
        # page 0 reserved: free slots alias it for garbage traffic
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._used: set = set()
        self._ref: Dict[int, int] = {}
        # refcount-0 pages whose content the prefix cache still indexes,
        # insertion-ordered: front = least recently released = evicted
        # first when alloc outruns the free list
        self._cached: "OrderedDict[int, None]" = OrderedDict()
        # policy hooks the PrefixCache installs; absent hooks give the
        # plain uncached allocator (decref-0 always frees)
        self.retain_hook: Optional[Callable[[int], bool]] = None
        self.evict_hook: Optional[Callable[[int], None]] = None
        self.cache_evictions = 0

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return len(self._used)

    @property
    def cached_count(self) -> int:
        return len(self._cached)

    @property
    def capacity(self) -> int:
        """Allocatable pages (excludes the reserved trash page)."""
        return self.num_pages - 1

    @property
    def occupancy(self) -> float:
        """Fraction of allocatable pages currently owned (cached pages
        are reclaimable, so they count as free here)."""
        return self.used_count / max(1, self.capacity)

    @property
    def refcounts(self) -> Dict[int, int]:
        """Copy of the live page -> refcount map (invariant checks)."""
        return dict(self._ref)

    @property
    def cached_pages(self) -> List[int]:
        """LRU-ordered refcount-0 retained pages (eviction order)."""
        return list(self._cached)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free) + len(self._cached)

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` pages at refcount 1, or None if free + reclaimable
        cached pages cannot supply all of them. Reclaims cached pages
        LRU-first, unindexing each via ``evict_hook``."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free) + len(self._cached):
            return None
        pages: List[int] = []
        for _ in range(n):
            if self._free:
                p = self._free.pop()
            else:
                p, _ = self._cached.popitem(last=False)
                self.cache_evictions += 1
                if self.evict_hook is not None:
                    self.evict_hook(p)
            self._used.add(p)
            self._ref[p] = 1
            pages.append(p)
        return pages

    def incref(self, page: int) -> None:
        """Add a reference: another block table now aliases ``page``.
        Reviving a cached page (a prefix-cache hit) moves it back to the
        used state."""
        if page in self._ref:
            self._ref[page] += 1
        elif page in self._cached:
            del self._cached[page]
            self._used.add(page)
            self._ref[page] = 1
        else:
            raise ValueError(f"incref of free/foreign page {page}")

    def decref(self, page: int) -> None:
        """Drop a reference. At refcount 0 the page frees — unless the
        retain hook claims it for the prefix cache, in which case it
        parks on the cached LRU list (most-recently-released last)."""
        if page not in self._ref:
            raise ValueError(f"double free / foreign page {page}")
        self._ref[page] -= 1
        if self._ref[page] == 0:
            del self._ref[page]
            self._used.discard(page)
            if self.retain_hook is not None and self.retain_hook(page):
                self._cached[page] = None
            else:
                self._free.append(page)

    def refcount(self, page: int) -> int:
        return self._ref.get(page, 0)

    def free(self, pages: List[int]) -> None:
        """Drop one reference per page (the historical bulk-release
        surface; exact old behavior when nothing is shared)."""
        for p in pages:
            self.decref(p)

    def uncache(self, page: int) -> None:
        """Drop a retained refcount-0 page straight to the free list
        (its index entry is gone, so there is nothing to hit)."""
        if page in self._cached:
            del self._cached[page]
            self._free.append(page)

    def reclaim_cached(self) -> int:
        """Evict EVERY retained refcount-0 page back to the free list,
        unindexing each via ``evict_hook`` — the degradation ladder's
        first rung under sustained pressure (alloc would reclaim them
        one-by-one anyway; this trades the whole cache for headroom at
        once). Pages still referenced by live block tables are untouched.
        Returns the number of pages reclaimed."""
        n = 0
        while self._cached:
            p, _ = self._cached.popitem(last=False)
            self.cache_evictions += 1
            if self.evict_hook is not None:
                self.evict_hook(p)
            self._free.append(p)
            n += 1
        return n


@dataclasses.dataclass(frozen=True)
class PageGeometry:
    """Static shape parameters of a paged pool — everything the jitted
    serving steps specialize on."""
    page_size: int
    num_pages: int
    num_slots: int
    pages_per_slot: int

    @property
    def slot_window(self) -> int:
        """S: the per-slot logical window the gather materializes."""
        return self.pages_per_slot * self.page_size

    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to hold ``n_tokens`` (ceil)."""
        return -(-n_tokens // self.page_size)


@dataclasses.dataclass
class _FullEntry:
    """Full-exact-prompt cache entry: the partial tail page (None when
    the prompt is page-aligned) plus the last-token prefill logits, so a
    repeat of the exact prompt skips prefill entirely."""
    tail_page: Optional[int]
    logits: np.ndarray


class PrefixCache:
    """Content-addressed index over the pool's pages.

    Two granularities:

    * **Full pages** — ``_index`` maps the exact token tuple of a
      page-aligned prefix to the physical page holding its KV. Keys are
      the tokens themselves (no hashing), so a hit is a guarantee, never
      a collision. A lookup walks prefixes page by page and stops at the
      first miss, so an interior eviction simply shortens later hits
      (orphaned longer entries age out via the allocator's LRU).
    * **Exact full prompts** — ``_full`` additionally remembers the
      partial tail page and the last-token prefill LOGITS for recently
      completed prompts (LRU-capped), so an identical prompt skips
      prefill completely: all pages alias (including the partial tail,
      which copy-on-write protects once decode writes into it) and the
      first token samples from the stored logits.

    The cache holds NO references itself: retention of refcount-0 pages
    happens through the allocator hooks installed here, and the pool
    reclaims retained pages LRU-first under allocation pressure.
    """

    def __init__(self, allocator: PageAllocator, page_size: int,
                 logits_capacity: int = 128):
        self.allocator = allocator
        self.page_size = page_size
        self.logits_capacity = max(1, int(logits_capacity))
        self._index: Dict[Tuple[int, ...], int] = {}
        # page -> ("page" | "tail", key): which entry retains this page
        self._page_key: Dict[int, Tuple[str, Tuple[int, ...]]] = {}
        self._full: "OrderedDict[Tuple[int, ...], _FullEntry]" = \
            OrderedDict()
        self.lookups = 0
        self.hit_tokens = 0
        self.evictions = 0
        self.peeks = 0
        allocator.retain_hook = self._retain
        allocator.evict_hook = self._on_evict

    # ------------------------------------------------------------- hooks

    def _retain(self, page: int) -> bool:
        return page in self._page_key

    def _on_evict(self, page: int) -> None:
        """The allocator reclaimed a retained page: forget its entry.
        Children of an evicted interior page stay indexed — harmlessly,
        since lookups walk from the start and stop at the hole."""
        self.evictions += 1
        kind, key = self._page_key.pop(page)
        if kind == "page":
            if self._index.get(key) == page:
                del self._index[key]
        else:
            self._full.pop(key, None)

    # ----------------------------------------------------------- queries

    def is_indexed(self, page: int) -> bool:
        """True when the cache indexes ``page``'s content — writing to
        it would corrupt future hits, so writers must copy first."""
        return page in self._page_key

    @staticmethod
    def _nskey(namespace: Optional[str], sub: Tuple[int, ...]):
        """Namespace a token-tuple key. Multi-tenant serving keys cached
        KV by ``(tenant, tokens)`` — adapters change KV contents, so one
        tenant's pages must never answer another's lookup. Applied at
        the dict-key layer only: prefix slicing stays on the raw token
        tuple, so page alignment is untouched."""
        return sub if namespace is None else (namespace,) + sub

    def lookup(self, tokens: Sequence[int], chunk: int,
               namespace: Optional[str] = None,
               ) -> Tuple[List[int], int, Optional[np.ndarray]]:
        """Longest usable cached prefix of ``tokens``.

        Returns ``(pages, hit_len, logits)`` with every returned page
        ALREADY increfed (the caller decrefs on admission failure). An
        exact-full-prompt hit returns every page plus the stored logits
        (``hit_len == len(tokens)``: no prefill at all). Otherwise the
        hit is truncated to a multiple of ``chunk`` and strictly below
        ``len(tokens)`` — chunked prefill restarts at a fixed absolute
        chunk boundary, which is what keeps cache-on decoding
        bit-identical to cache-off."""
        self.lookups += 1
        key = tuple(tokens)
        pages, hit, entry = self._walk(key, chunk, namespace)
        if entry is not None:
            self._full.move_to_end(self._nskey(namespace, key))
            for p in pages:
                self.allocator.incref(p)
            self.hit_tokens += hit
            return pages, hit, entry.logits
        for p in pages:
            self.allocator.incref(p)
        self.hit_tokens += hit
        return pages, hit, None

    def peek(self, tokens: Sequence[int], chunk: int,
             namespace: Optional[str] = None) -> int:
        """Read-only hit-length estimate: the ``hit_len`` a ``lookup``
        of ``tokens`` would return right now, WITHOUT taking page
        references, touching the full-prompt LRU order, or advancing the
        lookup/hit-token counters. The fleet router calls this on every
        candidate engine per placement decision, so a peek must be
        side-effect-free — a peek that increfed would leak references on
        the N-1 engines that lose the placement."""
        self.peeks += 1
        _, hit, _ = self._walk(tuple(tokens), chunk, namespace)
        return hit

    def _walk(self, key: Tuple[int, ...], chunk: int,
              namespace: Optional[str] = None,
              ) -> Tuple[List[int], int, Optional["_FullEntry"]]:
        """Shared read-only index walk behind ``lookup`` and ``peek``:
        ``(pages, hit_len, full_entry)`` with NO side effects — the
        caller applies increfs, LRU touches and counters (or, for peek,
        nothing at all). ``full_entry`` is non-None only on an
        exact-full-prompt hit (``hit_len == len(key)``)."""
        n = len(key)
        ps = self.page_size
        entry = self._full.get(self._nskey(namespace, key))
        if entry is not None:
            pages = self._assemble_full(key, entry, namespace)
            if pages is not None:
                return pages, n, entry
        # chunk-granular: the last token's logits must be recomputed, so
        # the hit stays < n; chunk alignment keeps the restart boundary
        # on the fixed absolute schedule
        max_hit = ((n - 1) // chunk) * chunk if chunk > 0 else 0
        pages: List[int] = []
        k = 1
        while k * ps <= max_hit:
            p = self._index.get(self._nskey(namespace, key[:k * ps]))
            if p is None:
                break
            pages.append(p)
            k += 1
        hit = (len(pages) * ps // chunk) * chunk if chunk > 0 else 0
        return pages[:hit // ps], hit, None

    def _assemble_full(self, key: Tuple[int, ...], entry: _FullEntry,
                       namespace: Optional[str] = None,
                       ) -> Optional[List[int]]:
        """All physical pages of an exact-prompt entry, or None when an
        interior page was evicted (fall back to the chunked walk)."""
        n, ps = len(key), self.page_size
        pages: List[int] = []
        for k in range(1, n // ps + 1):
            p = self._index.get(self._nskey(namespace, key[:k * ps]))
            if p is None:
                return None
            pages.append(p)
        if n % ps:
            if entry.tail_page is None:
                return None
            pages.append(entry.tail_page)
        return pages

    def acquire_pages(self, tokens: Sequence[int],
                      namespace: Optional[str] = None,
                      ) -> Optional[List[int]]:
        """Every full page of a PAGE-ALIGNED prefix, each ALREADY
        increfed — or None, with no references taken, when the prefix is
        not aligned or any page is missing (an interior eviction hole).

        This is the adopt-without-prefill surface behind
        ``ServingEngine.restore``'s cache fast path and KV import: unlike
        ``lookup`` there is no chunk truncation (the caller resumes
        DECODE, not prefill, so it needs the committed columns exactly)
        and no full-prompt logits (the next decode input is the last
        generated token, so no logits are consumed at all)."""
        key = tuple(tokens)
        n, ps = len(key), self.page_size
        self.lookups += 1
        if n == 0 or n % ps:
            return None
        pages: List[int] = []
        for k in range(1, n // ps + 1):
            p = self._index.get(self._nskey(namespace, key[:k * ps]))
            if p is None:
                for q in pages:
                    self.allocator.decref(q)
                return None
            self.allocator.incref(p)
            pages.append(p)
        self.hit_tokens += n
        return pages

    # ------------------------------------------------------- registration

    def register(self, tokens: Sequence[int], pages: Sequence[int],
                 logits: Optional[np.ndarray] = None,
                 namespace: Optional[str] = None) -> None:
        """Index a freshly prefilled prefix: one entry per FULL page
        (first writer wins — an existing entry for the same tokens keeps
        its page), plus, when ``logits`` is given, an exact-full-prompt
        entry retaining the partial tail page and the last-token logits.
        The trash page is never indexed."""
        key = tuple(tokens)
        n, ps = len(key), self.page_size
        for k in range(1, n // ps + 1):
            sub = self._nskey(namespace, key[:k * ps])
            page = pages[k - 1]
            if sub in self._index or page == 0:
                continue
            self._index[sub] = page
            self._page_key[page] = ("page", sub)
        nkey = self._nskey(namespace, key)
        if logits is None or nkey in self._full:
            return
        tail: Optional[int] = None
        if n % ps:
            tail = pages[n // ps]
            if tail == 0:
                return
            self._page_key[tail] = ("tail", nkey)
        self._full[nkey] = _FullEntry(tail, np.asarray(logits))
        while len(self._full) > self.logits_capacity:
            old_key, old = self._full.popitem(last=False)
            if old.tail_page is not None and \
                    self._page_key.get(old.tail_page) == ("tail", old_key):
                del self._page_key[old.tail_page]
                self.allocator.uncache(old.tail_page)


@jax.jit
def copy_page(k_pages: jnp.ndarray, v_pages: jnp.ndarray,
              src, dst) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Device-side physical page copy — the copy-on-write primitive.
    ``src``/``dst`` are traced scalars, so this compiles once per pool
    shape no matter which pages get copied."""
    return (k_pages.at[:, dst].set(k_pages[:, src]),
            v_pages.at[:, dst].set(v_pages[:, src]))


class PagedKVCache:
    """Device pool + host metadata mirror for the serving decode batch.

    Device state (jitted steps read/write):
      k_pages, v_pages  [L, num_pages, page_size, KH, D]

    Host mirror (authoritative, numpy — the scheduler mutates it and the
    engine ships it to device per step; decode-step updates are
    deterministic (+1 length, one valid column) so the host applies them
    itself rather than fetching arrays back):
      block_tables  [num_slots, pages_per_slot] int32 physical page ids
      valid         [num_slots, S] attendable columns
      pos           [num_slots, S] logical position per column
      lengths       [num_slots]    true tokens so far
      tokens        [num_slots]    last sampled token (next step's input)
    """

    def __init__(self, model, geom: PageGeometry):
        cfg = model.cfg
        self.geom = geom
        self.dtype = model.adtype
        shape = (cfg.num_layers, geom.num_pages, geom.page_size,
                 cfg.num_kv_heads, cfg.head_dim_)
        self.k_pages = jnp.zeros(shape, self.dtype)
        self.v_pages = jnp.zeros(shape, self.dtype)
        s = geom.slot_window
        self.block_tables = np.zeros(
            (geom.num_slots, geom.pages_per_slot), np.int32)
        self.valid = np.zeros((geom.num_slots, s), bool)
        self.pos = np.zeros((geom.num_slots, s), np.int32)
        self.lengths = np.zeros((geom.num_slots,), np.int32)
        self.tokens = np.zeros((geom.num_slots,), np.int32)
        self.allocator = PageAllocator(geom.num_pages)

    # ---------------------------------------------------- slot lifecycle

    def open_slot(self, slot: int, pages: List[int], prompt_len: int,
                  padded_len: int, first_token: int) -> None:
        """Bind ``pages`` to ``slot`` and set prompt metadata: columns
        [0, prompt_len) valid at positions 0..prompt_len-1 (prompts are
        right-padded to ``padded_len``; pad columns hold garbage KV and
        stay invalid). ``first_token`` is the token sampled from the
        prefill logits — the first decode step's input."""
        self.block_tables[slot] = 0
        self.block_tables[slot, :len(pages)] = pages
        self.valid[slot] = False
        self.valid[slot, :prompt_len] = True
        self.pos[slot] = 0
        self.pos[slot, :padded_len] = np.arange(padded_len)
        self.lengths[slot] = prompt_len
        self.tokens[slot] = first_token

    def open_slot_prefill(self, slot: int, pages: List[int],
                          cached_len: int) -> None:
        """Bind ``pages`` for a CHUNKED prefill: columns [0, cached_len)
        are shared cache pages, already valid and attendable; later
        columns become valid as chunks scatter into them
        (``mark_computed``). ``lengths`` stays 0 — the slot joins the
        decode batch only at ``begin_decode``."""
        self.block_tables[slot] = 0
        self.block_tables[slot, :len(pages)] = pages
        self.valid[slot] = False
        self.valid[slot, :cached_len] = True
        self.pos[slot] = np.arange(self.geom.slot_window)
        self.lengths[slot] = 0
        self.tokens[slot] = 0

    def mark_computed(self, slot: int, start: int, count: int) -> None:
        """A prefill chunk scattered columns [start, start+count)."""
        self.valid[slot, start:start + count] = True

    def begin_decode(self, slot: int, prompt_len: int,
                     first_token: int) -> None:
        """Prefill complete (chunked or fully cached): the slot enters
        the decode batch at position ``prompt_len`` with ``first_token``
        as its next input."""
        self.valid[slot, :prompt_len] = True
        self.lengths[slot] = prompt_len
        self.tokens[slot] = first_token

    def close_slot(self, slot: int) -> None:
        """Reset a slot to trash-page aliasing (pages are freed by the
        scheduler, which owns the request -> pages mapping)."""
        self.block_tables[slot] = 0
        self.valid[slot] = False
        self.pos[slot] = 0
        self.lengths[slot] = 0
        self.tokens[slot] = 0

    def advance_slot(self, slot: int, token: int) -> None:
        """Apply one decode step's deterministic metadata update: the
        step wrote this slot's KV at column ``lengths`` with logical
        position ``lengths``; ``token`` was sampled and becomes the next
        step's input.

        Speculative rounds commit per accepted token through this same
        method — the host mirrors only ever advance by the ACCEPTED
        prefix, so a rejected draft tail needs no rollback: its columns
        were written on device but never marked valid here, and the next
        round's scatter overwrites them (the write-cursor "rewind" is
        that the cursor simply never moved)."""
        col = int(self.lengths[slot])
        self.valid[slot, col] = True
        self.pos[slot, col] = col
        self.lengths[slot] = col + 1
        self.tokens[slot] = token

    def slot_page_index(self, slot: int) -> int:
        """Block-table index the NEXT decode write for ``slot`` needs
        (its write column / page_size)."""
        return int(self.lengths[slot]) // self.geom.page_size

    def cow_page(self, slot: int, page_index: int, new_page: int) -> None:
        """Copy-on-write: duplicate the physical page behind
        ``block_tables[slot, page_index]`` into ``new_page`` on device
        and repoint the table — the shared original stays pristine for
        its other readers and the index."""
        src = int(self.block_tables[slot, page_index])
        self.k_pages, self.v_pages = copy_page(
            self.k_pages, self.v_pages,
            jnp.asarray(src, jnp.int32), jnp.asarray(new_page, jnp.int32))
        self.block_tables[slot, page_index] = new_page
