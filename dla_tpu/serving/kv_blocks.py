"""Block-paged KV cache: a fixed page pool shared by every in-flight
sequence, so sequences of wildly different lengths never reserve
worst-case contiguous cache.

Layout: one preallocated pool ``k_pages``/``v_pages`` of shape
[L, num_pages, page_size, KH, D]. Each decode SLOT (a row of the
static-shape decode batch) owns a block table row — ``pages_per_slot``
physical page ids — and the in-graph gather

    k_view = k_pages[:, block_table]           # [L, B, P/slot, ps, KH, D]
             .reshape(L, B, S, KH, D)          # S = pages_per_slot * ps

rebuilds the contiguous [B, S] window ``Transformer.decode_step_paged``
consumes. The gather is the whole trick: attention math stays
layout-agnostic, the pool stays fixed-size, and page ownership is pure
host-side bookkeeping (PageAllocator) that never touches the graph.

Physical page 0 is RESERVED as the trash page: free slots' block tables
point at it, so the static-shape decode step can let inactive rows
write/read garbage there without branching. The allocator never hands
page 0 out.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

import jax.numpy as jnp
import numpy as np


class PageAllocator:
    """Host-side free-list allocator over the fixed page pool.

    Pages are fixed-size, so there is no external fragmentation — any
    interleaving of alloc/free keeps every free page usable. Allocation
    is all-or-nothing: a request that cannot get ALL ``n`` pages gets
    none (no partial reservations to unwind on admission failure).
    """

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is the trash page)")
        self.num_pages = num_pages
        # page 0 reserved: free slots alias it for garbage traffic
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._used: set = set()

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return len(self._used)

    @property
    def capacity(self) -> int:
        """Allocatable pages (excludes the reserved trash page)."""
        return self.num_pages - 1

    @property
    def occupancy(self) -> float:
        """Fraction of allocatable pages currently owned."""
        return self.used_count / max(1, self.capacity)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` pages, or None if the pool cannot supply all of them."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        self._used.update(pages)
        return pages

    def free(self, pages: List[int]) -> None:
        for p in pages:
            if p not in self._used:
                raise ValueError(f"double free / foreign page {p}")
            self._used.discard(p)
            self._free.append(p)


@dataclasses.dataclass(frozen=True)
class PageGeometry:
    """Static shape parameters of a paged pool — everything the jitted
    serving steps specialize on."""
    page_size: int
    num_pages: int
    num_slots: int
    pages_per_slot: int

    @property
    def slot_window(self) -> int:
        """S: the per-slot logical window the gather materializes."""
        return self.pages_per_slot * self.page_size

    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to hold ``n_tokens`` (ceil)."""
        return -(-n_tokens // self.page_size)


class PagedKVCache:
    """Device pool + host metadata mirror for the serving decode batch.

    Device state (jitted steps read/write):
      k_pages, v_pages  [L, num_pages, page_size, KH, D]

    Host mirror (authoritative, numpy — the scheduler mutates it and the
    engine ships it to device per step; decode-step updates are
    deterministic (+1 length, one valid column) so the host applies them
    itself rather than fetching arrays back):
      block_tables  [num_slots, pages_per_slot] int32 physical page ids
      valid         [num_slots, S] attendable columns
      pos           [num_slots, S] logical position per column
      lengths       [num_slots]    true tokens so far
      tokens        [num_slots]    last sampled token (next step's input)
    """

    def __init__(self, model, geom: PageGeometry):
        cfg = model.cfg
        self.geom = geom
        self.dtype = model.adtype
        shape = (cfg.num_layers, geom.num_pages, geom.page_size,
                 cfg.num_kv_heads, cfg.head_dim_)
        self.k_pages = jnp.zeros(shape, self.dtype)
        self.v_pages = jnp.zeros(shape, self.dtype)
        s = geom.slot_window
        self.block_tables = np.zeros(
            (geom.num_slots, geom.pages_per_slot), np.int32)
        self.valid = np.zeros((geom.num_slots, s), bool)
        self.pos = np.zeros((geom.num_slots, s), np.int32)
        self.lengths = np.zeros((geom.num_slots,), np.int32)
        self.tokens = np.zeros((geom.num_slots,), np.int32)
        self.allocator = PageAllocator(geom.num_pages)

    # ---------------------------------------------------- slot lifecycle

    def open_slot(self, slot: int, pages: List[int], prompt_len: int,
                  padded_len: int, first_token: int) -> None:
        """Bind ``pages`` to ``slot`` and set prompt metadata: columns
        [0, prompt_len) valid at positions 0..prompt_len-1 (prompts are
        right-padded to ``padded_len``; pad columns hold garbage KV and
        stay invalid). ``first_token`` is the token sampled from the
        prefill logits — the first decode step's input."""
        self.block_tables[slot] = 0
        self.block_tables[slot, :len(pages)] = pages
        self.valid[slot] = False
        self.valid[slot, :prompt_len] = True
        self.pos[slot] = 0
        self.pos[slot, :padded_len] = np.arange(padded_len)
        self.lengths[slot] = prompt_len
        self.tokens[slot] = first_token

    def close_slot(self, slot: int) -> None:
        """Reset a slot to trash-page aliasing (pages are freed by the
        scheduler, which owns the request -> pages mapping)."""
        self.block_tables[slot] = 0
        self.valid[slot] = False
        self.pos[slot] = 0
        self.lengths[slot] = 0
        self.tokens[slot] = 0

    def advance_slot(self, slot: int, token: int) -> None:
        """Apply one decode step's deterministic metadata update: the
        step wrote this slot's KV at column ``lengths`` with logical
        position ``lengths``; ``token`` was sampled and becomes the next
        step's input."""
        col = int(self.lengths[slot])
        self.valid[slot, col] = True
        self.pos[slot, col] = col
        self.lengths[slot] = col + 1
        self.tokens[slot] = token

    def slot_page_index(self, slot: int) -> int:
        """Block-table index the NEXT decode write for ``slot`` needs
        (its write column / page_size)."""
        return int(self.lengths[slot]) // self.geom.page_size
