"""Streaming HTTP front door for a serving engine or fleet.

``ServingGateway`` turns the in-process engine-shaped API (``submit`` /
``step`` / ``result``) into a network service — the ``MetricsHTTPServer``
idiom grown up: one stdlib ``ThreadingHTTPServer`` (no new deps, handler
threads carry the ``dla-`` name prefix) in front of ONE engine-stepping
thread, so the engine's single-threaded discipline is preserved while
any number of HTTP clients stream concurrently.

Routes:

- ``POST /v1/generate`` — submit + per-token streaming (SSE-style
  ``data: {json}\\n\\n`` events carrying token, logprob, and index; the
  final event carries the finish state). Backpressure maps onto the
  engine's existing admission machinery: shed at the gate or displaced
  from a full queue -> **429** with ``Retry-After``; a per-request
  deadline that expires before the first token -> **408**; draining ->
  **503** (load balancers stop routing via ``/healthz`` first). A
  broken pipe on an event write means the client hung up: the request
  is cancelled through ``scheduler.cancel`` and counted on
  ``serving/gateway/disconnect_cancels`` — slots and pages go back to
  the pool instead of decoding for nobody.
- ``GET /v1/stream?rid=N&have=K`` — re-attach to a live request's
  stream (the cross-fleet handoff consumer): events ``K..`` replay from
  the result surface, then the live stream continues.
- ``POST /v1/peek`` — the federation scoring surface: peeked prefix-
  cache hit fraction + pressure for a prompt, the same inputs
  ``FleetRouter._choose`` uses locally.
- ``POST /v1/migrate_out`` / ``POST /v1/migrate_in`` — a mid-decode
  request leaves/enters as a versioned ``MigrationTicket.to_bytes``
  wire payload (serving.migration), the cross-host handoff format.
- ``GET /healthz`` — readiness: 503 body ``draining`` while the
  owner refuses new work, the exporter's contract.
- ``GET /metrics`` — the gateway registry's Prometheus text.

Determinism: the gateway adds NO sampling state. A request's token
stream is the engine's ``fold_in(seed, k)`` stream — a pure function of
(sampling seed, token index) — so the same seeded trace through an
in-process router and through gateway-fronted fleets yields bit-
identical tokens (the federation acceptance test pins this).

Locking: ``_lock`` serializes every engine touch (handler submits vs
the step loop) and the stream table; ``_stats_lock`` guards the plain-
int handler counters and is only ever taken alone or inside ``_lock``
(one fixed order — the runtime lock witness sees no cycle). Handlers
never hold ``_lock`` while writing to a socket: a slow client must not
stall the engine.
"""
from __future__ import annotations

import dataclasses
import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler
from typing import Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from dla_tpu.ops.sampling import SamplingParams
from dla_tpu.serving.migration import MigrationError, MigrationTicket
from dla_tpu.serving.scheduler import TERMINAL_STATES, RequestState
from dla_tpu.telemetry.exporter import DlaThreadingHTTPServer, ReadinessProbe
from dla_tpu.telemetry.registry import MetricRegistry
from dla_tpu.telemetry.trace import get_tracer, register_trace_gauges
from dla_tpu.telemetry.trace_context import TRACEPARENT_HEADER, TraceContext


@dataclasses.dataclass(frozen=True)
class GatewayConfig:
    """Front-door knobs (``latency.serving.gateway`` in config)."""

    host: str = "127.0.0.1"
    port: int = 0                      # 0 -> ephemeral; .port reports it
    retry_after_s: float = 1.0         # Retry-After on 429/503
    idle_poll_s: float = 0.001         # engine-loop sleep when drained
    first_event_timeout_s: float = 300.0   # covers the first XLA compile
    event_timeout_s: float = 120.0
    max_body_bytes: int = 64 << 20


class GatewayMetrics:
    """The ``serving/gateway/*`` panel. Instruments live in the
    gateway's own registry, which outlives the engines behind it (the
    FleetMetrics idiom); handler threads bump plain ints and the engine
    loop delta-mirrors them in, so totals stay monotone across engine
    swaps and supervisor rebuilds."""

    def __init__(self, registry: Optional[MetricRegistry] = None):
        r = self.registry = registry or MetricRegistry()
        self.connections = r.counter("serving/gateway/connections")
        self.streamed_tokens = r.counter(
            "serving/gateway/streamed_tokens")
        self.disconnect_cancels = r.counter(
            "serving/gateway/disconnect_cancels")
        self.http_429 = r.counter("serving/gateway/http_429")
        self.http_408 = r.counter("serving/gateway/http_408")
        # the trainer tracer's accounting contract, extended to this
        # process's tracer ring: drops are visible in /metrics, not
        # silently evicted (FuncGauges follow the live install_tracer)
        register_trace_gauges(r)

    def snapshot(self) -> Dict[str, float]:
        return self.registry.snapshot()


class _Stream:
    """Per-request event mailbox between the engine loop (producer)
    and one handler thread (consumer)."""

    def __init__(self, rid: int, sent: int):
        self.rid = rid
        self.sent = sent               # tokens already delivered/owned
        self.q: "queue.Queue" = queue.Queue()


class ServingGateway:
    """One HTTP front door around anything engine-shaped: a
    ``ServingEngine``, a ``Supervisor``, or a ``FleetRouter``."""

    def __init__(self, engine, cfg: Optional[GatewayConfig] = None,
                 registry: Optional[MetricRegistry] = None):
        self.engine = engine
        self.cfg = cfg or GatewayConfig()
        self.metrics = GatewayMetrics(registry)
        self.readiness = ReadinessProbe()
        self._lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self._stats = {"connections": 0, "streamed_tokens": 0,
                       "disconnect_cancels": 0, "http_429": 0,
                       "http_408": 0}
        self._mirrored = dict.fromkeys(self._stats, 0)
        self._streams: Dict[int, _Stream] = {}
        # rid -> wire trace context (guarded by _lock, like _streams):
        # lets a later migrate_out parent the ticket onto the span tree
        # the request's origin minted
        self._trace_ctx: Dict[int, TraceContext] = {}
        # gossip metrics-digest rate state (only the beater thread calls
        # metrics_digest, but guard anyway — it is cheap)
        self._digest_t = time.monotonic()
        self._digest_tokens = 0
        self._stop = threading.Event()
        self.loop_error: Optional[str] = None
        handler = _make_handler(self)
        self._httpd = DlaThreadingHTTPServer(
            (self.cfg.host, self.cfg.port), handler)
        self._httpd.daemon_threads = True
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="dla-gateway-http",
            daemon=True)
        self._engine_thread = threading.Thread(
            target=self._engine_loop, name="dla-gateway-engine",
            daemon=True)
        self._http_thread.start()
        self._engine_thread.start()

    # ---------------------------------------------------------- lifecycle

    @property
    def port(self) -> int:
        return self._httpd.bound_port

    @property
    def url(self) -> str:
        return f"http://{self._httpd.server_address[0]}:{self.port}"

    def begin_drain(self) -> None:
        """Refuse new work: /healthz flips to 503 ``draining`` (load
        balancers stop routing) and admission starts answering 503."""
        self.readiness.set_draining("draining")
        with self._lock:
            self.engine.begin_drain()

    @property
    def draining(self) -> bool:
        return bool(getattr(self.engine, "draining", False)) \
            or self.readiness.drain_reason is not None

    def close(self, timeout: Optional[float] = 5.0) -> None:
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        self._http_thread.join(timeout=timeout)
        self._engine_thread.join(timeout=timeout)

    # -------------------------------------------------------- engine loop

    def _engine_loop(self) -> None:
        """The ONLY thread that steps the engine. Each iteration:
        step when there is work, fan the emitted (rid, token) stream
        out to registered per-request mailboxes, finalize terminal
        requests, mirror the handler counters."""
        while not self._stop.is_set():
            worked = False
            with self._lock:
                try:
                    if self.engine.has_work():
                        worked = True
                        self._dispatch(self.engine.step())
                    self._finalize()
                except Exception as exc:  # noqa: BLE001 — a dead loop
                    # must surface, not hang every stream forever
                    self.loop_error = repr(exc)
                    self._fail_streams(repr(exc))
                self._mirror_gateway_counters()
            self.readiness.beat()
            if not worked:
                self._stop.wait(self.cfg.idle_poll_s)

    def _dispatch(self, events) -> None:
        for rid, tok in events:
            st = self._streams.get(rid)
            if st is None:
                continue
            req = self.engine.result(rid)
            logp = (req.generated_logprobs[st.sent]
                    if st.sent < len(req.generated_logprobs) else 0.0)
            st.q.put(("tok", st.sent, int(tok), float(logp)))
            st.sent += 1

    def _finalize(self) -> None:
        for rid, st in list(self._streams.items()):
            try:
                req = self.engine.result(rid)
            except KeyError:
                # released after a migrate_out: the serialized ticket
                # owns the request now — tell the consumer to re-attach
                st.q.put(("done", "migrated", "migrated", st.sent))
                # dla: disable=unsynchronized-shared-state -- _finalize runs only inside the engine loop's `with self._lock` block; register_stream documents the same caller-holds-_lock contract
                del self._streams[rid]
                continue
            if req.state in TERMINAL_STATES:
                reason = req.finish_reason or req.state.name.lower()
                st.q.put(("done", req.state.name.lower(), reason,
                          len(req.generated)))
                del self._streams[rid]
                self._trace_ctx.pop(rid, None)

    def _fail_streams(self, err: str) -> None:
        for rid, st in list(self._streams.items()):
            st.q.put(("done", "error", err, st.sent))
            del self._streams[rid]
            self._trace_ctx.pop(rid, None)

    def _mirror_gateway_counters(self) -> None:
        """Delta-mirror the handler-thread stats into the registry
        instruments (the speculative-counter idiom: plain ints are the
        source of truth, the registry copy stays monotone)."""
        m = self.metrics
        with self._stats_lock:
            s, seen = self._stats, self._mirrored
            m.connections.inc(s["connections"] - seen["connections"])
            m.streamed_tokens.inc(
                s["streamed_tokens"] - seen["streamed_tokens"])
            m.disconnect_cancels.inc(
                s["disconnect_cancels"] - seen["disconnect_cancels"])
            m.http_429.inc(s["http_429"] - seen["http_429"])
            m.http_408.inc(s["http_408"] - seen["http_408"])
            seen.update(s)

    def _bump(self, name: str, by: int = 1) -> None:
        with self._stats_lock:
            self._stats[name] += by

    # ------------------------------------------------- handler-side hooks

    def register_stream(self, rid: int, sent: int) -> _Stream:
        """Caller must hold ``_lock`` (registration must be atomic with
        the submit/result read that produced ``rid``)."""
        st = _Stream(rid, sent)
        self._streams[rid] = st
        return st

    def unregister_stream(self, rid: int) -> None:
        with self._lock:
            self._streams.pop(rid, None)
            self._trace_ctx.pop(rid, None)

    def cancel_disconnected(self, rid: int) -> None:
        """Broken pipe on an event write: the client is gone — give the
        slot and pages back and count it."""
        with self._lock:
            self._streams.pop(rid, None)
            self._trace_ctx.pop(rid, None)
            try:
                self.engine.cancel(rid, "client_disconnect")
            except KeyError:
                pass
        self._bump("disconnect_cancels")

    def peek(self, prompt_tokens,
             tenant: Optional[str] = None) -> Tuple[float, float]:
        """(hit_frac, pressure) for a prompt — the federation scoring
        inputs. Caller must hold ``_lock``. ``tenant`` scopes the
        prefix peek to that tenant's KV namespace."""
        eng = self.engine
        if hasattr(eng, "peek_score"):          # FleetRouter
            return eng.peek_score(list(prompt_tokens), tenant=tenant)
        n = max(1, len(prompt_tokens))
        hit = 0.0
        if getattr(eng, "prefix_cache", None) is not None:
            hit = eng.prefix_cache.peek(
                list(prompt_tokens), eng.cfg.prefill_chunk,
                namespace=tenant) / n
        occ = eng.cache.allocator.occupancy
        qcap = (eng.admission.cfg.max_queue_depth
                if eng.admission is not None
                else max(8, 2 * eng.cfg.num_slots))
        return hit, max(occ, eng.scheduler.queue_depth / max(1, qcap))

    def metrics_digest(self) -> Dict[str, float]:
        """Small numeric health digest for the gossip beat — the inputs
        ``FleetMetricsAggregator`` rolls into the ``fleet/*`` panel.
        Called from the beater thread between beats; every key must be
        a finite float (the beat doc is strict JSON)."""
        with self._lock:
            try:
                _hit, pressure = self.peek([])
            except Exception:  # noqa: BLE001 — engine mid-swap: report
                pressure = 1.0  # saturated rather than kill the beat
            depth = float(len(self._streams))
        with self._stats_lock:
            tokens = self._stats["streamed_tokens"]
            now = time.monotonic()
            dt = now - self._digest_t
            tok_s = ((tokens - self._digest_tokens) / dt) if dt > 0 \
                else 0.0
            self._digest_t, self._digest_tokens = now, tokens
        tracer = get_tracer()
        return {
            "pressure": float(pressure),
            "queue_depth": depth,
            "goodput_tok_s": float(tok_s),
            "trace_dropped": float(tracer.dropped),
            "draining": 1.0 if self.draining else 0.0,
        }


def _make_handler(outer: ServingGateway):
    """Build the request-handler class closed over one gateway."""

    class _Handler(BaseHTTPRequestHandler):

        # ------------------------------------------------------ plumbing

        def log_message(self, *args):   # requests are metrics, not logs
            pass

        def _body(self) -> bytes:
            length = int(self.headers.get("Content-Length") or 0)
            if length > outer.cfg.max_body_bytes:
                raise ValueError(f"body of {length} bytes over the "
                                 f"{outer.cfg.max_body_bytes} cap")
            return self.rfile.read(length)

        def _json(self, status: int, obj,
                  retry_after: bool = False) -> None:
            body = json.dumps(obj).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if retry_after:
                self.send_header(
                    "Retry-After", f"{outer.cfg.retry_after_s:g}")
            self.end_headers()
            self.wfile.write(body)

        def _event(self, obj) -> None:
            self.wfile.write(b"data: " + json.dumps(obj).encode()
                             + b"\n\n")
            self.wfile.flush()

        # -------------------------------------------------------- routes

        def do_GET(self):           # noqa: N802 (http.server API)
            outer._bump("connections")
            path = self.path.split("?")[0]
            if path == "/healthz":
                self._healthz()
            elif path == "/metrics":
                self._metrics()
            elif path == "/v1/stream":
                self._stream_attach()
            elif path == "/v1/result":
                self._result()
            else:
                self.send_error(404)

        def do_POST(self):          # noqa: N802 (http.server API)
            outer._bump("connections")
            path = self.path.split("?")[0]
            try:
                if path == "/v1/generate":
                    self._generate()
                elif path == "/v1/peek":
                    self._peek()
                elif path == "/v1/migrate_out":
                    self._migrate_out()
                elif path == "/v1/migrate_in":
                    self._migrate_in()
                elif path == "/admin/drain":
                    outer.begin_drain()
                    self._json(200, {"draining": True})
                else:
                    self.send_error(404)
            except ValueError as exc:
                self._json(400, {"error": str(exc)})

        def _healthz(self):
            probe = outer.readiness
            if probe.drain_reason is not None or outer.draining:
                status = 503
                body = (probe.drain_reason or "draining") + "\n"
            elif probe.ready:
                status, body = 200, f"ok age_s={probe.age_s:.1f}\n"
            else:
                status = 503
                body = f"stale age_s={probe.age_s:.1f}\n"
            raw = body.encode()
            self.send_response(status)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(raw)))
            self.end_headers()
            self.wfile.write(raw)

        def _metrics(self):
            try:
                body = outer.metrics.registry.prometheus_text().encode()
            except Exception as exc:  # noqa: BLE001 — 500 > dead thread
                self.send_error(500, str(exc))
                return
            self.send_response(200)
            self.send_header(
                "Content-Type",
                "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        # ------------------------------------------------- generate path

        def _generate(self):
            spec = json.loads(self._body() or b"{}")
            prompt = [int(t) for t in spec.get("prompt") or ()]
            if not prompt:
                raise ValueError("generate wants a non-empty 'prompt'")
            sampling = spec.get("sampling")
            if sampling is not None:
                sampling = SamplingParams(**sampling)
            # multi-tenant serving: an unknown tenant raises ValueError
            # out of engine.submit and surfaces as HTTP 400
            tenant = spec.get("tenant")
            # trace context: continue the caller's trace (a federated
            # router hop) or mint a root here — the gateway IS the
            # request's origin for direct clients
            parent = TraceContext.from_header(
                self.headers.get(TRACEPARENT_HEADER))
            ctx = parent.child() if parent is not None \
                else TraceContext.mint()
            tracer = get_tracer()
            t0 = tracer.now()
            with outer._lock:
                try:
                    rid = outer.engine.submit(
                        prompt,
                        int(spec.get("max_new_tokens") or 16),
                        deadline_s=spec.get("deadline_s"),
                        priority=int(spec.get("priority") or 0),
                        sampling=sampling, tenant=tenant)
                except RuntimeError as exc:     # draining: admission shut
                    self._json(503, {"error": str(exc)},
                               retry_after=True)
                    return
                req = outer.engine.result(rid)
                if req.state is RequestState.SHED:
                    outer._bump("http_429")
                    self._json(429, {"error": "shed", "rid": rid},
                               retry_after=True)
                    return
                st = outer.register_stream(rid, sent=len(req.generated))
                outer._trace_ctx[rid] = ctx
            try:
                self._pump(rid, st, first_decides_status=True)
            finally:
                # one wire-request span covering submit -> last event,
                # tagged with the shared trace id so trace_merge can
                # stitch it under the remote caller's span
                tracer.complete(
                    "wire_request", t0, tracer.now(), cat="gateway",
                    args=dict(rid=rid, **ctx.tags(parent)))

        def _stream_attach(self):
            q = parse_qs(urlparse(self.path).query)
            rid = int(q.get("rid", ["-1"])[0])
            have = int(q.get("have", ["0"])[0])
            catchup, done_ev, st = [], None, None
            with outer._lock:
                try:
                    req = outer.engine.result(rid)
                except KeyError:
                    self._json(404, {"error": f"unknown rid {rid}"})
                    return
                toks = list(req.generated)
                logps = list(req.generated_logprobs)
                catchup = [("tok", i, int(toks[i]),
                            float(logps[i]) if i < len(logps) else 0.0)
                           for i in range(have, len(toks))]
                if req.state in TERMINAL_STATES:
                    reason = req.finish_reason or req.state.name.lower()
                    done_ev = ("done", req.state.name.lower(), reason,
                               len(toks))
                else:
                    st = outer.register_stream(rid, sent=len(toks))
            self._send_sse_headers(rid)
            try:
                for ev in catchup:
                    self._write_tok(ev)
                if done_ev is not None:
                    self._event({"done": True, "state": done_ev[1],
                                 "reason": done_ev[2], "n": done_ev[3]})
                    return
                self._pump_events(rid, st)
            except OSError:
                outer.cancel_disconnected(rid)

        def _send_sse_headers(self, rid: int) -> None:
            # the rid rides a response header so a federation client
            # can later migrate the request it is still streaming
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-store")
            self.send_header("Connection", "close")
            self.send_header("X-DLA-Rid", str(rid))
            self.end_headers()

        def _write_tok(self, ev):
            _, idx, tok, logp = ev
            self._event({"i": idx, "token": tok, "logprob": logp})
            outer._bump("streamed_tokens")

        def _pump(self, rid: int, st: _Stream,
                  first_decides_status: bool) -> None:
            """Wait for the first event; it picks the HTTP status (a
            deadline that beat the first token -> 408, a mid-queue shed
            -> 429, anything streamed -> 200). Then stream until the
            done event."""
            try:
                ev = st.q.get(timeout=outer.cfg.first_event_timeout_s)
            except queue.Empty:
                outer.unregister_stream(rid)
                self._json(504, {"error": "no first event before "
                                 "timeout", "rid": rid})
                return
            if ev[0] == "done" and first_decides_status:
                state = ev[1]
                if state == "timeout" and ev[3] == 0:
                    outer._bump("http_408")
                    self._json(408, {"error": "deadline expired before "
                                     "first token", "rid": rid})
                    return
                if state == "shed":
                    outer._bump("http_429")
                    self._json(429, {"error": "shed", "rid": rid},
                               retry_after=True)
                    return
            self._send_sse_headers(rid)
            try:
                if ev[0] == "tok":
                    self._write_tok(ev)
                    self._pump_events(rid, st)
                else:
                    self._event({"done": True, "state": ev[1],
                                 "reason": ev[2], "n": ev[3]})
            except OSError:
                outer.cancel_disconnected(rid)

        def _pump_events(self, rid: int, st: _Stream) -> None:
            """Stream mailbox events to the socket until done. OSError
            propagates to the caller's disconnect handler."""
            while True:
                try:
                    ev = st.q.get(timeout=outer.cfg.event_timeout_s)
                except queue.Empty:
                    outer.unregister_stream(rid)
                    self._event({"done": True, "state": "error",
                                 "reason": "event timeout", "n": -1})
                    return
                if ev[0] == "tok":
                    self._write_tok(ev)
                else:
                    self._event({"done": True, "state": ev[1],
                                 "reason": ev[2], "n": ev[3],
                                 "rid": rid})
                    return

        # ----------------------------------------------- federation path

        def _peek(self):
            spec = json.loads(self._body() or b"{}")
            prompt = [int(t) for t in spec.get("prompt") or ()]
            parent = TraceContext.from_header(
                self.headers.get(TRACEPARENT_HEADER))
            tracer = get_tracer()
            t0 = tracer.now()
            with outer._lock:
                hit, pressure = outer.peek(prompt, spec.get("tenant"))
                draining = outer.draining
            if parent is not None:
                ctx = parent.child()
                tracer.complete("peek", t0, tracer.now(), cat="gateway",
                                args=ctx.tags(parent))
            self._json(200, {"hit_frac": hit, "pressure": pressure,
                             "draining": draining})

        def _migrate_out(self):
            spec = json.loads(self._body() or b"{}")
            rid = int(spec.get("rid", -1))
            header_ctx = TraceContext.from_header(
                self.headers.get(TRACEPARENT_HEADER))
            tracer = get_tracer()
            t0 = tracer.now()
            with outer._lock:
                try:
                    ticket = outer.engine.export_request(rid)
                except KeyError:
                    self._json(404, {"error": f"unknown rid {rid}"})
                    return
                except MigrationError as exc:
                    self._json(409, {"error": str(exc)})
                    return
                # parent the migration onto the request's own wire span
                # when we minted/continued one here, else onto the
                # caller's context, else the ticket travels untraced
                base = outer._trace_ctx.pop(rid, None) or header_ctx
                # two-phase engines (ServingEngine) still hold the
                # source copy; FleetRouter.export_request has already
                # released it and owns no release_migrated
                release = getattr(outer.engine, "release_migrated", None)
                if release is not None:
                    release(rid)
                # the ticket owns the request now: close the source
                # stream with the re-attach signal here (FleetRouter
                # archives the exported rid, so the engine-loop's
                # KeyError path would never see it go away)
                st = outer._streams.pop(rid, None)
                if st is not None:
                    st.q.put(("done", "migrated", "migrated", st.sent))
            if base is not None:
                ctx = base.child()
                # the ticket carries the context so the TARGET process's
                # migrate_in span can parent onto this one
                ticket = dataclasses.replace(
                    ticket, trace_ctx=ctx.tags(base))
                tracer.complete(
                    "migrate_out", t0, tracer.now(), cat="gateway",
                    args=dict(rid=rid, **ctx.tags(base)))
            blob = ticket.to_bytes()
            self.send_response(200)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)

        def _migrate_in(self):
            blob = self._body()
            try:
                ticket = MigrationTicket.from_bytes(blob)
            except MigrationError as exc:
                self._json(400, {"error": str(exc)})
                return
            tracer = get_tracer()
            t0 = tracer.now()
            tct = ticket.trace_ctx
            remote = None
            if isinstance(tct, dict) and isinstance(tct.get("trace"), str) \
                    and isinstance(tct.get("span"), str):
                remote = TraceContext(tct["trace"], tct["span"])
            with outer._lock:
                try:
                    existing = outer.engine.result(ticket.rid)
                    if existing.state not in TERMINAL_STATES:
                        self._json(409, {"error": f"rid {ticket.rid} "
                                         "is live on this fleet"})
                        return
                except KeyError:
                    pass
                try:
                    req = outer.engine.import_request(ticket)
                except MigrationError as exc:
                    self._json(409, {"error": str(exc)})
                    return
                if remote is not None:
                    # the imported request keeps streaming HERE: adopt
                    # the ticket's context so its remaining spans stay
                    # in the origin's trace
                    ctx = remote.child()
                    outer._trace_ctx[req.rid] = ctx
            if remote is not None:
                tracer.complete(
                    "migrate_in", t0, tracer.now(), cat="gateway",
                    args=dict(rid=req.rid, **ctx.tags(remote)))
            self._json(200, {"rid": req.rid,
                             "generated": len(req.generated)})

        def _result(self):
            q = parse_qs(urlparse(self.path).query)
            rid = int(q.get("rid", ["-1"])[0])
            with outer._lock:
                try:
                    req = outer.engine.result(rid)
                except KeyError:
                    self._json(404, {"error": f"unknown rid {rid}"})
                    return
                doc = {"rid": rid, "state": req.state.name.lower(),
                       "reason": req.finish_reason,
                       "tokens": list(req.generated),
                       "logprobs": list(req.generated_logprobs)}
            self._json(200, doc)

    return _Handler
