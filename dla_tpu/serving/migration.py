"""KV page migration: move an in-flight request's committed state
between serving engines — the handoff primitive behind prefill/decode
disaggregation and rebalance-without-recompute.

A request mid-decode is fully described by host metadata the engine
already mirrors (rid, sampling params, streamed tokens and logprobs,
the committed length) plus the KV columns its pages hold for the
committed prefix. :class:`KVMigrator` serializes that into a
:class:`MigrationTicket`:

- **export** gathers the request's ordered page list out of the source
  pool in ONE fixed-shape jitted call (``ServingEngine._export_kv_fn``,
  compile counter pinned at 1 per engine build) — pad page ids route to
  the trash page, so every export of every request reuses one compile;
- **transfer** keeps the payload on device when source and target share
  a device (or ``jax.device_put`` reaches the target directly), with a
  host bounce as the fallback (``transport: host`` forces it; the
  bounced bytes are counted on ``serving/migration/host_bounce_bytes``);
- **install** (``ServingEngine.import_request``) allocates pages on the
  target, scatters the KV columns in ONE fixed-shape jitted call
  (``_import_kv_fn``, also pinned at 1), registers the committed full
  pages into the target's PrefixCache, and binds the request straight
  into a decode slot — it resumes mid-stream on the next engine step.

The continuation is bit-identical to never having moved: token k of a
request is sampled with ``fold_in(PRNGKey(seed), k)`` where the seed
depends only on (engine config seed, rid) or explicit SamplingParams —
never on slot, engine, or placement — and the import preserves rid,
sampling, and the generated-token index.

Failure semantics: export REJECTS requests that are not resumable in
place — queued, prefilling, evicted (their pages are gone: the
"eviction hole"), or with uncomputed committed columns — and import
rejects geometry mismatches and page-pool exhaustion, all as
:class:`MigrationError` with the source request untouched. The fleet's
handoff path moves the supervisor journal entry atomically with the
install, so a source-engine crash mid-handoff replays the request on
exactly one engine (docs/SERVING.md "Disaggregated prefill/decode").
"""
from __future__ import annotations

import dataclasses
import json
import struct
from typing import List, Optional

import jax
import numpy as np

TRANSPORTS = ("auto", "device", "host")

#: Wire format version for ``MigrationTicket.to_bytes``. Bump on any
#: header-field or payload-layout change; ``from_bytes`` rejects other
#: versions with :class:`MigrationError` rather than misparsing.
WIRE_VERSION = 1

_WIRE_MAGIC = b"DLAT"
# magic(4) | version u16 | header-json length u32, little-endian
_WIRE_HEAD = struct.Struct("<4sHI")


def _wire_dtype(name: str) -> np.dtype:
    """Resolve a serialized dtype name, including the ml_dtypes families
    (bfloat16, float8_*) jax payloads use that numpy does not register
    under their string names."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        try:
            return np.dtype(getattr(ml_dtypes, name))
        except AttributeError:
            raise MigrationError(
                f"ticket payload dtype {name!r} is not resolvable on "
                f"this host") from None


class MigrationError(RuntimeError):
    """A migration step refused or failed; the source request (when one
    exists) is untouched and keeps running where it was."""


@dataclasses.dataclass(frozen=True)
class MigrationConfig:
    """KV handoff knobs (``latency.serving.migration`` in config).

    ``transport`` picks how the page payload travels: ``auto`` stays on
    device when the pools share one (device-to-device put otherwise,
    host bounce only when that fails), ``device`` requires a device
    path, ``host`` forces the bounce — the portability/debug arm, and
    what exercises ``serving/migration/host_bounce_bytes``."""

    transport: str = "auto"

    def __post_init__(self):
        if self.transport not in TRANSPORTS:
            raise ValueError(
                f"migration transport must be one of {TRANSPORTS}, "
                f"got {self.transport!r}")

    @classmethod
    def from_config(cls, cfg: Optional[dict]) -> "MigrationConfig":
        if not cfg:
            return cls()
        cfg = dict(cfg)
        cfg.pop("enabled", None)
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(cfg) - known
        if unknown:
            raise ValueError(
                f"unknown migration config keys: {sorted(unknown)}")
        return cls(**cfg)


@dataclasses.dataclass
class MigrationTicket:
    """A request's complete resumable state, engine-independent.

    ``k_payload``/``v_payload`` are the gathered page contents, shape
    ``[L, pages_per_slot, page_size, KH, D]`` — fixed per engine
    geometry, with only the first ``n_pages`` rows real (the pad rows
    hold trash-page contents and are never scattered onto real pages).
    ``committed_len`` is the number of KV columns the payload covers:
    ``len(prompt) + len(generated) - 1`` — the last generated token is
    the next decode input and its column has not been written yet.
    """
    rid: int
    prompt_tokens: List[int]
    max_new_tokens: int
    generated: List[int]
    generated_logprobs: List[float]
    sampling: Optional[object]          # SamplingParams override or None
    arrival_time: float
    deadline: Optional[float]
    priority: int
    committed_len: int
    page_size: int
    n_pages: int                        # real payload rows (committed)
    k_payload: object                   # [L, P, page_size, KH, D]
    v_payload: object
    transport: str = "device"           # how the payload currently lives
    src_slot: Optional[int] = None      # fleet slot of the exporter
    # source-engine clocks, carried so TTFT is not double-counted and
    # the cross-engine ITL gap (the handoff wait) is real
    admitted_time: Optional[float] = None
    first_token_time: Optional[float] = None
    last_token_time: Optional[float] = None
    # distributed-tracing context ({"trace", "span", "parent"} hex ids,
    # docs/OBSERVABILITY.md): optional meta key read via ``meta.get`` on
    # the old side, so carrying it needs no WIRE_VERSION bump
    trace_ctx: Optional[dict] = None
    # owning tenant (multi-tenant serving): the importer re-binds the
    # request's adapter and KV namespace from this; optional meta key
    # read via ``meta.get``, so no WIRE_VERSION bump either
    tenant: Optional[str] = None

    @property
    def payload_bytes(self) -> int:
        k, v = self.k_payload, self.v_payload
        return int(getattr(k, "nbytes", 0)) + int(getattr(v, "nbytes", 0))

    # ------------------------------------------------------- wire format

    def to_bytes(self) -> bytes:
        """Serialize for a cross-host handoff: a versioned header
        (magic, :data:`WIRE_VERSION`, JSON metadata with payload
        dtype/shape) followed by the raw KV page bytes. The payload is
        host-bounced first (one D2H, same contract as ``transport:
        host``), and the round trip is bit-exact: ``from_bytes`` yields
        payload arrays whose bytes equal the originals, and float
        metadata (arrival clocks, logprobs) survives via JSON's
        shortest-roundtrip float repr."""
        # dla: disable=host-sync-in-hot-loop -- designed wire export: one D2H per shipped ticket, counted by the caller on serving/federation/handoff_bytes
        k = np.ascontiguousarray(np.asarray(self.k_payload))
        v = np.ascontiguousarray(np.asarray(self.v_payload))
        sampling = (None if self.sampling is None
                    else dataclasses.asdict(self.sampling))
        meta = {
            "rid": int(self.rid),
            "prompt_tokens": [int(t) for t in self.prompt_tokens],
            "max_new_tokens": int(self.max_new_tokens),
            "generated": [int(t) for t in self.generated],
            "generated_logprobs": [float(p)
                                   for p in self.generated_logprobs],
            "sampling": sampling,
            "arrival_time": float(self.arrival_time),
            "deadline": self.deadline,
            "priority": int(self.priority),
            "committed_len": int(self.committed_len),
            "page_size": int(self.page_size),
            "n_pages": int(self.n_pages),
            "src_slot": self.src_slot,
            "admitted_time": self.admitted_time,
            "first_token_time": self.first_token_time,
            "last_token_time": self.last_token_time,
            "trace_ctx": self.trace_ctx,
            "tenant": self.tenant,
            "k_dtype": str(k.dtype), "k_shape": list(k.shape),
            "v_dtype": str(v.dtype), "v_shape": list(v.shape),
        }
        header = json.dumps(meta, separators=(",", ":")).encode()
        return (_WIRE_HEAD.pack(_WIRE_MAGIC, WIRE_VERSION, len(header))
                + header + k.tobytes() + v.tobytes())

    @classmethod
    def from_bytes(cls, blob: bytes) -> "MigrationTicket":
        """Parse a :meth:`to_bytes` payload. Rejects a wrong magic,
        a version mismatch, and truncation at any layer (header or
        payload bytes) with :class:`MigrationError` — a half-received
        ticket must never install."""
        if len(blob) < _WIRE_HEAD.size:
            raise MigrationError(
                f"truncated ticket: {len(blob)} bytes is shorter than "
                f"the {_WIRE_HEAD.size}-byte wire header")
        magic, version, hlen = _WIRE_HEAD.unpack_from(blob)
        if magic != _WIRE_MAGIC:
            raise MigrationError(
                f"bad ticket magic {magic!r} (expected {_WIRE_MAGIC!r})")
        if version != WIRE_VERSION:
            raise MigrationError(
                f"ticket wire version {version} does not match this "
                f"host's {WIRE_VERSION}")
        if len(blob) < _WIRE_HEAD.size + hlen:
            raise MigrationError(
                f"truncated ticket header: need {hlen} bytes, have "
                f"{len(blob) - _WIRE_HEAD.size}")
        try:
            meta = json.loads(blob[_WIRE_HEAD.size:_WIRE_HEAD.size + hlen])
        except ValueError as exc:
            raise MigrationError(
                f"corrupt ticket header: {exc}") from exc
        k_dtype = _wire_dtype(meta["k_dtype"])
        v_dtype = _wire_dtype(meta["v_dtype"])
        k_shape = tuple(int(d) for d in meta["k_shape"])
        v_shape = tuple(int(d) for d in meta["v_shape"])
        k_bytes = int(np.prod(k_shape, dtype=np.int64)) * k_dtype.itemsize
        v_bytes = int(np.prod(v_shape, dtype=np.int64)) * v_dtype.itemsize
        off = _WIRE_HEAD.size + hlen
        if len(blob) != off + k_bytes + v_bytes:
            raise MigrationError(
                f"truncated ticket payload: header declares "
                f"{k_bytes + v_bytes} payload bytes, have "
                f"{len(blob) - off}")
        k = np.frombuffer(blob, dtype=k_dtype, count=int(
            np.prod(k_shape, dtype=np.int64)), offset=off
        ).reshape(k_shape).copy()
        v = np.frombuffer(blob, dtype=v_dtype, count=int(
            np.prod(v_shape, dtype=np.int64)), offset=off + k_bytes
        ).reshape(v_shape).copy()
        sampling = meta["sampling"]
        if sampling is not None:
            from dla_tpu.ops.sampling import SamplingParams
            sampling = SamplingParams(**sampling)
        return cls(
            rid=meta["rid"], prompt_tokens=meta["prompt_tokens"],
            max_new_tokens=meta["max_new_tokens"],
            generated=meta["generated"],
            generated_logprobs=meta["generated_logprobs"],
            sampling=sampling, arrival_time=meta["arrival_time"],
            deadline=meta["deadline"], priority=meta["priority"],
            committed_len=meta["committed_len"],
            page_size=meta["page_size"], n_pages=meta["n_pages"],
            k_payload=k, v_payload=v, transport="host",
            src_slot=meta["src_slot"],
            admitted_time=meta["admitted_time"],
            first_token_time=meta["first_token_time"],
            last_token_time=meta["last_token_time"],
            trace_ctx=meta.get("trace_ctx"),
            tenant=meta.get("tenant"))


class KVMigrator:
    """Orchestrates export -> transfer -> install between two engines.

    The migrator is stateless beyond its config; counters live on the
    ENGINES' ``_mig_stats`` (delta-mirrored into their registries each
    step, Supervisor-re-seeded across rebuilds — the speculative-counter
    idiom), so totals stay monotone however many migrators touch an
    engine. Export failures count on the source, import failures and
    successes on the target."""

    def __init__(self, cfg: Optional[MigrationConfig] = None):
        self.cfg = cfg or MigrationConfig()

    # ---------------------------------------------------------- pipeline

    def export_ticket(self, engine, rid: int,
                      src_slot: Optional[int] = None) -> MigrationTicket:
        """Serialize ``rid``'s committed state out of ``engine``. Raises
        :class:`MigrationError` (and counts a failed migration on the
        source) when the request is not resumable in place."""
        ticket = engine.export_request(rid)
        ticket.src_slot = src_slot
        return ticket

    def deliver(self, ticket: MigrationTicket, dst_engine) -> None:
        """Apply the transport policy: land the payload where the target
        engine's pool lives. Mutates the ticket in place."""
        mode = self.cfg.transport
        if mode == "host":
            self._bounce(ticket)
            return
        dst_dev = self._pool_device(dst_engine)
        src_dev = self._payload_device(ticket)
        if dst_dev is None or src_dev is None or src_dev == dst_dev:
            return                      # shared device: zero-copy handoff
        try:
            ticket.k_payload = jax.device_put(ticket.k_payload, dst_dev)
            ticket.v_payload = jax.device_put(ticket.v_payload, dst_dev)
        except Exception as exc:  # noqa: BLE001 — no D2D path: bounce
            if mode == "device":
                raise MigrationError(
                    f"device-to-device transfer failed and transport is "
                    f"pinned to 'device': {exc!r}") from exc
            self._bounce(ticket)

    def install(self, dst_engine, ticket: MigrationTicket):
        """Install the ticket into the target engine (see
        ``ServingEngine.import_request``); returns the live Request."""
        self.deliver(ticket, dst_engine)
        return dst_engine.import_request(ticket)

    def migrate(self, src_engine, rid: int, dst_engine):
        """Engine-level end-to-end move: export, transfer, install, then
        release the source copy. On an install failure the source
        request keeps running untouched. Fleet handoffs do NOT use this
        directly — they interleave the supervisor-journal move for the
        exactly-once crash contract (serving.fleet)."""
        ticket = self.export_ticket(src_engine, rid)
        req = self.install(dst_engine, ticket)
        src_engine.release_migrated(rid)
        return req

    # --------------------------------------------------------- internals

    @staticmethod
    def _pool_device(engine):
        devs = getattr(engine.cache.k_pages, "devices", None)
        if devs is None:
            return None
        try:
            return next(iter(devs()))
        except Exception:  # noqa: BLE001 — sharded/committed-less array
            return None

    @staticmethod
    def _payload_device(ticket: MigrationTicket):
        devs = getattr(ticket.k_payload, "devices", None)
        if devs is None:
            return None                 # host-resident payload
        try:
            return next(iter(devs()))
        except Exception:  # noqa: BLE001
            return None

    @staticmethod
    def _bounce(ticket: MigrationTicket) -> None:
        if ticket.transport == "host":
            return
        # dla: disable=host-sync-in-hot-loop -- designed migration host bounce: one D2H per migrated request, counted on serving/migration/host_bounce_bytes
        ticket.k_payload = np.asarray(ticket.k_payload)
        ticket.v_payload = np.asarray(ticket.v_payload)
        ticket.transport = "host"
