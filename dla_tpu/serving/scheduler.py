"""Continuous-batching request scheduler: the lifecycle state machine
that decides, each engine step, which waiting requests prefill into
freed decode slots and which in-flight requests must yield pages.

States:  WAITING -> PREFILL -> DECODE -> FINISHED
                        ^         |
                        +-- EVICTED (preempted on page-pool OOM; the
                            request keeps its generated tokens, re-enters
                            the queue head, and RECOMPUTES its whole
                            prefix — prompt + generated-so-far — on
                            re-admission)

Admission policy: FCFS with LONGEST-PREFIX BUCKETING — the queue head
fixes the prefill bucket (prompt width rounded up to a power-of-two page
count), then a bounded lookahead pulls queued requests that pad to the
same bucket into the same prefill batch. One compiled prefill per bucket
width, full FCFS fairness for the head, and the lookahead bound keeps a
stream of short prompts from starving a long one.

Backpressure: admission requires the FULL prompt page count plus one
decode page up front (no admission that would immediately preempt
someone). Mid-decode page exhaustion preempts the YOUNGEST running
request (LIFO eviction — it has the least sunk compute and its
recompute is the cheapest), freeing pages for requests ahead of it.
"""
from __future__ import annotations

import dataclasses
import enum
import itertools
from collections import deque
from typing import Deque, Dict, List, Optional

from dla_tpu.serving.kv_blocks import PagedKVCache


class RequestState(enum.Enum):
    WAITING = "waiting"
    PREFILL = "prefill"
    DECODE = "decode"
    FINISHED = "finished"
    EVICTED = "evicted"
    TIMEOUT = "timeout"      # deadline passed before completion


_rid_counter = itertools.count()


@dataclasses.dataclass
class Request:
    """One generation request moving through the serving engine."""
    prompt_tokens: List[int]
    max_new_tokens: int
    rid: int = dataclasses.field(default_factory=lambda: next(_rid_counter))
    arrival_time: float = 0.0
    state: RequestState = RequestState.WAITING
    generated: List[int] = dataclasses.field(default_factory=list)
    slot: Optional[int] = None
    pages: List[int] = dataclasses.field(default_factory=list)
    evictions: int = 0
    finish_reason: Optional[str] = None   # "eos" | "length" | "timeout"
                                          # | "cancelled"
    deadline: Optional[float] = None      # absolute engine-clock cutoff
    # wall-clock marks for TTFT / queue-wait / inter-token latency metrics
    admitted_time: Optional[float] = None  # first prefill admission
    first_token_time: Optional[float] = None
    last_token_time: Optional[float] = None

    @property
    def prefix_tokens(self) -> List[int]:
        """What a (re-)prefill must run: prompt plus everything already
        generated — the recompute contract of eviction."""
        return self.prompt_tokens + self.generated

    @property
    def remaining_new_tokens(self) -> int:
        return self.max_new_tokens - len(self.generated)


@dataclasses.dataclass(frozen=True)
class SchedulerConfig:
    max_prefill_batch: int = 4     # requests per bucketed prefill call
    lookahead: int = 16            # queue scan depth for bucket-mates
    decode_reserve_pages: int = 1  # pages beyond the prompt required to admit


class Scheduler:
    """Pure host-side state machine over a PagedKVCache's allocator and
    slots. The engine loop calls, per step:

      1. ``release(req)``      for finished requests (slots/pages back)
      2. ``ensure_decode_pages()``  grow running requests' block tables,
                                    preempting on OOM
      3. ``next_prefill_batch()``   FCFS+bucketed admission into free
                                    slots
    """

    def __init__(self, cache: PagedKVCache, cfg: SchedulerConfig,
                 bucket_widths: List[int]):
        self.cache = cache
        self.cfg = cfg
        # ascending padded prompt widths (multiples of page_size); a
        # prompt buckets to the smallest width that holds it
        self.bucket_widths = sorted(bucket_widths)
        self.queue: Deque[Request] = deque()
        self.running: Dict[int, Request] = {}   # slot -> request
        self.free_slots: List[int] = list(
            range(cache.geom.num_slots - 1, -1, -1))
        self.preemptions = 0

    # ------------------------------------------------------------- intake

    def submit(self, req: Request) -> None:
        geom = self.cache.geom
        need = len(req.prompt_tokens) + req.max_new_tokens
        if need > geom.slot_window:
            raise ValueError(
                f"request {req.rid}: prompt+max_new ({need}) exceeds the "
                f"slot window ({geom.slot_window} = {geom.pages_per_slot} "
                f"pages x {geom.page_size})")
        if not req.prompt_tokens:
            raise ValueError(f"request {req.rid}: empty prompt")
        req.state = RequestState.WAITING
        self.queue.append(req)

    def bucket_width(self, prefix_len: int) -> int:
        for w in self.bucket_widths:
            if prefix_len <= w:
                return w
        raise ValueError(
            f"prefix length {prefix_len} exceeds the largest prefill "
            f"bucket {self.bucket_widths[-1]}")

    # ---------------------------------------------------------- admission

    def next_prefill_batch(self) -> List[Request]:
        """FCFS + longest-prefix bucketing: the queue head fixes the
        bucket; a bounded lookahead fills the batch with same-bucket
        requests. Each admitted request gets a slot plus ALL its prompt
        pages and the decode reserve — all-or-nothing, so a half-admitted
        batch can't deadlock the pool. Admitted requests move to PREFILL
        with pages+slot bound; the engine runs the actual forward."""
        batch: List[Request] = []
        if not self.queue or not self.free_slots:
            return batch
        head = self.queue[0]
        width = self.bucket_width(len(head.prefix_tokens))
        geom = self.cache.geom
        limit = min(self.cfg.max_prefill_batch, len(self.free_slots))
        scanned = 0
        picked_ids = set()
        for req in list(self.queue):
            if len(batch) >= limit:
                break
            if scanned >= self.cfg.lookahead and batch:
                break
            scanned += 1
            if self.bucket_width(len(req.prefix_tokens)) != width:
                # bucketing never skips AHEAD of the head: only requests
                # behind it may ride along, so FCFS holds for the head
                continue
            # cap at the block table's width: a max-width prompt whose
            # reserve would overflow the table just starts reserve-less
            n_pages = min(geom.pages_for(width)
                          + self.cfg.decode_reserve_pages,
                          geom.pages_per_slot)
            pages = self.cache.allocator.alloc(n_pages)
            if pages is None:
                break  # backpressure: pool can't take another prefill
            req.pages = pages
            req.slot = self.free_slots.pop()
            req.state = RequestState.PREFILL
            picked_ids.add(req.rid)
            batch.append(req)
        if picked_ids:
            self.queue = deque(
                r for r in self.queue if r.rid not in picked_ids)
        return batch

    def activate(self, req: Request) -> None:
        """PREFILL -> DECODE once the engine has run the prefill forward
        and opened the slot."""
        req.state = RequestState.DECODE
        self.running[req.slot] = req

    # --------------------------------------------------- page-pool safety

    def ensure_decode_pages(self) -> List[Request]:
        """Before a decode step: every running request whose next write
        column crosses into an unallocated page gets one. On exhaustion,
        preempt the youngest running request (free its slot AND pages)
        and retry; the preempted requests are returned (already re-queued
        at the head, FIFO among themselves)."""
        evicted: List[Request] = []
        for slot in sorted(self.running):
            req = self.running.get(slot)
            if req is None:
                continue   # evicted while growing an earlier slot
            while self._needs_page(req):
                page = self.cache.allocator.alloc(1)
                if page is not None:
                    # table entry i holds req.pages[i]; the new page
                    # lands at the next free entry
                    req.pages.extend(page)
                    self.cache.block_tables[
                        slot, len(req.pages) - 1] = page[0]
                    continue
                victim = self._youngest_running(exclude_rid=None)
                if victim is None or victim.rid == req.rid:
                    # nothing left to evict but this request itself:
                    # evict it (its own pages may unblock older ones)
                    victim = req
                self.evict(victim)
                evicted.append(victim)
                if victim.rid == req.rid:
                    break  # this request is gone; stop growing it
        return evicted

    def _needs_page(self, req: Request) -> bool:
        geom = self.cache.geom
        next_col = int(self.cache.lengths[req.slot])
        return next_col // geom.page_size >= len(req.pages)

    def _youngest_running(self, exclude_rid=None) -> Optional[Request]:
        cands = [r for r in self.running.values()
                 if r.rid != exclude_rid]
        if not cands:
            return None
        return max(cands, key=lambda r: r.rid)

    def evict(self, req: Request) -> None:
        """Preempt: free slot + pages, keep generated tokens, requeue at
        the FRONT (it was admitted before everything still waiting)."""
        self.preemptions += 1
        req.evictions += 1
        self._release_resources(req)
        req.state = RequestState.EVICTED
        self.queue.appendleft(req)
        req.state = RequestState.WAITING

    def finish(self, req: Request, reason: str) -> None:
        req.finish_reason = reason
        self._release_resources(req)
        req.state = RequestState.FINISHED

    def cancel(self, req: Request, reason: str,
               state: RequestState = RequestState.FINISHED) -> None:
        """Terminal removal from wherever the request currently lives —
        the queue (waiting/evicted) or a decode slot. Generated-so-far
        tokens stay on the request; resources go back to the pool. Used
        for deadline expiry (state=TIMEOUT) and drain cancellation."""
        self.queue = deque(r for r in self.queue if r.rid != req.rid)
        self._release_resources(req)
        req.finish_reason = reason
        req.state = state

    def expired(self, now: float) -> List[Request]:
        """Every queued or running request whose deadline has passed."""
        out = [r for r in self.queue
               if r.deadline is not None and now >= r.deadline]
        out += [r for r in self.running.values()
                if r.deadline is not None and now >= r.deadline]
        return out

    def _release_resources(self, req: Request) -> None:
        if req.slot is not None:
            self.running.pop(req.slot, None)
            self.cache.close_slot(req.slot)
            self.free_slots.append(req.slot)
            req.slot = None
        if req.pages:
            self.cache.allocator.free(req.pages)
            req.pages = []

    # ------------------------------------------------------------- status

    @property
    def queue_depth(self) -> int:
        return len(self.queue)

    @property
    def active_count(self) -> int:
        return len(self.running)

    def assert_consistent(self) -> None:
        """Slot/page accounting invariants (tests call this every step):
        no slot leaks, no page leaks, no slot double-booked."""
        geom = self.cache.geom
        assert len(self.free_slots) + len(self.running) == geom.num_slots, (
            f"slot leak: {len(self.free_slots)} free + "
            f"{len(self.running)} running != {geom.num_slots}")
        assert len(set(self.free_slots)) == len(self.free_slots)
        assert not (set(self.free_slots) & set(self.running))
        held = sum(len(r.pages) for r in self.running.values())
        assert held == self.cache.allocator.used_count, (
            f"page leak: running hold {held}, allocator says "
            f"{self.cache.allocator.used_count}")
