"""Continuous-batching request scheduler: the lifecycle state machine
that decides, each engine step, which waiting requests prefill into
freed decode slots and which in-flight requests must yield pages.

States:  WAITING -> PREFILL -> DECODE -> FINISHED
                        ^         |
                        +-- EVICTED (preempted on page-pool OOM; the
                            request keeps its generated tokens, re-enters
                            the queue head, and RECOMPUTES its prefix —
                            prompt + generated-so-far — on re-admission;
                            with the prefix cache on, the recompute
                            restarts from the longest still-cached
                            chunk-aligned prefix, not from token 0)

Admission policy (monolithic prefill): FCFS with LONGEST-PREFIX
BUCKETING — the queue head fixes the prefill bucket (prompt width
rounded up to a power-of-two page count), then a bounded lookahead pulls
queued requests that pad to the same bucket into the same prefill batch.
One compiled prefill per bucket width, full FCFS fairness for the head,
and the lookahead bound keeps a stream of short prompts from starving a
long one.

Admission policy (chunked prefill, ``prefill_chunk > 0``): strict FCFS,
one request prefilling at a time. The head takes a slot plus every page
its prompt needs up front — aliasing already-cached prefix pages via the
:class:`~dla_tpu.serving.kv_blocks.PrefixCache` (incref, no copy) and
allocating only the rest — then the engine advances it one fixed-shape
chunk per engine step, co-scheduled with the running decode batch under
``prefill_token_budget``.

Backpressure: admission requires the FULL prompt page count plus one
decode page up front (no admission that would immediately preempt
someone). Mid-decode page exhaustion preempts the YOUNGEST running
request (LIFO eviction — it has the least sunk compute and its
recompute is the cheapest), freeing pages for requests ahead of it.
Eviction is refcount-aware: a victim's shared pages just drop one
reference, so pages another request (or the cache) still needs are
never actually freed.
"""
from __future__ import annotations

import dataclasses
import enum
import itertools
from collections import deque
from typing import Deque, Dict, List, Optional

from dla_tpu.serving.kv_blocks import PagedKVCache, PrefixCache


class RequestState(enum.Enum):
    WAITING = "waiting"
    PREFILL = "prefill"
    DECODE = "decode"
    FINISHED = "finished"
    EVICTED = "evicted"
    TIMEOUT = "timeout"      # deadline passed before completion
    SHED = "shed"            # dropped by admission control / load shed


#: states a request never leaves — the "every request terminates"
#: contract the resilience layer (and its chaos tests) assert on
TERMINAL_STATES = (RequestState.FINISHED, RequestState.TIMEOUT,
                   RequestState.SHED)


_rid_counter = itertools.count()


@dataclasses.dataclass
class Request:
    """One generation request moving through the serving engine."""
    prompt_tokens: List[int]
    max_new_tokens: int
    rid: int = dataclasses.field(default_factory=lambda: next(_rid_counter))
    arrival_time: float = 0.0
    state: RequestState = RequestState.WAITING
    generated: List[int] = dataclasses.field(default_factory=list)
    slot: Optional[int] = None
    pages: List[int] = dataclasses.field(default_factory=list)
    evictions: int = 0
    finish_reason: Optional[str] = None   # "eos" | "length" | "timeout"
                                          # | "cancelled" | "shed"
    deadline: Optional[float] = None      # absolute engine-clock cutoff
    # load-shed ranking: HIGHER outranks lower; shedding drops the
    # lowest-priority queued request first (FIFO-tail among equals)
    priority: int = 0
    # chunked prefill progress: prefix tokens already in the cache pool
    # (shared hit pages + chunks computed so far)
    prefill_pos: int = 0
    # exact-full-prompt cache hit: the stored last-token prefill logits
    # (numpy [V]); decoding starts from these with no prefill at all
    cached_logits: Optional[object] = None
    # per-request sampling override (ops.sampling.SamplingParams); None
    # means the engine-global GenerationConfig with a seed derived from
    # (engine seed, rid)
    sampling: Optional[object] = None
    # multi-tenant serving: the tenant whose LoRA adapter (and quota /
    # SLO accounting) this request runs under; None = base model
    tenant: Optional[str] = None
    # chosen-token logprobs under the raw model distribution, parallel
    # to `generated` — the per-request logprob surface (rollout behavior
    # logps, eval/debugging)
    generated_logprobs: List[float] = dataclasses.field(default_factory=list)
    # wall-clock marks for TTFT / queue-wait / inter-token latency metrics
    admitted_time: Optional[float] = None  # first prefill admission
    first_token_time: Optional[float] = None
    last_token_time: Optional[float] = None

    @property
    def prefix_tokens(self) -> List[int]:
        """What a (re-)prefill must run: prompt plus everything already
        generated — the recompute contract of eviction."""
        return self.prompt_tokens + self.generated

    @property
    def remaining_new_tokens(self) -> int:
        return self.max_new_tokens - len(self.generated)


@dataclasses.dataclass(frozen=True)
class SchedulerConfig:
    max_prefill_batch: int = 4     # requests per bucketed prefill call
    lookahead: int = 16            # queue scan depth for bucket-mates
    decode_reserve_pages: int = 1  # pages beyond the prompt required to admit
    prefill_chunk: int = 0         # chunk width in tokens; 0 = monolithic
    prefill_token_budget: int = 0  # per-engine-step token cap; 0 = none


class Scheduler:
    """Pure host-side state machine over a PagedKVCache's allocator and
    slots. The engine loop calls, per step:

      1. ``release(req)``      for finished requests (slots/pages back)
      2. ``ensure_decode_pages()``  grow running requests' block tables,
                                    copy-on-write shared write targets,
                                    preempting on OOM
      3. ``next_prefill_batch()`` / ``admit_chunk_prefill()``
                                    admission into free slots
    """

    def __init__(self, cache: PagedKVCache, cfg: SchedulerConfig,
                 bucket_widths: List[int],
                 prefix_cache: Optional[PrefixCache] = None):
        self.cache = cache
        self.cfg = cfg
        self.prefix_cache = prefix_cache
        # ascending padded prompt widths (multiples of page_size); a
        # prompt buckets to the smallest width that holds it
        self.bucket_widths = sorted(bucket_widths)
        self.queue: Deque[Request] = deque()
        self.running: Dict[int, Request] = {}    # slot -> request
        self.prefilling: Dict[int, Request] = {} # slot -> mid-chunk req
        self.free_slots: List[int] = list(
            range(cache.geom.num_slots - 1, -1, -1))
        self.preemptions = 0
        # degradation-ladder batch shrink: admission stops once this many
        # requests hold slots (None = every slot usable). Purely an
        # admission cap — shapes stay static, running requests finish.
        self.max_active: Optional[int] = None
        # called with the request on every slot release (finish, evict,
        # cancel) — the engine pairs it with its per-slot-bind adapter
        # acquire so AdapterStore refcounts track slot residency exactly
        self.release_hook = None

    def _admission_headroom(self) -> Optional[int]:
        """Slots admission may still fill under ``max_active``; None
        means unlimited."""
        if self.max_active is None:
            return None
        held = len(self.running) + len(self.prefilling)
        return max(0, self.max_active - held)

    # ------------------------------------------------------------- intake

    def submit(self, req: Request) -> None:
        geom = self.cache.geom
        need = len(req.prompt_tokens) + req.max_new_tokens
        if need > geom.slot_window:
            raise ValueError(
                f"request {req.rid}: prompt+max_new ({need}) exceeds the "
                f"slot window ({geom.slot_window} = {geom.pages_per_slot} "
                f"pages x {geom.page_size})")
        if not req.prompt_tokens:
            raise ValueError(f"request {req.rid}: empty prompt")
        req.state = RequestState.WAITING
        self.queue.append(req)

    def bucket_width(self, prefix_len: int) -> int:
        for w in self.bucket_widths:
            if prefix_len <= w:
                return w
        raise ValueError(
            f"prefix length {prefix_len} exceeds the largest prefill "
            f"bucket {self.bucket_widths[-1]}")

    # ----------------------------------------- admission (monolithic)

    def next_prefill_batch(self) -> List[Request]:
        """FCFS + longest-prefix bucketing: the queue head fixes the
        bucket; a bounded lookahead fills the batch with same-bucket
        requests. Each admitted request gets a slot plus ALL its prompt
        pages and the decode reserve — all-or-nothing, so a half-admitted
        batch can't deadlock the pool. Admitted requests move to PREFILL
        with pages+slot bound; the engine runs the actual forward."""
        batch: List[Request] = []
        if not self.queue or not self.free_slots:
            return batch
        head = self.queue[0]
        width = self.bucket_width(len(head.prefix_tokens))
        geom = self.cache.geom
        limit = min(self.cfg.max_prefill_batch, len(self.free_slots))
        headroom = self._admission_headroom()
        if headroom is not None:
            limit = min(limit, headroom)
        if limit <= 0:
            return batch
        scanned = 0
        picked_ids = set()
        for req in list(self.queue):
            if len(batch) >= limit:
                break
            if scanned >= self.cfg.lookahead and batch:
                break
            scanned += 1
            if self.bucket_width(len(req.prefix_tokens)) != width:
                # bucketing never skips AHEAD of the head: only requests
                # behind it may ride along, so FCFS holds for the head
                continue
            # cap at the block table's width: a max-width prompt whose
            # reserve would overflow the table just starts reserve-less
            n_pages = min(geom.pages_for(width)
                          + self.cfg.decode_reserve_pages,
                          geom.pages_per_slot)
            pages = self.cache.allocator.alloc(n_pages)
            if pages is None:
                break  # backpressure: pool can't take another prefill
            req.pages = pages
            req.slot = self.free_slots.pop()
            req.state = RequestState.PREFILL
            picked_ids.add(req.rid)
            batch.append(req)
        if picked_ids:
            self.queue = deque(
                r for r in self.queue if r.rid not in picked_ids)
        return batch

    # -------------------------------------------- admission (chunked)

    def admit_chunk_prefill(self) -> Optional[Request]:
        """Strict-FCFS chunked admission: at most one request is
        mid-prefill at a time (its chunks run one per engine step). The
        head gets a slot plus its FULL page demand up front — cached
        prefix pages alias (incref, no copy, no recompute), only the
        uncovered suffix and the decode reserve allocate fresh.

        Returns the admitted request, or None (queue empty, no slot, a
        request already prefilling, or the pool can't cover the fresh
        pages — hit pages are released again on that backpressure path).
        A returned request with ``prefill_pos == len(prefix_tokens)``
        was an exact-full-prompt hit: ``cached_logits`` is set, no
        prefill runs, and the engine activates it directly."""
        if not self.queue or not self.free_slots or self.prefilling:
            return None
        if self._admission_headroom() == 0:
            return None
        req = self.queue[0]
        geom = self.cache.geom
        prefix = req.prefix_tokens
        n = len(prefix)
        hit_pages: List[int] = []
        hit = 0
        logits = None
        if self.prefix_cache is not None:
            # namespaced by tenant: one tenant's cached KV never serves
            # another's lookups (adapters change the KV contents)
            hit_pages, hit, logits = self.prefix_cache.lookup(
                prefix, self.cfg.prefill_chunk, namespace=req.tenant)
        total = min(geom.pages_for(n) + self.cfg.decode_reserve_pages,
                    geom.pages_per_slot)
        fresh = self.cache.allocator.alloc(total - len(hit_pages))
        if fresh is None:
            # backpressure: give the hit references back and wait
            for p in hit_pages:
                self.cache.allocator.decref(p)
            return None
        self.queue.popleft()
        req.pages = hit_pages + fresh        # block-table order
        req.slot = self.free_slots.pop()
        req.state = RequestState.PREFILL
        req.prefill_pos = hit
        req.cached_logits = logits
        self.cache.open_slot_prefill(req.slot, req.pages, hit)
        if hit < n:
            self.prefilling[req.slot] = req
        return req

    def activate(self, req: Request) -> None:
        """PREFILL -> DECODE once the engine has run the prefill forward
        (all chunks, for chunked prefill) and opened the slot."""
        req.state = RequestState.DECODE
        self.prefilling.pop(req.slot, None)
        self.running[req.slot] = req

    def adopt(self, req: Request, pages: List[int]) -> int:
        """Bind a request whose committed KV already sits in the pool
        straight into a DECODE slot — the KV-import and restore-from-
        cache entry point (no queue, no prefill). The caller owns one
        reference per page in ``pages`` (freshly allocated, or increfed
        cache aliases) and sets up the slot's cache metadata itself;
        from here the request is indistinguishable from one that
        prefilled locally. Raises when the request cannot fit the slot
        window or no slot is free — the caller unwinds its references."""
        geom = self.cache.geom
        need = len(req.prompt_tokens) + req.max_new_tokens
        if need > geom.slot_window:
            raise ValueError(
                f"request {req.rid}: prompt+max_new ({need}) exceeds the "
                f"slot window ({geom.slot_window})")
        if not self.free_slots:
            raise RuntimeError(
                f"request {req.rid}: no free slot to adopt into")
        req.pages = list(pages)
        req.slot = self.free_slots.pop()
        req.state = RequestState.DECODE
        self.running[req.slot] = req
        return req.slot

    # --------------------------------------------------- page-pool safety

    def ensure_decode_pages(self, span: int = 1) -> List[Request]:
        """Before a decode step: every running request whose next write
        column crosses into an unallocated page gets one, and a next
        write landing on a SHARED or cache-indexed page is copy-on-
        written to a private one first (the shared original stays
        pristine for its other readers). On exhaustion, preempt the
        youngest running request (drop its slot AND its page references)
        and retry; the preempted requests are returned (already
        re-queued at the head, FIFO among themselves).

        ``span`` is the number of columns the coming step may COMMIT per
        slot (K+1 for a speculative round, 1 otherwise): headroom and
        COW cover the whole write range ``[lengths, lengths+need)`` where
        ``need = min(span, remaining_new_tokens)`` — a request near its
        token budget never reserves pages it cannot fill. Speculative
        scatters beyond the allocated range hit the trash page by the
        block-table-zero convention and are rolled back for free (their
        columns are never marked valid)."""
        evicted: List[Request] = []
        for slot in sorted(self.running):
            req = self.running.get(slot)
            if req is None:
                continue   # evicted while growing an earlier slot
            while True:
                if self._needs_page(req, span):
                    page = self.cache.allocator.alloc(1)
                    if page is not None:
                        # table entry i holds req.pages[i]; the new page
                        # lands at the next free entry
                        req.pages.extend(page)
                        self.cache.block_tables[
                            slot, len(req.pages) - 1] = page[0]
                        continue
                elif self._ensure_writable(req, span):
                    break
                victim = self._youngest_running(exclude_rid=None)
                if victim is None or victim.rid == req.rid:
                    # nothing left to evict but this request itself:
                    # evict it (its own pages may unblock older ones)
                    victim = req
                self.evict(victim)
                evicted.append(victim)
                if victim.rid == req.rid:
                    break  # this request is gone; stop growing it
        return evicted

    def _write_need(self, req: Request, span: int) -> int:
        """Columns the next step may commit for this request: the span,
        clamped to its remaining token budget (always >= 1 — a running
        request has at least one token left to emit)."""
        return max(1, min(int(span), req.remaining_new_tokens))

    def _needs_page(self, req: Request, span: int = 1) -> bool:
        geom = self.cache.geom
        next_col = int(self.cache.lengths[req.slot])
        last_col = next_col + self._write_need(req, span) - 1
        return last_col // geom.page_size >= len(req.pages)

    def _ensure_writable(self, req: Request, span: int = 1) -> bool:
        """Copy-on-write guard: every page under this request's write
        range (``span`` columns from the next decode write) must be
        exclusively owned and unindexed, or the writes would corrupt
        pages other readers / the prefix cache still rely on. Returns
        False only when a COW copy can't get a destination page (caller
        preempts and retries)."""
        if self.prefix_cache is None:
            return True
        geom = self.cache.geom
        next_col = int(self.cache.lengths[req.slot])
        last_col = next_col + self._write_need(req, span) - 1
        alloc = self.cache.allocator
        for idx in range(next_col // geom.page_size,
                         last_col // geom.page_size + 1):
            if idx >= len(req.pages):
                break      # beyond allocation: trash-page writes only
            page = int(self.cache.block_tables[req.slot, idx])
            if page == 0:
                continue
            if alloc.refcount(page) <= 1 and \
                    not self.prefix_cache.is_indexed(page):
                continue
            fresh = alloc.alloc(1)
            if fresh is None:
                return False
            self.cache.cow_page(req.slot, idx, fresh[0])
            req.pages[idx] = fresh[0]
            alloc.decref(page)
        return True

    def _youngest_running(self, exclude_rid=None) -> Optional[Request]:
        cands = [r for r in self.running.values()
                 if r.rid != exclude_rid]
        if not cands:
            return None
        return max(cands, key=lambda r: r.rid)

    def evict(self, req: Request) -> None:
        """Preempt: free slot, DROP this request's page references
        (shared pages survive for their other holders — refcounting is
        what makes eviction safe under prefix sharing), keep generated
        tokens, requeue at the FRONT (it was admitted before everything
        still waiting)."""
        self.preemptions += 1
        req.evictions += 1
        self._release_resources(req)
        req.state = RequestState.EVICTED
        self.queue.appendleft(req)
        req.state = RequestState.WAITING

    def finish(self, req: Request, reason: str) -> None:
        req.finish_reason = reason
        self._release_resources(req)
        req.state = RequestState.FINISHED

    def cancel(self, req: Request, reason: str,
               state: RequestState = RequestState.FINISHED) -> None:
        """Terminal removal from wherever the request currently lives —
        the queue (waiting/evicted), a decode slot, or mid-chunked-
        prefill. Generated-so-far tokens stay on the request; resources
        go back to the pool. Used for deadline expiry (state=TIMEOUT)
        and drain cancellation."""
        self.queue = deque(r for r in self.queue if r.rid != req.rid)
        self._release_resources(req)
        req.finish_reason = reason
        req.state = state

    def expired(self, now: float) -> List[Request]:
        """Every queued, prefilling, or running request whose deadline
        has passed."""
        out = [r for r in self.queue
               if r.deadline is not None and now >= r.deadline]
        out += [r for r in self.running.values()
                if r.deadline is not None and now >= r.deadline]
        out += [r for r in self.prefilling.values()
                if r.deadline is not None and now >= r.deadline]
        return out

    def sheddable_queued(self) -> List[Request]:
        """Queued requests load shedding may drop, worst-first: lowest
        priority, then latest arrival (least sunk wait) among equals.
        Evicted in-flight requests are exempt — they hold generated
        tokens and sunk compute, and shedding them would break the
        streaming contract mid-request."""
        cands = [r for r in self.queue if not r.generated]
        cands.sort(key=lambda r: (r.priority, -r.arrival_time, -r.rid))
        return cands

    def _release_resources(self, req: Request) -> None:
        if req.slot is not None:
            if self.release_hook is not None:
                self.release_hook(req)
            self.running.pop(req.slot, None)
            self.prefilling.pop(req.slot, None)
            self.cache.close_slot(req.slot)
            self.free_slots.append(req.slot)
            req.slot = None
        if req.pages:
            # one decref per held reference: uniquely-owned pages free
            # (or park on the cache's LRU), shared pages merely lose
            # this holder
            self.cache.allocator.free(req.pages)
            req.pages = []
        req.prefill_pos = 0
        req.cached_logits = None

    # ------------------------------------------------------------- status

    @property
    def queue_depth(self) -> int:
        return len(self.queue)

    @property
    def active_count(self) -> int:
        return len(self.running)

    def assert_consistent(self) -> None:
        """Slot/page accounting invariants (tests call this every step):
        no slot leaks, no page leaks, no slot double-booked, and — under
        prefix sharing — reference counts exactly equal to the number of
        block tables holding each page."""
        from collections import Counter
        geom = self.cache.geom
        holders = list(self.running.values()) + \
            list(self.prefilling.values())
        assert len(self.free_slots) + len(holders) == geom.num_slots, (
            f"slot leak: {len(self.free_slots)} free + "
            f"{len(holders)} held != {geom.num_slots}")
        assert len(set(self.free_slots)) == len(self.free_slots)
        booked = set(self.running) | set(self.prefilling)
        assert not (set(self.free_slots) & booked)
        assert not (set(self.running) & set(self.prefilling))
        held = Counter(p for r in holders for p in r.pages)
        refs = self.cache.allocator.refcounts
        assert held == Counter(refs), (
            f"page refcount drift: requests hold {dict(held)}, "
            f"allocator says {refs}")
        alloc = self.cache.allocator
        assert alloc.used_count + alloc.free_count + \
            alloc.cached_count == alloc.capacity, (
            f"page state leak: {alloc.used_count} used + "
            f"{alloc.free_count} free + {alloc.cached_count} cached "
            f"!= {alloc.capacity}")
        assert 0 not in refs and 0 not in alloc.cached_pages, (
            "trash page entered the allocator")
