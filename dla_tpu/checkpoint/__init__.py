from dla_tpu.checkpoint.checkpointer import (
    Checkpointer,
    is_checkpoint_path,
    load_tree_numpy,
    resolve_checkpoint_dir,
)

__all__ = [
    "Checkpointer",
    "is_checkpoint_path",
    "load_tree_numpy",
    "resolve_checkpoint_dir",
]
