"""Sharded checkpoint save/restore.

The reference only *saves* (accelerator.save_state, utils.py:99-102); no
trainer can resume, no `latest` pointer is ever written, and keep-last-N is
documented but unimplemented (SURVEY.md sec 5). Here all three are
first-class:

- step-tagged directories ``step_000123/`` + a ``latest`` pointer file
- atomic writes (tmp dir + rename)
- keep-last-N retention
- restore onto an arbitrary mesh/sharding (cross-topology reshard: leaves
  are stored as whole logical arrays; ``jax.make_array_from_callback``
  reads just the slice each device needs via np.load mmap)
- multi-host: partially-addressable leaves are allgathered across hosts
  and process 0 writes whole logical arrays. This is simple and correct
  but serializes I/O through host 0 and materializes full arrays in host
  RAM — per-host shard files (no gather) are planned once the multi-host
  path is exercised on real pods.

Format: one ``.npy`` per pytree leaf (path-encoded filename) + an
``index.json`` with tree structure, dtypes, shapes, and auxiliary
JSON-serializable state (step, data-iterator position, RNG key data).
"""
from __future__ import annotations

import json
import os
import re
import shutil
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

SEP = "."


def _as_logical(arr: np.ndarray, dtype_str: str) -> np.ndarray:
    """Undo npy's void-encoding of non-native dtypes (bfloat16 etc. save as
    |V2); view back to the logical dtype recorded in the index."""
    if arr.dtype.kind == "V":
        import ml_dtypes  # ships with jax; registers bfloat16/fp8 dtypes
        return arr.view(np.dtype(dtype_str))
    return arr


def _flatten_with_paths(tree: Any) -> List[Tuple[str, Any]]:
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = []
    for path, leaf in flat:
        keys = []
        for p in path:
            if hasattr(p, "key"):
                keys.append(str(p.key))
            elif hasattr(p, "idx"):
                keys.append(str(p.idx))
            else:
                keys.append(str(p))
        out.append((SEP.join(keys), leaf))
    return out


def _leaf_filename(path: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.\-]", "_", path) + ".npy"


class Checkpointer:
    def __init__(self, output_dir: str, keep_last_n: int = 3):
        self.dir = Path(output_dir)
        self.keep_last_n = keep_last_n
        self.is_main = jax.process_index() == 0

    # ----------------------------------------------------------------- save

    def save(self, step: int, tree: Any, aux: Optional[Dict[str, Any]] = None,
             tag: Optional[str] = None) -> Path:
        tag = tag or f"step_{step:08d}"
        final = self.dir / tag
        tmp = self.dir / f".tmp_{tag}_{jax.process_index()}"
        if self.is_main:
            tmp.mkdir(parents=True, exist_ok=True)

        leaves = _flatten_with_paths(tree)
        index = {"format": 1, "step": int(step), "aux": aux or {},
                 "leaves": {}}
        for path, leaf in leaves:
            if leaf is None:
                continue
            # All hosts participate (partially-addressable arrays gather via
            # a collective); only process 0 writes.
            np_arr = self._to_numpy(leaf)
            index["leaves"][path] = {
                "file": _leaf_filename(path),
                "shape": list(np_arr.shape),
                "dtype": str(np_arr.dtype),
            }
            if self.is_main:
                np.save(tmp / _leaf_filename(path), np_arr)
        if self.is_main:
            with (tmp / "index.json").open("w") as fh:
                json.dump(index, fh)
            if final.exists():
                shutil.rmtree(final)
            tmp.rename(final)
            self._write_latest(tag)
            self._retain()
        return final

    @staticmethod
    def _to_numpy(arr: Any) -> np.ndarray:
        if isinstance(arr, (np.ndarray, np.generic, int, float)):
            return np.asarray(arr)
        if hasattr(arr, "is_fully_addressable") and not arr.is_fully_addressable:
            from jax.experimental import multihost_utils
            arr = multihost_utils.process_allgather(arr)
        if hasattr(arr, "dtype") and jax.dtypes.issubdtype(
                arr.dtype, jax.dtypes.prng_key):
            arr = jax.random.key_data(arr)
        return np.asarray(arr)

    def _write_latest(self, tag: str) -> None:
        with (self.dir / "latest").open("w") as fh:
            fh.write(tag)

    def _retain(self) -> None:
        if self.keep_last_n <= 0:
            return
        steps = sorted(
            (d for d in self.dir.glob("step_*") if d.is_dir()),
            key=lambda d: d.name)
        for old in steps[: max(0, len(steps) - self.keep_last_n)]:
            shutil.rmtree(old, ignore_errors=True)

    # -------------------------------------------------------------- restore

    def latest_tag(self) -> Optional[str]:
        latest = self.dir / "latest"
        if latest.is_file():
            tag = latest.read_text().strip()
            if (self.dir / tag).is_dir():
                return tag
        return self.newest_step_tag()

    def newest_step_tag(self) -> Optional[str]:
        steps = sorted(d.name for d in self.dir.glob("step_*") if d.is_dir())
        return steps[-1] if steps else None

    def restore(self, template: Any, tag: Optional[str] = None,
                shardings: Optional[Any] = None
                ) -> Tuple[Any, Dict[str, Any]]:
        """Restore a pytree like ``template``; place leaves per ``shardings``
        (a matching pytree of jax.sharding.Sharding) or on the default
        device. Returns (tree, aux)."""
        tag = tag or self.latest_tag()
        if tag is None:
            raise FileNotFoundError(f"No checkpoint under {self.dir}")
        ckpt = resolve_checkpoint_dir(self.dir / tag)
        with (ckpt / "index.json").open() as fh:
            index = json.load(fh)

        leaves_t = _flatten_with_paths(template)
        shard_leaves = (_flatten_with_paths(shardings)[0:] if shardings is not None
                        else None)
        shard_by_path = dict(shard_leaves) if shard_leaves else {}
        restored: Dict[str, Any] = {}
        for path, tmpl_leaf in leaves_t:
            meta = index["leaves"].get(path)
            if meta is None:
                raise KeyError(f"Checkpoint {ckpt} missing leaf '{path}'")
            fname = ckpt / meta["file"]
            arr = _as_logical(np.load(fname, mmap_mode="r"), meta["dtype"])
            is_key = hasattr(tmpl_leaf, "dtype") and jax.dtypes.issubdtype(
                getattr(tmpl_leaf, "dtype", None), jax.dtypes.prng_key)
            sharding = shard_by_path.get(path)
            if sharding is not None and not is_key:
                out = jax.make_array_from_callback(
                    tuple(meta["shape"]), sharding,
                    lambda idx, a=arr: np.asarray(a[idx]))
            else:
                out = jax.device_put(np.asarray(arr))
                if is_key:
                    out = jax.random.wrap_key_data(out)
            restored[path] = out

        tree = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(template),
            [restored[p] for p, _ in leaves_t])
        return tree, index.get("aux", {})


def load_tree_numpy(ckpt_dir, prefix: Optional[str] = None
                    ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Load a checkpoint's leaves as host numpy arrays, rebuilt into nested
    dicts from their path-encoded names. Used for model loading, where the
    caller shards the result onto its own mesh afterwards. Returns
    (tree, aux)."""
    ckpt = resolve_checkpoint_dir(ckpt_dir)
    with (ckpt / "index.json").open() as fh:
        index = json.load(fh)
    tree: Dict[str, Any] = {}
    for path, meta in index["leaves"].items():
        if prefix is not None:
            if not path.startswith(prefix + SEP):
                continue
            rel = path[len(prefix) + 1:]
        else:
            rel = path
        node = tree
        keys = rel.split(SEP)
        for k in keys[:-1]:
            node = node.setdefault(k, {})
        node[keys[-1]] = _as_logical(
            np.load(ckpt / meta["file"]), meta["dtype"])
    return tree, index.get("aux", {})


def resolve_checkpoint_dir(path) -> Path:
    """Follow a ``latest`` pointer if ``path`` is a checkpoint root or ends
    in /latest (the reference configs point at ``checkpoints/X/latest``,
    e.g. dpo_config.yaml:6-7)."""
    p = Path(path)
    if p.name == "latest":
        root = p.parent
        ck = Checkpointer(str(root))
        tag = ck.latest_tag()
        if tag is None:
            raise FileNotFoundError(f"No checkpoint under {root}")
        return root / tag
    if (p / "index.json").is_file():
        return p
    ck = Checkpointer(str(p))
    tag = ck.latest_tag()
    if tag:
        return p / tag
    raise FileNotFoundError(f"No checkpoint at {p}")


def is_checkpoint_path(path) -> bool:
    try:
        resolve_checkpoint_dir(path)
        return True
    except (FileNotFoundError, NotADirectoryError):
        return False
