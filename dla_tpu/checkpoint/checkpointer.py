"""Sharded checkpoint save/restore.

The reference only *saves* (accelerator.save_state, utils.py:99-102); no
trainer can resume, no `latest` pointer is ever written, and keep-last-N is
documented but unimplemented (SURVEY.md sec 5). Here all three are
first-class:

- step-tagged directories ``step_000123/`` + a ``latest`` pointer file
- atomic writes (tmp dir + rename)
- keep-last-N retention
- restore onto an arbitrary mesh/sharding (cross-topology reshard:
  ``jax.make_array_from_callback`` reads just the slice each device
  needs, assembled from shard files via np.load mmap)
- **per-host shard I/O**: sharded leaves are written one file per
  distinct index region, each host writing only the regions it owns
  (``replica_id == 0`` rule). Nothing is gathered through host 0 and no
  host ever materializes a full logical array — a 70B param+opt-state
  tree streams out as ~per-device-sized files in parallel across hosts.
  Replicated/small leaves are written whole by process 0. Multi-host
  save assumes the checkpoint dir is on a filesystem all hosts share
  (GCS/NFS — the standard pod setup).

Format (index.json): ``format: 2``. Whole leaves carry
``{file, shape, dtype}``; sharded leaves carry
``{shape, dtype, shards: [{file, index: [[start, stop], ...]}]}``.
Format-1 checkpoints (whole-file only) load unchanged.
"""
from __future__ import annotations

import json
import math
import os
import re
import shutil
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from dla_tpu.parallel.dist import barrier as _barrier

SEP = "."


def _as_logical(arr: np.ndarray, dtype_str: str) -> np.ndarray:
    """Undo npy's void-encoding of non-native dtypes (bfloat16 etc. save as
    |V2); view back to the logical dtype recorded in the index."""
    if arr.dtype.kind == "V":
        import ml_dtypes  # ships with jax; registers bfloat16/fp8 dtypes

        return arr.view(np.dtype(dtype_str))
    return arr


def _flatten_with_paths(tree: Any) -> List[Tuple[str, Any]]:
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = []
    for path, leaf in flat:
        keys = []
        for p in path:
            if hasattr(p, "key"):
                keys.append(str(p.key))
            elif hasattr(p, "idx"):
                keys.append(str(p.idx))
            else:
                keys.append(str(p))
        out.append((SEP.join(keys), leaf))
    return out


def _leaf_filename(path: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.\-]", "_", path) + ".npy"


def _shard_filename(path: str, starts: Sequence[int]) -> str:
    stem = re.sub(r"[^A-Za-z0-9_.\-]", "_", path)
    suffix = "_".join(str(s) for s in starts) or "scalar"
    return f"{stem}-shard{suffix}.npy"


def _normalize_index(idx, shape) -> Tuple[Tuple[int, int], ...]:
    """Device index (tuple of slices) -> ((start, stop), ...) per dim."""
    out = []
    for sl, dim in zip(idx, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return tuple(out)


class _ShardReader:
    """Assemble arbitrary slices of a logical array from its shard files.

    Files are opened with mmap, so reading a cross-topology slice touches
    only the bytes that overlap it."""

    def __init__(self, ckpt_dir: Path, meta: Dict[str, Any]):
        self.dir = ckpt_dir
        self.shape = tuple(meta["shape"])
        self.dtype = np.dtype(meta["dtype"])
        self.shards = meta["shards"]
        self._by_region = {
            tuple(tuple(se) for se in sh["index"]): sh["file"]
            for sh in self.shards}

    @classmethod
    def from_meta(cls, ckpt_dir: Path, meta: Dict[str, Any]) -> "_ShardReader":
        """Reader for either index format: a format-1 whole-file leaf is
        exactly the one-shard case."""
        if "shards" in meta:
            return cls(ckpt_dir, meta)
        whole = dict(meta)
        whole["shards"] = [{
            "file": meta["file"],
            "index": [[0, d] for d in meta["shape"]],
        }]
        return cls(ckpt_dir, whole)

    def _load(self, fname: str) -> np.ndarray:
        return _as_logical(
            np.load(self.dir / fname, mmap_mode="r"), str(self.dtype))

    def read(self, idx) -> np.ndarray:
        """idx: tuple of slices into the global shape."""
        region = _normalize_index(idx, self.shape)
        exact = self._by_region.get(region)
        if exact is not None:  # fast path: slice == one shard file
            # dla: disable=host-sync-in-hot-loop -- restore path: runs once at resume, not per step
            return np.asarray(self._load(exact))
        out_shape = tuple(stop - start for start, stop in region)
        out = np.empty(out_shape, self.dtype)
        filled = 0
        for sh in self.shards:
            sh_region = [tuple(se) for se in sh["index"]]
            dst, src = [], []
            empty = False
            for (want_s, want_e), (have_s, have_e) in zip(region, sh_region):
                lo, hi = max(want_s, have_s), min(want_e, have_e)
                if lo >= hi:
                    empty = True
                    break
                dst.append(slice(lo - want_s, hi - want_s))
                src.append(slice(lo - have_s, hi - have_s))
            if empty:
                continue
            arr = self._load(sh["file"])
            out[tuple(dst)] = arr[tuple(src)]
            filled += math.prod(s.stop - s.start for s in dst)
        if filled < math.prod(out_shape):
            raise ValueError(
                f"shard files do not cover requested region {region} "
                f"of shape {self.shape}")
        return out

    def full(self) -> np.ndarray:
        return self.read(tuple(slice(0, d) for d in self.shape))


def _is_prng_key(x: Any) -> bool:
    return hasattr(x, "dtype") and jax.dtypes.issubdtype(
        getattr(x, "dtype", None), jax.dtypes.prng_key)


class Checkpointer:
    def __init__(self, output_dir: str, keep_last_n: int = 3):
        self.dir = Path(output_dir)
        self.keep_last_n = keep_last_n
        self.is_main = jax.process_index() == 0

    # ----------------------------------------------------------------- save

    def save(self, step: int, tree: Any, aux: Optional[Dict[str, Any]] = None,
             tag: Optional[str] = None) -> Path:
        tag = tag or f"step_{step:08d}"
        final = self.dir / tag
        tmp = self.dir / f".tmp_{tag}"
        if self.is_main:
            if tmp.exists():
                shutil.rmtree(tmp)
            tmp.mkdir(parents=True, exist_ok=True)
        _barrier(f"ckpt_mkdir_{tag}")

        index, writes = self.plan(step, tree, aux)
        for fname, arr in writes:
            np.save(tmp / fname, arr)
        _barrier(f"ckpt_written_{tag}")
        if self.is_main:
            with (tmp / "index.json").open("w") as fh:
                json.dump(index, fh)
            if final.exists():
                shutil.rmtree(final)
            tmp.rename(final)
            self._write_latest(tag)
            self._retain()
        _barrier(f"ckpt_final_{tag}")
        return final

    def plan(self, step: int, tree: Any, aux: Optional[Dict[str, Any]] = None,
             copy: bool = False
             ) -> Tuple[Dict[str, Any], List[Tuple[str, np.ndarray]]]:
        """Separate WHAT to write from the writing: returns
        ``(index, writes)`` where ``writes`` is this process's
        ``[(filename, host_array), ...]``. With ``copy=True`` every array
        is a fresh host copy — the snapshot an async save needs so the
        background write never reads a donated device buffer the next
        step has already reused."""
        index = {"format": 2, "step": int(step), "aux": aux or {},
                 "leaves": {}}
        writes: List[Tuple[str, np.ndarray]] = []
        for path, leaf in _flatten_with_paths(tree):
            if leaf is None:
                continue
            index["leaves"][path] = self._plan_leaf(path, leaf, writes, copy)
        return index, writes

    def _plan_leaf(self, path: str, leaf: Any,
                   writes: List[Tuple[str, np.ndarray]],
                   copy: bool) -> Dict[str, Any]:
        """Plan one leaf; return its index entry, appending this process's
        file writes. Sharded jax.Arrays get one file per distinct index
        region, this process contributing only regions whose replica-0
        copy it holds — across all hosts every region is written exactly
        once, with no gather anywhere."""
        if _is_prng_key(leaf):
            leaf = jax.random.key_data(leaf)
        # The shard path handles every case np.asarray cannot: sharded
        # arrays AND any multi-host array this process cannot fully
        # address (even a replicated or single-remote-device one — the
        # replica-0 owner writes its one region, others skip).
        if isinstance(leaf, jax.Array) and (
                not leaf.is_fully_addressable
                or (len(leaf.devices()) > 1
                    and not leaf.is_fully_replicated)):
            shape, dtype = leaf.shape, str(leaf.dtype)
            regions: Dict[Tuple, str] = {}
            for dev, idx in leaf.sharding.devices_indices_map(shape).items():
                region = _normalize_index(idx, shape)
                regions.setdefault(region, _shard_filename(
                    path, [s for s, _ in region]))
            for shard in leaf.addressable_shards:
                if shard.replica_id != 0:
                    continue
                region = _normalize_index(shard.index, shape)
                data = np.asarray(shard.data)
                writes.append((regions[region],
                               np.array(data, copy=True) if copy else data))
            return {"shape": list(shape), "dtype": dtype,
                    "shards": [{"file": fname,
                                "index": [list(se) for se in region]}
                               for region, fname in sorted(regions.items())]}
        # replicated / host / scalar leaf: process 0 writes it whole
        np_arr = np.asarray(leaf)
        if self.is_main:
            writes.append((_leaf_filename(path),
                           np.array(np_arr, copy=True) if copy else np_arr))
        return {"file": _leaf_filename(path),
                "shape": list(np_arr.shape), "dtype": str(np_arr.dtype)}

    def _write_latest(self, tag: str) -> None:
        # atomic: a crash mid-write must never leave a truncated pointer
        # (readers would then resolve a garbage tag). Write-aside, fsync,
        # rename — rename is atomic on POSIX.
        tmp = self.dir / ".latest.tmp"
        with tmp.open("w") as fh:
            fh.write(tag)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.dir / "latest")

    def sweep_stale_tmp(self) -> List[str]:
        """Startup hygiene: remove ``.tmp_*`` staging directories (and a
        stray ``.latest.tmp``) left by a save that died mid-write. They
        are never valid checkpoints, but they leak disk and a later save
        of the same tag would have to clear them anyway. Call once at
        trainer startup (rank 0), NEVER concurrently with a save."""
        removed: List[str] = []
        if not self.is_main or not self.dir.is_dir():
            return removed
        for stale in self.dir.glob(".tmp_*"):
            if stale.is_dir():
                shutil.rmtree(stale, ignore_errors=True)
                removed.append(stale.name)
        latest_tmp = self.dir / ".latest.tmp"
        if latest_tmp.is_file():
            latest_tmp.unlink(missing_ok=True)
            removed.append(latest_tmp.name)
        return removed

    def _retain(self) -> None:
        if self.keep_last_n <= 0:
            return
        steps = sorted(
            (d for d in self.dir.glob("step_*") if d.is_dir()),
            key=lambda d: d.name)
        for old in steps[: max(0, len(steps) - self.keep_last_n)]:
            shutil.rmtree(old, ignore_errors=True)

    # -------------------------------------------------------------- restore

    def latest_tag(self) -> Optional[str]:
        latest = self.dir / "latest"
        if latest.is_file():
            tag = latest.read_text().strip()
            if (self.dir / tag).is_dir():
                return tag
        return self.newest_step_tag()

    def step_tags(self) -> List[str]:
        """All ``step_*`` checkpoint tags on disk, ascending."""
        return sorted(d.name for d in self.dir.glob("step_*") if d.is_dir())

    def newest_step_tag(self) -> Optional[str]:
        steps = self.step_tags()
        return steps[-1] if steps else None

    def peek_aux(self, tag: Optional[str] = None) -> Dict[str, Any]:
        """Read a checkpoint's aux dict without touching any tensor data —
        the cheap pre-restore peek entry points use to size data iterators
        to the SAVED global batch before ``Trainer.try_resume`` adopts it
        (a topology-shift resume must see full-size batches from its
        first step). Returns {} when nothing restorable exists."""
        tag = tag or self.latest_tag()
        if tag is None:
            return {}
        try:
            idx = resolve_checkpoint_dir(self.dir / tag) / "index.json"
            with idx.open() as fh:
                return json.load(fh).get("aux", {})
        except (OSError, ValueError):
            return {}

    def restore(self, template: Any, tag: Optional[str] = None,
                shardings: Optional[Any] = None
                ) -> Tuple[Any, Dict[str, Any]]:
        """Restore a pytree like ``template``; place leaves per ``shardings``
        (a matching pytree of jax.sharding.Sharding) or on the default
        device. Returns (tree, aux)."""
        tag = tag or self.latest_tag()
        if tag is None:
            raise FileNotFoundError(f"No checkpoint under {self.dir}")
        ckpt = resolve_checkpoint_dir(self.dir / tag)
        with (ckpt / "index.json").open() as fh:
            index = json.load(fh)

        leaves_t = _flatten_with_paths(template)
        shard_leaves = (_flatten_with_paths(shardings)[0:] if shardings is not None
                        else None)
        shard_by_path = dict(shard_leaves) if shard_leaves else {}
        restored: Dict[str, Any] = {}
        reshaped_paths = []
        for path, tmpl_leaf in leaves_t:
            meta = index["leaves"].get(path)
            if meta is None:
                raise KeyError(f"Checkpoint {ckpt} missing leaf '{path}'")
            is_key = _is_prng_key(tmpl_leaf)
            sharding = shard_by_path.get(path)
            reader = _ShardReader.from_meta(ckpt, meta)
            saved_shape = tuple(meta["shape"])
            want_shape = tuple(getattr(tmpl_leaf, "shape", saved_shape))
            if (not is_key
                    and _is_layer_stack_reshape(path, saved_shape,
                                                want_shape)):
                # layer-stack layout adaptation: the interleaved-PP
                # block-major storage ([V, S, c, ...] leaves) is a
                # row-major reshape of the canonical [L, ...] stack, so
                # checkpoints written under either layout — or a
                # different stage count — restore into the other by
                # plain reshape (models/transformer.py
                # _interleaved_storage). Deliberately NARROW: only
                # "layers" leaves whose trailing dims match exactly and
                # whose leading dims are a pure regrouping qualify — any
                # other shape mismatch keeps restore's longstanding
                # behavior (saved shape wins, mismatch surfaces at
                # first use). The adapted leaf is read WHOLE on every
                # host (migration-scale path; for in-place topology
                # flips of very large trees prefer an offline
                # to_canonical_layout/to_storage_layout conversion).
                full = reader.full().reshape(want_shape)
                out = (jax.device_put(full, sharding)
                       if sharding is not None else jax.device_put(full))
                reshaped_paths.append(path)
                restored[path] = out
                continue
            if sharding is not None and not is_key:
                out = jax.make_array_from_callback(
                    saved_shape, sharding,
                    lambda idx, r=reader: r.read(idx))
            else:
                out = jax.device_put(reader.full())
                if is_key:
                    out = jax.random.wrap_key_data(out)
            restored[path] = out
        if reshaped_paths and jax.process_index() == 0:
            print(f"[dla_tpu][checkpoint] adapted layer-stack layout of "
                  f"{len(reshaped_paths)} leaves on restore (e.g. "
                  f"{reshaped_paths[0]})", flush=True)

        tree = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(template),
            [restored[p] for p, _ in leaves_t])
        return tree, index.get("aux", {})


def _is_layer_stack_reshape(path: str, saved: Tuple[int, ...],
                            want: Tuple[int, ...]) -> bool:
    """Whether a saved leaf may be row-major-reshaped into the template
    shape: a layer-stack leaf (path under a "layers" subtree) whose
    trailing dims are IDENTICAL and whose differing leading dims ([L]
    vs [V, S, c], any grouping) regroup the same element count. Equal
    trailing dims rule out transposes and other coincidental
    size matches — reshape is only sound for the leading-dim
    regrouping the interleaved-PP storage uses."""
    if saved == want or f"{SEP}layers{SEP}" not in f"{SEP}{path}{SEP}":
        return False
    if int(np.prod(saved)) != int(np.prod(want)):
        return False
    # strip the longest common SUFFIX; the remainders are the leading
    # group dims on each side — both must be pure regroupings
    i = 0
    while (i < min(len(saved), len(want))
           and saved[len(saved) - 1 - i] == want[len(want) - 1 - i]):
        i += 1
    lead_saved = saved[:len(saved) - i]
    lead_want = want[:len(want) - i]
    return (math.prod(lead_saved or (1,)) == math.prod(lead_want or (1,))
            and len(lead_saved) in (1, 2, 3) and len(lead_want) in (1, 2, 3))


def load_tree_numpy(ckpt_dir, prefix: Optional[str] = None
                    ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Load a checkpoint's leaves as host numpy arrays, rebuilt into nested
    dicts from their path-encoded names. Used for model loading, where the
    caller shards the result onto its own mesh afterwards. Returns
    (tree, aux)."""
    ckpt = resolve_checkpoint_dir(ckpt_dir)
    with (ckpt / "index.json").open() as fh:
        index = json.load(fh)
    tree: Dict[str, Any] = {}
    for path, meta in index["leaves"].items():
        if prefix is not None:
            if not path.startswith(prefix + SEP):
                continue
            rel = path[len(prefix) + 1:]
        else:
            rel = path
        node = tree
        keys = rel.split(SEP)
        for k in keys[:-1]:
            node = node.setdefault(k, {})
        node[keys[-1]] = _ShardReader.from_meta(ckpt, meta).full()
    return tree, index.get("aux", {})


def resolve_checkpoint_dir(path) -> Path:
    """Follow a ``latest`` pointer if ``path`` is a checkpoint root or ends
    in /latest (the reference configs point at ``checkpoints/X/latest``,
    e.g. dpo_config.yaml:6-7)."""
    p = Path(path)
    if p.name == "latest":
        root = p.parent
        ck = Checkpointer(str(root))
        tag = ck.latest_tag()
        if tag is None:
            raise FileNotFoundError(f"No checkpoint under {root}")
        return root / tag
    if (p / "index.json").is_file():
        return p
    ck = Checkpointer(str(p))
    tag = ck.latest_tag()
    if tag:
        return p / tag
    raise FileNotFoundError(f"No checkpoint at {p}")


def is_checkpoint_path(path) -> bool:
    try:
        resolve_checkpoint_dir(path)
        return True
    except (FileNotFoundError, NotADirectoryError):
        return False
