"""Background batch prefetch: overlap host-side data work with device steps.

The reference gets pipeline overlap from torch ``DataLoader`` workers
(``num_workers``, config/sft_config.yaml:14, loaders built in
src/training/train_sft.py); the TPU-native equivalent is this bounded
producer/consumer: a daemon thread pulls batches from the source iterator
(tokenization, packing, collation — all host work) while the device runs
step N, so batch N+1 is ready the moment the step completes and the chip
never idles waiting on the host.

Resume correctness: the worker runs ahead of consumption, so the source
iterator's own position includes batches still sitting in the queue.
Each queue item therefore carries the source state *after producing that
batch*, and ``state_dict()`` returns the state of the last batch the
consumer actually received — checkpoints never skip queued-but-unseen
batches.
"""
from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Iterator, Optional

_SENTINEL = object()


class _WorkerError:
    def __init__(self, exc: BaseException):
        self.exc = exc


class PrefetchIterator:
    """Wrap a (resumable) batch iterator with an N-deep prefetch queue."""

    def __init__(self, source: Any, prefetch: int = 2, tracer=None):
        if prefetch < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {prefetch}")
        self.source = source
        # host-trace feed: each produced batch is a "prefetch_next" slice
        # on the worker thread, concurrent with the trainer's step slices
        # (the overlap this class exists to create, made visible). The
        # default global tracer is disabled -> zero overhead.
        if tracer is None:
            from dla_tpu.telemetry.trace import get_tracer
            tracer = get_tracer()
        self.tracer = tracer
        self._q: "queue.Queue" = queue.Queue(maxsize=prefetch)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._finished = False  # worker exhausted the source or errored
        self._last_state: Dict = self._source_state()
        self.produced = 0  # batches the worker has finished (for tests)

    # ---------------------------------------------------------------- state

    def _source_state(self) -> Dict:
        if hasattr(self.source, "state_dict"):
            return dict(self.source.state_dict())
        return {}

    def state_dict(self) -> Dict:
        """Position of the last *consumed* batch (not the read-ahead)."""
        return dict(self._last_state)

    def load_state_dict(self, state: Dict) -> None:
        if self._thread is not None:
            raise RuntimeError(
                "load_state_dict after iteration started; create a fresh "
                "PrefetchIterator to seek")
        if hasattr(self.source, "load_state_dict"):
            self.source.load_state_dict(state)
        self._last_state = self._source_state()

    # ------------------------------------------------------------- iterate

    def _put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self) -> None:
        try:
            it = iter(self.source)
            while True:
                # span covers only the source's own work (tokenize/pack/
                # collate), not time blocked on a full queue — a full
                # queue means the host is AHEAD, which is not a cost.
                try:
                    with self.tracer.span("prefetch_next", cat="data",
                                          index=self.produced):
                        batch = next(it)
                except StopIteration:
                    break
                if not self._put((batch, self._source_state())):
                    return
                self.produced += 1
            self._put((_SENTINEL, None))
        except BaseException as exc:  # noqa: BLE001 — relayed to consumer
            self._put((_WorkerError(exc), None))

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._stop.is_set():
            raise RuntimeError("PrefetchIterator used after close()")
        if self._finished:
            # worker already exhausted the source or died: never block on
            # the empty queue of a dead producer
            raise StopIteration
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._worker, name="dla-prefetch", daemon=True)
            self._thread.start()
        item, state = self._q.get()
        if item is _SENTINEL:
            self._finished = True
            raise StopIteration
        if isinstance(item, _WorkerError):
            self._finished = True
            raise item.exc
        self._last_state = state or {}
        return item

    # -------------------------------------------------------------- close

    def close(self) -> None:
        self._stop.set()
        # unblock a worker stuck on a full queue
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def __enter__(self) -> "PrefetchIterator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
