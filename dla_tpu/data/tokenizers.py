"""Tokenizer layer.

The reference uses HF ``AutoTokenizer`` everywhere (src/models/base_model.py:23-28,
pad_token := eos). Here tokenization is host-side and pluggable:

- ``HFTokenizer`` wraps a transformers tokenizer (local path or hub id)
  when one is available.
- ``ByteTokenizer`` is a dependency-free byte-level tokenizer used by
  tests and smoke runs (zero-egress environments cannot fetch HF vocab
  files).

Both satisfy the small protocol the data layer needs: ``encode``,
``decode``, ``pad_token_id``, ``eos_token_id``, ``vocab_size``.
"""
from __future__ import annotations

import os
from typing import List, Optional, Protocol, Sequence


class Tokenizer(Protocol):
    pad_token_id: int
    eos_token_id: int
    bos_token_id: Optional[int]
    vocab_size: int

    def encode(self, text: str, *, add_bos: bool = True,
               add_eos: bool = False) -> List[int]: ...

    def decode(self, ids: Sequence[int]) -> str: ...


class ByteTokenizer:
    """UTF-8 bytes shifted by 3; ids 0/1/2 = pad/bos/eos. vocab_size 259."""

    def __init__(self) -> None:
        self.pad_token_id = 0
        self.bos_token_id = 1
        self.eos_token_id = 2
        self.vocab_size = 259

    def encode(self, text: str, *, add_bos: bool = True,
               add_eos: bool = False) -> List[int]:
        ids = [b + 3 for b in text.encode("utf-8")]
        if add_bos:
            ids = [self.bos_token_id] + ids
        if add_eos:
            ids = ids + [self.eos_token_id]
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(i - 3 for i in ids if 3 <= i <= 258)
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    """Adapter over ``transformers`` tokenizers; pad falls back to eos like
    the reference (base_model.py:26-28)."""

    def __init__(self, name_or_path: str):
        from transformers import AutoTokenizer  # heavy import kept local
        self._tok = AutoTokenizer.from_pretrained(name_or_path)
        if self._tok.pad_token is None:
            self._tok.pad_token = self._tok.eos_token
        self.pad_token_id = int(self._tok.pad_token_id)
        self.eos_token_id = int(self._tok.eos_token_id)
        self.bos_token_id = (int(self._tok.bos_token_id)
                             if self._tok.bos_token_id is not None else None)
        self.vocab_size = int(len(self._tok))

    def encode(self, text: str, *, add_bos: bool = True,
               add_eos: bool = False) -> List[int]:
        ids = self._tok.encode(text, add_special_tokens=add_bos)
        if add_eos:
            ids = ids + [self.eos_token_id]
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)


def load_tokenizer(name_or_path: str) -> Tokenizer:
    """Resolve a tokenizer: 'byte' -> ByteTokenizer; otherwise HF (local
    path or hub id). Falls back to ByteTokenizer with a warning when the HF
    load fails (e.g. zero-egress machine and no local files)."""
    if name_or_path in ("byte", "bytes", "test"):
        return ByteTokenizer()
    try:
        return HFTokenizer(name_or_path)
    except Exception as exc:  # noqa: BLE001 — any load failure gets the fallback
        print(f"[dla_tpu] tokenizer '{name_or_path}' unavailable ({exc}); "
              "falling back to ByteTokenizer", flush=True)
        return ByteTokenizer()
