"""Tokenized datasets for every phase, with the reference's exact text
contract but TPU-static batch shapes.

Contract parity (reference src/data/datasets.py):
- template ``"{prompt}\n\n{response}{eos}"`` (datasets.py:56,107,177)
- prompt masking: the tokens of ``"{prompt}\n\n"`` get label -100
  (datasets.py:66-75)
- preference pairs tokenize chosen/rejected independently (datasets.py:121-122)
- teacher rollouts: labels = input_ids, no prompt mask, scalar reward
  carried through (datasets.py:172-190)

Deliberate divergence (documented, SURVEY.md sec 7): batches are padded to a
**fixed** ``max_length``, not to the batch max — dynamic shapes force XLA
recompilation per batch; a single static shape compiles once. Sequence
packing (dla_tpu.data.packing) recovers the wasted pad FLOPs.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from dla_tpu.data.jsonl import read_jsonl
from dla_tpu.data.tokenizers import Tokenizer

IGNORE_INDEX = -100

PROMPT_TEMPLATE = "{prompt}\n\n"
FULL_TEMPLATE = "{prompt}\n\n{response}"


def encode_prompt_response(
    tokenizer: Tokenizer, prompt: str, response: str, max_length: int,
    mask_prompt: bool = True,
) -> Dict[str, np.ndarray]:
    """Tokenize one example to (input_ids, attention_mask, labels), unpadded."""
    prompt = prompt.strip()
    response = response.strip()
    full_ids = tokenizer.encode(
        FULL_TEMPLATE.format(prompt=prompt, response=response), add_eos=True)
    prompt_ids = tokenizer.encode(
        PROMPT_TEMPLATE.format(prompt=prompt), add_eos=False)
    full_ids = full_ids[:max_length]
    labels = list(full_ids)
    if mask_prompt:
        cut = min(len(prompt_ids), len(labels))
        labels[:cut] = [IGNORE_INDEX] * cut
    return {
        "input_ids": np.asarray(full_ids, np.int32),
        "attention_mask": np.ones(len(full_ids), np.int32),
        "labels": np.asarray(labels, np.int32),
    }


def pad_to(arr: np.ndarray, length: int, pad_value: int) -> np.ndarray:
    if arr.shape[0] >= length:
        return arr[:length]
    pad = np.full((length - arr.shape[0],) + arr.shape[1:], pad_value, arr.dtype)
    return np.concatenate([arr, pad], axis=0)


def pad_batch(examples: Sequence[Dict[str, np.ndarray]], pad_token_id: int,
              length: int) -> Dict[str, np.ndarray]:
    """Stack variable-length examples into fixed [B, length] arrays.

    Pad values follow the reference (datasets.py:212-229): input_ids ->
    pad_token_id, attention_mask -> 0, labels -> -100; any other integer
    key -> 0; scalar keys are stacked unpadded.
    """
    out: Dict[str, np.ndarray] = {}
    for key in examples[0]:
        vals = [ex[key] for ex in examples]
        if vals[0].ndim == 0:
            out[key] = np.stack(vals)
            continue
        if key == "labels":
            pv = IGNORE_INDEX
        elif key == "input_ids":
            pv = pad_token_id
        else:
            pv = 0
        out[key] = np.stack([pad_to(v, length, pv) for v in vals])
    return out


class _RecordDataset:
    records: List[Dict[str, Any]]

    def __init__(self, tokenizer: Tokenizer, max_length: int,
                 path: Optional[str] = None,
                 records: Optional[List[Dict[str, Any]]] = None):
        if records is None and path is None:
            raise ValueError(f"{type(self).__name__} needs records or a path")
        self.records = records if records is not None else read_jsonl(path)
        self.tokenizer = tokenizer
        self.max_length = max_length

    def __len__(self) -> int:
        return len(self.records)


class InstructionDataset(_RecordDataset):
    """SFT examples: {prompt, response} with prompt-masked labels."""

    def __init__(self, tokenizer: Tokenizer, max_length: int,
                 mask_prompt: bool = True, path: Optional[str] = None,
                 records: Optional[List[Dict[str, Any]]] = None):
        super().__init__(tokenizer, max_length, path, records)
        self.mask_prompt = mask_prompt

    def __getitem__(self, idx: int) -> Dict[str, np.ndarray]:
        rec = self.records[idx]
        return encode_prompt_response(
            self.tokenizer, rec["prompt"], rec["response"],
            self.max_length, self.mask_prompt)

    def collate(self, batch: Sequence[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
        return pad_batch(batch, self.tokenizer.pad_token_id, self.max_length)


class PreferenceDataset(_RecordDataset):
    """DPO / reward-model pairs: {prompt, chosen, rejected}."""

    def __getitem__(self, idx: int) -> Dict[str, Dict[str, np.ndarray]]:
        rec = self.records[idx]
        return {
            "chosen": encode_prompt_response(
                self.tokenizer, rec["prompt"], rec["chosen"],
                self.max_length, mask_prompt=True),
            "rejected": encode_prompt_response(
                self.tokenizer, rec["prompt"], rec["rejected"],
                self.max_length, mask_prompt=True),
        }

    def collate(self, batch) -> Dict[str, Dict[str, np.ndarray]]:
        return {
            side: pad_batch([ex[side] for ex in batch],
                            self.tokenizer.pad_token_id, self.max_length)
            for side in ("chosen", "rejected")
        }


class TeacherRolloutDataset(_RecordDataset):
    """Distillation examples: {prompt, teacher_response, reward?}.

    Labels = input_ids (no prompt mask) and the scalar reward rides along,
    matching reference datasets.py:172-190.
    """

    def __getitem__(self, idx: int) -> Dict[str, np.ndarray]:
        rec = self.records[idx]
        ex = encode_prompt_response(
            self.tokenizer, rec["prompt"], rec["teacher_response"],
            self.max_length, mask_prompt=False)
        ex["labels"] = ex["input_ids"].copy()
        ex["reward"] = np.asarray(float(rec.get("reward", 1.0)), np.float32)
        return ex

    def collate(self, batch) -> Dict[str, np.ndarray]:
        return pad_batch(batch, self.tokenizer.pad_token_id, self.max_length)


class EvalPromptDataset:
    """Plain prompt records for evaluation (reference datasets.py:199-209)."""

    def __init__(self, path: str):
        self.records = read_jsonl(path)

    def __len__(self) -> int:
        return len(self.records)

    def __getitem__(self, idx: int) -> Dict[str, Any]:
        return self.records[idx]
