"""Per-host sharded batch iteration with deterministic shuffling and
resumable position.

Replaces the reference's ``DistributedSampler`` + torch ``DataLoader``
(src/training/utils.py:110-118): each host draws a disjoint slice of every
global batch; devices within the host receive their shard when the batch
is placed with ``make_global_batch``. The iterator state (epoch, step) is
part of the checkpoint so resume continues mid-epoch — capability the
reference lacks entirely (SURVEY.md sec 5, checkpoint/resume row).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, Optional

import numpy as np


class ShardedBatchIterator:
    def __init__(
        self,
        dataset: Any,                     # needs __len__, __getitem__, collate
        global_batch_size: int,
        *,
        seed: int = 0,
        shuffle: bool = True,
        drop_last: bool = True,
        process_index: int = 0,
        process_count: int = 1,
    ):
        if global_batch_size % process_count != 0:
            raise ValueError(
                f"global batch {global_batch_size} not divisible by "
                f"{process_count} processes")
        self.dataset = dataset
        self.global_batch_size = global_batch_size
        self.local_batch_size = global_batch_size // process_count
        self.seed = seed
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.process_index = process_index
        self.process_count = process_count
        self.epoch = 0
        self.step_in_epoch = 0  # batches already emitted this epoch

    # ---------------------------------------------------------------- state

    def state_dict(self) -> Dict[str, int]:
        return {"epoch": self.epoch, "step_in_epoch": self.step_in_epoch}

    def load_state_dict(self, state: Dict[str, int]) -> None:
        self.epoch = int(state.get("epoch", 0))
        self.step_in_epoch = int(state.get("step_in_epoch", 0))

    def steps_per_epoch(self) -> int:
        n = len(self.dataset)
        if self.drop_last:
            return n // self.global_batch_size
        return (n + self.global_batch_size - 1) // self.global_batch_size

    # ------------------------------------------------------------- iterate

    def _epoch_order(self, epoch: int) -> np.ndarray:
        idx = np.arange(len(self.dataset))
        if self.shuffle:
            rng = np.random.default_rng((self.seed, epoch))
            rng.shuffle(idx)
        return idx

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        while True:
            order = self._epoch_order(self.epoch)
            spe = self.steps_per_epoch()
            if spe == 0:
                raise ValueError(
                    f"dataset of {len(self.dataset)} examples smaller than "
                    f"global batch {self.global_batch_size}")
            while self.step_in_epoch < spe:
                start = self.step_in_epoch * self.global_batch_size
                sl = order[start:start + self.global_batch_size]
                if len(sl) < self.global_batch_size:  # non-drop_last tail: wrap
                    sl = np.concatenate(
                        [sl, order[: self.global_batch_size - len(sl)]])
                local = sl[self.process_index::self.process_count]
                examples = [self.dataset[int(i)] for i in local]
                self.step_in_epoch += 1
                yield self.dataset.collate(examples)
            self.epoch += 1
            self.step_in_epoch = 0
