"""JSONL IO — the on-disk data contract shared with the reference
(README.md:88-94: {prompt, response}, {prompt, chosen, rejected},
{prompt, teacher_response, reward?}).

Sharded reads (``shard_index``/``shard_count``) partition a corpus by
record position so independent jobs each parse only their share — used by
``generate_teacher_data --shard_index k --shard_count n`` to fan rollout
generation over several processes. When the native line indexer
(dla_tpu/native: mmap + C++ offset scan) is built, a shard decodes only
its owned byte ranges; the pure-Python fallback returns identical
results. (Training-time per-host batch sharding is a different mechanism:
the iterator shards shuffled example indices, dla_tpu/data/iterator.py.)
"""
from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, Iterable, Iterator, List, Optional, Union

PathLike = Union[str, Path]


def read_jsonl(path: PathLike, shard_index: int = 0,
               shard_count: int = 1) -> List[Dict[str, Any]]:
    """Parse a JSONL file; with ``shard_count > 1`` return only records
    ``shard_index::shard_count`` (by non-empty-line position).

    Sharded reads use the native index (parse cost ~1/shard_count: only
    the owned byte ranges are decoded, via mmap — no whole-file heap
    copy). Full reads stay on Python line iteration — measured faster
    than index+slice for shard_count == 1.

    Native/Python agreement is validated before the index is trusted:
    the C scanner treats only ASCII whitespace as blank while Python
    ``str.strip()`` drops Unicode whitespace (U+00A0 etc.), so the native
    record set is always a superset of Python's — divergence happens
    exactly when some native record decodes to all-whitespace. Each
    record's byte range is checked for a printable-ASCII byte (O(1) for
    real JSON, which starts with ``{``); only byte ranges with none are
    decoded and stripped. Any divergent record (or per-record parse
    failure) drops the whole read to the Python path, so all shards of a
    fan-out see one consistent striding.
    """
    if shard_count < 1:
        raise ValueError(f"shard_count must be >= 1, got {shard_count}")
    if not 0 <= shard_index < shard_count:
        raise ValueError(
            f"shard_index must be in [0, {shard_count}), got {shard_index}"
            " — a misconfigured fan-out would silently produce nothing")
    if shard_count > 1:
        index = _native_index(path)
        if index is not None:
            starts, ends = index
            try:
                import mmap as _mmap
                with Path(path).open("rb") as fh:
                    with _mmap.mmap(fh.fileno(), 0,
                                    access=_mmap.ACCESS_READ) as mm:
                        if _native_records_match_python(mm, starts, ends):
                            return [json.loads(mm[s:e])
                                    for s, e in zip(
                                        starts[shard_index::shard_count],
                                        ends[shard_index::shard_count])]
            except (ValueError, OSError):
                pass  # empty file / parse disagreement -> Python path
    out: List[Dict[str, Any]] = []
    pos = 0
    with Path(path).open("r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if line:
                if pos % shard_count == shard_index:
                    out.append(json.loads(line))
                pos += 1
    return out


def _native_records_match_python(mm, starts, ends) -> bool:
    """True iff every native record is also a record to Python (non-empty
    after *Unicode* strip). A record containing any printable-ASCII byte
    (0x21-0x7E) cannot strip to empty — real JSON starts with '{', so the
    common case is a one-byte check; only exotic all-non-ASCII ranges pay
    a decode."""
    for s, e in zip(starts, ends):
        # index reads (mm[j] is an int) — no per-record bytes copy; real
        # JSON hits a printable byte at position 0
        printable = False
        for j in range(s, e):
            if 0x21 <= mm[j] <= 0x7E:
                printable = True
                break
        if printable:
            continue
        try:
            decoded = mm[s:e].decode("utf-8")
        except UnicodeDecodeError:
            return False
        if not decoded.strip():
            return False  # C counted it; Python would drop it
    return True


def _native_index(path: PathLike) -> Optional[tuple]:
    try:
        from dla_tpu import native
        return native.jsonl_index(path)
    except Exception:  # noqa: BLE001 — native layer must never break IO
        return None


def iter_jsonl(path: PathLike) -> Iterator[Dict[str, Any]]:
    with Path(path).open("r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if line:
                yield json.loads(line)


def write_jsonl(path: PathLike, records: Iterable[Dict[str, Any]]) -> None:
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    with p.open("w", encoding="utf-8") as fh:
        for rec in records:
            fh.write(json.dumps(rec, ensure_ascii=False) + "\n")


def append_jsonl(path: PathLike, record: Dict[str, Any]) -> None:
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    with p.open("a", encoding="utf-8") as fh:
        fh.write(json.dumps(record, ensure_ascii=False) + "\n")
