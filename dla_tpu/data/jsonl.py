"""JSONL IO — the on-disk data contract shared with the reference
(README.md:88-94: {prompt, response}, {prompt, chosen, rejected},
{prompt, teacher_response, reward?})."""
from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, Iterable, Iterator, List, Union

PathLike = Union[str, Path]


def read_jsonl(path: PathLike) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    with Path(path).open("r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def iter_jsonl(path: PathLike) -> Iterator[Dict[str, Any]]:
    with Path(path).open("r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if line:
                yield json.loads(line)


def write_jsonl(path: PathLike, records: Iterable[Dict[str, Any]]) -> None:
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    with p.open("w", encoding="utf-8") as fh:
        for rec in records:
            fh.write(json.dumps(rec, ensure_ascii=False) + "\n")


def append_jsonl(path: PathLike, record: Dict[str, Any]) -> None:
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    with p.open("a", encoding="utf-8") as fh:
        fh.write(json.dumps(record, ensure_ascii=False) + "\n")
