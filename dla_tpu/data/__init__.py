from dla_tpu.data.jsonl import append_jsonl, iter_jsonl, read_jsonl, write_jsonl
from dla_tpu.data.tokenizers import ByteTokenizer, HFTokenizer, load_tokenizer
from dla_tpu.data.datasets import (
    IGNORE_INDEX,
    EvalPromptDataset,
    InstructionDataset,
    PreferenceDataset,
    TeacherRolloutDataset,
    encode_prompt_response,
    pad_batch,
)
from dla_tpu.data.loaders import (
    build_instruction_dataset,
    build_preference_dataset,
    build_teacher_dataset,
    load_instruction_records,
    load_preference_records,
    load_prompt_records,
)
from dla_tpu.data.iterator import ShardedBatchIterator
from dla_tpu.data.packing import PackedInstructionDataset
from dla_tpu.data.prefetch import PrefetchIterator

__all__ = [
    "append_jsonl", "iter_jsonl", "read_jsonl", "write_jsonl",
    "ByteTokenizer", "HFTokenizer", "load_tokenizer",
    "IGNORE_INDEX", "EvalPromptDataset", "InstructionDataset",
    "PreferenceDataset", "TeacherRolloutDataset", "encode_prompt_response",
    "pad_batch", "build_instruction_dataset", "build_preference_dataset",
    "build_teacher_dataset", "load_instruction_records",
    "load_preference_records", "load_prompt_records",
    "ShardedBatchIterator", "PackedInstructionDataset", "PrefetchIterator",
]
