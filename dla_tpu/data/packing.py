"""Sequence packing: fill fixed-length rows with multiple examples.

Implements for real the ``data.packing: true`` config key the reference
declares but never wires (config/sft_config.yaml:16, SURVEY.md sec 2.5).
Packed rows carry ``segment_ids``; the transformer masks cross-segment
attention and restarts positions per segment
(dla_tpu.models.transformer.Transformer.hidden_states), so packing is
loss-equivalent to unpacked batching while filling the pad FLOPs that
fixed-shape batching would otherwise waste.
"""
from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from dla_tpu.data.datasets import IGNORE_INDEX


def pack_first_fit_python(lengths: np.ndarray, max_length: int,
                          close_margin: int):
    """Reference implementation of greedy first-fit placement. Returns
    (row_assignment per example, n_rows). The native packer
    (dla_tpu/native) must match this bit-for-bit — tests enforce it."""
    assign = np.empty(len(lengths), np.int32)
    row_len: List[int] = []
    open_rows: List[int] = []
    for i, raw in enumerate(lengths):
        n = min(int(raw), max_length)
        placed = False
        for r in open_rows:
            if row_len[r] + n <= max_length:
                row_len[r] += n
                assign[i] = r
                placed = True
                break
        if not placed:
            row_len.append(n)
            open_rows.append(len(row_len) - 1)
            assign[i] = len(row_len) - 1
        open_rows = [r for r in open_rows
                     if row_len[r] + close_margin <= max_length]
    return assign, len(row_len)


class PackedInstructionDataset:
    """Greedy first-fit packing of tokenized instruction examples into rows
    of exactly ``max_length`` tokens. Presents the same dataset protocol
    (__len__/__getitem__/collate) as InstructionDataset, so it is a drop-in
    for the trainer's iterator."""

    CLOSE_MARGIN = 8  # close rows that cannot take even a tiny example

    def __init__(self, base, max_length: int, lazy: bool = True):
        """``base``: an InstructionDataset (or anything yielding dicts with
        input_ids/attention_mask/labels 1-D arrays).

        ``lazy`` (default): __init__ makes one lengths-only pass (token
        arrays are discarded immediately, O(n_examples) memory instead of
        O(corpus tokens)) and rows re-tokenize their examples on access —
        with the trainer's background prefetch that work overlaps the
        device step. ``lazy=False`` keeps every tokenized example in
        memory (fastest per-epoch for small corpora/tests).
        """
        self.max_length = max_length
        self.pad_token_id = base.tokenizer.pad_token_id
        self.base = base
        self.lazy = lazy
        self._examples: List[Dict[str, np.ndarray]] = []
        lengths_l: List[int] = []
        for i in range(len(base)):
            ex = base[i]
            lengths_l.append(min(int(ex["input_ids"].shape[0]), max_length))
            if not lazy:
                if int(ex["input_ids"].shape[0]) > max_length:
                    # 0-d extras (TeacherRolloutDataset's reward) pass
                    # through untouched
                    ex = {k: v[:max_length] if getattr(v, "ndim", 1) else v
                          for k, v in ex.items()}
                self._examples.append(ex)
        self.lengths = np.asarray(lengths_l, np.int32)
        assign, n_rows = self._place(self.lengths)
        # rows hold example *indices*; lazy mode fetches from base on demand
        self.rows: List[List[int]] = [[] for _ in range(n_rows)]
        for i, r in enumerate(assign):
            self.rows[int(r)].append(i)

    def _example(self, i: int) -> Dict[str, np.ndarray]:
        if not self.lazy:
            return self._examples[i]
        ex = self.base[i]
        if int(ex["input_ids"].shape[0]) > self.max_length:
            ex = {k: v[: self.max_length] if getattr(v, "ndim", 1) else v
                  for k, v in ex.items()}
        return ex

    def _place(self, lengths: np.ndarray):
        """Row assignment per example: native C++ first-fit when built
        (dla_tpu/native/src/dla_data.cpp dla_pack_ffd — placement is
        bit-identical), else the pure-Python loop."""
        try:
            from dla_tpu import native
            out = native.pack_ffd(lengths, self.max_length, self.CLOSE_MARGIN)
            if out is not None:
                return out
        except Exception:  # noqa: BLE001 — fall through to Python packer
            pass
        return pack_first_fit_python(
            lengths, self.max_length, self.CLOSE_MARGIN)

    def __len__(self) -> int:
        return len(self.rows)

    def __getitem__(self, idx: int) -> Dict[str, np.ndarray]:
        segs = [self._example(i) for i in self.rows[idx]]
        L = self.max_length
        input_ids = np.full(L, self.pad_token_id, np.int32)
        labels = np.full(L, IGNORE_INDEX, np.int32)
        attention_mask = np.zeros(L, np.int32)
        segment_ids = np.zeros(L, np.int32)  # 0 = padding segment
        pos = 0
        for si, ex in enumerate(segs, start=1):
            n = ex["input_ids"].shape[0]
            input_ids[pos:pos + n] = ex["input_ids"]
            labels[pos:pos + n] = ex["labels"]
            # the next-token shift would otherwise train segment i's last
            # token to predict segment i+1's first token
            labels[pos] = IGNORE_INDEX
            attention_mask[pos:pos + n] = 1
            segment_ids[pos:pos + n] = si
            pos += n
        return {
            "input_ids": input_ids,
            "attention_mask": attention_mask,
            "labels": labels,
            "segment_ids": segment_ids,
        }

    def collate(self, batch: Sequence[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
        return {k: np.stack([ex[k] for ex in batch]) for k in batch[0]}

    def packing_efficiency(self) -> float:
        """Fraction of token slots holding real tokens (1.0 = perfect)."""
        total = len(self.rows) * self.max_length
        return int(self.lengths.sum()) / max(total, 1)


class PackedTeacherDataset(PackedInstructionDataset):
    """Packing for distillation rows (TeacherRolloutDataset): identical
    segment machinery, plus the per-example scalar ``reward`` carried as
    a token-weighted row mean. The trainer re-weights its reward_mean
    metric by row token counts for packed batches (train_distill.py), so
    the logged value is the corpus TOKEN-weighted reward mean — exact
    under any row/batch split, unlike a mean of per-row means over
    unevenly filled rows. Extends the SFT-only scope of the reference's
    dead ``packing`` key (config/sft_config.yaml:16) to phase 4."""

    def __init__(self, base, max_length: int, lazy: bool = True):
        super().__init__(base, max_length, lazy=lazy)
        # one extra scalar per example — cheap even for lazy mode when
        # the base caches records (tokenization is NOT repeated: rewards
        # come from the raw records, not the encoded arrays)
        self.rewards = np.asarray(
            [float(base.records[i].get("reward", 1.0))
             for i in range(len(base))], np.float32)

    def __getitem__(self, idx: int) -> Dict[str, np.ndarray]:
        row = super().__getitem__(idx)
        ex_idx = self.rows[idx]
        w = self.lengths[ex_idx].astype(np.float32)
        r = self.rewards[ex_idx]
        row["reward"] = np.asarray(
            float((w * r).sum() / max(w.sum(), 1.0)), np.float32)
        return row


class PackedPreferenceDataset:
    """Greedy joint first-fit packing of preference PAIRS: pair i goes
    into row r only if BOTH its chosen sequence fits r's chosen row and
    its rejected sequence fits r's rejected row — so segment j of a
    chosen row is always the partner of segment j of the same rejected
    row, and the DPO/reward pair algebra needs no index plumbing beyond
    the shared (row, segment) coordinate. Segments are numbered from 1
    per row (0 = padding), matching PackedInstructionDataset.

    The joint two-sided constraint is why this does not reuse the
    native single-length packer (dla_pack_ffd): placement must check
    both fills at once. The greedy loop is O(pairs * open_rows) in
    Python at init time — dataset sizes for preference phases are far
    below the SFT corpora the native packer exists for.

    Batch items:
      chosen / rejected: {input_ids, attention_mask, labels,
                          segment_ids} [L] each
      pair_mask: [max_pairs] 1.0 for real pairs (segment j+1 exists)
    """

    CLOSE_MARGIN = 8

    def __init__(self, base, max_length: int, lazy: bool = True):
        self.max_length = max_length
        self.pad_token_id = base.tokenizer.pad_token_id
        self.base = base
        self.lazy = lazy
        self._examples: List[Dict[str, Dict[str, np.ndarray]]] = []
        len_c, len_r = [], []
        for i in range(len(base)):
            ex = base[i]
            len_c.append(min(int(ex["chosen"]["input_ids"].shape[0]),
                             max_length))
            len_r.append(min(int(ex["rejected"]["input_ids"].shape[0]),
                             max_length))
            if not lazy:
                self._examples.append(self._truncate(ex))
        self.len_c = np.asarray(len_c, np.int32)
        self.len_r = np.asarray(len_r, np.int32)

        rows: List[List[int]] = []
        fill_c: List[int] = []
        fill_r: List[int] = []
        open_rows: List[int] = []
        for i in range(len(base)):
            lc, lr = int(self.len_c[i]), int(self.len_r[i])
            placed = False
            for r in open_rows:
                if (fill_c[r] + lc <= max_length
                        and fill_r[r] + lr <= max_length):
                    rows[r].append(i)
                    fill_c[r] += lc
                    fill_r[r] += lr
                    placed = True
                    break
            if not placed:
                rows.append([i])
                fill_c.append(lc)
                fill_r.append(lr)
                open_rows.append(len(rows) - 1)
            open_rows = [
                r for r in open_rows
                if (fill_c[r] + self.CLOSE_MARGIN <= max_length
                    and fill_r[r] + self.CLOSE_MARGIN <= max_length)]
        self.rows = rows
        self.max_pairs = max(len(r) for r in rows) if rows else 1

    def _truncate(self, ex):
        L = self.max_length
        return {side: {k: v[:L] for k, v in ex[side].items()}
                for side in ("chosen", "rejected")}

    def _example(self, i: int):
        if not self.lazy:
            return self._examples[i]
        return self._truncate(self.base[i])

    def __len__(self) -> int:
        return len(self.rows)

    def _pack_side(self, exs: Sequence[Dict[str, np.ndarray]]):
        L = self.max_length
        out = {
            "input_ids": np.full(L, self.pad_token_id, np.int32),
            "labels": np.full(L, IGNORE_INDEX, np.int32),
            "attention_mask": np.zeros(L, np.int32),
            "segment_ids": np.zeros(L, np.int32),
        }
        pos = 0
        for si, ex in enumerate(exs, start=1):
            n = ex["input_ids"].shape[0]
            out["input_ids"][pos:pos + n] = ex["input_ids"]
            out["labels"][pos:pos + n] = ex["labels"]
            out["labels"][pos] = IGNORE_INDEX   # next-token shift guard
            out["attention_mask"][pos:pos + n] = 1
            out["segment_ids"][pos:pos + n] = si
            pos += n
        return out

    def __getitem__(self, idx: int) -> Dict[str, Dict[str, np.ndarray]]:
        exs = [self._example(i) for i in self.rows[idx]]
        pair_mask = np.zeros(self.max_pairs, np.float32)
        pair_mask[:len(exs)] = 1.0
        return {
            "chosen": self._pack_side([e["chosen"] for e in exs]),
            "rejected": self._pack_side([e["rejected"] for e in exs]),
            "pair_mask": pair_mask,
        }

    def collate(self, batch):
        out = {
            side: {k: np.stack([ex[side][k] for ex in batch])
                   for k in batch[0][side]}
            for side in ("chosen", "rejected")
        }
        out["pair_mask"] = np.stack([ex["pair_mask"] for ex in batch])
        return out

    def packing_efficiency(self) -> float:
        total = 2 * len(self.rows) * self.max_length
        return ((int(self.len_c.sum()) + int(self.len_r.sum()))
                / max(total, 1))


def pack_preference_splits(train_ds, eval_ds, max_length: int):
    """Wrap train/eval preference splits for packing with ONE shared
    static pair width (the jitted loss closes over a single n_segments;
    both splits pad their pair_mask to the wider). Returns
    (packed_train, packed_eval_or_None, n_segments) — the shared setup
    for train_dpo and train_reward."""
    train_p = PackedPreferenceDataset(train_ds, max_length)
    eval_p = (PackedPreferenceDataset(eval_ds, max_length)
              if eval_ds is not None else None)
    n = max([train_p.max_pairs]
            + ([eval_p.max_pairs] if eval_p is not None else []))
    train_p.max_pairs = n
    if eval_p is not None:
        eval_p.max_pairs = n
    return train_p, eval_p, n
