"""Sequence packing: fill fixed-length rows with multiple examples.

Implements for real the ``data.packing: true`` config key the reference
declares but never wires (config/sft_config.yaml:16, SURVEY.md sec 2.5).
Packed rows carry ``segment_ids``; the transformer masks cross-segment
attention and restarts positions per segment
(dla_tpu.models.transformer.Transformer.hidden_states), so packing is
loss-equivalent to unpacked batching while filling the pad FLOPs that
fixed-shape batching would otherwise waste.
"""
from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from dla_tpu.data.datasets import IGNORE_INDEX


def pack_first_fit_python(lengths: np.ndarray, max_length: int,
                          close_margin: int):
    """Reference implementation of greedy first-fit placement. Returns
    (row_assignment per example, n_rows). The native packer
    (dla_tpu/native) must match this bit-for-bit — tests enforce it."""
    assign = np.empty(len(lengths), np.int32)
    row_len: List[int] = []
    open_rows: List[int] = []
    for i, raw in enumerate(lengths):
        n = min(int(raw), max_length)
        placed = False
        for r in open_rows:
            if row_len[r] + n <= max_length:
                row_len[r] += n
                assign[i] = r
                placed = True
                break
        if not placed:
            row_len.append(n)
            open_rows.append(len(row_len) - 1)
            assign[i] = len(row_len) - 1
        open_rows = [r for r in open_rows
                     if row_len[r] + close_margin <= max_length]
    return assign, len(row_len)


class PackedInstructionDataset:
    """Greedy first-fit packing of tokenized instruction examples into rows
    of exactly ``max_length`` tokens. Presents the same dataset protocol
    (__len__/__getitem__/collate) as InstructionDataset, so it is a drop-in
    for the trainer's iterator."""

    CLOSE_MARGIN = 8  # close rows that cannot take even a tiny example

    def __init__(self, base, max_length: int, lazy: bool = True):
        """``base``: an InstructionDataset (or anything yielding dicts with
        input_ids/attention_mask/labels 1-D arrays).

        ``lazy`` (default): __init__ makes one lengths-only pass (token
        arrays are discarded immediately, O(n_examples) memory instead of
        O(corpus tokens)) and rows re-tokenize their examples on access —
        with the trainer's background prefetch that work overlaps the
        device step. ``lazy=False`` keeps every tokenized example in
        memory (fastest per-epoch for small corpora/tests).
        """
        self.max_length = max_length
        self.pad_token_id = base.tokenizer.pad_token_id
        self.base = base
        self.lazy = lazy
        self._examples: List[Dict[str, np.ndarray]] = []
        lengths_l: List[int] = []
        for i in range(len(base)):
            ex = base[i]
            lengths_l.append(min(int(ex["input_ids"].shape[0]), max_length))
            if not lazy:
                if int(ex["input_ids"].shape[0]) > max_length:
                    ex = {k: v[:max_length] for k, v in ex.items()}
                self._examples.append(ex)
        self.lengths = np.asarray(lengths_l, np.int32)
        assign, n_rows = self._place(self.lengths)
        # rows hold example *indices*; lazy mode fetches from base on demand
        self.rows: List[List[int]] = [[] for _ in range(n_rows)]
        for i, r in enumerate(assign):
            self.rows[int(r)].append(i)

    def _example(self, i: int) -> Dict[str, np.ndarray]:
        if not self.lazy:
            return self._examples[i]
        ex = self.base[i]
        if int(ex["input_ids"].shape[0]) > self.max_length:
            ex = {k: v[: self.max_length] for k, v in ex.items()}
        return ex

    def _place(self, lengths: np.ndarray):
        """Row assignment per example: native C++ first-fit when built
        (dla_tpu/native/src/dla_data.cpp dla_pack_ffd — placement is
        bit-identical), else the pure-Python loop."""
        try:
            from dla_tpu import native
            out = native.pack_ffd(lengths, self.max_length, self.CLOSE_MARGIN)
            if out is not None:
                return out
        except Exception:  # noqa: BLE001 — fall through to Python packer
            pass
        return pack_first_fit_python(
            lengths, self.max_length, self.CLOSE_MARGIN)

    def __len__(self) -> int:
        return len(self.rows)

    def __getitem__(self, idx: int) -> Dict[str, np.ndarray]:
        segs = [self._example(i) for i in self.rows[idx]]
        L = self.max_length
        input_ids = np.full(L, self.pad_token_id, np.int32)
        labels = np.full(L, IGNORE_INDEX, np.int32)
        attention_mask = np.zeros(L, np.int32)
        segment_ids = np.zeros(L, np.int32)  # 0 = padding segment
        pos = 0
        for si, ex in enumerate(segs, start=1):
            n = ex["input_ids"].shape[0]
            input_ids[pos:pos + n] = ex["input_ids"]
            labels[pos:pos + n] = ex["labels"]
            # the next-token shift would otherwise train segment i's last
            # token to predict segment i+1's first token
            labels[pos] = IGNORE_INDEX
            attention_mask[pos:pos + n] = 1
            segment_ids[pos:pos + n] = si
            pos += n
        return {
            "input_ids": input_ids,
            "attention_mask": attention_mask,
            "labels": labels,
            "segment_ids": segment_ids,
        }

    def collate(self, batch: Sequence[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
        return {k: np.stack([ex[k] for ex in batch]) for k in batch[0]}

    def packing_efficiency(self) -> float:
        """Fraction of token slots holding real tokens (1.0 = perfect)."""
        total = len(self.rows) * self.max_length
        return int(self.lengths.sum()) / max(total, 1)
