"""Sequence packing: fill fixed-length rows with multiple examples.

Implements for real the ``data.packing: true`` config key the reference
declares but never wires (config/sft_config.yaml:16, SURVEY.md sec 2.5).
Packed rows carry ``segment_ids``; the transformer masks cross-segment
attention and restarts positions per segment
(dla_tpu.models.transformer.Transformer.hidden_states), so packing is
loss-equivalent to unpacked batching while filling the pad FLOPs that
fixed-shape batching would otherwise waste.
"""
from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from dla_tpu.data.datasets import IGNORE_INDEX


class PackedInstructionDataset:
    """Greedy first-fit packing of tokenized instruction examples into rows
    of exactly ``max_length`` tokens. Presents the same dataset protocol
    (__len__/__getitem__/collate) as InstructionDataset, so it is a drop-in
    for the trainer's iterator."""

    def __init__(self, base, max_length: int):
        """``base``: an InstructionDataset (or anything yielding dicts with
        input_ids/attention_mask/labels 1-D arrays)."""
        self.max_length = max_length
        self.pad_token_id = base.tokenizer.pad_token_id
        self.rows: List[List[Dict[str, np.ndarray]]] = []
        open_rows: List[int] = []   # indices into self.rows still open
        lengths: List[int] = []
        for i in range(len(base)):
            ex = base[i]
            n = int(ex["input_ids"].shape[0])
            if n > max_length:
                ex = {k: v[:max_length] for k, v in ex.items()}
                n = max_length
            placed = False
            for open_i in open_rows:
                if lengths[open_i] + n <= max_length:
                    self.rows[open_i].append(ex)
                    lengths[open_i] += n
                    placed = True
                    break
            if not placed:
                self.rows.append([ex])
                lengths.append(n)
                open_rows.append(len(self.rows) - 1)
            # close rows that cannot take even a tiny example
            open_rows = [r for r in open_rows if lengths[r] + 8 <= max_length]

    def __len__(self) -> int:
        return len(self.rows)

    def __getitem__(self, idx: int) -> Dict[str, np.ndarray]:
        segs = self.rows[idx]
        L = self.max_length
        input_ids = np.full(L, self.pad_token_id, np.int32)
        labels = np.full(L, IGNORE_INDEX, np.int32)
        attention_mask = np.zeros(L, np.int32)
        segment_ids = np.zeros(L, np.int32)  # 0 = padding segment
        pos = 0
        for si, ex in enumerate(segs, start=1):
            n = ex["input_ids"].shape[0]
            input_ids[pos:pos + n] = ex["input_ids"]
            labels[pos:pos + n] = ex["labels"]
            # the next-token shift would otherwise train segment i's last
            # token to predict segment i+1's first token
            labels[pos] = IGNORE_INDEX
            attention_mask[pos:pos + n] = 1
            segment_ids[pos:pos + n] = si
            pos += n
        return {
            "input_ids": input_ids,
            "attention_mask": attention_mask,
            "labels": labels,
            "segment_ids": segment_ids,
        }

    def collate(self, batch: Sequence[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
        return {k: np.stack([ex[k] for ex in batch]) for k in batch[0]}

    def packing_efficiency(self) -> float:
        """Fraction of token slots holding real tokens (1.0 = perfect)."""
        total = len(self.rows) * self.max_length
        used = sum(sum(int(e["input_ids"].shape[0]) for e in row)
                   for row in self.rows)
        return used / max(total, 1)
