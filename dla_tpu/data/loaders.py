"""Record loaders + dataset builders: local JSONL and HF-hub sources with
the reference's preset schema (source/hf_path/hf_name/split/columns/
template/limit — reference src/data/datasets.py:232-315, presets under
config/data_sources/)."""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from dla_tpu.data.datasets import (
    InstructionDataset,
    PreferenceDataset,
    TeacherRolloutDataset,
)
from dla_tpu.data.jsonl import read_jsonl
from dla_tpu.data.tokenizers import Tokenizer


def _hf_rows(cfg: Dict[str, Any], split: str):
    from datasets import load_dataset  # local import: optional heavy dep
    split_name = cfg.get(f"{split}_split") or cfg.get("split", split)
    return load_dataset(cfg["hf_path"], cfg.get("hf_name"), split=split_name)


def _apply_limit(records: List[Dict[str, Any]], limit) -> List[Dict[str, Any]]:
    return records[: int(limit)] if limit else records


def load_instruction_records(cfg: Dict[str, Any],
                             split: str = "train") -> List[Dict[str, Any]]:
    """{prompt, response} records from a local JSONL or an HF dataset with
    column remapping and optional prompt template."""
    if cfg.get("source", "local") == "hf":
        cols = cfg.get("columns", {})
        pk = cols.get("prompt", "prompt")
        rk = cols.get("response", "response")
        template = cfg.get("template")
        records = []
        for row in _hf_rows(cfg, split):
            prompt = template.format(**row) if template else row[pk]
            records.append({"prompt": prompt, "response": row[rk]})
    else:
        path = cfg.get(f"{split}_path")
        if path is None and split == "train":
            path = cfg.get("path")
        if path is None:
            # never silently fall back to the training file for eval
            raise ValueError(f"No {split}_path in data config")
        records = read_jsonl(path)
    return _apply_limit(records, cfg.get("limit"))


def load_preference_records(cfg: Dict[str, Any],
                            split: str = "train") -> List[Dict[str, Any]]:
    """{prompt, chosen, rejected} records; same source rules."""
    if cfg.get("source", "local") == "hf":
        cols = cfg.get("columns", {})
        pk = cols.get("prompt", "prompt")
        ck = cols.get("chosen", "chosen")
        rk = cols.get("rejected", "rejected")
        template = cfg.get("template")
        records = []
        for row in _hf_rows(cfg, split):
            prompt = template.format(**row) if template else row[pk]
            records.append(
                {"prompt": prompt, "chosen": row[ck], "rejected": row[rk]})
    else:
        path = cfg.get(f"{split}_path")
        if path is None and split == "train":
            path = cfg.get("path") or cfg.get("preference_path")
        if path is None:
            raise ValueError(f"No {split}_path in data config")
        records = read_jsonl(path)
    return _apply_limit(records, cfg.get("limit"))


def load_prompt_records(cfg: Dict[str, Any],
                        split: str = "train") -> List[str]:
    """Bare prompt strings for RLHF rollouts (reference train_rlhf.py:34-47:
    HF source with prompt_key, else local JSONL with 'prompt')."""
    if cfg.get("source", "local") == "hf":
        pk = cfg.get("prompt_key", "prompt")
        rows = _hf_rows(cfg, split)
        prompts = [row[pk] for row in rows]
    else:
        path = cfg.get("prompt_path") or cfg.get("path")
        if path is None:
            raise ValueError("No prompt_path/path in sampling config")
        prompts = [r["prompt"] for r in read_jsonl(path)]
    return [p for p in _apply_limit(prompts, cfg.get("limit")) if p]


def build_instruction_dataset(cfg: Dict[str, Any], tokenizer: Tokenizer,
                              split: str = "train") -> InstructionDataset:
    return InstructionDataset(
        tokenizer=tokenizer,
        max_length=int(cfg.get("max_length", cfg.get("max_seq_length", 2048))),
        mask_prompt=bool(cfg.get("mask_prompt", True)),
        records=load_instruction_records(cfg, split),
    )


def build_preference_dataset(cfg: Dict[str, Any], tokenizer: Tokenizer,
                             split: str = "train") -> PreferenceDataset:
    return PreferenceDataset(
        tokenizer=tokenizer,
        max_length=int(cfg.get("max_length", cfg.get("max_seq_length", 1024))),
        records=load_preference_records(cfg, split),
    )


def build_teacher_dataset(cfg: Dict[str, Any], tokenizer: Tokenizer,
                          ) -> TeacherRolloutDataset:
    return TeacherRolloutDataset(
        tokenizer=tokenizer,
        max_length=int(cfg.get("max_length", cfg.get("max_seq_length", 2048))),
        path=cfg.get("teacher_samples_path") or cfg.get("path"),
    )
