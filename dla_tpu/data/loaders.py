"""Record loaders + dataset builders: local JSONL and HF-hub sources with
the reference's preset schema (source/hf_path/hf_name/split/columns/
template/limit — reference src/data/datasets.py:232-315, presets under
config/data_sources/)."""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from dla_tpu.data.datasets import (
    InstructionDataset,
    PreferenceDataset,
    TeacherRolloutDataset,
)
from dla_tpu.data.jsonl import read_jsonl
from dla_tpu.data.tokenizers import Tokenizer


def _hf_rows(cfg: Dict[str, Any], split: str):
    from datasets import load_dataset  # local import: optional heavy dep
    split_name = cfg.get(f"{split}_split") or cfg.get("split", split)
    return load_dataset(cfg["hf_path"], cfg.get("hf_name"), split=split_name)


def _apply_limit(records: List[Dict[str, Any]], limit) -> List[Dict[str, Any]]:
    return records[: int(limit)] if limit else records


def _load_mixture(cfg: Dict[str, Any], split: str, loader
                  ) -> List[Dict[str, Any]]:
    """Weighted multi-source mixture (beyond-reference capability; the
    reference is strictly single-source per run, src/data/datasets.py).

    ``data.mixture`` is a list of per-source config fragments, each with
    an optional ``weight`` (default 1.0); fragments inherit the outer
    data config's keys (template, limit, max_length stay shared unless
    overridden). The epoch holds ``data.mixture_size`` records (default:
    combined size of all sources), apportioned to sources by weight
    (largest-remainder, so counts sum exactly); undersized sources
    repeat deterministically after a seeded shuffle. The final epoch
    order is shuffled with ``data.mixture_seed`` (default 0) so the
    interleave is reproducible across hosts and resumes.
    """
    import random as _random

    entries = cfg["mixture"]
    if not entries:
        raise ValueError("data.mixture is empty")
    # entries inherit shared shaping keys (limit, template, max_length)
    # but NEVER the outer source selection — otherwise a local-path entry
    # under an outer `source: hf` config would silently load the outer
    # HF dataset instead of its own JSONL
    _source_keys = ("source", "hf_path", "hf_name", "path", "train_path",
                    "eval_path", "prompt_path", "preference_path", "split",
                    "train_split", "eval_split", "columns")
    outer = {k: v for k, v in cfg.items()
             if k not in ("mixture", "mixture_size", "mixture_seed")
             and k not in _source_keys}
    per = [loader({**outer, **e}, split) for e in entries]
    for e, recs in zip(entries, per):
        if not recs:
            raise ValueError(f"mixture source produced no records: {e}")
    weights = [max(0.0, float(e.get("weight", 1.0))) for e in entries]
    wsum = sum(weights)
    if wsum <= 0:
        raise ValueError("mixture weights sum to zero")
    total = int(cfg.get("mixture_size", sum(len(r) for r in per)))
    # largest-remainder apportionment: counts sum to exactly `total`
    quotas = [w / wsum * total for w in weights]
    counts = [int(q) for q in quotas]
    rema = sorted(range(len(quotas)), key=lambda i: quotas[i] - counts[i],
                  reverse=True)
    for i in rema[: total - sum(counts)]:
        counts[i] += 1

    seed = int(cfg.get("mixture_seed", 0))
    out: List[Dict[str, Any]] = []
    for si, (recs, n) in enumerate(zip(per, counts)):
        order = list(range(len(recs)))
        _random.Random(f"{seed}:src{si}").shuffle(order)
        out.extend(recs[order[i % len(recs)]] for i in range(n))
    _random.Random(f"{seed}:epoch").shuffle(out)
    return out


def load_instruction_records(cfg: Dict[str, Any],
                             split: str = "train") -> List[Dict[str, Any]]:
    """{prompt, response} records from a local JSONL or an HF dataset with
    column remapping and optional prompt template; ``data.mixture``
    composes several such sources by weight."""
    if cfg.get("mixture") and split == "train":
        # the mixture weights/resampling shape the TRAINING epoch only;
        # eval stays the outer config's single held-out set (weighted
        # oversampling of an eval file would duplicate rows and skew the
        # metric)
        return _load_mixture(cfg, split, load_instruction_records)
    if cfg.get("source", "local") == "hf":
        cols = cfg.get("columns", {})
        pk = cols.get("prompt", "prompt")
        rk = cols.get("response", "response")
        template = cfg.get("template")
        records = []
        for row in _hf_rows(cfg, split):
            prompt = template.format(**row) if template else row[pk]
            records.append({"prompt": prompt, "response": row[rk]})
    else:
        path = cfg.get(f"{split}_path")
        if path is None and split == "train":
            path = cfg.get("path")
        if path is None:
            # never silently fall back to the training file for eval
            raise ValueError(f"No {split}_path in data config")
        records = read_jsonl(path)
    return _apply_limit(records, cfg.get("limit"))


def load_preference_records(cfg: Dict[str, Any],
                            split: str = "train") -> List[Dict[str, Any]]:
    """{prompt, chosen, rejected} records; same source rules (incl.
    ``data.mixture``, train split only)."""
    if cfg.get("mixture") and split == "train":
        return _load_mixture(cfg, split, load_preference_records)
    if cfg.get("source", "local") == "hf":
        cols = cfg.get("columns", {})
        pk = cols.get("prompt", "prompt")
        ck = cols.get("chosen", "chosen")
        rk = cols.get("rejected", "rejected")
        template = cfg.get("template")
        records = []
        for row in _hf_rows(cfg, split):
            prompt = template.format(**row) if template else row[pk]
            records.append(
                {"prompt": prompt, "chosen": row[ck], "rejected": row[rk]})
    else:
        path = cfg.get(f"{split}_path")
        if path is None and split == "train":
            path = cfg.get("path") or cfg.get("preference_path")
        if path is None:
            raise ValueError(f"No {split}_path in data config")
        records = read_jsonl(path)
    return _apply_limit(records, cfg.get("limit"))


def load_prompt_records(cfg: Dict[str, Any],
                        split: str = "train") -> List[str]:
    """Bare prompt strings for RLHF rollouts (reference train_rlhf.py:34-47:
    HF source with prompt_key, else local JSONL with 'prompt')."""
    if cfg.get("source", "local") == "hf":
        pk = cfg.get("prompt_key", "prompt")
        rows = _hf_rows(cfg, split)
        prompts = [row[pk] for row in rows]
    else:
        path = cfg.get("prompt_path") or cfg.get("path")
        if path is None:
            raise ValueError("No prompt_path/path in sampling config")
        prompts = [r["prompt"] for r in read_jsonl(path)]
    return [p for p in _apply_limit(prompts, cfg.get("limit")) if p]


def build_instruction_dataset(cfg: Dict[str, Any], tokenizer: Tokenizer,
                              split: str = "train") -> InstructionDataset:
    return InstructionDataset(
        tokenizer=tokenizer,
        max_length=int(cfg.get("max_length", cfg.get("max_seq_length", 2048))),
        mask_prompt=bool(cfg.get("mask_prompt", True)),
        records=load_instruction_records(cfg, split),
    )


def build_preference_dataset(cfg: Dict[str, Any], tokenizer: Tokenizer,
                             split: str = "train") -> PreferenceDataset:
    return PreferenceDataset(
        tokenizer=tokenizer,
        max_length=int(cfg.get("max_length", cfg.get("max_seq_length", 1024))),
        records=load_preference_records(cfg, split),
    )


def build_teacher_dataset(cfg: Dict[str, Any], tokenizer: Tokenizer,
                          ) -> TeacherRolloutDataset:
    return TeacherRolloutDataset(
        tokenizer=tokenizer,
        max_length=int(cfg.get("max_length", cfg.get("max_seq_length", 2048))),
        path=cfg.get("teacher_samples_path") or cfg.get("path"),
    )
