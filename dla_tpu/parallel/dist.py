"""Multi-host rendezvous and process-level helpers.

TPU-native replacement for ``accelerate launch``'s process bootstrap
(reference config/accelerate_config.yaml: MULTI_GPU, num_processes 8,
static rendezvous on port 29500). On TPU pods each host runs the same
program; ``jax.distributed.initialize`` wires the coordination service and
``jax.devices()`` then spans the whole slice. Collectives ride ICI within
a slice and DCN across slices — chosen by XLA from the mesh layout, not by
us.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax

_INITIALIZED = False


def initialize_distributed(hardware_cfg: Optional[Dict[str, Any]] = None) -> None:
    """Initialize multi-host JAX if requested / detectable; idempotent.

    Config keys (all optional, under ``hardware:``):
      coordinator_address: "host:port" of process 0
      num_processes:       world size (reference key reused; on TPU this is
                           the host count, not the chip count)
      process_id:          this host's rank

    On single-host (or when nothing is configured and no cloud TPU env is
    present) this is a no-op — jax works out of the box.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    cfg = hardware_cfg or {}
    coord = cfg.get("coordinator_address") or os.environ.get("DLA_COORDINATOR")
    if coord:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(cfg.get("num_processes",
                                      os.environ.get("DLA_NUM_PROCESSES", 1))),
            process_id=int(cfg.get("process_id",
                                   os.environ.get("DLA_PROCESS_ID", 0))),
        )
        _INITIALIZED = True
    elif os.environ.get("TPU_WORKER_HOSTNAMES") and cfg.get("auto_initialize", False):
        # Cloud TPU pod: zero-arg initialize discovers topology from metadata.
        jax.distributed.initialize()
        _INITIALIZED = True


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_main_process() -> bool:
    """Rank-0 predicate for logging/IO (reference utils.py:105-107 log_rank_zero)."""
    return jax.process_index() == 0


def log_main(*args: Any) -> None:
    if is_main_process():
        print(*args, flush=True)


def barrier(name: str = "barrier") -> None:
    """Cross-host barrier (reference: accelerator.wait_for_everyone,
    train_rlhf.py:164)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(name)


def allgather_floats(row) -> "np.ndarray":
    """Gather one small float row from every host: [k] -> [hosts, k].

    The telemetry aggregation path (telemetry.aggregate) rides this at
    log cadence; it is a rendezvous, so every host must call it at the
    same point. Single-process returns the row as [1, k] with no
    collective at all.
    """
    import numpy as np
    arr = np.asarray(row, dtype=np.float64)
    if jax.process_count() == 1:
        return arr[None, :]
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(arr))
