"""Multi-host rendezvous and process-level helpers.

TPU-native replacement for ``accelerate launch``'s process bootstrap
(reference config/accelerate_config.yaml: MULTI_GPU, num_processes 8,
static rendezvous on port 29500). On TPU pods each host runs the same
program; ``jax.distributed.initialize`` wires the coordination service and
``jax.devices()`` then spans the whole slice. Collectives ride ICI within
a slice and DCN across slices — chosen by XLA from the mesh layout, not by
us.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional, Sequence

import jax

_INITIALIZED = False

# Default collective deadline (seconds) + suspect resolver, armed by the
# elastic GangMonitor: a hung collective then surfaces as a typed
# CollectiveTimeout naming the stale rank(s) instead of 40 identical
# stuck stacks. None = unbounded (the pre-elastic behavior).
_DEADLINE_S: Optional[float] = None
_SUSPECTS: Optional[Callable[[], Sequence[int]]] = None


class CollectiveTimeout(RuntimeError):
    """A cross-host collective exceeded its deadline — some peer never
    arrived. ``suspects`` carries the rank(s) whose heartbeat lease was
    stale when the deadline fired (empty when no resolver is armed)."""

    def __init__(self, name: str, deadline_s: float,
                 suspects: Sequence[int] = ()):
        self.name = name
        self.deadline_s = float(deadline_s)
        self.suspects = tuple(suspects)
        sus = (f"; suspect rank(s): {list(self.suspects)}"
               if self.suspects else "")
        super().__init__(
            f"collective {name!r} exceeded its {self.deadline_s:.1f}s "
            f"deadline{sus}")


def set_collective_deadline(
        seconds: Optional[float],
        suspects: Optional[Callable[[], Sequence[int]]] = None) -> None:
    """Arm a default deadline for :func:`barrier` / :func:`allgather_floats`
    (the elastic path ties it to the gang lease TTL). ``suspects`` is a
    zero-arg callable returning the currently-stale ranks — typically
    ``GangMonitor.stale_ranks`` — consulted only when a timeout fires.
    ``None`` seconds disarms."""
    global _DEADLINE_S, _SUSPECTS
    # dla: disable=host-sync-in-hot-loop -- config scalar coercion; armed once at fit entry, not per step
    _DEADLINE_S = float(seconds) if seconds else None
    _SUSPECTS = suspects


def clear_collective_deadline() -> None:
    set_collective_deadline(None, None)


def _resolve_suspects() -> Sequence[int]:
    if _SUSPECTS is None:
        return ()
    try:
        return tuple(_SUSPECTS())
    except Exception:  # noqa: BLE001 — attribution must not mask the timeout
        return ()


def _run_with_deadline(fn: Callable[[], Any], name: str,
                       deadline_s: float) -> Any:
    """Run a (potentially hanging) collective under a wall-clock bound.

    The collective runs on a daemon worker thread; on timeout the thread
    is abandoned — a hung rendezvous cannot be cancelled, only orphaned —
    and :class:`CollectiveTimeout` raises on the caller with the suspect
    ranks resolved at that instant. The caller is expected to exit the
    process (ElasticRestart), so the orphan never outlives the run."""
    out: Dict[str, Any] = {}
    done = threading.Event()

    def _call() -> None:
        try:
            out["value"] = fn()
        except BaseException as exc:  # noqa: BLE001 — re-raised on caller
            out["error"] = exc
        finally:
            done.set()

    t = threading.Thread(target=_call, name=f"dla-collective-{name}",
                         daemon=True)
    t.start()
    if not done.wait(deadline_s):
        raise CollectiveTimeout(name, deadline_s, _resolve_suspects())
    t.join()
    if "error" in out:
        raise out["error"]
    return out.get("value")


def initialize_distributed(hardware_cfg: Optional[Dict[str, Any]] = None) -> None:
    """Initialize multi-host JAX if requested / detectable; idempotent.

    Config keys (all optional, under ``hardware:``):
      coordinator_address: "host:port" of process 0
      num_processes:       world size (reference key reused; on TPU this is
                           the host count, not the chip count)
      process_id:          this host's rank

    On single-host (or when nothing is configured and no cloud TPU env is
    present) this is a no-op — jax works out of the box.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    cfg = hardware_cfg or {}
    coord = cfg.get("coordinator_address") or os.environ.get("DLA_COORDINATOR")
    if coord:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(cfg.get("num_processes",
                                      os.environ.get("DLA_NUM_PROCESSES", 1))),
            process_id=int(cfg.get("process_id",
                                   os.environ.get("DLA_PROCESS_ID", 0))),
        )
        _INITIALIZED = True
    elif os.environ.get("TPU_WORKER_HOSTNAMES") and cfg.get("auto_initialize", False):
        # Cloud TPU pod: zero-arg initialize discovers topology from metadata.
        jax.distributed.initialize()
        _INITIALIZED = True


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_main_process() -> bool:
    """Rank-0 predicate for logging/IO (reference utils.py:105-107 log_rank_zero)."""
    return jax.process_index() == 0


def log_main(*args: Any) -> None:
    if is_main_process():
        print(*args, flush=True)


def barrier(name: str = "barrier",
            deadline_s: Optional[float] = None) -> None:
    """Cross-host barrier (reference: accelerator.wait_for_everyone,
    train_rlhf.py:164). ``deadline_s`` (or the armed module default)
    bounds the rendezvous: past it, :class:`CollectiveTimeout` raises
    with the stale rank(s) attributed instead of hanging forever."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils
    deadline = deadline_s if deadline_s is not None else _DEADLINE_S
    if deadline:
        _run_with_deadline(
            lambda: multihost_utils.sync_global_devices(name),
            name, deadline)
    else:
        multihost_utils.sync_global_devices(name)


def allgather_floats(row, deadline_s: Optional[float] = None) -> "np.ndarray":
    """Gather one small float row from every host: [k] -> [hosts, k].

    The telemetry aggregation path (telemetry.aggregate) rides this at
    log cadence; it is a rendezvous, so every host must call it at the
    same point. Single-process returns the row as [1, k] with no
    collective at all. ``deadline_s`` (or the armed module default)
    bounds the rendezvous like :func:`barrier`.
    """
    import numpy as np
    arr = np.asarray(row, dtype=np.float64)
    if jax.process_count() == 1:
        return arr[None, :]
    from jax.experimental import multihost_utils

    def _gather() -> "np.ndarray":
        return np.asarray(multihost_utils.process_allgather(arr))

    deadline = deadline_s if deadline_s is not None else _DEADLINE_S
    if deadline:
        return _run_with_deadline(_gather, "allgather_floats", deadline)
    return _gather()
