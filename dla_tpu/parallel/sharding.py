"""Sharding helpers: the discipline that every array has a NamedSharding.

Replaces the reference's implicit ZeRO-3/FSDP parameter sharding
(DeepSpeedPlugin / FullyShardedDataParallelPlugin, reference
src/training/utils.py:62-65): here sharding is declarative — a
PartitionSpec pytree mirrors the param pytree, and GSPMD emits the
all-gather / reduce-scatter collectives the DeepSpeed engine performs
imperatively.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Pytree = Any


def named_sharding(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)


def batch_spec(extra_dims: int = 1) -> P:
    """Spec for a [batch, ...] array: batch split over both batch axes."""
    return P(("data", "fsdp"), *([None] * extra_dims))


def prune_spec_for_mesh(spec: P, mesh: Mesh) -> P:
    """Drop mesh axes of size 1 from a spec (no-op axes confuse nothing, but
    pruning keeps HLO sharding annotations minimal)."""
    def prune_entry(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if mesh.shape.get(a, 1) > 1)
            if not kept:
                return None
            return kept if len(kept) > 1 else kept[0]
        return entry if mesh.shape.get(entry, 1) > 1 else None

    return P(*(prune_entry(e) for e in spec))


def shard_pytree(tree: Pytree, spec_tree: Pytree, mesh: Mesh) -> Pytree:
    """device_put every leaf with its NamedSharding (specs pruned for mesh)."""
    def place(x, spec):
        s = NamedSharding(mesh, prune_spec_for_mesh(spec, mesh))
        return jax.device_put(x, s)

    return jax.tree.map(place, tree, spec_tree,
                        is_leaf=lambda x: x is None)


def sharding_tree(spec_tree: Pytree, mesh: Mesh) -> Pytree:
    """PartitionSpec pytree -> NamedSharding pytree."""
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, prune_spec_for_mesh(spec, mesh)),
        spec_tree,
        is_leaf=lambda x: isinstance(x, P) or x is None,
    )


def with_constraint(x: Pytree, spec: P) -> Pytree:
    """``lax.with_sharding_constraint`` that is a no-op outside a mesh context."""
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except (ValueError, RuntimeError):
        return x


def fully_replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def host_local_batch_size(global_batch: int, mesh: Mesh) -> int:
    """Per-process slice of the global batch (for multi-host data loading).

    Replaces the reference's DistributedSampler rank arithmetic
    (src/training/utils.py:110-118).
    """
    n_proc = jax.process_count()
    if global_batch % n_proc != 0:
        raise ValueError(
            f"global batch {global_batch} not divisible by process count {n_proc}")
    return global_batch // n_proc


def local_numpy(arr) -> "np.ndarray":
    """Bring this host's slice of a batch-sharded global array to host
    numpy (inverse of make_global_batch). Single-host: the whole array.
    Multi-host: the addressable rows, deduped across replica shards and
    ordered by global offset."""
    import numpy as np
    if jax.process_count() == 1:
        return np.asarray(arr)
    by_start = {}
    for shard in arr.addressable_shards:
        idx = shard.index[0]
        start = idx.start or 0
        by_start.setdefault(start, np.asarray(shard.data))
    return np.concatenate(
        [by_start[s] for s in sorted(by_start)], axis=0)


def make_global_batch(local_arrays: Pytree, mesh: Mesh, spec: Optional[P] = None) -> Pytree:
    """Assemble per-host numpy batches into globally-sharded jax.Arrays.

    Single-host: a device_put with the batch sharding. Multi-host: uses
    ``jax.make_array_from_process_local_data`` so each host contributes its
    slice without any gather through host 0.
    """
    def place(x):
        s = NamedSharding(
            mesh, prune_spec_for_mesh(
                spec if spec is not None else batch_spec(x.ndim - 1), mesh))
        if jax.process_count() == 1:
            return jax.device_put(x, s)
        return jax.make_array_from_process_local_data(s, x)

    return jax.tree.map(place, local_arrays)
