from dla_tpu.parallel.mesh import MeshConfig, build_mesh, mesh_from_config
from dla_tpu.parallel.sharding import (
    batch_spec,
    named_sharding,
    shard_pytree,
    with_constraint,
)

__all__ = [
    "MeshConfig",
    "build_mesh",
    "mesh_from_config",
    "batch_spec",
    "named_sharding",
    "shard_pytree",
    "with_constraint",
]
