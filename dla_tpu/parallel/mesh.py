"""Device-mesh construction — the distributed backbone of the framework.

This module is the TPU-native replacement for the reference's entire
distributed-orchestration layer (HF Accelerate process groups + DeepSpeed
ZeRO-3 + NCCL; reference src/training/utils.py:55-75 and
config/accelerate_config.yaml). There is no NCCL-analog code to write: we
construct a ``jax.sharding.Mesh`` and annotate arrays with
``NamedSharding``; GSPMD inserts the ICI collectives.

Axis semantics:
  stage     pipeline parallelism (layer stack split into stages; GPipe
            microbatch schedule, activations collective-permuted between
            stages — the point-to-point pattern that rides DCN well, so
            it is the OUTERMOST axis for multi-slice scale-out)
  data      pure data parallelism (batch split; grads psum-ed by XLA)
  fsdp      ZeRO-3-equivalent: parameters/opt-state sharded on this axis,
            all-gathered per-layer on use; also acts as a batch axis
  model     tensor parallelism (attention heads / MLP hidden dim)
  sequence  context parallelism (ring attention / long-context)

The reference's ZeRO-3 stage-3 (config/deepspeed_zero3.json:6) maps to
``fsdp > 1``; its plain DDP maps to ``data > 1``; TP/PP/CP have no
reference equivalent (SURVEY.md sec 2.3) and are new capability.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("stage", "data", "fsdp", "model", "sequence", "expert")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical mesh shape. -1 on exactly one axis means "absorb remaining devices"."""

    stage: int = 1
    data: int = 1
    fsdp: int = -1
    model: int = 1
    sequence: int = 1
    # reserved for expert parallelism (MoE). The reference is dense-only
    # (SURVEY.md sec 2.3 EP row: "reserve an expert axis, don't
    # implement"); the axis exists so configs and partition specs have a
    # stable name the day MoE layers land, but nothing shards over it yet.
    expert: int = 1

    @classmethod
    def from_dict(cls, cfg: Optional[Dict[str, Any]]) -> "MeshConfig":
        cfg = cfg or {}
        return cls(
            stage=int(cfg.get("stage", 1)),
            data=int(cfg.get("data", 1)),
            fsdp=int(cfg.get("fsdp", -1)),
            model=int(cfg.get("model", 1)),
            sequence=int(cfg.get("sequence", 1)),
            expert=int(cfg.get("expert", 1)),
        )

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = {"stage": self.stage, "data": self.data, "fsdp": self.fsdp,
                 "model": self.model, "sequence": self.sequence,
                 "expert": self.expert}
        wild = [k for k, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"At most one mesh axis may be -1, got {wild}")
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}")
            sizes[wild[0]] = n_devices // fixed
        if math.prod(sizes.values()) != n_devices:
            raise ValueError(
                f"Mesh {sizes} does not cover {n_devices} devices")
        return sizes


def auto_axes(mesh) -> set:
    """Axes of ``mesh`` not already manualized by an enclosing shard_map.

    The one definition of "which axes may this op still shard_map over":
    ring/ulysses CP and the flash wrapper all nest partial-manual inside
    the pipeline's stage schedule, and each must exclude the axes the
    enclosing scope already made manual. A concrete ``jax.sharding.Mesh``
    has no ``manual_axes`` — everything is auto there."""
    manual = set(getattr(mesh, "manual_axes", ()) or ())
    return {a for a in mesh.shape if a not in manual}


def build_mesh(
    mesh_config: Optional[MeshConfig] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh over the given (default: all) devices.

    Axis order is (stage, data, fsdp, model, sequence, expert): the
    innermost axes (model, sequence) get adjacent devices, which on real
    TPU topologies keeps TP/CP collectives on the shortest ICI paths;
    stage is outermost so pipeline hops land on the outer (possibly DCN)
    dimension where point-to-point traffic is the right pattern.
    """
    mesh_config = mesh_config or MeshConfig()
    if devices is None:
        devices = jax.devices()
    sizes = mesh_config.resolve(len(devices))
    dev_array = np.asarray(devices).reshape(
        [sizes[a] for a in AXES])
    return Mesh(dev_array, AXES)


def mesh_from_config(hardware_cfg: Optional[Dict[str, Any]] = None,
                     devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a mesh from a config ``hardware:`` block.

    Understands the new ``hardware.mesh: {data,fsdp,model,sequence}`` block
    and tolerates the reference's GPU-era keys (``deepspeed_config``,
    ``fsdp``, ``mixed_precision``, ``num_processes``) by ignoring them —
    parity requirement from SURVEY.md sec 7 (config surface must keep
    launching runs).
    """
    hardware_cfg = hardware_cfg or {}
    mc = MeshConfig.from_dict(hardware_cfg.get("mesh"))
    return build_mesh(mc, devices=devices)


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis]


def data_parallel_size(mesh: Mesh) -> int:
    """Number of distinct batch shards: data * fsdp (both are batch axes)."""
    return mesh.shape["data"] * mesh.shape["fsdp"]
