"""Serving subsystem tests: page allocator invariants, scheduler state
machine (admission, bucketing, eviction-recompute, no leaks), and the
load-bearing e2e guarantees — paged decode is TOKEN-IDENTICAL to the
contiguous GenerationEngine path, and mid-decode arrivals never
recompile the decode step."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dla_tpu.generation.engine import GenerationConfig, build_generate_fn
from dla_tpu.models.config import get_model_config
from dla_tpu.models.transformer import Transformer
from dla_tpu.serving import (
    PageAllocator,
    PagedKVCache,
    PageGeometry,
    Request,
    RequestState,
    Scheduler,
    SchedulerConfig,
    ServingConfig,
    ServingEngine,
)


# ---------------------------------------------------------------------------
# page allocator (pure host, no model)
# ---------------------------------------------------------------------------

def test_allocator_basic_alloc_free():
    a = PageAllocator(8)
    assert a.capacity == 7          # page 0 reserved
    pages = a.alloc(3)
    assert len(pages) == 3 and 0 not in pages
    assert a.used_count == 3 and a.free_count == 4
    a.free(pages)
    assert a.used_count == 0 and a.free_count == 7


def test_allocator_all_or_nothing_exhaustion():
    a = PageAllocator(5)            # capacity 4
    first = a.alloc(3)
    assert first is not None
    assert a.alloc(2) is None       # only 1 free: nothing handed out
    assert a.free_count == 1        # failed alloc left the pool untouched
    assert a.alloc(1) is not None
    assert a.alloc(1) is None
    assert not a.can_alloc(1)


def test_allocator_no_fragmentation_across_interleaving():
    """Fixed-size pages: any alloc/free interleaving keeps every free
    page usable (no external fragmentation)."""
    a = PageAllocator(9)            # capacity 8
    held = [a.alloc(2) for _ in range(4)]
    a.free(held[1])
    a.free(held[3])
    big = a.alloc(4)                # freed pages coalesce trivially
    assert big is not None and len(big) == 4
    assert a.free_count == 0


def test_allocator_double_free_and_trash_page():
    a = PageAllocator(4)
    pages = a.alloc(2)
    a.free(pages)
    with pytest.raises(ValueError):
        a.free(pages)               # double free
    with pytest.raises(ValueError):
        a.free([0])                 # trash page is never allocatable
    seen = set()
    while a.can_alloc(1):
        seen.update(a.alloc(1))
    assert 0 not in seen


# ---------------------------------------------------------------------------
# scheduler state machine (host-only: a model-free cache stand-in)
# ---------------------------------------------------------------------------

class _Cfg:
    num_layers = 1
    num_kv_heads = 1
    head_dim_ = 2


class _ModelStub:
    cfg = _Cfg()
    adtype = jnp.float32


def _sched(page_size=4, num_pages=16, num_slots=2, pages_per_slot=4,
           **cfg_kw):
    geom = PageGeometry(page_size=page_size, num_pages=num_pages,
                        num_slots=num_slots, pages_per_slot=pages_per_slot)
    cache = PagedKVCache(_ModelStub(), geom)
    widths = [page_size, 2 * page_size, geom.slot_window]
    return Scheduler(cache, SchedulerConfig(**cfg_kw), widths), cache


def test_scheduler_admission_binds_slot_and_pages():
    sched, cache = _sched()
    req = Request(prompt_tokens=[1, 2, 3], max_new_tokens=4)
    sched.submit(req)
    batch = sched.next_prefill_batch()
    assert batch == [req]
    assert req.state is RequestState.PREFILL
    assert req.slot is not None
    # 3 tokens -> 4-wide bucket -> 1 prompt page + 1 decode reserve
    assert len(req.pages) == 2
    sched.activate(req)
    assert req.state is RequestState.DECODE
    sched.assert_consistent()
    sched.finish(req, "length")
    assert req.state is RequestState.FINISHED
    assert cache.allocator.used_count == 0
    assert len(sched.free_slots) == cache.geom.num_slots
    sched.assert_consistent()


def test_scheduler_bucketing_head_fixes_bucket():
    """The head's bucket decides the batch; a same-bucket request behind
    a different-bucket one rides along, the different one waits."""
    sched, _ = _sched(num_slots=4, max_prefill_batch=4)
    short1 = Request(prompt_tokens=[1, 2], max_new_tokens=2)        # w=4
    longer = Request(prompt_tokens=list(range(1, 7)), max_new_tokens=2)  # w=8
    short2 = Request(prompt_tokens=[3], max_new_tokens=2)           # w=4
    for r in (short1, longer, short2):
        sched.submit(r)
    batch = sched.next_prefill_batch()
    assert [r.rid for r in batch] == [short1.rid, short2.rid]
    assert list(sched.queue) == [longer]
    batch2 = sched.next_prefill_batch()
    assert batch2 == [longer]


def test_scheduler_rejects_oversized_and_empty():
    sched, _ = _sched()   # slot window = 16
    with pytest.raises(ValueError):
        sched.submit(Request(prompt_tokens=list(range(10)),
                             max_new_tokens=10))
    with pytest.raises(ValueError):
        sched.submit(Request(prompt_tokens=[], max_new_tokens=4))


def test_scheduler_eviction_on_oom_requeues_and_frees():
    """Page exhaustion mid-decode evicts the YOUNGEST running request:
    its pages return to the pool, it re-enters the queue head with its
    generated tokens intact (the recompute contract)."""
    # capacity 5: two requests at 2 pages each fit, growth doesn't
    sched, cache = _sched(num_pages=6, num_slots=2)
    old = Request(prompt_tokens=[1, 2, 3], max_new_tokens=8)
    young = Request(prompt_tokens=[4, 5, 6], max_new_tokens=8)
    sched.submit(old)
    sched.submit(young)
    for req in sched.next_prefill_batch():
        cache.open_slot(req.slot, req.pages, 3, 4, 7)
        sched.activate(req)
    sched.assert_consistent()
    old_slot = old.slot
    # drive the old request's length to column 12: page index 3, two
    # pages past its allocation — the pool has only 1 spare, so the
    # second growth must evict `young`
    for _ in range(9):
        cache.advance_slot(old_slot, 9)
    evicted = sched.ensure_decode_pages()
    assert evicted == [young]
    assert young.state is RequestState.WAITING
    assert young.evictions == 1
    assert young.slot is None and young.pages == []
    assert sched.queue[0] is young           # requeued at the FRONT
    assert young.prefix_tokens == [4, 5, 6]  # prompt kept for recompute
    assert old.state is RequestState.DECODE  # survivor kept running
    sched.assert_consistent()
    sched.finish(old, "length")
    assert cache.allocator.used_count == 0   # no page leaked through OOM
    sched.assert_consistent()


# ---------------------------------------------------------------------------
# e2e on the tiny model
# ---------------------------------------------------------------------------

MAX_NEW = 5


@pytest.fixture(scope="module")
def model_and_params():
    cfg = get_model_config("tiny")
    model = Transformer(cfg)
    return model, model.init(jax.random.key(7))


@pytest.fixture(scope="module")
def reference_tokens(model_and_params):
    """Greedy reference per prompt from the contiguous fixed-batch
    engine — the serving path must reproduce these exactly."""
    model, params = model_and_params
    rs = np.random.RandomState(3)
    prompts = [list(rs.randint(3, 500, (n,))) for n in (6, 4, 9, 5)]
    width = max(len(p) for p in prompts)
    ids = np.zeros((len(prompts), width), np.int32)
    mask = np.zeros_like(ids)
    for i, p in enumerate(prompts):
        ids[i, :len(p)] = p
        mask[i, :len(p)] = 1
    gen = GenerationConfig(max_new_tokens=MAX_NEW, do_sample=False,
                           eos_token_id=2, pad_token_id=0)
    fn = jax.jit(build_generate_fn(model, gen))
    out = fn(params, jnp.asarray(ids), jnp.asarray(mask), jax.random.key(0))
    resp = np.asarray(out["response_tokens"])
    rmask = np.asarray(out["response_mask"])
    ref = [[int(t) for t, m in zip(resp[i], rmask[i]) if m]
           for i in range(len(prompts))]
    return prompts, ref, gen


def _drain(eng):
    results = eng.run_until_drained(max_steps=500)
    eng.scheduler.assert_consistent()
    return results


def test_serving_matches_contiguous_engine(model_and_params,
                                           reference_tokens):
    """THE parity pin: block-paged decode through gather/scatter and the
    static-shape slot batch produces byte-for-byte the tokens of the
    contiguous GenerationEngine decode on the same model."""
    model, params = model_and_params
    prompts, ref, gen = reference_tokens
    eng = ServingEngine(model, params, gen,
                        ServingConfig(page_size=4, num_pages=32,
                                      num_slots=3, max_model_len=32,
                                      max_prefill_batch=2))
    rids = [eng.submit(p, MAX_NEW) for p in prompts]
    results = _drain(eng)
    for i, rid in enumerate(rids):
        assert results[rid].generated == ref[i], f"prompt {i} diverged"
        assert results[rid].state is RequestState.FINISHED
    assert eng.cache.allocator.used_count == 0, "pages leaked after drain"


def test_serving_no_recompile_and_no_leaks_across_arrivals(
        model_and_params, reference_tokens):
    """Mid-decode arrivals land in freed slots without retracing the
    decode step (static shapes), and the page pool drains to empty."""
    model, params = model_and_params
    prompts, ref, gen = reference_tokens
    eng = ServingEngine(model, params, gen,
                        ServingConfig(page_size=4, num_pages=32,
                                      num_slots=2, max_model_len=32,
                                      max_prefill_batch=2))
    # wave 1: two requests saturate both slots
    rids = {eng.submit(p, MAX_NEW): i for i, p in enumerate(prompts[:2])}
    for _ in range(2):
        eng.step()
    assert eng.scheduler.active_count == 2
    # wave 2 arrives mid-decode; admitted only as slots free up
    for i, p in enumerate(prompts[2:], start=2):
        rids[eng.submit(p, MAX_NEW)] = i
        eng.step()
        eng.scheduler.assert_consistent()
    results = _drain(eng)
    for rid, i in rids.items():
        assert results[rid].generated == ref[i], f"prompt {i} diverged"
    assert eng.decode_compiles == 1, (
        f"decode step retraced {eng.decode_compiles}x — static-shape "
        "guarantee broken")
    assert eng.cache.allocator.used_count == 0
    assert len(eng.scheduler.free_slots) == 2
    # prefill compiles once per bucket width used, never per prompt
    widths = {eng.scheduler.bucket_width(len(p)) for p in prompts}
    assert eng.prefill_compiles == len(widths)


def test_serving_eviction_recomputes_identically(model_and_params):
    """A pool sized to force mid-decode preemption: the evicted request
    re-prefills prompt+generated and still lands on the reference
    tokens (greedy recompute is deterministic)."""
    model, params = model_and_params
    rs = np.random.RandomState(11)
    use = [list(rs.randint(3, 500, (4,))) for _ in range(2)]
    gen = GenerationConfig(max_new_tokens=MAX_NEW, do_sample=False,
                           eos_token_id=2, pad_token_id=0)
    fn = jax.jit(build_generate_fn(model, gen))
    ids = np.asarray(use, np.int32)
    out = fn(params, jnp.asarray(ids), jnp.ones_like(jnp.asarray(ids)),
             jax.random.key(0))
    resp = np.asarray(out["response_tokens"])
    rmask = np.asarray(out["response_mask"])
    want = [[int(t) for t, m in zip(resp[i], rmask[i]) if m]
            for i in range(len(use))]
    # capacity 7 pages: both 4-token prompts admit at 3 pages (2 prompt
    # + reserve) but cannot BOTH grow to 9 tokens (5 pages each) ->
    # someone gets preempted mid-decode
    eng = ServingEngine(model, params, gen,
                        ServingConfig(page_size=2, num_pages=8,
                                      num_slots=2, max_model_len=12,
                                      max_prefill_batch=2))
    rids = [eng.submit(p, MAX_NEW) for p in use]
    results = _drain(eng)
    assert eng.metrics.preemptions.value >= 1, (
        "config was meant to force at least one preemption")
    for rid, expect in zip(rids, want):
        req = results[rid]
        assert req.generated == expect, (
            f"eviction recompute diverged (evictions={req.evictions})")
    assert eng.cache.allocator.used_count == 0
    eng.scheduler.assert_consistent()


def test_serving_metrics_surface(model_and_params, reference_tokens):
    model, params = model_and_params
    prompts, _, gen = reference_tokens
    eng = ServingEngine(model, params, gen,
                        ServingConfig(page_size=4, num_pages=32,
                                      num_slots=2, max_model_len=32))
    for p in prompts[:2]:
        eng.submit(p, MAX_NEW)
    _drain(eng)
    snap = eng.metrics.snapshot()
    assert snap["serving/requests_submitted"] == 2.0
    assert snap["serving/requests_finished"] == 2.0
    assert snap["serving/tokens_generated"] == 2.0 * MAX_NEW
    assert snap["serving/ttft_ms_count"] == 2.0
    assert snap["serving/itl_ms_count"] > 0
    assert snap["serving/ttft_ms_p50"] >= 0.0
    assert snap["serving/page_occupancy_peak"] > 0.0
    assert snap["serving/page_occupancy"] == 0.0   # drained


def test_serving_rejects_request_that_can_never_fit(model_and_params):
    model, params = model_and_params
    gen = GenerationConfig(max_new_tokens=4, do_sample=False,
                           eos_token_id=2, pad_token_id=0)
    # pool capacity (3 pages) below one slot's worst-case demand
    eng = ServingEngine(model, params, gen,
                        ServingConfig(page_size=4, num_pages=4,
                                      num_slots=1, max_model_len=32))
    with pytest.raises(ValueError):
        eng.submit(list(range(1, 20)), 8)


# ---------------------------------------------------------------------------
# per-request sampling + streamed logprobs
# ---------------------------------------------------------------------------

def test_serving_greedy_logprobs_match_teacher_forced_rescore(
        model_and_params, reference_tokens):
    """The chosen-token logprobs streamed during decode are log-softmax
    of the RAW logits (pre-temperature/filter): for greedy they must
    equal a teacher-forced re-score of the final sequence through the
    full forward pass."""
    model, params = model_and_params
    prompts, _, gen = reference_tokens
    eng = ServingEngine(model, params, gen,
                        ServingConfig(page_size=4, num_pages=32,
                                      num_slots=3, max_model_len=32,
                                      max_prefill_batch=2))
    rids = [eng.submit(p, MAX_NEW) for p in prompts]
    results = _drain(eng)
    for i, rid in enumerate(rids):
        req = results[rid]
        assert len(req.generated_logprobs) == len(req.generated)
        seq = list(prompts[i]) + list(req.generated)
        logits = np.asarray(model.apply(
            params, jnp.asarray([seq], jnp.int32),
            jnp.ones((1, len(seq)), jnp.int32))[0], np.float64)
        lse = np.log(np.sum(np.exp(
            logits - logits.max(-1, keepdims=True)), -1)) \
            + logits.max(-1)
        for k, (tok, lp) in enumerate(zip(req.generated,
                                          req.generated_logprobs)):
            pos = len(prompts[i]) - 1 + k   # column scoring token k
            want = logits[pos, tok] - lse[pos]
            assert abs(lp - want) < 1e-4, (i, k, lp, want)


def test_serving_per_request_seed_determinism(model_and_params):
    """A request's sampled stream is a pure function of (seed, token
    index): identical across engines, across co-resident requests, and
    distinct for distinct seeds."""
    from dla_tpu.serving import SamplingParams
    model, params = model_and_params
    gen = GenerationConfig(max_new_tokens=MAX_NEW, do_sample=True,
                           temperature=0.9, top_p=0.9, top_k=8,
                           eos_token_id=2, pad_token_id=0)
    prompt = list(range(5, 13))
    sp = SamplingParams(temperature=0.9, top_p=0.9, top_k=8,
                        seed=77, do_sample=True)
    sp2 = SamplingParams(temperature=0.9, top_p=0.9, top_k=8,
                         seed=78, do_sample=True)
    streams = []
    for extra in (sp2, sp):     # engine 2 flips submission order
        eng = ServingEngine(model, params, gen,
                            ServingConfig(page_size=4, num_pages=32,
                                          num_slots=2, max_model_len=32))
        rid = eng.submit(prompt, MAX_NEW, sampling=sp)
        rid_x = eng.submit(prompt, MAX_NEW, sampling=extra)
        results = _drain(eng)
        streams.append((results[rid].generated,
                        results[rid].generated_logprobs,
                        results[rid_x].generated))
    (tok_a, lp_a, x_a), (tok_b, lp_b, x_b) = streams
    assert tok_a == tok_b                  # same seed, different engine
    np.testing.assert_allclose(lp_a, lp_b, atol=1e-5, rtol=0)
    assert x_b == tok_a                    # seed 77 again, other slot
    assert x_a != tok_a                    # seed 78 diverges
