"""Streaming-gateway tests: the HTTP front door (serving/gateway.py)
streams token/logprob/finish SSE events bit-identically — greedy and
explicitly-seeded — to driving the engine in-process, maps
backpressure onto the existing admission machinery (shed -> 429 +
Retry-After, expired deadline -> 408, draining -> 503 + /healthz
flip), cancels and counts requests whose client hung up mid-stream,
binds port=0 to a real ephemeral port with dla-named handler threads,
and the MigrationTicket wire format round-trips bit-identically while
rejecting truncation / bad magic / version skew. The ``net=`` fault
scope parses and fires one-shot like every other scope."""
import http.client
import json
import threading
import time

import jax
import numpy as np
import pytest

from dla_tpu.resilience.faults import FaultPlan
from dla_tpu.serving import (
    MigrationError,
    MigrationTicket,
    RequestState,
    SamplingParams,
    ServingConfig,
    ServingEngine,
    ServingGateway,
    TERMINAL_STATES,
)
from dla_tpu.serving.gateway import GatewayConfig

MAX_NEW = 4
PAGE = 4


@pytest.fixture(scope="module")
def serve_setup():
    from dla_tpu.generation.engine import GenerationConfig
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer
    cfg = get_model_config("tiny")
    model = Transformer(cfg)
    params = model.init(jax.random.key(7))
    gen = GenerationConfig(max_new_tokens=16, do_sample=False,
                           eos_token_id=-1, pad_token_id=0)
    return model, params, gen


def _engine(serve_setup, **cfg_kw):
    model, params, gen = serve_setup
    kw = dict(page_size=PAGE, num_pages=64, num_slots=2,
              max_model_len=32, max_prefill_batch=2, prefill_chunk=PAGE,
              prefix_cache=True, fault_plan="")
    kw.update(cfg_kw)
    return ServingEngine(model, params, gen, ServingConfig(**kw))


def _prompts(n=4, seed=11, length=6):
    rs = np.random.RandomState(seed)
    return [[int(t) for t in rs.randint(3, 500, (length,))]
            for _ in range(n)]


def _open_generate(port, prompt, new_tokens=MAX_NEW, sampling=None,
                   deadline_s=None):
    """POST /v1/generate; returns the live (conn, response)."""
    body = {"prompt": prompt, "max_new_tokens": new_tokens}
    if sampling is not None:
        body["sampling"] = sampling
    if deadline_s is not None:
        body["deadline_s"] = deadline_s
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("POST", "/v1/generate", json.dumps(body).encode(),
                 {"Content-Type": "application/json"})
    return conn, conn.getresponse()


def _read_stream(resp):
    """-> (tokens, logprobs, done_event_dict)."""
    toks, logps, done = [], [], None
    while True:
        line = resp.readline()
        if not line:
            break
        line = line.strip()
        if not line.startswith(b"data: "):
            continue
        ev = json.loads(line[len(b"data: "):])
        if ev.get("done"):
            done = ev
            break
        toks.append(int(ev["token"]))
        logps.append(float(ev["logprob"]))
    return toks, logps, done


def _generate(port, prompt, **kw):
    conn, resp = _open_generate(port, prompt, **kw)
    try:
        assert resp.status == 200, (resp.status, resp.read())
        return _read_stream(resp)
    finally:
        conn.close()


def _slow(eng, delay_s):
    """Pad each engine step so streams stay open long enough for the
    test to act mid-stream (deterministic on any CPU)."""
    orig = eng.step

    def step():
        time.sleep(delay_s)
        return orig()
    eng.step = step
    return eng


# ---------------------------------------------------------------------------
# MigrationTicket wire format (satellite: versioned header + validation)
# ---------------------------------------------------------------------------

def _mid_decode_ticket(serve_setup):
    eng = _engine(serve_setup)
    rid = eng.submit(_prompts(1)[0], 8,
                     sampling=SamplingParams(seed=5, do_sample=True,
                                             temperature=0.9))
    for _ in range(40):
        eng.step()
        if len(eng.result(rid).generated) >= 3:
            break
    return eng.export_request(rid)


def test_ticket_wire_roundtrip_bit_identical(serve_setup):
    ticket = _mid_decode_ticket(serve_setup)
    blob = ticket.to_bytes()
    back = MigrationTicket.from_bytes(blob)
    assert back.rid == ticket.rid
    assert back.prompt_tokens == ticket.prompt_tokens
    assert back.generated == ticket.generated
    assert back.generated_logprobs == pytest.approx(
        ticket.generated_logprobs)
    assert back.sampling == ticket.sampling
    assert back.committed_len == ticket.committed_len
    assert back.n_pages == ticket.n_pages
    k0 = np.asarray(ticket.k_payload)
    v0 = np.asarray(ticket.v_payload)
    k1, v1 = np.asarray(back.k_payload), np.asarray(back.v_payload)
    assert k1.dtype == k0.dtype and k1.shape == k0.shape
    # bit-identity, not tolerance: the payload must survive the wire
    assert k0.tobytes() == k1.tobytes()
    assert v0.tobytes() == v1.tobytes()
    # serialization is pure: a second encode is byte-stable
    assert MigrationTicket.from_bytes(blob).to_bytes() == blob


def test_ticket_wire_rejects_corruption(serve_setup):
    blob = _mid_decode_ticket(serve_setup).to_bytes()
    with pytest.raises(MigrationError, match="truncat"):
        MigrationTicket.from_bytes(blob[:-7])
    with pytest.raises(MigrationError, match="magic"):
        MigrationTicket.from_bytes(b"NOPE" + blob[4:])
    with pytest.raises(MigrationError, match="version"):
        MigrationTicket.from_bytes(blob[:4] + b"\x63\x00" + blob[6:])
    with pytest.raises(MigrationError):
        MigrationTicket.from_bytes(b"")


# ---------------------------------------------------------------------------
# the front door
# ---------------------------------------------------------------------------

def test_gateway_binds_ephemeral_port_with_dla_threads(serve_setup):
    gw = ServingGateway(_slow(_engine(serve_setup), 0.03))
    try:
        assert gw.port != 0
        assert str(gw.port) in gw.url
        done_box = {}

        def client():
            done_box["out"] = _generate(gw.port, _prompts(1)[0],
                                        new_tokens=8)
        t = threading.Thread(target=client, name="dla-test-client",
                             daemon=True)
        t.start()
        # while the stream is live, the server-side threads are visible
        # and every one carries the dla- prefix (docs/ANALYSIS.md thread
        # naming policy — observable at runtime, not just statically)
        deadline = time.monotonic() + 30
        seen = set()
        while time.monotonic() < deadline:
            seen = {th.name for th in threading.enumerate()
                    if th.name.startswith("dla-")}
            if any(n.startswith("dla-http-") for n in seen):
                break
            time.sleep(0.01)
        assert "dla-gateway-engine" in seen
        assert "dla-gateway-http" in seen
        assert any(n.startswith("dla-http-") for n in seen), seen
        t.join(timeout=60)
        toks, logps, done = done_box["out"]
        assert done["state"] == "finished" and len(toks) == 8
    finally:
        gw.close()


def test_gateway_streams_bit_identical_greedy_and_seeded(serve_setup):
    prompts = _prompts(4)
    eng = _engine(serve_setup)
    sp = dict(temperature=0.9, top_p=0.95, top_k=0, seed=123,
              do_sample=True)
    rids = [eng.submit(p, MAX_NEW) for p in prompts]
    rids += [eng.submit(p, MAX_NEW, sampling=SamplingParams(**sp))
             for p in prompts]
    results = eng.run_until_drained(max_steps=5000)
    ref = [(list(results[r].generated),
            [pytest.approx(lp) for lp in results[r].generated_logprobs])
           for r in rids]

    gw = ServingGateway(_engine(serve_setup))
    try:
        wire = [_generate(gw.port, p) for p in prompts]
        wire += [_generate(gw.port, p, sampling=sp) for p in prompts]
        for (toks, logps, done), (rtoks, rlogps) in zip(wire, ref):
            assert toks == rtoks          # bit-identical token stream
            assert logps == rlogps        # per-event logprobs ride along
            assert done["state"] == "finished"
            assert done["n"] == len(toks)
        # the counter is delta-mirrored by the engine loop, so give the
        # next mirror pass a moment to fold in the final event
        expect = sum(len(w[0]) for w in wire)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            snap = gw.metrics.registry.snapshot()
            if snap["serving/gateway/streamed_tokens"] >= expect:
                break
            time.sleep(0.01)
        assert snap["serving/gateway/streamed_tokens"] == expect
    finally:
        gw.close()


def test_gateway_shed_answers_429_with_retry_after(serve_setup):
    # one slot + a one-deep wait queue, slow steps: the third
    # concurrent request overflows admission and sheds
    gw = ServingGateway(
        _slow(_engine(serve_setup, num_slots=1,
                      shed={"max_queue_depth": 1}), 0.05),
        GatewayConfig(retry_after_s=2.5))
    try:
        outs = []

        def client(i):
            conn, resp = _open_generate(gw.port, _prompts(4, seed=i)[0],
                                        new_tokens=8)
            try:
                outs.append((resp.status,
                             resp.getheader("Retry-After"),
                             _read_stream(resp) if resp.status == 200
                             else resp.read()))
            finally:
                conn.close()

        ts = []
        for i in range(4):
            t = threading.Thread(target=client, args=(i,),
                                 name=f"dla-test-shed-{i}", daemon=True)
            ts.append(t)
            t.start()
            time.sleep(0.05)       # ordered arrivals: 3rd+ must shed
        for t in ts:
            t.join(timeout=120)
        statuses = sorted(s for s, _, _ in outs)
        assert 429 in statuses, statuses
        assert statuses.count(200) >= 1
        for s, retry, _ in outs:
            if s == 429:
                assert retry == "2.5"
        expect = statuses.count(429)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            snap = gw.metrics.registry.snapshot()
            if snap["serving/gateway/http_429"] >= expect:
                break
            time.sleep(0.01)
        assert snap["serving/gateway/http_429"] == expect
    finally:
        gw.close()


def test_gateway_expired_deadline_answers_408(serve_setup):
    gw = ServingGateway(_slow(_engine(serve_setup, num_slots=1), 0.05))
    try:
        # occupy the single slot, then submit with a deadline shorter
        # than the occupant's remaining stream: expires while queued
        hold = {}

        def occupant():
            hold["out"] = _generate(gw.port, _prompts(1, seed=1)[0],
                                    new_tokens=10)
        t = threading.Thread(target=occupant, name="dla-test-occupant",
                             daemon=True)
        t.start()
        time.sleep(0.15)           # occupant is decoding by now
        conn, resp = _open_generate(gw.port, _prompts(1, seed=2)[0],
                                    new_tokens=4, deadline_s=0.05)
        try:
            assert resp.status == 408, (resp.status, resp.read())
        finally:
            conn.close()
        t.join(timeout=120)
        deadline = time.monotonic() + 30
        got = 0.0
        while got < 1 and time.monotonic() < deadline:
            got = gw.metrics.registry.snapshot()[
                "serving/gateway/http_408"]
            time.sleep(0.01)
        assert got >= 1
    finally:
        gw.close()


def test_gateway_drain_answers_503_and_flips_healthz(serve_setup):
    gw = ServingGateway(_engine(serve_setup))
    try:
        conn = http.client.HTTPConnection("127.0.0.1", gw.port,
                                          timeout=30)
        conn.request("GET", "/healthz")
        assert conn.getresponse().status == 200
        conn.close()

        gw.begin_drain()
        conn, resp = _open_generate(gw.port, _prompts(1)[0])
        assert resp.status == 503
        assert resp.getheader("Retry-After") is not None
        conn.close()
        conn = http.client.HTTPConnection("127.0.0.1", gw.port,
                                          timeout=30)
        conn.request("GET", "/healthz")
        assert conn.getresponse().status == 503
        conn.close()
    finally:
        gw.close()


def test_gateway_client_disconnect_cancels_request(serve_setup):
    eng = _slow(_engine(serve_setup), 0.05)
    gw = ServingGateway(eng)
    try:
        conn, resp = _open_generate(gw.port, _prompts(1)[0],
                                    new_tokens=12)
        assert resp.status == 200
        rid = int(resp.headers["X-DLA-Rid"])
        # read one event, then hang up mid-stream
        while True:
            line = resp.readline().strip()
            if line.startswith(b"data: "):
                break
        # close-delimited SSE: the response object owns the socket
        resp.close()
        conn.close()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            snap = gw.metrics.registry.snapshot()
            if snap["serving/gateway/disconnect_cancels"] >= 1:
                break
            time.sleep(0.02)
        assert snap["serving/gateway/disconnect_cancels"] == 1
        req = eng.result(rid)
        assert req.state in TERMINAL_STATES
        assert req.state is not RequestState.TIMEOUT
        # the freed slot serves the next request normally
        toks, _, done = _generate(gw.port, _prompts(1, seed=3)[0])
        assert done["state"] == "finished" and len(toks) == MAX_NEW
    finally:
        gw.close()


# ---------------------------------------------------------------------------
# net= fault scope
# ---------------------------------------------------------------------------

def test_net_fault_scope_parses_and_fires_one_shot():
    plan = FaultPlan.parse(
        "net=1:delay:0.2;net=2:drop;net=3:disconnect")
    assert plan.take("drop", 1, site="net") is None    # not due yet
    d = plan.take("delay", 1, site="net")
    assert d is not None and d.arg == pytest.approx(0.2)
    assert plan.take("delay", 5, site="net") is None   # one-shot
    assert plan.take("drop", 2, site="net").kind == "drop"
    assert plan.take("disconnect", 3, site="net") is not None
    # net kinds stay inside the net scope
    assert FaultPlan.parse("net=1:drop").take("drop", 1) is None
    with pytest.raises(ValueError):
        FaultPlan.parse("net=1:wedge")
    # round-trips through spec() like every other scope
    assert "net=" in FaultPlan.parse("net=4:disconnect").spec()
