"""Profiling / numerics-debug subsystem (SURVEY.md sec 5 rows
"Tracing / profiling" and "Race detection / sanitizers")."""
import os

import jax
import numpy as np
import pytest

from dla_tpu.utils.profiling import (
    ProfileWindow,
    annotate,
    apply_debug_flags,
    step_annotation,
)


def test_profile_window_captures_trace(tmp_path):
    trace_dir = str(tmp_path / "trace")
    window = ProfileWindow(
        {"trace_dir": trace_dir, "start_step": 2, "num_steps": 2})
    assert window.enabled
    x = jax.numpy.ones((8, 8))
    fn = jax.jit(lambda a: a @ a)
    for step in range(6):
        window.on_step(step)
        with step_annotation(step):
            fn(x).block_until_ready()
    window.close()
    # an xplane dump must exist under trace_dir
    found = []
    for root, _dirs, files in os.walk(trace_dir):
        found += [f for f in files if f.endswith(".xplane.pb")]
    assert found, f"no xplane trace written under {trace_dir}"


def test_profile_window_disabled_without_dir():
    window = ProfileWindow(None)
    assert not window.enabled
    window.on_step(1)  # all no-ops
    window.close()


def test_profile_window_cut_short_stops_cleanly(tmp_path):
    window = ProfileWindow(
        {"trace_dir": str(tmp_path / "t"), "start_step": 0, "num_steps": 100})
    window.on_step(0)
    assert window._active
    window.close()  # loop ended mid-window; must stop the trace
    assert not window._active


def test_profile_window_fires_when_resumed_past_start(tmp_path):
    # a run resumed at step 500 with start_step 10 must still capture
    trace_dir = str(tmp_path / "resumed")
    window = ProfileWindow(
        {"trace_dir": trace_dir, "start_step": 10, "num_steps": 1})
    x = jax.numpy.ones((4, 4))
    for step in range(500, 504):
        window.on_step(step)
        jax.jit(lambda a: a + 1)(x).block_until_ready()
    window.close()
    found = []
    for root, _dirs, files in os.walk(trace_dir):
        found += [f for f in files if f.endswith(".xplane.pb")]
    assert found, "resumed run never opened its profile window"


def test_annotate_is_usable_outside_trace():
    with annotate("region"):
        jax.numpy.zeros((2,)).block_until_ready()


def test_debug_nans_flag_catches_nan():
    apply_debug_flags({"debug_nans": True})
    try:
        with pytest.raises(FloatingPointError):
            jax.jit(lambda x: x / 0.0)(np.float32(0.0)).block_until_ready()
    finally:
        apply_debug_flags({"debug_nans": False})
    # off again: same op runs silently
    jax.jit(lambda x: x / 0.0)(np.float32(0.0)).block_until_ready()


def test_apply_debug_flags_ignores_gpu_era_keys():
    apply_debug_flags({"deepspeed_config": "config/deepspeed_zero3.json",
                       "mixed_precision": "bf16", "num_processes": 8})
