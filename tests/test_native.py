"""Native data-plane library: build, bindings, and bit-parity with the
pure-Python fallbacks (SURVEY.md sec 2.2: first-party native host
runtime replacing the torch/HF-internal data path)."""
import json

import numpy as np
import pytest

from dla_tpu import native
from dla_tpu.data.jsonl import read_jsonl, write_jsonl
from dla_tpu.data.packing import pack_first_fit_python

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable")


RECORDS = [
    {"prompt": "hello", "response": "world"},
    {"prompt": "unicode é中文 😀", "response": "ok"},
    {"prompt": "esc \"quotes\" and \\ backslash\nnewline", "response": "x"},
    {"prompt": "last", "chosen": "a", "rejected": "b", "reward": -1.5},
]


def _write_messy(path):
    # hand-written file with blank lines, stray whitespace, no trailing \n
    lines = [json.dumps(r, ensure_ascii=False) for r in RECORDS]
    raw = ("\n\n  \n" + lines[0] + "\n" + "  " + lines[1] + "  \r\n" +
           lines[2] + "\n\t\n" + lines[3])
    path.write_bytes(raw.encode("utf-8"))


def test_jsonl_index_matches_python_line_scan(tmp_path):
    p = tmp_path / "messy.jsonl"
    _write_messy(p)
    starts, ends = native.jsonl_index(p)
    assert len(starts) == len(RECORDS)
    data = p.read_bytes()
    parsed = [json.loads(data[s:e]) for s, e in zip(starts, ends)]
    assert parsed == RECORDS


def test_read_jsonl_native_vs_fallback(tmp_path, monkeypatch):
    p = tmp_path / "data.jsonl"
    _write_messy(p)
    assert read_jsonl(p) == RECORDS
    # sharded reads take the native byte-range path; parity vs fallback
    native_shard = read_jsonl(p, shard_index=1, shard_count=2)
    monkeypatch.setattr("dla_tpu.data.jsonl._native_index", lambda _p: None)
    python_shard = read_jsonl(p, shard_index=1, shard_count=2)
    assert native_shard == python_shard == RECORDS[1::2]


def test_read_jsonl_shards_partition_the_file(tmp_path):
    p = tmp_path / "big.jsonl"
    recs = [{"i": i} for i in range(103)]
    write_jsonl(p, recs)
    shards = [read_jsonl(p, shard_index=k, shard_count=4) for k in range(4)]
    assert sum(len(s) for s in shards) == len(recs)
    merged = sorted((r["i"] for s in shards for r in s))
    assert merged == list(range(103))
    # deterministic striding: shard k holds records k::4
    assert [r["i"] for r in shards[1]] == list(range(1, 103, 4))


def test_cr_and_crlf_line_endings_match_python(tmp_path, monkeypatch):
    # Python text mode treats \r and \r\n as line terminators (universal
    # newlines); the C scanner must agree so shard striding is identical
    p = tmp_path / "cr.jsonl"
    lines = [json.dumps(r) for r in RECORDS]
    p.write_bytes((lines[0] + "\r" + lines[1] + "\r\n" + lines[2] +
                   "\n\x0c\n" + lines[3]).encode())
    starts, ends = native.jsonl_index(p)
    data = p.read_bytes()
    parsed = [json.loads(data[s:e]) for s, e in zip(starts, ends)]
    native_shard = read_jsonl(p, shard_index=0, shard_count=2)
    monkeypatch.setattr("dla_tpu.data.jsonl._native_index", lambda _p: None)
    python_shard = read_jsonl(p, shard_index=0, shard_count=2)
    assert parsed == RECORDS
    assert native_shard == python_shard == RECORDS[0::2]


def test_unicode_whitespace_line_keeps_shards_consistent(tmp_path,
                                                         monkeypatch):
    # A line of only non-ASCII Unicode whitespace (U+00A0): the C scanner
    # counts it as a record, Python str.strip() drops it. The count
    # cross-check must reject the native index so EVERY shard uses
    # Python striding — not just the shard the bogus line lands in.
    p = tmp_path / "nbsp.jsonl"
    lines = [json.dumps(r) for r in RECORDS]
    p.write_bytes((lines[0] + "\n  \n" + lines[1] + "\n"
                   + lines[2] + "\n" + lines[3] + "\n").encode("utf-8"))
    shards = [read_jsonl(p, shard_index=k, shard_count=2) for k in range(2)]
    monkeypatch.setattr("dla_tpu.data.jsonl._native_index", lambda _p: None)
    py_shards = [read_jsonl(p, shard_index=k, shard_count=2)
                 for k in range(2)]
    assert shards == py_shards
    assert sorted((r.get("prompt") for s in shards for r in s)) == sorted(
        r["prompt"] for r in RECORDS)


def test_shard_index_out_of_range_raises(tmp_path):
    p = tmp_path / "r.jsonl"
    write_jsonl(p, RECORDS)
    with pytest.raises(ValueError):
        read_jsonl(p, shard_index=2, shard_count=2)
    with pytest.raises(ValueError):
        read_jsonl(p, shard_index=-1, shard_count=2)
    with pytest.raises(ValueError):
        read_jsonl(p, shard_index=0, shard_count=0)


def test_empty_and_missing_files(tmp_path):
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert read_jsonl(empty) == []
    assert native.jsonl_index(empty)[0].shape == (0,)
    assert native.jsonl_index(tmp_path / "nope.jsonl") is None


def test_pack_ffd_parity_random():
    rng = np.random.default_rng(0)
    for trial in range(20):
        n = int(rng.integers(1, 400))
        max_len = int(rng.integers(16, 512))
        lengths = rng.integers(1, max_len * 2, size=n).astype(np.int32)
        got = native.pack_ffd(lengths, max_len)
        assert got is not None
        assign_c, rows_c = got
        assign_py, rows_py = pack_first_fit_python(lengths, max_len, 8)
        assert rows_c == rows_py, f"trial {trial}"
        np.testing.assert_array_equal(assign_c, assign_py)
        # validity: no row overflows max_len
        totals = np.zeros(rows_c, np.int64)
        np.add.at(totals, assign_c, np.minimum(lengths, max_len))
        assert totals.max(initial=0) <= max_len


def test_packed_dataset_uses_native_and_matches_python(tmp_path, monkeypatch):
    from dla_tpu.data.loaders import build_instruction_dataset
    from dla_tpu.data.packing import PackedInstructionDataset

    p = tmp_path / "sft.jsonl"
    write_jsonl(p, [{"prompt": f"q{i}" * (1 + i % 7),
                     "response": f"a{i}" * (1 + i % 5)} for i in range(40)])
    from dla_tpu.data.tokenizers import ByteTokenizer
    cfg = {"source": "local", "train_path": str(p), "max_seq_length": 48}
    base = build_instruction_dataset(cfg, ByteTokenizer(), split="train")
    packed_native = PackedInstructionDataset(base, 48)
    monkeypatch.setattr(
        "dla_tpu.native.pack_ffd", lambda *a, **k: None)
    packed_py = PackedInstructionDataset(base, 48)
    assert len(packed_native) == len(packed_py)
    for i in range(len(packed_py)):
        a, b = packed_native[i], packed_py[i]
        for key in a:
            np.testing.assert_array_equal(a[key], b[key])
