"""Sharding-layout regression tests (VERDICT round-1 items 3 and 7).

1. The token-embedding table must not be model(TP)-sharded: a gather
   whose operand is sharded on the indexed dim makes the SPMD partitioner
   replicate the full table every forward ("involuntary full
   rematerialization") — a silent model-axis all-gather tax per step.
2. Inter-block activations must actually carry ACT_SPEC sharding under a
   TP/CP mesh — the with_sharding_constraint calls are only useful if the
   compiled program honors them; a wrong constraint would silently
   degrade to replication.
"""
import re

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
import pytest

from dla_tpu.models.config import get_model_config
from dla_tpu.models.transformer import ACT_SPEC, Transformer
from dla_tpu.parallel.mesh import MeshConfig, build_mesh
from dla_tpu.parallel.sharding import prune_spec_for_mesh, shard_pytree


def test_embed_spec_has_no_model_axis():
    """Guard: no partition spec on the embedding table mentions the TP axis."""
    for preset in ("tiny", "tiny-gqa", "phi-2"):
        model = Transformer(get_model_config(preset))
        spec = model.partition_specs()["embed"]["embedding"]
        flat = []
        for entry in spec:
            if isinstance(entry, (tuple, list)):
                flat.extend(entry)
            elif entry is not None:
                flat.append(entry)
        assert "model" not in flat, (
            f"{preset}: embedding spec {spec} is TP-sharded; the gather "
            "would force full-table rematerialization")


def test_no_model_axis_allgather_of_embedding_table():
    """On a pure data x TP mesh (fsdp=1) the embedding table must compile
    with zero collectives: any all-gather materializing the full [V, D]
    table is the involuntary-full-remat tax this layout exists to avoid."""
    cfg = get_model_config("tiny")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    mesh = build_mesh(MeshConfig(data=4, fsdp=1, model=2, sequence=1))
    sharded = shard_pytree(params, model.partition_specs(), mesh)
    ids = jnp.ones((4, 16), jnp.int32)
    with jax.sharding.set_mesh(mesh):
        compiled = jax.jit(model.apply).lower(sharded, ids).compile()
    hlo = compiled.as_text()
    V, D = cfg.vocab_size, cfg.hidden_size
    table_shape = rf"\[{V},{D}\]"
    offenders = [ln for ln in hlo.splitlines()
                 if "all-gather" in ln and re.search(table_shape, ln)]
    assert not offenders, (
        "embedding table is re-materialized by all-gather:\n"
        + "\n".join(offenders[:3]))


def test_interblock_activations_sharded_under_tp_cp(tiny_cfg):
    """hidden_states under a TP x CP x batch mesh must come out sharded per
    ACT_SPEC (batch over data+fsdp, sequence over the CP axis) — proves the
    activation constraints are honored, not silently replicated."""
    mesh = build_mesh(MeshConfig(data=2, fsdp=1, model=2, sequence=2))
    model = Transformer(tiny_cfg)
    params = model.init(jax.random.key(0))
    sharded = shard_pytree(params, model.partition_specs(), mesh)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(1, 100, (4, 16)), jnp.int32)
    with jax.sharding.set_mesh(mesh):
        h = jax.jit(model.hidden_states)(sharded, ids)
    h.block_until_ready()
    expected = NamedSharding(mesh, prune_spec_for_mesh(ACT_SPEC, mesh))
    assert h.sharding.is_equivalent_to(expected, h.ndim), (
        f"inter-block activations carry {h.sharding.spec}, "
        f"expected {expected.spec}")


def test_interblock_activation_sharding_constraint_annotated(tiny_cfg):
    """The pre-SPMD lowering must contain ACT_SPEC Sharding custom-calls on
    [B, T, D] activations: deleting a with_sharding_constraint would pass
    output-propagation tests by luck but fails this one."""
    mesh = build_mesh(MeshConfig(data=2, fsdp=2, model=2, sequence=1))
    model = Transformer(tiny_cfg)
    params = model.init(jax.random.key(0))
    sharded = shard_pytree(params, model.partition_specs(), mesh)
    ids = jnp.ones((4, 16), jnp.int32)
    with jax.sharding.set_mesh(mesh):
        lowered = jax.jit(model.hidden_states).lower(sharded, ids)
    txt = lowered.as_text()
    d = tiny_cfg.hidden_size
    # Shardy lowering: sdy.sharding_constraint <@mesh, [{"data","fsdp"},
    # {"sequence"}, {}]> on a [B, T, D] tensor. (Pre-Shardy jax lowered the
    # same thing as a @Sharding custom call; accept either.)
    sdy = re.compile(
        r'sdy\.sharding_constraint[^\n]*\[\{"data", "fsdp"\}, '
        r'\{"sequence"\}, \{\}\][^\n]*tensor<4x16x%d' % d)
    if "sdy.sharding_constraint" in txt:
        assert sdy.search(txt), (
            "no ACT_SPEC sharding_constraint on [B,T,D] activations in "
            "the lowering")
    else:
        want = NamedSharding(mesh, prune_spec_for_mesh(ACT_SPEC, mesh))
        hlo_sharding = str(want._to_xla_hlo_sharding(3))
        assert "@Sharding" in txt and hlo_sharding in txt, (
            f"no activation sharding annotation {hlo_sharding} in lowering")


def test_optimizer_state_inherits_param_shardings(mesh8, tiny_cfg):
    """Adam moments must be sharded exactly like their params (partitioned
    optimizer state = the ZeRO-3 analog). jit output propagation does NOT
    guarantee this (observed: fully-replicated opt state), so the Trainer
    matches shardings explicitly — this pins it."""
    import jax
    from dla_tpu.training.trainer import Trainer
    from dla_tpu.ops.losses import cross_entropy_loss

    model = Transformer(tiny_cfg)
    params = model.init(jax.random.key(0))

    def loss_fn(p, frozen, batch, rng):
        del frozen, rng
        logits = model.apply(p, batch["input_ids"])
        loss, _ = cross_entropy_loss(logits, batch["labels"])
        return loss, {}

    config = {
        "experiment_name": "optshard",
        "optimization": {"total_batch_size": 4, "micro_batch_size": 1,
                         "learning_rate": 1e-3, "max_train_steps": 1,
                         "lr_scheduler": "constant", "max_grad_norm": 1.0},
        "logging": {"output_dir": "/tmp/optshard_ck", "log_dir": None},
        "hardware": {"gradient_accumulation_steps": 1},
    }
    with jax.sharding.set_mesh(mesh8):
        trainer = Trainer(config=config, mesh=mesh8, loss_fn=loss_fn,
                          params=params,
                          param_specs=model.partition_specs())
        flat_p = {tuple(str(k) for k in path): leaf for path, leaf in
                  jax.tree_util.tree_flatten_with_path(trainer.params)[0]}
        checked = 0
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                trainer.opt_state)[0]:
            keys = tuple(str(k) for k in path)
            for n in range(len(keys)):
                p_leaf = flat_p.get(keys[n:])
                if p_leaf is not None and p_leaf.shape == leaf.shape:
                    assert leaf.sharding.is_equivalent_to(
                        p_leaf.sharding, leaf.ndim), (
                        f"opt leaf {keys} sharding {leaf.sharding} != "
                        f"param {p_leaf.sharding}")
                    checked += 1
                    break
        # every param has mu and nu moments
        n_params = len(jax.tree.leaves(trainer.params))
        assert checked >= 2 * n_params, (checked, n_params)
