"""Generation engine tests: greedy parity with full re-forward, eos early
stop, left_align compaction, rng determinism, text round-trip."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dla_tpu.data.tokenizers import ByteTokenizer
from dla_tpu.generation.engine import (
    GenerationConfig,
    GenerationEngine,
    build_generate_fn,
    left_align,
)
from dla_tpu.models.config import get_model_config
from dla_tpu.models.transformer import Transformer


@pytest.fixture(scope="module")
def model_and_params():
    cfg = get_model_config("tiny")
    model = Transformer(cfg)
    return model, model.init(jax.random.key(7))


def test_left_align():
    ids = jnp.asarray([[5, 0, 0, 7, 8], [1, 2, 0, 0, 3]])
    mask = jnp.asarray([[1, 0, 0, 1, 1], [1, 1, 0, 0, 1]])
    a_ids, a_mask = left_align(ids, mask)
    np.testing.assert_array_equal(np.asarray(a_ids[0, :3]), [5, 7, 8])
    np.testing.assert_array_equal(np.asarray(a_mask[0]), [1, 1, 1, 0, 0])
    np.testing.assert_array_equal(np.asarray(a_ids[1, :3]), [1, 2, 3])


def test_greedy_generate_matches_full_forward(model_and_params):
    model, params = model_and_params
    rs = np.random.RandomState(0)
    lens = [6, 4]
    width = 7
    ids = np.zeros((2, width), np.int32)
    mask = np.zeros((2, width), np.int32)
    for i, L in enumerate(lens):
        ids[i, :L] = rs.randint(3, 200, (L,))
        mask[i, :L] = 1

    gen = GenerationConfig(max_new_tokens=5, do_sample=False,
                           eos_token_id=2, pad_token_id=0)
    fn = jax.jit(build_generate_fn(model, gen))
    out = fn(params, jnp.asarray(ids), jnp.asarray(mask), jax.random.key(0))

    for i, L in enumerate(lens):
        seq = list(ids[i, :L])
        for s in range(5):
            logits = model.apply(
                params, jnp.asarray(np.asarray(seq)[None, :], jnp.int32))
            nxt = int(np.argmax(np.asarray(logits[0, -1])))
            want = int(np.asarray(out["response_tokens"])[i, s])
            assert want == nxt, f"row {i} step {s}: {want} != {nxt}"
            if nxt == 2:
                break
            seq.append(nxt)


def test_generate_stops_at_eos(model_and_params):
    """Declare the model's natural first greedy token to be eos; generation
    must emit it once, stop, and pad the rest."""
    model, params = model_and_params
    ids = jnp.asarray([[5, 6, 7, 0]], jnp.int32)
    mask = jnp.asarray([[1, 1, 1, 0]], jnp.int32)
    probe = jax.jit(build_generate_fn(
        model, GenerationConfig(max_new_tokens=1, do_sample=False)))
    first = int(np.asarray(
        probe(params, ids, mask, jax.random.key(0))["response_tokens"])[0, 0])

    gen = GenerationConfig(max_new_tokens=4, do_sample=False,
                           eos_token_id=first, pad_token_id=0)
    fn = jax.jit(build_generate_fn(model, gen))
    out = fn(params, ids, mask, jax.random.key(0))
    resp = np.asarray(out["response_tokens"])[0]
    rmask = np.asarray(out["response_mask"])[0]
    assert resp[0] == first and rmask[0] == 1
    np.testing.assert_array_equal(resp[1:], [0, 0, 0])
    np.testing.assert_array_equal(rmask[1:], [0, 0, 0])
    assert int(out["lengths"][0]) == 4  # 3 prompt + 1 eos
    # compacted sequence is contiguous: [5, 6, 7, eos, pad...]
    np.testing.assert_array_equal(
        np.asarray(out["sequences"])[0, :4], [5, 6, 7, first])


def test_public_single_steps_match_fused_loop(model_and_params):
    """The public step-at-a-time surface (build_prefill_step +
    build_decode_step, the API the serving scheduler and latency
    harness drive) reproduces the fused generate loop exactly."""
    from dla_tpu.generation.engine import (
        build_decode_step,
        build_prefill_step,
    )
    model, params = model_and_params
    ids = jnp.asarray([[5, 9, 14, 0], [21, 8, 3, 17]], jnp.int32)
    mask = jnp.asarray([[1, 1, 1, 0], [1, 1, 1, 1]], jnp.int32)
    n = 5
    gen = GenerationConfig(max_new_tokens=n, do_sample=False,
                           eos_token_id=2, pad_token_id=0)
    fused = jax.jit(build_generate_fn(model, gen))
    out = fused(params, ids, mask, jax.random.key(0))

    prefill = jax.jit(build_prefill_step(model, n))
    decode = jax.jit(build_decode_step(model, gen))
    logits, cache = prefill(params, ids, mask)
    done = jnp.zeros((2,), bool)
    toks, emits = [], []
    for s in range(n):
        tok, emit, logits, cache, done = decode(
            jax.random.key(s), params, logits, cache, done)
        toks.append(np.asarray(tok))
        emits.append(np.asarray(emit))
    np.testing.assert_array_equal(np.stack(toks, 1),
                                  np.asarray(out["response_tokens"]))
    np.testing.assert_array_equal(np.stack(emits, 1),
                                  np.asarray(out["response_mask"]))


def test_sampling_deterministic_per_key(model_and_params):
    model, params = model_and_params
    gen = GenerationConfig(max_new_tokens=6, do_sample=True,
                           temperature=1.0, top_p=0.9)
    fn = jax.jit(build_generate_fn(model, gen))
    ids = jnp.asarray([[9, 10, 11]], jnp.int32)
    mask = jnp.ones((1, 3), jnp.int32)
    a = fn(params, ids, mask, jax.random.key(3))
    b = fn(params, ids, mask, jax.random.key(3))
    c = fn(params, ids, mask, jax.random.key(4))
    np.testing.assert_array_equal(np.asarray(a["response_tokens"]),
                                  np.asarray(b["response_tokens"]))
    assert not np.array_equal(np.asarray(a["response_tokens"]),
                              np.asarray(c["response_tokens"]))


def test_engine_text_roundtrip(model_and_params):
    model, params = model_and_params
    tok = ByteTokenizer()
    eng = GenerationEngine(model, tok, GenerationConfig(
        max_new_tokens=8, do_sample=False))
    texts, out = eng.generate_text(
        params, ["hello", "a much longer prompt here"], 32, jax.random.key(0))
    assert len(texts) == 2
    assert all(isinstance(t, str) for t in texts)


def test_early_exit_while_matches_scan_path(model_and_params):
    """The early-exit while_loop (eos >= 0) must produce bit-identical
    outputs to the fixed-length scan path (eos < 0) when no row ever
    hits EOS — same pre-split rng keys indexed by step."""
    model, params = model_and_params
    import dataclasses

    from dla_tpu.generation.engine import GenerationConfig, build_generate_fn

    rs = np.random.RandomState(7)
    ids = jnp.asarray(rs.randint(3, 100, (2, 8)), jnp.int32)
    mask = jnp.ones((2, 8), jnp.int32)
    base = GenerationConfig(max_new_tokens=6, do_sample=True,
                            temperature=1.0, pad_token_id=0,
                            eos_token_id=-1)
    # an eos id outside the vocab is never sampled: the while path runs
    # all n steps and must match the scan path exactly
    unreachable = dataclasses.replace(
        base, eos_token_id=model.cfg.vocab_size + 7)
    out_scan = jax.jit(build_generate_fn(model, base))(
        params, ids, mask, jax.random.key(3))
    out_while = jax.jit(build_generate_fn(model, unreachable))(
        params, ids, mask, jax.random.key(3))
    for k in out_scan:
        np.testing.assert_array_equal(np.asarray(out_scan[k]),
                                      np.asarray(out_while[k]), err_msg=k)


def test_chunked_early_exit_matches_per_step_while(model_and_params):
    """early_exit_chunk > 0 (while over chunks, scan of C steps inside)
    must be bit-identical to the per-step while path — both when EOS
    fires mid-sequence (incl. a ragged final chunk, C not dividing n)
    and when it never fires."""
    model, params = model_and_params
    import dataclasses

    from dla_tpu.generation.engine import GenerationConfig, build_generate_fn

    rs = np.random.RandomState(11)
    ids = jnp.asarray(rs.randint(3, 100, (2, 8)), jnp.int32)
    mask = jnp.ones((2, 8), jnp.int32)
    for eos, n, c in [(-1, 6, 4), (0, 6, 4), (0, 7, 3), (0, 5, 8)]:
        base = GenerationConfig(max_new_tokens=n, do_sample=True,
                                temperature=1.0, pad_token_id=0,
                                eos_token_id=eos if eos >= 0
                                else model.cfg.vocab_size + 7)
        ref = jax.jit(build_generate_fn(model, base))(
            params, ids, mask, jax.random.key(5))
        chunked = dataclasses.replace(base, early_exit_chunk=c)
        out = jax.jit(build_generate_fn(model, chunked))(
            params, ids, mask, jax.random.key(5))
        for k in ref:
            np.testing.assert_array_equal(
                np.asarray(ref[k]), np.asarray(out[k]),
                err_msg=f"{k} eos={eos} n={n} c={c}")


def test_early_exit_actually_exits_and_matches_masked_scan(
        model_and_params):
    """When EOS really fires mid-sequence, the while path must equal the
    fixed-length scan output with post-EOS positions replaced by
    pad/emit-0 — covering the early-termination machinery itself (buffer
    prefill, cond's all(done) exit), not just the never-fires case."""
    model, params = model_and_params
    import dataclasses

    from dla_tpu.generation.engine import GenerationConfig, build_generate_fn

    rs = np.random.RandomState(9)
    ids = jnp.asarray(rs.randint(3, 100, (2, 8)), jnp.int32)
    mask = jnp.ones((2, 8), jnp.int32)
    base = GenerationConfig(max_new_tokens=6, do_sample=False,
                            pad_token_id=0, eos_token_id=-1)
    ref = jax.jit(build_generate_fn(model, base))(
        params, ids, mask, jax.random.key(0))
    # pick the token row 0 emits greedily at step 2 as the EOS id: it
    # demonstrably fires mid-sequence for at least that row
    eos = int(np.asarray(ref["response_tokens"])[0, 2])
    out = jax.jit(build_generate_fn(
        model, dataclasses.replace(base, eos_token_id=eos)))(
        params, ids, mask, jax.random.key(0))

    want_toks = np.asarray(ref["response_tokens"]).copy()
    want_mask = np.ones_like(want_toks)
    for r in range(want_toks.shape[0]):
        hits = np.where(want_toks[r] == eos)[0]
        if hits.size:                      # eos kept, everything after pad
            want_toks[r, hits[0] + 1:] = 0
            want_mask[r, hits[0] + 1:] = 0
    np.testing.assert_array_equal(np.asarray(out["response_tokens"]),
                                  want_toks)
    np.testing.assert_array_equal(np.asarray(out["response_mask"]),
                                  want_mask)


def test_int8_kv_cache_decode_close_to_fp():
    """kv_cache_dtype: int8 halves decode's cache HBM traffic; per-token
    logits must track the full-precision cache closely and greedy
    generations should agree on a tiny model."""
    import dataclasses

    import jax

    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer

    cfg_fp = get_model_config("tiny-gqa")
    cfg_q = dataclasses.replace(cfg_fp, kv_cache_dtype="int8")
    model_fp = Transformer(cfg_fp)
    model_q = Transformer(cfg_q)
    params = model_fp.init(jax.random.key(0))

    rs = np.random.RandomState(11)
    ids = jnp.asarray(rs.randint(1, 100, (2, 12)), jnp.int32)
    mask = jnp.ones((2, 12), jnp.int32)
    n_new = 6

    lf, cf = model_fp.start_decode(params, ids, mask, n_new)
    lq, cq = model_q.start_decode(params, ids, mask, n_new)
    assert cq["k"].dtype == jnp.int8 and "k_scale" in cq
    for _ in range(n_new):
        tok = jnp.argmax(lf, axis=-1).astype(jnp.int32)
        tok_q = jnp.argmax(lq, axis=-1).astype(jnp.int32)
        np.testing.assert_array_equal(np.asarray(tok), np.asarray(tok_q))
        lf, cf = model_fp.decode_step(params, cf, tok)
        lq, cq = model_q.decode_step(params, cq, tok)
        # compare AFTER stepping so the final step — whose attention
        # reads the most quantized columns — is asserted too
        np.testing.assert_allclose(np.asarray(lq), np.asarray(lf),
                                   rtol=0.05, atol=0.08)


def test_int8_weight_only_decode_tracks_fp():
    """quantize_weights (weight-only int8 rollout params): full forward
    and KV-cache decode over the quantized tree stay close to full
    precision, and greedy decode agrees on a tiny model — the
    ppo.rollout_quantize_weights path."""
    import jax

    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer

    model = Transformer(get_model_config("tiny-gqa"))
    params = model.init(jax.random.key(0))
    qparams = model.quantize_weights(params)
    assert qparams["layers"]["wq"].dtype == jnp.int8
    assert "wq_wscale" in qparams["layers"]
    assert qparams["lm_head"].dtype == jnp.int8

    rs = np.random.RandomState(12)
    ids = jnp.asarray(rs.randint(1, 100, (2, 12)), jnp.int32)
    full = model.apply(params, ids)
    quant = model.apply(qparams, ids)
    np.testing.assert_allclose(np.asarray(quant), np.asarray(full),
                               rtol=0.08, atol=0.25)

    mask = jnp.ones((2, 12), jnp.int32)
    lf, cf = model.start_decode(params, ids, mask, 5)
    lq, cq = model.start_decode(qparams, ids, mask, 5)
    for _ in range(5):
        tok = jnp.argmax(lf, axis=-1).astype(jnp.int32)
        tok_q = jnp.argmax(lq, axis=-1).astype(jnp.int32)
        np.testing.assert_array_equal(np.asarray(tok), np.asarray(tok_q))
        lf, cf = model.decode_step(params, cf, tok)
        lq, cq = model.decode_step(qparams, cq, tok)
        np.testing.assert_allclose(np.asarray(lq), np.asarray(lf),
                                   rtol=0.08, atol=0.3)


def test_quantize_kv_roundtrip_error_bound():
    import dataclasses

    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer

    rs = np.random.RandomState(0)
    x32 = rs.randn(3, 7, 2, 16).astype(np.float32) * 3.0

    # fp32 activations: worst-case error is half a quantization step
    # (scale = absmax/127 per (pos, head))
    model = Transformer(get_model_config("tiny", kv_cache_dtype="int8"))
    q, s = model._quantize_kv(jnp.asarray(x32))
    back = model._dequantize_kv(q, s)
    step = np.asarray(s)[..., None]
    err = np.abs(np.asarray(back) - np.asarray(x32))
    assert (err < 0.51 * step + 1e-6).all(), float((err / step).max())

    # the production default is bfloat16 activations: dequant casts the
    # fp32 scale to bf16 AND rounds the product to bf16 (two ~2^-9
    # relative roundings on top of the half-step quantization error) —
    # the SHIPPED path must stay within that combined bound
    cfg16 = dataclasses.replace(
        get_model_config("tiny", kv_cache_dtype="int8"),
        dtype="bfloat16")
    model16 = Transformer(cfg16)
    x16 = jnp.asarray(x32, jnp.bfloat16)
    q, s = model16._quantize_kv(x16)
    back = np.asarray(model16._dequantize_kv(q, s), np.float32)
    x_ref = np.asarray(x16, np.float32)
    err = np.abs(back - x_ref)
    bound = 0.6 * np.asarray(s)[..., None] + 2.0 ** -7 * np.abs(x_ref)
    assert (err < bound + 1e-6).all(), float((err / bound).max())


def test_flash_prefill_matches_xla_prefill():
    """Prefill through the blockwise flash kernel == XLA-mask prefill on
    right-padded prompts, for everything downstream consumes: last-real-
    token logits, cache k/v at real positions, valid mask, lengths."""
    cfg = get_model_config("tiny", attention="flash")
    model_f = Transformer(cfg)
    model_x = Transformer(get_model_config("tiny"))
    params = model_f.init(jax.random.key(3))

    rs = np.random.RandomState(5)
    t = 128  # tiles the flash blocks -> flash path taken
    lens = [128, 77]
    ids = np.zeros((2, t), np.int32)
    mask = np.zeros((2, t), np.int32)
    for i, L in enumerate(lens):
        ids[i, :L] = rs.randint(1, 100, (L,))
        mask[i, :L] = 1
    ids, mask = jnp.asarray(ids), jnp.asarray(mask)

    cache0 = model_f.init_cache(2, t + 4)
    logits_f, cache_f = model_f.prefill(params, cache0, ids, mask)
    logits_x, cache_x = model_x.prefill(params, cache0, ids, mask)

    np.testing.assert_allclose(np.asarray(logits_f), np.asarray(logits_x),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_array_equal(np.asarray(cache_f["valid"]),
                                  np.asarray(cache_x["valid"]))
    np.testing.assert_array_equal(np.asarray(cache_f["lengths"]),
                                  np.asarray(cache_x["lengths"]))
    for key in ("k", "v"):
        for i, L in enumerate(lens):
            np.testing.assert_allclose(
                np.asarray(cache_f[key][:, i, :L]),
                np.asarray(cache_x[key][:, i, :L]), rtol=2e-4, atol=2e-5)


def test_flash_prefill_drops_quadratic_mask():
    """The flash prefill lowering must not materialize any [B, T, T]
    tensor (the O(T^2) HBM mask the XLA path builds)."""
    cfg = get_model_config("tiny", attention="flash")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    b, t = 1, 1024
    cache0 = model.init_cache(b, t + 4)
    ids = jnp.ones((b, t), jnp.int32)
    mask = jnp.ones((b, t), jnp.int32)
    lowered = jax.jit(model.prefill).lower(params, cache0, ids, mask)
    txt = lowered.as_text()
    assert f"x{t}x{t}x" not in txt and f"<{t}x{t}x" not in txt, (
        "prefill lowering contains a [T, T] tensor — quadratic mask is back")


def test_flash_prefill_decode_roundtrip():
    """Greedy decode after a flash prefill matches full-forward re-runs."""
    cfg = get_model_config("tiny", attention="flash")
    model = Transformer(cfg)
    params = model.init(jax.random.key(11))
    rs = np.random.RandomState(2)
    t = 128
    L = 70
    ids = np.zeros((1, t), np.int32)
    mask = np.zeros((1, t), np.int32)
    ids[0, :L] = rs.randint(1, 100, (L,))
    mask[0, :L] = 1
    ids, mask = jnp.asarray(ids), jnp.asarray(mask)
    n_new = 3

    logits, cache = model.start_decode(params, ids, mask, n_new)
    got = []
    for _ in range(n_new):
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        got.append(int(tok[0]))
        logits, cache = model.decode_step(params, cache, tok)

    seq = list(np.asarray(ids[0, :L]))
    want = []
    for _ in range(n_new):
        arr = jnp.asarray(np.asarray(seq)[None, :], jnp.int32)
        full = model.apply(params, arr)
        nxt = int(np.argmax(np.asarray(full[0, -1])))
        want.append(nxt)
        seq.append(nxt)
    assert got == want
