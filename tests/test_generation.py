"""Generation engine tests: greedy parity with full re-forward, eos early
stop, left_align compaction, rng determinism, text round-trip."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dla_tpu.data.tokenizers import ByteTokenizer
from dla_tpu.generation.engine import (
    GenerationConfig,
    GenerationEngine,
    build_generate_fn,
    left_align,
)
from dla_tpu.models.config import get_model_config
from dla_tpu.models.transformer import Transformer


@pytest.fixture(scope="module")
def model_and_params():
    cfg = get_model_config("tiny")
    model = Transformer(cfg)
    return model, model.init(jax.random.key(7))


def test_left_align():
    ids = jnp.asarray([[5, 0, 0, 7, 8], [1, 2, 0, 0, 3]])
    mask = jnp.asarray([[1, 0, 0, 1, 1], [1, 1, 0, 0, 1]])
    a_ids, a_mask = left_align(ids, mask)
    np.testing.assert_array_equal(np.asarray(a_ids[0, :3]), [5, 7, 8])
    np.testing.assert_array_equal(np.asarray(a_mask[0]), [1, 1, 1, 0, 0])
    np.testing.assert_array_equal(np.asarray(a_ids[1, :3]), [1, 2, 3])


def test_greedy_generate_matches_full_forward(model_and_params):
    model, params = model_and_params
    rs = np.random.RandomState(0)
    lens = [6, 4]
    width = 7
    ids = np.zeros((2, width), np.int32)
    mask = np.zeros((2, width), np.int32)
    for i, L in enumerate(lens):
        ids[i, :L] = rs.randint(3, 200, (L,))
        mask[i, :L] = 1

    gen = GenerationConfig(max_new_tokens=5, do_sample=False,
                           eos_token_id=2, pad_token_id=0)
    fn = jax.jit(build_generate_fn(model, gen))
    out = fn(params, jnp.asarray(ids), jnp.asarray(mask), jax.random.key(0))

    for i, L in enumerate(lens):
        seq = list(ids[i, :L])
        for s in range(5):
            logits = model.apply(
                params, jnp.asarray(np.asarray(seq)[None, :], jnp.int32))
            nxt = int(np.argmax(np.asarray(logits[0, -1])))
            want = int(np.asarray(out["response_tokens"])[i, s])
            assert want == nxt, f"row {i} step {s}: {want} != {nxt}"
            if nxt == 2:
                break
            seq.append(nxt)


def test_generate_stops_at_eos(model_and_params):
    """Declare the model's natural first greedy token to be eos; generation
    must emit it once, stop, and pad the rest."""
    model, params = model_and_params
    ids = jnp.asarray([[5, 6, 7, 0]], jnp.int32)
    mask = jnp.asarray([[1, 1, 1, 0]], jnp.int32)
    probe = jax.jit(build_generate_fn(
        model, GenerationConfig(max_new_tokens=1, do_sample=False)))
    first = int(np.asarray(
        probe(params, ids, mask, jax.random.key(0))["response_tokens"])[0, 0])

    gen = GenerationConfig(max_new_tokens=4, do_sample=False,
                           eos_token_id=first, pad_token_id=0)
    fn = jax.jit(build_generate_fn(model, gen))
    out = fn(params, ids, mask, jax.random.key(0))
    resp = np.asarray(out["response_tokens"])[0]
    rmask = np.asarray(out["response_mask"])[0]
    assert resp[0] == first and rmask[0] == 1
    np.testing.assert_array_equal(resp[1:], [0, 0, 0])
    np.testing.assert_array_equal(rmask[1:], [0, 0, 0])
    assert int(out["lengths"][0]) == 4  # 3 prompt + 1 eos
    # compacted sequence is contiguous: [5, 6, 7, eos, pad...]
    np.testing.assert_array_equal(
        np.asarray(out["sequences"])[0, :4], [5, 6, 7, first])


def test_sampling_deterministic_per_key(model_and_params):
    model, params = model_and_params
    gen = GenerationConfig(max_new_tokens=6, do_sample=True,
                           temperature=1.0, top_p=0.9)
    fn = jax.jit(build_generate_fn(model, gen))
    ids = jnp.asarray([[9, 10, 11]], jnp.int32)
    mask = jnp.ones((1, 3), jnp.int32)
    a = fn(params, ids, mask, jax.random.key(3))
    b = fn(params, ids, mask, jax.random.key(3))
    c = fn(params, ids, mask, jax.random.key(4))
    np.testing.assert_array_equal(np.asarray(a["response_tokens"]),
                                  np.asarray(b["response_tokens"]))
    assert not np.array_equal(np.asarray(a["response_tokens"]),
                              np.asarray(c["response_tokens"]))


def test_engine_text_roundtrip(model_and_params):
    model, params = model_and_params
    tok = ByteTokenizer()
    eng = GenerationEngine(model, tok, GenerationConfig(
        max_new_tokens=8, do_sample=False))
    texts, out = eng.generate_text(
        params, ["hello", "a much longer prompt here"], 32, jax.random.key(0))
    assert len(texts) == 2
    assert all(isinstance(t, str) for t in texts)
