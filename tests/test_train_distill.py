"""Distillation pipeline tests: teacher-gen CLI -> rollouts JSONL -> CE and
ensemble-KL student training."""
import json

import numpy as np
import yaml

from dla_tpu.data.jsonl import read_jsonl, write_jsonl


def test_generate_teacher_data_cli(tmp_path):
    from dla_tpu.training.generate_teacher_data import main
    write_jsonl(tmp_path / "prompts.jsonl",
                [{"prompt": f"question {i}"} for i in range(5)])
    out = tmp_path / "rollouts.jsonl"
    main(["--model_name_or_path", "tiny",
          "--tokenizer", "byte",
          "--prompts_path", str(tmp_path / "prompts.jsonl"),
          "--output_path", str(out),
          "--batch_size", "2",
          "--max_prompt_length", "24",
          "--max_new_tokens", "6",
          "--temperature", "0.7"])
    recs = read_jsonl(out)
    assert len(recs) == 5  # tail batch padded but not duplicated in output
    assert all("teacher_response" in r and "reward" not in r for r in recs)


def test_generate_teacher_data_with_reward(tmp_path):
    from dla_tpu.training.generate_teacher_data import main
    write_jsonl(tmp_path / "prompts.jsonl",
                [{"prompt": f"question {i}"} for i in range(3)])
    out = tmp_path / "rollouts.jsonl"
    main(["--model_name_or_path", "tiny",
          "--tokenizer", "byte",
          "--prompts_path", str(tmp_path / "prompts.jsonl"),
          "--output_path", str(out),
          "--reward_model_path", "tiny",
          "--batch_size", "3",
          "--max_prompt_length", "24",
          "--max_new_tokens", "4"])
    recs = read_jsonl(out)
    assert len(recs) == 3
    assert all(np.isfinite(r["reward"]) for r in recs)


def _distill_cfg(tmp_path, use_kl=False, n_teachers=1):
    rollouts = [{"prompt": f"q {i}", "teacher_response": f"answer {i}",
                 "reward": 0.5 + 0.1 * (i % 3)} for i in range(32)]
    write_jsonl(tmp_path / "rollouts.jsonl", rollouts)
    cfg = {
        "experiment_name": "distill_smoke",
        "seed": 0,
        "model": {"student_model_name_or_path": "tiny", "tokenizer": "byte",
                  "max_seq_length": 24},
        "distill": {
            "use_kl": use_kl, "on_policy": use_kl,
            "teacher_model_names_or_paths": ["tiny"] * n_teachers,
        },
        "data": {"teacher_samples_path": str(tmp_path / "rollouts.jsonl")},
        "optimization": {
            "total_batch_size": 8, "micro_batch_size": 1,
            "learning_rate": 1e-3, "max_train_steps": 6,
            "temperature": 2.0,
        },
        "logging": {"output_dir": str(tmp_path / "ckpt"),
                    "log_dir": str(tmp_path / "logs"),
                    "log_every_steps": 2},
        "hardware": {"gradient_accumulation_steps": 2,
                     "mesh": {"data": 2, "fsdp": 2, "model": 2}},
    }
    p = tmp_path / "distill.yaml"
    p.write_text(yaml.safe_dump(cfg))
    return p


def _last_metrics(tmp_path):
    with open(tmp_path / "logs" / "metrics.jsonl") as fh:
        return json.loads(fh.readlines()[-1])


def test_distill_ce_mode(tmp_path):
    from dla_tpu.training.train_distill import main
    main(["--config", str(_distill_cfg(tmp_path, use_kl=False))])
    last = _last_metrics(tmp_path)
    assert np.isfinite(last["train/ce"])
    assert abs(last["train/reward_mean"] - 0.6) < 0.2  # rewards logged


def test_distill_kl_ensemble_mode(tmp_path):
    from dla_tpu.training.train_distill import main
    main(["--config", str(_distill_cfg(tmp_path, use_kl=True, n_teachers=2))])
    last = _last_metrics(tmp_path)
    assert np.isfinite(last["train/kl"])
    assert "train/ce" not in last
