"""Elastic sampler fleet tests (rollout/actor_fleet): broadcast-tree
refit fanout (all members, zero recompiles, wedged member retired
without stalling), lease-based lose-a-sampler-not-the-run reassignment
regenerating bit-identically from journaled (prompt, seed) pairs,
per-trajectory (heterogeneous) staleness tagging, and the chaos
acceptance — an N=4 async fleet run that loses one sampler mid-rollout
produces rollouts and final params bit-identical to a planned N=3
run."""
import threading
import time

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dla_tpu.generation.engine import GenerationConfig, build_generate_fn
from dla_tpu.models.config import get_model_config
from dla_tpu.models.transformer import Transformer
from dla_tpu.ops.sampling import derive_rollout_seeds
from dla_tpu.resilience.faults import FaultPlan
from dla_tpu.rollout import (
    RolloutMetrics,
    SamplerFleet,
    SamplerFleetConfig,
    SamplerFleetMetrics,
    TrajectoryGroup,
    WeightRefitter,
    apply_staleness_correction,
    build_rollout_pipeline,
    make_staleness_corrector,
    shard_trajectory_groups,
)
from dla_tpu.rollout.pipeline import RolloutPipeline
from dla_tpu.serving.fleet import broadcast_waves
from dla_tpu.serving.server import ServingConfig

MAX_NEW = 5


@pytest.fixture(scope="module")
def model_and_params():
    cfg = get_model_config("tiny")
    model = Transformer(cfg)
    return model, model.init(jax.random.key(7))


@pytest.fixture(scope="module")
def prompt_batch():
    rs = np.random.RandomState(3)
    prompts = [list(rs.randint(3, 500, (n,))) for n in (6, 4, 9, 5)]
    width = max(len(p) for p in prompts)
    ids = np.zeros((len(prompts), width), np.int32)
    mask = np.zeros_like(ids)
    for i, p in enumerate(prompts):
        ids[i, :len(p)] = p
        mask[i, :len(p)] = 1
    return ids, mask


def _serving_cfg(**kw):
    base = dict(page_size=4, num_pages=64, num_slots=3,
                max_model_len=32, max_prefill_batch=2, fault_plan="")
    base.update(kw)
    return ServingConfig(**base)


def _gen(**kw):
    base = dict(max_new_tokens=MAX_NEW, do_sample=True, temperature=0.9,
                top_p=0.9, top_k=8, eos_token_id=2, pad_token_id=0)
    base.update(kw)
    return GenerationConfig(**base)


def _batch_reference(model, params, gen, ids, mask, seeds):
    fn = jax.jit(build_generate_fn(model, gen, group_size=1,
                                   per_request_seeds=True))
    return fn(params, jnp.asarray(ids), jnp.asarray(mask),
              jnp.asarray(seeds, jnp.uint32))


def _assert_parity(ref, out):
    """Tokens/masks bit-identical to the batch path; logps to float32
    ulp (paged and contiguous attention round differently — same
    tolerance test_rollout pins for the single engine). Fleet-vs-fleet
    comparisons (the chaos acceptance) assert FULL bit identity
    instead, logps included."""
    for key in ("response_mask", "response_tokens", "sequence_mask",
                "sequences", "lengths"):
        assert np.array_equal(np.asarray(ref[key]),
                              np.asarray(out[key])), key
    rmask = np.asarray(ref["response_mask"])
    np.testing.assert_allclose(
        np.asarray(out["response_logps"]) * rmask,
        np.asarray(ref["response_logps"]) * rmask,
        atol=1e-5, rtol=0)


# ---------------------------------------------------------------------------
# pure pieces: wave schedule, fault grammar, sharding, config
# ---------------------------------------------------------------------------

def test_broadcast_waves_depth_not_n():
    # root holds the payload; coverage multiplies by (1 + branch)/wave
    assert broadcast_waves(4, 2) == [[0, 1], [2, 3]]
    assert broadcast_waves(1, 2) == [[0]]
    assert broadcast_waves(7, 2) == [[0, 1], [2, 3, 4, 5, 6]]
    assert broadcast_waves(0, 2) == []
    # depth grows logarithmically: 64 members in 4 waves at branch 2
    assert len(broadcast_waves(64, 2)) == 4
    with pytest.raises(ValueError):
        broadcast_waves(4, 0)
    covered = [i for w in broadcast_waves(13, 3) for i in w]
    assert covered == list(range(13))


def test_sampler_fault_grammar_roundtrip():
    plan = FaultPlan.parse(
        "sampler=1:rollout_step=2:lost;sampler=0:rollout_step=0:slow:0.2")
    assert len(plan.entries) == 2
    by_kind = {f.kind: f for f in plan.entries}
    lost, slow = by_kind["lost"], by_kind["slow"]
    assert (lost.site, lost.host, lost.step, lost.kind) == \
        ("sampler", 1, 2, "lost")
    assert (slow.site, slow.host, slow.step, slow.kind, slow.arg) == \
        ("sampler", 0, 0, "slow", 0.2)
    assert FaultPlan.parse(plan.spec()).spec() == plan.spec()
    # one-shot take, disjoint from the other five scopes
    assert plan.take("lost", 2, site="sampler") is lost
    assert plan.take("lost", 2, site="sampler") is None
    assert plan.take("slow", 5, site="host") is None
    with pytest.raises(ValueError):        # must be rollout_step=
        FaultPlan.parse("sampler=1:step=2:lost")
    with pytest.raises(ValueError):        # not a sampler kind
        FaultPlan.parse("sampler=1:rollout_step=2:wedge")


def test_shard_trajectory_groups_deterministic():
    def tg(g):
        return TrajectoryGroup(group=g, member=0, version=0, epoch=0,
                               rows={})
    # completion order scrambled; sharding must not care
    groups = [tg(g) for g in (5, 0, 3, 6, 1, 4, 2)]
    shards = shard_trajectory_groups(groups, 3)
    assert [[g.group for g in s] for s in shards] == \
        [[0, 1, 2], [3, 4], [5, 6]]
    assert shard_trajectory_groups([], 2) == [[], []]
    with pytest.raises(ValueError):
        shard_trajectory_groups(groups, 0)


def test_fleet_config_validation():
    cfg = SamplerFleetConfig.from_config(None)
    assert cfg.samplers == 2 and cfg.min_samplers == 1
    assert SamplerFleetConfig.from_config(
        {"samplers": 4, "lease_ttl_s": 0.5}).samplers == 4
    with pytest.raises(ValueError, match="unknown ppo.rollout.fleet"):
        SamplerFleetConfig.from_config({"smaplers": 4})
    with pytest.raises(ValueError):
        SamplerFleetConfig(samplers=0)
    with pytest.raises(ValueError):
        SamplerFleetConfig(samplers=2, min_samplers=3)


def test_fleet_metrics_snapshot_names():
    assert set(SamplerFleetMetrics().snapshot()) == {
        "rollout/fleet/samplers_active",
        "rollout/fleet/refit_fanout_ms",
        "rollout/fleet/retired_samplers",
        "rollout/fleet/reassigned_rollouts",
        "rollout/fleet/trajectory_queue_depth",
    }


# ---------------------------------------------------------------------------
# parity + refit fanout
# ---------------------------------------------------------------------------

def test_fleet_parity_refit_fanout_versions(model_and_params,
                                            prompt_batch):
    """An N=3 fleet (uneven 4-groups-over-3 split) reproduces the
    seeded batch path bit-identically; one publish_params fans out to
    every member over the broadcast tree with zero recompiles, and
    ``row_versions`` carries the stamped version."""
    model, params = model_and_params
    ids, mask = prompt_batch
    gen = _gen()
    seeds = derive_rollout_seeds(123, len(ids))
    ref = _batch_reference(model, params, gen, ids, mask, seeds)

    fleet = SamplerFleet(model, params, gen, _serving_cfg(),
                         SamplerFleetConfig(samplers=3))
    try:
        out = fleet.generate(ids, mask, seeds)
        _assert_parity(ref, out)
        assert np.asarray(out["row_versions"]).tolist() == [0] * len(ids)

        # same-tree refit through the shared WeightRefitter surface:
        # every member lands on version 1, outputs reproduce
        refitter = WeightRefitter(fleet, lambda: params)
        refitter.refit(version=1)
        assert [m.version for m in fleet.active()] == [1, 1, 1]
        assert fleet.version == 1
        out1 = fleet.generate(ids, mask, seeds)
        _assert_parity(ref, out1)
        assert np.asarray(out1["row_versions"]).tolist() == [1] * len(ids)

        # perturbed tree changes outputs; compile counters stay pinned
        bumped = jax.tree.map(lambda x: x * 1.01, params)
        refitter.refit(bumped, version=2)
        out2 = fleet.generate(ids, mask, seeds)
        assert not np.array_equal(np.asarray(ref["response_logps"]),
                                  np.asarray(out2["response_logps"]))
        for m in fleet.active():
            assert m.engine.engine.decode_compiles == 1
        snap = fleet.fleet_metrics.snapshot()
        assert snap["rollout/fleet/samplers_active"] == 3
        assert snap["rollout/fleet/refit_fanout_ms"] > 0
        assert snap["rollout/fleet/retired_samplers"] == 0
        # validation errors surface per member, not silently swallowed
        assert fleet.metrics.snapshot()["rollout/rollouts"] == 3
    finally:
        fleet.close()


def test_refit_timeout_retires_member_without_stalling(model_and_params,
                                                       prompt_batch):
    """A member whose executor is wedged misses its publish deadline;
    the fanout retires it after the bounded retries instead of
    stalling the learner, and the survivor finishes the next rollout
    with full parity."""
    model, params = model_and_params
    ids, mask = prompt_batch
    gen = _gen()
    seeds = derive_rollout_seeds(123, len(ids))
    ref = _batch_reference(model, params, gen, ids, mask, seeds)

    fleet = SamplerFleet(
        model, params, gen, _serving_cfg(),
        SamplerFleetConfig(samplers=2, refit_timeout_s=0.15,
                           refit_retries=1, retire_after_failures=1))
    try:
        wedged = fleet.active()[1]
        wedged.pool.submit(time.sleep, 4.0)      # occupy its executor
        t0 = time.monotonic()
        fleet.publish_params(params, version=1)
        wall = time.monotonic() - t0
        # bounded by (1 + retries) * timeout per member, NOT the wedge
        assert wall < 2.0, f"fanout stalled {wall:.2f}s on wedged member"
        assert wedged.retired
        snap = fleet.fleet_metrics.snapshot()
        assert snap["rollout/fleet/retired_samplers"] == 1
        assert snap["rollout/fleet/samplers_active"] == 1
        assert fleet.active()[0].version == 1

        out = fleet.generate(ids, mask, seeds)
        _assert_parity(ref, out)
        assert np.asarray(out["row_versions"]).tolist() == [1] * len(ids)
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# lose a sampler, not the run
# ---------------------------------------------------------------------------

def test_sampler_lost_reassigned_bit_identical(model_and_params,
                                               prompt_batch):
    """``sampler=1:rollout_step=0:lost`` silences member 1 mid-rollout;
    the collector detects the stale lease, retires it, reassigns its
    journaled (prompt, seed) groups to the survivor — and the rollout
    arrays come out bit-identical to the fault-free reference. With
    ``regrow``, the next rollout respawns to target size."""
    model, params = model_and_params
    ids, mask = prompt_batch
    gen = _gen()
    seeds = derive_rollout_seeds(123, len(ids))
    ref = _batch_reference(model, params, gen, ids, mask, seeds)

    fleet = SamplerFleet(
        model, params, gen,
        _serving_cfg(fault_plan="sampler=1:rollout_step=0:lost"),
        SamplerFleetConfig(samplers=2, lease_ttl_s=0.3, regrow=True))
    try:
        out = fleet.generate(ids, mask, seeds)
        _assert_parity(ref, out)
        snap = fleet.fleet_metrics.snapshot()
        assert snap["rollout/fleet/retired_samplers"] == 1
        assert snap["rollout/fleet/reassigned_rollouts"] >= 1
        assert snap["rollout/fleet/samplers_active"] == 1

        # regrow: back to target size, and the respawned member samples
        # from the CURRENT tree — next rollout still bit-identical
        out2 = fleet.generate(ids, mask, seeds)
        _assert_parity(ref, out2)
        assert fleet.fleet_metrics.snapshot()[
            "rollout/fleet/samplers_active"] == 2
    finally:
        fleet.close()


def test_sampler_slow_completes_without_retire(model_and_params,
                                               prompt_batch):
    """``slow`` lags a member below the lease TTL: an early-warning
    path, not a death — nothing retires, output parity holds."""
    model, params = model_and_params
    ids, mask = prompt_batch
    gen = _gen()
    seeds = derive_rollout_seeds(123, len(ids))
    ref = _batch_reference(model, params, gen, ids, mask, seeds)

    fleet = SamplerFleet(
        model, params, gen,
        _serving_cfg(fault_plan="sampler=0:rollout_step=0:slow:0.01"),
        SamplerFleetConfig(samplers=2, lease_ttl_s=5.0))
    try:
        out = fleet.generate(ids, mask, seeds)
        _assert_parity(ref, out)
        snap = fleet.fleet_metrics.snapshot()
        assert snap["rollout/fleet/retired_samplers"] == 0
        assert snap["rollout/fleet/samplers_active"] == 2
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# cross-rollout isolation: stale producers can never corrupt a rollout
# ---------------------------------------------------------------------------

def test_stale_queue_entries_never_leak_across_rollouts(model_and_params,
                                                        prompt_batch):
    """A retired-but-alive member may leave emissions on the trajectory
    queue between rollouts; ``generate`` drains leftovers before
    dispatching and the collector discards any group not tagged (this
    rollout, current owner). The poisoned entries below carry empty
    rows — if any were ever seated, assembly would crash or parity
    would break."""
    model, params = model_and_params
    ids, mask = prompt_batch
    gen = _gen()
    seeds = derive_rollout_seeds(123, len(ids))
    ref = _batch_reference(model, params, gen, ids, mask, seeds)

    fleet = SamplerFleet(model, params, gen, _serving_cfg(),
                         SamplerFleetConfig(samplers=2))
    try:
        for g in range(3):
            fleet._traj_q.put(TrajectoryGroup(
                group=g, member=0, version=9, epoch=0, rows={},
                rollout=-1))
        out = fleet.generate(ids, mask, seeds)
        _assert_parity(ref, out)
        assert np.asarray(out["row_versions"]).tolist() == [0] * len(ids)
    finally:
        fleet.close()


def test_collect_rejects_stale_rollout_and_foreign_owner(model_and_params):
    """``_collect`` accepts a group only from its current owner for the
    current rollout index: a stale-rollout emission and one from a
    member whose groups were reassigned away are both discarded rather
    than seated via first-arrival."""
    model, params = model_and_params
    gen = _gen()
    fleet = SamplerFleet(model, params, gen, _serving_cfg(),
                         SamplerFleetConfig(samplers=1))
    try:
        slot = fleet.active()[0].slot
        stale = TrajectoryGroup(group=0, member=slot, version=0, epoch=0,
                                rows={}, rollout=99)
        foreign = TrajectoryGroup(group=0, member=slot + 1, version=0,
                                  epoch=0, rows={}, rollout=3)
        good = TrajectoryGroup(group=0, member=slot, version=0, epoch=0,
                               rows={}, rollout=3)
        for tg in (stale, foreign, good):
            fleet._traj_q.put(tg)
        done = fleet._collect(3, 1, {0: slot}, (4, MAX_NEW))
        assert done[0] is good
    finally:
        fleet.close()


def test_retired_member_emit_drops_instead_of_spinning(model_and_params):
    """A member retired while blocked on a full queue must drop its
    group and release its executor thread — not spin re-filling the
    bounded queue with garbage for the rest of the run."""
    model, params = model_and_params
    gen = _gen()
    fleet = SamplerFleet(model, params, gen, _serving_cfg(),
                         SamplerFleetConfig(samplers=1, traj_queue_cap=1))
    try:
        m = fleet.active()[0]
        fleet._traj_q.put(TrajectoryGroup(group=0, member=m.slot,
                                          version=0, epoch=0, rows={},
                                          rollout=0))   # queue now full
        fleet._retire(m, "test")
        t = threading.Thread(target=fleet._emit, args=(m, 1, {}, 0),
                             daemon=True)
        t.start()
        t.join(timeout=5.0)
        assert not t.is_alive(), "_emit spun on a retired member"
        assert fleet._traj_q.qsize() == 1   # nothing new enqueued
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# heterogeneous per-trajectory staleness
# ---------------------------------------------------------------------------

def test_heterogeneous_staleness_per_trajectory(model_and_params,
                                                prompt_batch):
    """Members refit at different learner versions inside ONE batch:
    the staleness/IS machinery must act per trajectory. Rows from the
    current-version member keep weight exactly 1; only the laggard's
    rows get the truncated-IS correction — different from the old
    per-batch path, which would have corrected every row. Advantages
    stay finite throughout."""
    model, params = model_and_params
    ids, mask = prompt_batch
    gen = _gen()
    seeds = derive_rollout_seeds(123, len(ids))

    fleet = SamplerFleet(model, params, gen, _serving_cfg(),
                         SamplerFleetConfig(samplers=2))
    try:
        # refit ONLY member 0 to the bumped tree at version 2 — the
        # shape a fanout-failed member leaves behind (it keeps its old
        # weights and old tag)
        bumped = jax.tree.map(lambda x: x * 1.05, params)
        m0 = fleet.active()[0]
        m0.pool.submit(fleet._publish_one, m0, bumped, False, 2).result()
        out = fleet.generate(ids, mask, seeds)
        # round-robin: even groups -> member 0 (fresh), odd -> member 1
        versions = np.asarray(out["row_versions"])
        assert versions.tolist() == [2, 0, 2, 0]

        # the pipeline helper turns tags into the per-trajectory vector
        pipe = RolloutPipeline.__new__(RolloutPipeline)
        pipe._state_lock = threading.Lock()
        pipe._updates = 2            # learner is at update 2
        worst = pipe._attach_row_staleness(out)
        stale = np.asarray(out["staleness_updates"])
        assert stale.tolist() == [0, 2, 0, 2] and worst == 2

        corr = make_staleness_corrector(model, is_clip=2.0)
        w = np.asarray(corr(bumped, out))
        assert np.all(np.isfinite(w)) and np.all(w <= 2.0)
        # laggard rows sampled under OLD weights: ratio visibly != 1
        assert np.any(np.abs(w[stale > 0] - 1.0) > 1e-4)

        # per-trajectory gating (the train_rlhf path): fresh rows are
        # weight 1 EXACTLY; the old per-batch path corrected them too
        w_traj = np.asarray(jnp.where(jnp.asarray(stale) > 0,
                                      jnp.asarray(w), jnp.float32(1.0)))
        assert np.all(w_traj[stale == 0] == 1.0)
        assert not np.array_equal(w_traj, w)
        adv = apply_staleness_correction(
            jnp.ones((len(w_traj), 3)), jnp.asarray(w_traj))
        assert np.all(np.isfinite(np.asarray(adv)))

        # sharding carries the heterogeneous tags through untouched
        tgs = [TrajectoryGroup(group=g, member=g % 2,
                               version=int(versions[g]), epoch=0, rows={})
               for g in range(4)]
        shards = shard_trajectory_groups(tgs, 2)
        assert [[t.version for t in s] for s in shards] == [[2, 0], [2, 0]]
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# chaos acceptance: elastic run == planned run
# ---------------------------------------------------------------------------

def _learner_loop(model, params, gen, ids, mask, *, samplers, fault_plan,
                  rollouts=3):
    """A deterministic async-fleet learner loop: consume rollout k,
    derive the next params deterministically FROM the rollout (so final
    params pin every intermediate rollout bit-for-bit), notify. Returns
    (rollout outputs, final params, per-member decode compiles, fleet
    metric snapshot)."""
    def sample_fn(idx):
        return ids, mask, derive_rollout_seeds(9000 + idx, len(ids))

    pipe = build_rollout_pipeline(
        model, params, gen, sample_fn,
        rows=len(ids), prompt_width=ids.shape[1], mode="async",
        max_staleness_updates=2,
        serving={"page_size": 4, "fault_plan": fault_plan},
        fleet={"samplers": samplers, "lease_ttl_s": 0.3})
    assert pipe.deterministic_refit
    try:
        outs = []
        p = params
        for k in range(rollouts):
            out, staleness = pipe.get(k)
            assert staleness <= 2
            # zero lost trajectory groups: every row came home
            assert np.asarray(out["response_tokens"]).shape[0] == len(ids)
            outs.append({k: np.asarray(v) for k, v in out.items()})
            # the "update": a deterministic function of the rollout
            seen = int(np.asarray(out["response_tokens"]).sum()
                       + np.asarray(out["lengths"]).sum())
            scale = np.float32(1.0 + 1e-4 * (seen % 13))
            p = jax.tree.map(lambda x, s=scale: x * s, p)
            pipe.notify_updates(1, params=p)
        compiles = sorted(
            (m.engine.engine.prefill_compiles,
             m.engine.engine.decode_compiles)
            for m in pipe.rollout._samplers
            if m.engine.engine.decode_compiles)
        snap = pipe.rollout.fleet_metrics.snapshot()
        return outs, p, compiles, snap
    finally:
        pipe.close()


def test_chaos_acceptance_elastic_equals_planned(model_and_params,
                                                 prompt_batch):
    """THE acceptance property: an N=4 async fleet run that loses
    sampler 1 mid-rollout (``sampler=`` plan) completes every rollout
    with zero lost trajectory groups, regenerates the reassigned groups
    bit-identically from the journal, and lands on final params
    bit-identical to a planned N=3 run — with decode/prefill compile
    counters at one per engine build in both runs."""
    model, params = model_and_params
    gen = _gen()
    # 8 groups over 4 members = 2 per member: the killed member's one
    # kill-budget group leaves its SECOND group in flight — the
    # reassignment path must fire, not just the retirement
    rs = np.random.RandomState(11)
    prompts = [list(rs.randint(3, 500, (n,)))
               for n in (6, 4, 9, 5, 7, 3, 8, 5)]
    width = max(len(p) for p in prompts)
    ids = np.zeros((len(prompts), width), np.int32)
    mask = np.zeros_like(ids)
    for i, p in enumerate(prompts):
        ids[i, :len(p)] = p
        mask[i, :len(p)] = 1

    chaos = _learner_loop(model, params, gen, ids, mask, samplers=4,
                          fault_plan="sampler=1:rollout_step=1:lost")
    planned = _learner_loop(model, params, gen, ids, mask, samplers=3,
                            fault_plan="")

    c_outs, c_params, c_compiles, c_snap = chaos
    p_outs, p_params, p_compiles, p_snap = planned
    assert c_snap["rollout/fleet/retired_samplers"] == 1
    assert c_snap["rollout/fleet/reassigned_rollouts"] >= 1
    assert c_snap["rollout/fleet/samplers_active"] == 3
    assert p_snap["rollout/fleet/retired_samplers"] == 0

    # every rollout bit-identical across the two topologies
    for k, (co, po) in enumerate(zip(c_outs, p_outs)):
        for key in ("response_tokens", "response_mask", "sequences",
                    "sequence_mask", "response_logps", "lengths"):
            assert np.array_equal(co[key], po[key]), (k, key)
    # ... so the final params are too
    c_leaves = jax.tree_util.tree_leaves(c_params)
    p_leaves = jax.tree_util.tree_leaves(p_params)
    assert len(c_leaves) == len(p_leaves)
    for cl, pl in zip(c_leaves, p_leaves):
        assert np.array_equal(np.asarray(cl), np.asarray(pl))
    # decode compiled exactly once per engine build, elastic or
    # planned; prefill compiles once per width BUCKET a member saw
    # (reassignment shifts widths between members, never re-traces a
    # width twice)
    assert all(d == 1 for _, d in c_compiles)
    assert all(d == 1 for _, d in p_compiles)
    assert all(pf >= 1 for pf, _ in c_compiles + p_compiles)


# ---------------------------------------------------------------------------
# bench: fanout bounded by tree depth, zero steps lost
# ---------------------------------------------------------------------------

def test_bench_rollout_fleet_depth_bound_and_zero_loss():
    """The bench A/B the fanout exists for: at N=4 branch=2 the
    broadcast refit pays ~2 per-member delays (tree depth) where the
    serial baseline pays ~4 (N) — and the chaos leg loses zero learner
    steps to a sampler death."""
    import bench
    row = bench.run_rollout_fleet_bench()
    assert row["metric"] == "rollout_fleet_fanout_speedup"
    d = row["detail"]
    # wall time bounded by tree depth, not N: ideal ratio N/waves = 2
    assert row["value"] > 1.4
    assert d["broadcast_refit_ms"] < d["serial_refit_ms"]
    assert d["fanout_waves"] == 2 and d["samplers"] == 4
    assert d["steps_lost_to_sampler_death"] == 0
    assert d["outputs_identical_n1_n4"]
    assert d["retired_samplers"] == 1 and d["reassigned_rollouts"] >= 1


# ---------------------------------------------------------------------------
# pipeline close ordering (satellite regression)
# ---------------------------------------------------------------------------

class _BlockingRollout:
    """Minimal rollout double whose generate() is instant — so the
    generator thread races ahead and blocks on the depth-1 queue's
    put — and whose close() records whether the generator had already
    exited (the ordering the fix guarantees)."""

    def __init__(self):
        self.metrics = RolloutMetrics()
        self.stop_requested = False
        self.generator_alive_at_close = None
        self._thread_ref = None

    def generate(self, ids, mask, seeds, max_new=None):
        return {"response_tokens": np.zeros((2, 2), np.int32),
                "response_mask": np.ones((2, 2), np.int32)}

    def request_stop(self):
        self.stop_requested = True

    def close(self):
        t = self._thread_ref
        self.generator_alive_at_close = bool(t and t.is_alive())


def test_close_releases_blocked_generator():
    """Regression: close() must release a generator thread blocked on
    the depth-1 queue BEFORE tearing the engine down — closing the
    supervisor under a live generator was a deadlock."""
    roll = _BlockingRollout()
    pipe = RolloutPipeline(roll, lambda i: (np.zeros((2, 2), np.int32),
                                            np.ones((2, 2), np.int32),
                                            [0, 1]),
                           mode="async")
    out, staleness = pipe.get(0)
    assert staleness == 0
    deadline = time.monotonic() + 10.0
    while not pipe._q.full() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert pipe._q.full(), "generator never refilled the queue"
    # generator is now (or is about to be) blocked in the queue put
    roll._thread_ref = pipe._thread
    t0 = time.monotonic()
    pipe.close(timeout=5.0)
    assert time.monotonic() - t0 < 5.0, "close() hit its deadline"
    assert roll.stop_requested
    assert roll.generator_alive_at_close is False, \
        "engine closed while the generator thread was still alive"


def test_close_releases_deterministic_handoff_wait():
    """Same ordering guarantee for a generator parked in the
    deterministic-refit handoff wait (no notify ever arrives)."""
    roll = _BlockingRollout()
    pipe = RolloutPipeline(roll, lambda i: (np.zeros((2, 2), np.int32),
                                            np.ones((2, 2), np.int32),
                                            [0, 1]),
                           mode="async", deterministic_refit=True)
    out, _ = pipe.get(0)                 # rollout 0 needs no handoff
    time.sleep(0.1)                      # generator enters the wait
    roll._thread_ref = pipe._thread
    t0 = time.monotonic()
    pipe.close(timeout=5.0)
    assert time.monotonic() - t0 < 5.0
    assert roll.generator_alive_at_close is False
