"""Scale-out dress rehearsal (r4 VERDICT item 8): the virtual-mesh
memory-analysis tool must compile real configs at 16 and 32 devices and
report per-device numbers that scale with the mesh."""
import json
import subprocess
import sys
from pathlib import Path

import pytest
import yaml

REPO_ROOT = Path(__file__).resolve().parent.parent


def _run_rehearsal(tmp_path, n_devices, mesh_override, **model_over):
    sys.path.insert(0, str(REPO_ROOT))
    from _cpuhost import scrubbed_cpu_env

    cfg = yaml.safe_load(
        (REPO_ROOT / "config" / "sft_llama2_70b_v5e256.yaml").read_text())
    cfg["model"]["model_name_or_path"] = "tiny-gqa"
    cfg["model"]["max_seq_length"] = 128
    cfg["model"].update(model_over)
    cfg["optimization"]["micro_batch_size"] = 2
    cfg["optimization"]["total_batch_size"] = (
        2 * mesh_override.get("fsdp", 1) * mesh_override.get("data", 1)
        * int(cfg["hardware"]["gradient_accumulation_steps"]))
    p = tmp_path / "rehearse.yaml"
    p.write_text(yaml.safe_dump(cfg))
    mesh_s = ",".join(f"{k}={v}" for k, v in mesh_override.items())
    out = subprocess.run(
        [sys.executable, str(REPO_ROOT / "tools" / "scale_rehearsal.py"),
         str(p), str(n_devices), mesh_s],
        env=scrubbed_cpu_env(n_devices, str(REPO_ROOT)),
        cwd=str(REPO_ROOT), capture_output=True, text=True, timeout=420)
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_rehearsal_16_devices(tmp_path):
    r = _run_rehearsal(tmp_path, 16, {"fsdp": 8, "model": 2})
    assert r["n_devices"] == 16
    assert r["mesh"]["fsdp"] == 8 and r["mesh"]["model"] == 2
    assert r["per_device"]["total_gb"] > 0
    assert r["fits_v5e"] is True  # tiny model trivially fits


def test_rehearsal_32_devices_args_shrink(tmp_path):
    """Per-device argument bytes (params + opt shards) must shrink as
    the fsdp axis widens — the partitioned-residency claim itself."""
    r16 = _run_rehearsal(tmp_path, 16, {"fsdp": 8, "model": 2})
    r32 = _run_rehearsal(tmp_path, 32, {"fsdp": 16, "model": 2})
    assert r32["per_device"]["arguments_gb"] < r16["per_device"]["arguments_gb"]


def test_rehearsal_pp_config_compiles(tmp_path):
    """PP configs rehearse in their real dtype: the tool disables
    XLA:CPU's all-reduce-promotion pass (which check-fails on the
    pipeline shard_map program, "Invalid binary instruction opcode
    copy" — CPU-only pass, bisected r5; irrelevant to a compile-only
    analysis and never run on TPU)."""
    r = _run_rehearsal(tmp_path, 16, {"stage": 2, "fsdp": 4, "model": 2},
                       pipeline_microbatches=4)
    assert r["mesh"]["stage"] == 2
    assert r["per_device"]["total_gb"] > 0
