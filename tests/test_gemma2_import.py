"""Gemma-2 architecture: logits parity with transformers'
Gemma2ForCausalLM — attention + final logit softcapping, pre+post norms
(four RMSNorms per block, (1+w) folded at import), alternating-layer
sliding window (even layers slide), query_pre_attn_scalar softmax scale
— plus decode parity and the fused-CE softcap path. Closes the one
refused HF family from round 3 (VERDICT item 8)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def tiny_gemma2_dir(tmp_path_factory):
    from transformers import Gemma2Config, Gemma2ForCausalLM
    cfg = Gemma2Config(
        vocab_size=160, hidden_size=32, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64, rms_norm_eps=1e-6,
        rope_theta=10000.0, hidden_activation="gelu_pytorch_tanh",
        tie_word_embeddings=True,
        # small window + 10-token prompts make the alternation visible;
        # query_pre_attn_scalar != head_dim pins the custom scale
        sliding_window=4, attn_logit_softcapping=50.0,
        final_logit_softcapping=30.0, query_pre_attn_scalar=8,
        # sdpa silently ignores gemma-2 softcapping; eager implements it
        attn_implementation="eager")
    torch.manual_seed(0)
    model = Gemma2ForCausalLM(cfg).eval()
    d = tmp_path_factory.mktemp("hf_gemma2")
    model.save_pretrained(str(d), safe_serialization=True)
    return d, model


def _load(d):
    from dla_tpu.models.hf_import import (
        hf_config_to_model_config,
        import_hf_weights,
        read_hf_config,
    )
    cfg = hf_config_to_model_config(
        read_hf_config(d), dtype="float32", param_dtype="float32",
        remat="none")
    return cfg, import_hf_weights(d, cfg)


def test_gemma2_config_mapping(tiny_gemma2_dir):
    d, _ = tiny_gemma2_dir
    cfg, params = _load(d)
    assert cfg.arch == "gemma2"
    assert cfg.attn_logit_softcap == 50.0
    assert cfg.final_logit_softcap == 30.0
    assert cfg.query_pre_attn_scalar == 8
    assert cfg.sliding_window == 4 and cfg.sliding_window_pattern == 2
    assert cfg.tie_embeddings
    for k in ("attn_norm", "attn_post_norm", "mlp_norm", "mlp_post_norm"):
        assert k in params["layers"], k


def test_gemma2_import_matches_hf_logits(tiny_gemma2_dir):
    d, hf_model = tiny_gemma2_dir
    import jax.numpy as jnp
    from dla_tpu.models.transformer import Transformer

    cfg, params = _load(d)
    model = Transformer(cfg)
    rs = np.random.RandomState(0)
    # 10 tokens > window 4: positions past the window differ between the
    # sliding (even) and full (odd) layers — parity proves alternation
    ids = rs.randint(0, 160, (2, 10))
    ours = np.asarray(model.apply(params, jnp.asarray(ids, jnp.int32)))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=2e-4)


def test_gemma2_window_actually_alternates(tiny_gemma2_dir):
    """Sanity: with the window forced UNIFORM (pattern=1) the logits must
    DIFFER from HF (which alternates) — guards against a vacuous parity
    test where the window never engages."""
    d, hf_model = tiny_gemma2_dir
    import dataclasses
    import jax.numpy as jnp
    from dla_tpu.models.transformer import Transformer

    cfg, params = _load(d)
    uni = Transformer(dataclasses.replace(cfg, sliding_window_pattern=1))
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 160, (2, 10))
    ours = np.asarray(uni.apply(params, jnp.asarray(ids, jnp.int32)))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(ids)).logits.numpy()
    assert not np.allclose(ours, theirs, rtol=2e-3, atol=2e-4)


def test_gemma2_decode_matches_forward(tiny_gemma2_dir):
    """Softcaps, alternating window, and post-norms reach the KV-cache
    decode path; run past the window so old keys drop out on the
    sliding layers."""
    d, _ = tiny_gemma2_dir
    import jax.numpy as jnp
    from dla_tpu.models.transformer import Transformer

    cfg, params = _load(d)
    model = Transformer(cfg)
    rs = np.random.RandomState(1)
    ids = jnp.asarray(rs.randint(1, 160, (2, 6)), jnp.int32)
    mask = jnp.ones((2, 6), jnp.int32)
    n_new = 4
    logits, cache = model.start_decode(params, ids, mask, n_new)
    got = []
    for _ in range(n_new):
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        got.append(np.asarray(tok))
        logits, cache = model.decode_step(params, cache, tok)
    got = np.stack(got, axis=1)

    want = np.zeros_like(got)
    for i in range(2):
        seq = list(np.asarray(ids[i]))
        for s in range(n_new):
            full = model.apply(params, jnp.asarray([seq], jnp.int32))
            nxt = int(np.argmax(np.asarray(full[0, -1])))
            want[i, s] = nxt
            seq.append(nxt)
    np.testing.assert_array_equal(got, want)


def test_gemma2_export_roundtrip(tmp_path, tiny_gemma2_dir):
    """Export writes the 4-norm layout with the (1+w) fold undone and a
    Gemma2Config transformers can load with identical logits."""
    d, _ = tiny_gemma2_dir
    import jax
    import jax.numpy as jnp
    from dla_tpu.models.hf_export import export_hf_weights
    from dla_tpu.models.hf_import import (
        hf_config_to_model_config,
        import_hf_weights,
        read_hf_config,
    )
    from dla_tpu.models.transformer import Transformer

    cfg, params = _load(d)
    out = export_hf_weights(params, cfg, tmp_path / "hf_gemma2_out")
    hf_cfg2 = read_hf_config(out)
    assert hf_cfg2["model_type"] == "gemma2"
    assert hf_cfg2["attn_logit_softcapping"] == 50.0
    params2 = import_hf_weights(out, hf_config_to_model_config(
        hf_cfg2, dtype="float32", param_dtype="float32", remat="none"))
    for a, b in zip(jax.tree.leaves(jax.tree.map(np.asarray, params)),
                    jax.tree.leaves(params2)):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)

    from transformers import Gemma2ForCausalLM
    model2 = Gemma2ForCausalLM.from_pretrained(
        str(out), torch_dtype=torch.float32,
        attn_implementation="eager").eval()
    rs = np.random.RandomState(3)
    ids = rs.randint(0, 160, (1, 9))
    ours = np.asarray(Transformer(cfg).apply(
        params, jnp.asarray(ids, jnp.int32)))
    with torch.no_grad():
        theirs = model2(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=2e-4)


def test_gemma2_token_logps_respect_softcap(tiny_gemma2_dir):
    """The RLHF per-token logp path (_token_logps_and_values, the GAE
    update/score math) must compute over CAPPED logits — regression for
    the round-4 review finding where it skipped the softcap while every
    other logprob path applied it."""
    d, _ = tiny_gemma2_dir
    import jax
    import jax.numpy as jnp
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.training.train_rlhf import _token_logps_and_values

    cfg, params = _load(d)
    model = Transformer(cfg)
    rs = np.random.RandomState(4)
    seqs = jnp.asarray(rs.randint(1, 160, (2, 8)), jnp.int32)
    mask = jnp.ones((2, 8), jnp.int32)
    lp, _, _ = _token_logps_and_values(model, params, seqs, mask)
    logits = model.apply(params, seqs, attention_mask=mask)  # capped
    want = jnp.take_along_axis(
        jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1),
        seqs[:, 1:, None], axis=-1)[..., 0]
    np.testing.assert_allclose(np.asarray(lp), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_gemma2_export_refuses_nonstandard_window_pattern(
        tmp_path, tiny_gemma2_dir):
    """Gemma2Config cannot express sliding_window_pattern != 2 (the
    alternation is implicit); exporting such a model must REFUSE rather
    than silently round-tripping to different logits (import hard-codes
    pattern 2 back)."""
    d, _ = tiny_gemma2_dir
    import dataclasses

    import pytest as _pytest

    from dla_tpu.models.hf_export import export_hf_weights

    cfg, params = _load(d)
    uni = dataclasses.replace(cfg, sliding_window_pattern=1)
    with _pytest.raises(ValueError, match="sliding_window_pattern"):
        export_hf_weights(params, uni, tmp_path / "refused")


def test_gemma2_int8_cache_decode_tracks_fp(tiny_gemma2_dir):
    """gemma-2 x int8 KV cache: softcapped, alternating-window decode
    over a quantized cache stays close to the full-precision cache."""
    d, _ = tiny_gemma2_dir
    import dataclasses
    import jax.numpy as jnp
    from dla_tpu.models.transformer import Transformer

    cfg, params = _load(d)
    m_fp = Transformer(cfg)
    m_q = Transformer(dataclasses.replace(cfg, kv_cache_dtype="int8"))
    rs = np.random.RandomState(6)
    ids = jnp.asarray(rs.randint(1, 160, (2, 6)), jnp.int32)
    mask = jnp.ones((2, 6), jnp.int32)
    lf, cf = m_fp.start_decode(params, ids, mask, 4)
    lq, cq = m_q.start_decode(params, ids, mask, 4)
    for _ in range(4):
        tok = jnp.argmax(lf, axis=-1).astype(jnp.int32)
        lf, cf = m_fp.decode_step(params, cf, tok)
        lq, cq = m_q.decode_step(params, cq, tok)
        # asserted after stepping: the final step reads the most
        # quantized columns
        np.testing.assert_allclose(np.asarray(lq), np.asarray(lf),
                                   rtol=0.06, atol=0.2)


def test_gemma2_long_seq_factored_mask_matches_short_path(tiny_gemma2_dir):
    """At T > DEFAULT_Q_CHUNK the flash-ineligible gemma-2 forward takes
    the chunked path with FACTORED masks (no [B,T,T]); its output on a
    padded+packed batch must match running the same rows through the
    short-path (materialized-mask) forward, position by position."""
    d, _ = tiny_gemma2_dir
    import jax.numpy as jnp
    from dla_tpu.models.transformer import Transformer

    cfg, params = _load(d)
    model = Transformer(cfg)
    rs = np.random.RandomState(10)
    t_long = 640  # > DEFAULT_Q_CHUNK: factored/chunked engages
    ids = jnp.asarray(rs.randint(1, 160, (2, t_long)), jnp.int32)
    mask = np.ones((2, t_long), np.int32)
    mask[1, 600:] = 0                      # right padding on row 1
    mask = jnp.asarray(mask)
    long_out = np.asarray(model.apply(params, ids, attention_mask=mask))

    # reference: same rows re-run at short length through the
    # materialized-mask path — prefix logits must agree
    short = np.asarray(model.apply(params, ids[:, :160],
                                   attention_mask=mask[:, :160]))
    np.testing.assert_allclose(long_out[:, :160], short,
                               rtol=3e-3, atol=3e-4)



def test_gemma2_fused_ce_matches_unfused(tiny_gemma2_dir):
    """The chunked fused-CE path must apply the final-logit softcap —
    loss and grads equal the unfused logits+CE computation."""
    d, _ = tiny_gemma2_dir
    import jax
    import jax.numpy as jnp
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.ops.fused_ce import model_fused_ce
    from dla_tpu.ops.losses import cross_entropy_loss

    cfg, params = _load(d)
    model = Transformer(cfg)
    rs = np.random.RandomState(2)
    batch = {
        "input_ids": jnp.asarray(rs.randint(1, 160, (2, 12)), jnp.int32),
        "attention_mask": jnp.ones((2, 12), jnp.int32),
        "labels": jnp.asarray(
            np.where(rs.rand(2, 12) < 0.2, -100,
                     rs.randint(1, 160, (2, 12))), jnp.int32),
    }

    def fused(p):
        return model_fused_ce(model, p, batch)[0]

    def unfused(p):
        logits = model.apply(p, batch["input_ids"],
                             attention_mask=batch["attention_mask"])
        return cross_entropy_loss(logits, batch["labels"])[0]

    lf, gf = jax.value_and_grad(fused)(params)
    lu, gu = jax.value_and_grad(unfused)(params)
    np.testing.assert_allclose(float(lf), float(lu), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(gf), jax.tree.leaves(gu)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)
