"""Prefix-cache + chunked-prefill tests: allocator refcount/tri-state
invariants (shared pages are never freed while referenced, the trash
page is never cached), PrefixCache register/lookup/eviction semantics,
token-budget chunk admission, and the load-bearing e2e guarantees — on
a shared-prefix trace the cache saves >= 50% of prefill token compute,
greedy decode is TOKEN-IDENTICAL cache on vs off, and neither chunked
prefill nor the cache ever recompiles a step function."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dla_tpu.generation.engine import GenerationConfig, build_generate_fn
from dla_tpu.models.config import get_model_config
from dla_tpu.models.transformer import Transformer
from dla_tpu.serving import (
    PageAllocator,
    PrefixCache,
    ServingConfig,
    ServingEngine,
)


# ---------------------------------------------------------------------------
# allocator refcounting + cached tri-state (pure host)
# ---------------------------------------------------------------------------

def test_allocator_incref_keeps_shared_page_allocated():
    a = PageAllocator(8)
    pages = a.alloc(2)
    a.incref(pages[0])               # second holder
    a.decref(pages[0])               # first holder drops
    assert a.refcount(pages[0]) == 1  # still allocated: not freed
    assert a.used_count == 2
    a.free(pages)                     # last references drop
    assert a.used_count == 0
    assert a.free_count == 7


def test_allocator_refzero_is_cached_not_free_with_retain_hook():
    """With a retain hook, a page dropping to refcount 0 parks on the
    cached LRU (revivable via incref) instead of returning to the free
    list; alloc under pressure reclaims cached pages oldest-first and
    fires the evict hook."""
    evicted = []
    a = PageAllocator(4)
    a.retain_hook = lambda p: True
    a.evict_hook = evicted.append
    pages = a.alloc(3)               # whole capacity
    a.decref(pages[0])
    a.decref(pages[1])
    assert a.cached_count == 2 and a.free_count == 0
    a.incref(pages[1])               # revive from cached
    assert a.refcount(pages[1]) == 1 and a.cached_count == 1
    got = a.alloc(1)                 # no free page: reclaims cached
    assert got == [pages[0]]
    assert evicted == [pages[0]]
    assert a.cache_evictions == 1
    a.free(got + [pages[1], pages[2]])


def test_allocator_trash_page_never_cached_and_errors_surface():
    a = PageAllocator(4)
    a.retain_hook = lambda p: True
    with pytest.raises(ValueError):
        a.incref(0)                  # trash page has no refcount
    with pytest.raises(ValueError):
        a.decref(0)
    pages = a.alloc(a.capacity)
    a.free(pages)                    # all parked on the cached LRU
    assert 0 not in a.cached_pages
    with pytest.raises(ValueError):
        a.decref(pages[0])           # page is cached, not referenced


def test_allocator_accounting_partitions_pool():
    a = PageAllocator(10)
    a.retain_hook = lambda p: p % 2 == 1
    held = a.alloc(6)
    for p in held[:4]:
        a.decref(p)                  # odd pages cache, even pages free
    assert a.used_count + a.free_count + a.cached_count == a.capacity
    assert a.used_count == 2
    assert a.cached_count == len([p for p in held[:4] if p % 2 == 1])


# ---------------------------------------------------------------------------
# PrefixCache register / lookup / eviction (host + tiny device pool)
# ---------------------------------------------------------------------------

PS = 4      # page size for the cache-level tests
CHUNK = 4


def _cache(num_pages=16):
    a = PageAllocator(num_pages)
    return PrefixCache(a, PS), a


def test_prefix_lookup_hits_full_pages_truncated_to_chunks():
    pc, a = _cache()
    toks = list(range(100, 112))            # 12 tokens = 3 full pages
    pages = a.alloc(3)
    pc.register(toks, pages)
    # identical 12-token prompt: hit must stay STRICTLY below n so the
    # final chunk always runs (it produces the first decode logits)...
    hit_pages, hit, logits = pc.lookup(toks, CHUNK)
    assert hit == 8 and hit_pages == pages[:2] and logits is None
    assert [a.refcount(p) for p in hit_pages] == [2, 2]  # pre-increfed
    for p in hit_pages:
        a.decref(p)
    # ...and a hit is truncated to a CHUNK multiple: 6 shared tokens
    # cover 1 full page but only chunk-aligned reuse keeps the absolute
    # chunk schedule (and the compiled chunk shape) intact
    hit_pages, hit, _ = pc.lookup(toks[:6] + [7, 8], CHUNK)
    assert hit == 4 and hit_pages == pages[:1]
    a.decref(pages[0])


def test_prefix_lookup_stops_at_first_hole():
    pc, a = _cache()
    toks = list(range(100, 112))
    pages = a.alloc(3)
    pc.register(toks, pages)
    pc.uncache_page = None  # not part of the API: just documenting
    # evict the MIDDLE page: the chain must truncate there, even though
    # the third page is still indexed
    a.free([pages[1]])  # refcount 0 -> cached
    # force reclaim of exactly that page
    while pages[1] in a.cached_pages:
        a.alloc(1)
    hit_pages, hit, _ = pc.lookup(toks, CHUNK)
    assert hit == 4 and hit_pages == pages[:1]
    a.decref(pages[0])


def test_prefix_register_first_writer_wins():
    pc, a = _cache()
    toks = list(range(100, 108))
    first = a.alloc(2)
    second = a.alloc(2)
    pc.register(toks, first)
    pc.register(toks, second)               # duplicate content: ignored
    hit_pages, hit, _ = pc.lookup(toks + [1, 2, 3, 4], CHUNK)
    assert hit_pages == first
    for p in first:
        a.decref(p)


def test_prefix_full_prompt_hit_returns_logits():
    pc, a = _cache()
    toks = list(range(100, 110))            # 10 tokens: 2 full + tail
    pages = a.alloc(3)
    stored = np.arange(8, dtype=np.float32)
    pc.register(toks, pages, stored)
    hit_pages, hit, logits = pc.lookup(toks, CHUNK)
    assert hit == len(toks)                 # exact-prompt: zero prefill
    assert hit_pages == pages               # tail page aliased too
    np.testing.assert_array_equal(logits, stored)
    # a DIFFERENT prompt sharing the full pages gets only those
    for p in hit_pages:
        a.decref(p)
    hit_pages, hit, logits = pc.lookup(toks[:9] + [7, 8, 9], CHUNK)
    assert hit == 8 and logits is None and hit_pages == pages[:2]
    for p in hit_pages:
        a.decref(p)


def test_prefix_peek_matches_lookup_without_side_effects():
    """peek() is the fleet router's placement probe: it must predict
    exactly what lookup() would hit while leaving refcounts, the cached
    LRU, and the lookup/hit counters untouched — probing N engines per
    admission must not distort cache behavior on any of them."""
    pc, a = _cache()
    toks = list(range(100, 112))            # 12 tokens = 3 full pages
    pages = a.alloc(3)
    pc.register(toks, pages)
    for p in pages:
        a.decref(p)                         # park all three on the LRU
    lru_before = list(a.cached_pages)
    lookups_before, hit_tokens_before = pc.lookups, pc.hit_tokens

    assert pc.peek(toks, CHUNK) == 8        # strict-below-n truncation
    assert pc.peek(toks[:6] + [7, 8], CHUNK) == 4
    assert pc.peek([1, 2, 3], CHUNK) == 0   # cold prompt

    # no refcounts taken, no LRU touch, no stats drift, peeks counted
    assert [a.refcount(p) for p in pages] == [0, 0, 0]
    assert list(a.cached_pages) == lru_before
    assert pc.lookups == lookups_before
    assert pc.hit_tokens == hit_tokens_before
    assert pc.peeks == 3

    # the probe's promise: the subsequent lookup hits exactly peek's
    # estimate (and only the lookup increfs)
    hit_pages, hit, _ = pc.lookup(toks, CHUNK)
    assert hit == 8 and [a.refcount(p) for p in hit_pages] == [1, 1]
    for p in hit_pages:
        a.decref(p)


def test_prefix_peek_full_prompt_and_eviction_order_unchanged():
    pc, a = _cache(num_pages=5)             # 4 usable: pool exactly full
    toks_a = list(range(100, 108))          # 2 full pages each
    toks_b = list(range(200, 208))
    pages_a, pages_b = a.alloc(2), a.alloc(2)
    pc.register(toks_a, pages_a, np.arange(4, dtype=np.float32))
    pc.register(toks_b, pages_b, np.arange(4, dtype=np.float32))
    for p in pages_a + pages_b:
        a.decref(p)
    assert pc.peek(toks_a, CHUNK) == len(toks_a)   # exact-prompt hit
    # peek must NOT refresh a's LRU position: under pressure a's pages
    # (the oldest) are still reclaimed first, exactly as if never peeked
    got = a.alloc(2)
    assert set(got) == set(pages_a)
    assert pc.peek(toks_a, CHUNK) < len(toks_a)    # full entry pruned
    a.free(got)


# ---------------------------------------------------------------------------
# e2e on the tiny model
# ---------------------------------------------------------------------------

MAX_NEW = 3
FAMILIES = 8
PER_FAMILY = 16
PREFIX_LEN = 9
SUFFIX_LEN = 3


@pytest.fixture(scope="module")
def model_and_params():
    cfg = get_model_config("tiny")
    model = Transformer(cfg)
    return model, model.init(jax.random.key(7))


@pytest.fixture(scope="module")
def shared_prefix_prompts():
    rs = np.random.RandomState(11)
    prompts = []
    for _ in range(FAMILIES):
        head = [int(t) for t in rs.randint(3, 500, (PREFIX_LEN,))]
        for _ in range(PER_FAMILY):
            prompts.append(head + [int(t)
                                   for t in rs.randint(3, 500, (SUFFIX_LEN,))])
    return prompts


def _engine(model, params, **kw):
    gen = GenerationConfig(max_new_tokens=MAX_NEW, do_sample=False,
                           temperature=0.0, eos_token_id=-1)
    scfg = ServingConfig(page_size=4, num_pages=kw.pop("num_pages", 64),
                         num_slots=4, max_model_len=16,
                         prefill_chunk=kw.pop("prefill_chunk", 4), **kw)
    return ServingEngine(model, params, gen, scfg)


def _serve(eng, prompts):
    # rids are process-global and results accumulate across drains:
    # return THIS call's outputs, in submission order
    rids = [eng.submit(p, MAX_NEW) for p in prompts]
    results = eng.run_until_drained(max_steps=5000)
    eng.scheduler.assert_consistent()
    return [results[r].generated for r in rids]


def test_prefix_cache_saves_half_of_prefill_bit_identically(
        model_and_params, shared_prefix_prompts):
    """The acceptance gate: 8 families x 16 requests, prefill token
    compute drops >= 50%, greedy outputs are bit-identical cache on vs
    off, and both engines pin their compile counts (one decode, one
    chunk fn, zero monolithic prefills)."""
    model, params = model_and_params
    prompts = shared_prefix_prompts
    total = sum(len(p) for p in prompts)

    on = _engine(model, params, prefix_cache=True)
    out_on = _serve(on, prompts)
    off = _engine(model, params)
    out_off = _serve(off, prompts)

    assert out_on == out_off                    # greedy decode unchanged
    snap = on.metrics.snapshot()
    saved = snap["serving/prefill/tokens_saved"]
    assert saved >= 0.5 * total
    assert snap["serving/prefix_cache/hit_tokens"] == saved
    assert snap["serving/prefix_cache/lookups"] == len(prompts)
    # computed + saved covers every prompt token (chunks are shape-
    # padded, so count VALID tokens: total - saved must equal the sum
    # of per-chunk nvalid, bounded by chunks * chunk_size)
    chunks_on = snap["serving/prefill/chunks"]
    assert (total - saved) <= chunks_on * 4
    for eng in (on, off):
        assert eng.decode_compiles == 1
        assert eng.prefill_chunk_compiles == 1
        assert eng.prefill_compiles == 0


def test_full_prompt_hit_skips_prefill_and_cow_protects_pages(
        model_and_params):
    """Identical prompts: the second is an exact-full-prompt hit (zero
    chunks run — stored logits + aliased tail page), and the THIRD still
    matches, proving the second request's first decode write went to a
    copy, not the cached tail page."""
    model, params = model_and_params
    rs = np.random.RandomState(5)
    prompt = [int(t) for t in rs.randint(3, 500, (10,))]

    eng = _engine(model, params, prefix_cache=True)
    base = _serve(eng, [prompt])
    chunks_before = eng.metrics.snapshot()["serving/prefill/chunks"]
    second = _serve(eng, [prompt])
    snap = eng.metrics.snapshot()
    assert snap["serving/prefill/chunks"] == chunks_before  # no chunks ran
    assert snap["serving/prefix_cache/hit_tokens"] >= len(prompt)
    third = _serve(eng, [prompt])
    assert base == second == third


def test_eviction_under_cache_pressure_recomputes_identically(
        model_and_params, shared_prefix_prompts):
    """A pool too small to retain every family's chain forces cached-
    page eviction; outputs must still match the cache-off run (evicted
    prefixes recompute, stale chains never resurface)."""
    model, params = model_and_params
    prompts = shared_prefix_prompts
    # 24 pages: 4 slots x 4 pages in flight leaves ~7 cacheable pages —
    # far fewer than 8 families x 3 pages of prefix
    on = _engine(model, params, prefix_cache=True, num_pages=24)
    out_on = _serve(on, prompts)
    off = _engine(model, params, num_pages=24)
    out_off = _serve(off, prompts)
    assert out_on == out_off
    snap = on.metrics.snapshot()
    assert snap["serving/prefix_cache/evictions"] > 0
    assert on.cache.allocator.used_count == 0   # nothing leaked


def test_token_budget_defers_chunk_while_decodes_fill_it(
        model_and_params):
    """prefill_token_budget co-schedules: while running decodes fill the
    per-step budget the pending chunk waits, and with NO running decodes
    the chunk always runs (no livelock)."""
    model, params = model_and_params
    rs = np.random.RandomState(9)
    # budget 4 == one chunk exactly: any running decode defers the chunk
    eng = _engine(model, params, prefill_token_budget=4)
    a = eng.submit([int(t) for t in rs.randint(3, 500, (4,))], MAX_NEW)
    eng.step()                       # empty engine: chunk ALWAYS runs
    assert a in {r.rid for r in eng.scheduler.running.values()}
    chunks_a = eng.metrics.snapshot()["serving/prefill/chunks"]
    assert chunks_a == 1
    b = eng.submit([int(t) for t in rs.randint(3, 500, (8,))], MAX_NEW)
    eng.step()
    # B is admitted (slot + pages bound) but its chunk waits: 1 running
    # decode + chunk of 4 > budget 4
    breq = next(r for r in eng.scheduler.prefilling.values()
                if r.rid == b)
    assert breq.prefill_pos == 0
    assert eng.metrics.snapshot()["serving/prefill/chunks"] == chunks_a
    results = eng.run_until_drained(max_steps=5000)
    # once A drains the budget frees up and B's chunks run to completion
    assert sorted(results) == [a, b]
    assert all(len(r.generated) == MAX_NEW for r in results.values())
    eng.scheduler.assert_consistent()


def test_chunked_matches_monolithic_prefill(model_and_params):
    """Chunked prefill (no cache) reproduces the monolithic engine's
    greedy tokens exactly — the chunk path is a pure re-schedule."""
    model, params = model_and_params
    rs = np.random.RandomState(13)
    prompts = [[int(t) for t in rs.randint(3, 500, (n,))]
               for n in (5, 9, 12, 7)]
    chunked = _engine(model, params)
    out_chunked = _serve(chunked, prompts)
    mono = _engine(model, params, prefill_chunk=0)
    out_mono = _serve(mono, prompts)
    assert out_chunked == out_mono


def test_prefix_cache_requires_chunked_prefill(model_and_params):
    model, params = model_and_params
    gen = GenerationConfig(max_new_tokens=2, do_sample=False,
                           eos_token_id=-1)
    with pytest.raises(ValueError):
        ServingEngine(model, params, gen,
                      ServingConfig(page_size=4, num_pages=32, num_slots=2,
                                    max_model_len=16, prefill_chunk=0,
                                    prefix_cache=True))
    with pytest.raises(ValueError):
        ServingEngine(model, params, gen,
                      ServingConfig(page_size=4, num_pages=32, num_slots=2,
                                    max_model_len=16, prefill_chunk=6,
                                    prefix_cache=True))  # not page-aligned


# ---------------------------------------------------------------------------
# grouped generation (RLHF rollout reuse)
# ---------------------------------------------------------------------------

def test_grouped_generation_matches_repeated_prompts(model_and_params):
    """build_generate_fn(group_size=G) on B unique prompts must emit the
    SAME tokens as group_size=1 on the G-fold repeated batch — prompt KV
    is computed once per unique prompt and expanded in-graph, and greedy
    decode is row-independent."""
    model, params = model_and_params
    rs = np.random.RandomState(17)
    uniq = np.asarray(rs.randint(3, 500, (2, 6)), np.int32)
    mask = np.ones_like(uniq)
    G = 3
    gen = GenerationConfig(max_new_tokens=4, do_sample=False,
                           eos_token_id=-1)
    grouped = jax.jit(build_generate_fn(model, gen, group_size=G))
    flat = jax.jit(build_generate_fn(model, gen))
    out_g = grouped(params, jnp.asarray(uniq), jnp.asarray(mask),
                    jax.random.key(0))
    rep_ids = jnp.asarray(np.repeat(uniq, G, axis=0))
    rep_mask = jnp.asarray(np.repeat(mask, G, axis=0))
    out_f = flat(params, rep_ids, rep_mask, jax.random.key(0))
    np.testing.assert_array_equal(np.asarray(out_g["response_tokens"]),
                                  np.asarray(out_f["response_tokens"]))
    np.testing.assert_array_equal(np.asarray(out_g["sequences"]),
                                  np.asarray(out_f["sequences"]))
