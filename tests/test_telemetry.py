"""Unified telemetry tests (docs/OBSERVABILITY.md): step-time/goodput
accounting, the in-graph scalar collector, MFU, the shared metric
registry + Prometheus exposition, the flight recorder's postmortems,
and the static metric-name check.

THE pins: (a) step segments sum to the step's wall clock and goodput
falls when a checkpoint stall is injected via DLA_FAULT_PLAN, (b) the
collector adds ZERO train-step compiles (trace-time counter stays 1),
(c) the Prometheus text a live engine serves round-trips through a
strict parser, (d) crash paths write a postmortem JSON naming the last
completed step.
"""
import json
import math
import threading
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dla_tpu.resilience import ENV_VAR, PreemptionExit, Watchdog
from dla_tpu.telemetry import (
    CATALOG,
    FlightRecorder,
    Gauge,
    Histogram,
    MFUCalculator,
    MetricRegistry,
    MetricsHTTPServer,
    StepClock,
    flops_per_token,
    hbm_bw_for,
    is_catalog_name,
    parse_prometheus_text,
    peak_flops_for,
    prometheus_name,
    stash_rms,
    stash_scalar,
)
from dla_tpu.utils.logging import MetricsLogger


# ---------------------------------------------------------------------------
# satellite regressions: Gauge.peak and strict-JSON logging
# ---------------------------------------------------------------------------

def test_gauge_peak_seeds_from_first_value_not_zero():
    """A gauge that only ever holds negative values must report that
    value as its peak — the old init-to-0.0 reported a phantom 0.0."""
    g = Gauge()
    g.set(-7.0)
    assert g.peak == -7.0
    g.set(-3.0)
    assert g.peak == -3.0
    g.set(-9.0)
    assert g.peak == -3.0          # peak still tracks the maximum
    fresh = Gauge()
    assert fresh.peak == 0.0       # never-set gauge mirrors its value


def test_metrics_logger_emits_strict_json_for_nonfinite(tmp_path):
    """A diverging loss (NaN/inf) must not corrupt metrics.jsonl: the
    row stays strict JSON with the non-finite scalars nulled."""
    logger = MetricsLogger(str(tmp_path), "t")
    logger.log({"train/loss": float("nan"),
                "train/grad_norm": float("inf"),
                "train/lr": 0.5}, step=3)
    line = (tmp_path / "metrics.jsonl").read_text().strip()

    def _reject(tok):
        raise ValueError(f"bare {tok} is not strict JSON")

    row = json.loads(line, parse_constant=_reject)   # must not raise
    assert row["train/loss"] is None
    assert row["train/grad_norm"] is None
    assert row["train/lr"] == 0.5 and row["step"] == 3


# ---------------------------------------------------------------------------
# step clock: attribution, goodput, interval metrics
# ---------------------------------------------------------------------------

class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def test_stepclock_segments_sum_to_wall_clock():
    fc = FakeClock()
    clock = StepClock(now=fc)
    with clock.segment("data_wait"):
        fc.advance(0.010)
    with clock.segment("h2d"):
        fc.advance(0.005)
    with clock.segment("compute"):
        fc.advance(0.080)
    fc.advance(0.005)              # unattributed -> "other"
    clock.end_step(ok=True)
    assert clock.wall_total == pytest.approx(0.100)
    attributed = sum(clock.seg_total.values()) + clock.other_total
    assert attributed == pytest.approx(clock.wall_total, rel=1e-9)
    assert clock.other_total == pytest.approx(0.005)
    assert clock.goodput() == pytest.approx(0.80)


def test_stepclock_compile_fault_and_checkpoint_attribution():
    fc = FakeClock()
    clock = StepClock(now=fc)
    # step 1: compile — its compute is badput_compile, not goodput
    clock.mark_compile()
    with clock.segment("compute"):
        fc.advance(1.0)
    clock.end_step(ok=True)
    assert clock.goodput() == 0.0
    assert clock.badput()["compile"] == pytest.approx(1.0)
    # step 2: a failed attempt charges its WHOLE wall to fault
    with clock.segment("compute"):
        fc.advance(0.5)
    clock.end_step(ok=False)
    assert clock.lost["fault"] == pytest.approx(0.5)
    assert clock.steps_failed == 1
    # step 3: checkpoint stall is both a segment and badput_checkpoint
    with clock.segment("compute"):
        fc.advance(0.5)
    with clock.segment("checkpoint_stall"):
        fc.advance(2.0)
    clock.end_step(ok=True)
    assert clock.seg_total["checkpoint_stall"] == pytest.approx(2.0)
    assert clock.badput()["checkpoint"] == pytest.approx(2.0 / 4.0)
    assert clock.goodput() == pytest.approx(0.5 / 4.0)


def test_stepclock_interval_metrics_catalog_named_and_windowed():
    fc = FakeClock()
    clock = StepClock(now=fc)
    for _ in range(4):
        with clock.segment("compute"):
            fc.advance(0.020)
        clock.end_step(ok=True)
    out = clock.interval_metrics()
    for k in out:
        assert is_catalog_name(k), k
    assert out["telemetry/step_ms"] == pytest.approx(20.0)
    assert out["telemetry/compute_ms"] == pytest.approx(20.0)
    # the window reset: a second call with no new steps means empty means
    out2 = clock.interval_metrics()
    assert out2["telemetry/step_ms"] == 0.0
    # cumulative goodput survives the window reset
    assert out2["telemetry/goodput"] == out["telemetry/goodput"]


def test_stepclock_disabled_is_inert():
    clock = StepClock(enabled=False)
    with clock.segment("compute"):
        pass
    clock.mark_compile()
    clock.end_step(ok=True)
    assert clock.wall_total == 0.0
    assert clock.interval_metrics() == {}


def test_stepclock_rejects_unknown_segment():
    with pytest.raises(ValueError, match="unknown step segment"):
        with StepClock().segment("coffee"):
            pass


# ---------------------------------------------------------------------------
# MFU calculator + chip tables
# ---------------------------------------------------------------------------

def test_mfu_formula_and_peak_tables():
    assert flops_per_token(125_000_000, training=True) == 6 * 125_000_000
    assert flops_per_token(125_000_000, training=False) == 2 * 125_000_000
    assert peak_flops_for("TPU v5 lite", "tpu") == pytest.approx(197e12)
    assert peak_flops_for("TPU v5p", "tpu") == pytest.approx(459e12)
    # unknown TPU falls back to v5e; cpu uses the cpu row
    assert peak_flops_for("TPU v99", "tpu") == pytest.approx(197e12)
    assert peak_flops_for("cpu", "cpu") == pytest.approx(5e11)
    bw, assumed = hbm_bw_for("TPU v4", "tpu")
    assert bw == pytest.approx(1228e9) and not assumed
    calc = MFUCalculator(1_000_000, "TPU v5 lite", "tpu", training=True)
    # 1M params * 6 flops/token: mfu = rate * 6e6 / 197e12
    assert calc.mfu(1e6) == pytest.approx(6e12 / 197e12)
    assert calc.mfu(0.0) == 0.0
    assert calc.mfu(None) == 0.0


# ---------------------------------------------------------------------------
# registry: catalog validation, snapshot, Prometheus round-trip
# ---------------------------------------------------------------------------

def test_registry_rejects_undeclared_names():
    r = MetricRegistry()
    with pytest.raises(ValueError, match="CATALOG"):
        r.gauge("train/definitely_not_declared")
    # dynamic families are legal without a catalog row
    r.gauge("train/rms/layers/0/attn")
    r.gauge("train/aux/router_entropy")
    r.gauge("eval/my_benchmark")


def test_registry_snapshot_and_prometheus_round_trip():
    r = MetricRegistry()
    c = r.counter("serving/tokens_generated")
    g = r.gauge("serving/page_occupancy")
    h = r.histogram("serving/ttft_ms")
    r.func_gauge("resilience/guard_bad_steps", lambda: 5)
    c.inc(41)
    c.inc()
    g.set(0.75)
    g.set(float("nan"))            # scrapers must never see a NaN
    for v in (10.0, 20.0, 30.0):
        h.record(v)

    snap = r.snapshot()
    assert snap["serving/tokens_generated"] == 42.0
    assert snap["serving/page_occupancy_peak"] == 0.75
    assert snap["serving/ttft_ms_p50"] == 20.0
    assert snap["serving/ttft_ms_count"] == 3.0
    assert snap["resilience/guard_bad_steps"] == 5.0
    for k in snap:
        assert is_catalog_name(k), k

    text = r.prometheus_text()
    samples = parse_prometheus_text(text)   # strict: raises on bad lines
    assert samples[("dla_serving_tokens_generated_total", ())] == 42.0
    assert samples[("dla_serving_page_occupancy", ())] == 0.0  # NaN -> 0
    assert samples[("dla_serving_page_occupancy_peak", ())] == 0.75
    assert samples[("dla_serving_ttft_ms",
                    (("quantile", "0.5"),))] == 20.0
    assert samples[("dla_serving_ttft_ms_sum", ())] == 60.0
    assert samples[("dla_serving_ttft_ms_count", ())] == 3.0
    # counters follow the _total convention; TYPE comments are present
    assert "# TYPE dla_serving_tokens_generated_total counter" in text
    assert "# TYPE dla_serving_page_occupancy gauge" in text


def test_parse_prometheus_rejects_malformed_lines():
    with pytest.raises(ValueError, match="not a prometheus sample"):
        parse_prometheus_text("dla_x 1.0\nthis is { not a sample\n")
    with pytest.raises(ValueError, match="unquoted label"):
        parse_prometheus_text('dla_x{quantile=0.5} 1.0\n')


def test_prometheus_name_sanitizes():
    assert prometheus_name("serving/ttft_ms") == "dla_serving_ttft_ms"
    assert prometheus_name("train/rms/layers/0") == "dla_train_rms_layers_0"


def test_histogram_summary_is_windowed_but_totals_monotonic():
    h = Histogram(window=4)
    for v in (1.0, 2.0, 3.0, 4.0, 100.0, 100.0, 100.0, 100.0):
        h.record(v)
    s = h.summary()
    assert s["p50"] == 100.0       # window holds only the last 4
    assert h.total_count == 8      # but _count/_sum never forget
    assert h.total_sum == pytest.approx(410.0)


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_flight_recorder_ring_postmortem_and_sanitize(tmp_path):
    rec = FlightRecorder(capacity=4, out_dir=str(tmp_path))
    for s in range(1, 8):
        rec.record("step_end", step=s, loss=0.1 * s)
    rec.record("guard_bad_step", step=7, loss=float("nan"))
    assert len(rec.events) == 4    # bounded ring: oldest events dropped
    assert rec.last_completed_step() == 7

    path = rec.dump("watchdog_hang", extra={"stacks": "MainThread ..."})
    assert path is not None and path.name == "postmortem_watchdog_hang.json"

    def _reject(tok):
        raise ValueError(tok)

    doc = json.loads(path.read_text(), parse_constant=_reject)
    assert doc["reason"] == "watchdog_hang"
    assert doc["last_completed_step"] == 7
    assert doc["num_events"] == 4
    assert doc["stacks"] == "MainThread ..."
    nan_evt = [e for e in doc["events"]
               if e["kind"] == "guard_bad_step"][0]
    assert nan_evt["loss"] is None   # strict JSON even for a NaN loss
    # re-dump overwrites the same reason file (LAST occurrence survives)
    rec.record("step_end", step=9)
    rec.dump("watchdog_hang")
    assert json.loads(path.read_text())["last_completed_step"] == 9
    assert rec.dumps_written == 2


def test_flight_recorder_without_out_dir_needs_explicit_path(tmp_path):
    rec = FlightRecorder()
    rec.record("step_end", step=1)
    assert rec.dump("oops") is None
    p = rec.dump("oops", path=str(tmp_path / "pm.json"))
    assert p is not None and json.loads(p.read_text())["num_events"] == 1


def test_watchdog_fire_writes_postmortem(tmp_path):
    """Pin (d): a watchdog-style hang dumps the ring to a postmortem
    naming the last completed step — before on_hang/abort can kill the
    process."""
    rec = FlightRecorder(out_dir=str(tmp_path))
    for s in range(1, 6):
        rec.record("step_end", step=s)
    fired = threading.Event()
    wd = Watchdog(timeout_s=0.15, poll_s=0.03, abort=False,
                  on_hang=lambda dump: fired.set(), recorder=rec)
    wd.start()
    try:
        assert fired.wait(timeout=5.0)   # no beats -> it trips
    finally:
        wd.stop()
    pm = tmp_path / "postmortem_watchdog_hang.json"
    assert pm.exists()
    doc = json.loads(pm.read_text())
    assert doc["last_completed_step"] == 5
    assert "MainThread" in doc["stacks"]
    assert doc["events"][-1]["kind"] == "watchdog_hang"


# ---------------------------------------------------------------------------
# static metric-name check (tools/check_metric_names.py)
# ---------------------------------------------------------------------------

def test_check_metric_names_repo_is_clean_and_drift_detected(tmp_path,
                                                            capsys):
    from tools.check_metric_names import run
    from pathlib import Path
    assert run() == 0                      # the repo itself passes

    bad = tmp_path / "dla_tpu"
    bad.mkdir()
    (bad / "x.py").write_text('m = "train/not_in_the_catalog"\n')
    (tmp_path / "bench.py").write_text("")
    assert run(Path(tmp_path)) == 1
    err = capsys.readouterr().err
    assert "x.py:1" in err and "train/not_in_the_catalog" in err


def test_catalog_specs_are_well_formed():
    seen = set()
    for spec in CATALOG:
        assert spec.name not in seen, f"duplicate catalog row {spec.name}"
        seen.add(spec.name)
        assert spec.kind in ("counter", "gauge", "histogram"), spec


# ---------------------------------------------------------------------------
# trainer integration: zero-compile collector, goodput under stall,
# postmortem on preemption — tiny regression problem on mesh8
# ---------------------------------------------------------------------------

DIM = 8


def _make_batch(i, bs=8):
    rs = np.random.RandomState(2000 + i)
    x = rs.normal(size=(bs, DIM)).astype(np.float32)
    w_true = np.arange(1, DIM + 1, dtype=np.float32)
    return {"x": x, "y": (x @ w_true).astype(np.float32)}


class BatchIter:
    def __init__(self):
        self.i = 0

    def __iter__(self):
        return self

    def __next__(self):
        b = _make_batch(self.i)
        self.i += 1
        return b

    def state_dict(self):
        return {"i": self.i}

    def load_state_dict(self, state):
        self.i = int(state["i"])


def _stashing_loss(params, frozen, batch, rng):
    """Loss that exercises the trace-time scalar stash from 'model
    code': per-layer RMS and an auxiliary scalar, both riding the
    existing step's metrics pytree."""
    del frozen, rng
    pred = batch["x"] @ params["w"]
    stash_rms("pred", pred)
    stash_scalar("pred_mean", jnp.mean(pred))
    return jnp.mean((pred - batch["y"]) ** 2), {}


def _make_trainer(mesh, out_dir, *, max_steps=8, save_every=0,
                  log_every=10 ** 6, telemetry=None, resilience=None,
                  loss_fn=_stashing_loss):
    from dla_tpu.training.trainer import Trainer
    logging_cfg = {"output_dir": str(out_dir), "log_dir": None,
                   "save_every_steps": save_every,
                   "log_every_steps": log_every}
    if telemetry is not None:
        logging_cfg["telemetry"] = telemetry
    config = {
        "experiment_name": "telemetry_test",
        "data": {"prefetch": 0},
        "optimization": {"total_batch_size": 8, "micro_batch_size": 1,
                         "learning_rate": 1e-2, "max_train_steps": max_steps,
                         "lr_scheduler": "constant", "max_grad_norm": 1.0},
        "logging": logging_cfg,
        "hardware": {"gradient_accumulation_steps": 2},
    }
    if resilience is not None:
        config["resilience"] = resilience
    return Trainer(config=config, mesh=mesh, loss_fn=loss_fn,
                   params={"w": jnp.zeros((DIM,), jnp.float32)},
                   param_specs={"w": P()})


def test_collector_adds_zero_compiles_and_surfaces_scalars(mesh8,
                                                           tmp_path):
    """Pin (b): the in-graph collector + stash ride the ONE jitted train
    step — the trace-time compile counter stays at exactly 1 — and the
    collected scalars surface under their catalog names."""
    with jax.sharding.set_mesh(mesh8):
        tr = _make_trainer(mesh8, tmp_path / "run", max_steps=8,
                           log_every=4,
                           telemetry={"collector": {"per_layer": True}})
        it = BatchIter()
        tr.fit(it, rng=jax.random.key(0), data_state=it.state_dict)
        assert tr.step == 8
        assert tr.train_step_compiles == 1     # THE zero-extra-compile pin

        snap = tr.registry.snapshot()
        # collector norms + per-layer grad RMS + the stash, catalog-named
        assert snap["train/param_norm"] > 0.0
        assert snap["train/update_norm"] > 0.0
        assert snap["train/rms/w"] > 0.0       # per-leaf grad RMS
        assert snap["train/rms/pred"] > 0.0    # stash_rms from loss code
        assert "train/aux/pred_mean" in snap   # stash_scalar
        assert snap["train/grad_norm"] > 0.0
        # step-time decomposition + MFU made it into the same snapshot
        assert snap["telemetry/step_ms"] > 0.0
        assert 0.0 <= snap["telemetry/goodput"] <= 1.0
        assert 0.0 <= snap["telemetry/mfu"] <= 1.0
        assert snap["tokens_per_sec_per_chip"] > 0.0

        # segment attribution is exhaustive: segments + other == wall
        clk = tr.clock
        attributed = sum(clk.seg_total.values()) + clk.other_total
        assert attributed == pytest.approx(clk.wall_total, rel=1e-6)
        assert clk.seg_total["compute"] > 0.0
        assert clk.steps_ok == 8


def test_collector_off_switch_disables_cleanly(mesh8, tmp_path):
    with jax.sharding.set_mesh(mesh8):
        tr = _make_trainer(mesh8, tmp_path / "run", max_steps=4,
                           telemetry={"enabled": False})
        it = BatchIter()
        tr.fit(it, rng=jax.random.key(0), data_state=it.state_dict)
        assert tr.step == 4
        assert tr.train_step_compiles == 1
        assert tr.clock.wall_total == 0.0      # clock fully inert
        snap = tr.registry.snapshot()
        assert "train/param_norm" not in snap  # collector off too


def test_goodput_falls_under_injected_checkpoint_stall(mesh8, tmp_path,
                                                       monkeypatch):
    """Pin (a): an io_error injected via DLA_FAULT_PLAN makes the
    background checkpoint writer retry with backoff; the NEXT save's
    backpressure wait shows up as checkpoint_stall and drags goodput
    down vs the fault-free run."""
    with jax.sharding.set_mesh(mesh8):
        monkeypatch.delenv(ENV_VAR, raising=False)
        clean = _make_trainer(mesh8, tmp_path / "clean", max_steps=6,
                              save_every=2,
                              resilience={"async_checkpointing": True})
        it = BatchIter()
        clean.fit(it, rng=jax.random.key(0), data_state=it.state_dict)
        clean.checkpointer.wait()

        monkeypatch.setenv(ENV_VAR, "step=2:io_error")
        tr = _make_trainer(
            mesh8, tmp_path / "stalled", max_steps=6, save_every=2,
            resilience={"async_checkpointing": True, "save_retries": 3,
                        "retry_backoff_s": 0.4})
        it2 = BatchIter()
        tr.fit(it2, rng=jax.random.key(0), data_state=it2.state_dict)
        tr.checkpointer.wait()

        assert tr.checkpointer.retries_total == 1
        # the retry backoff surfaced as step-loop checkpoint stall
        assert tr.clock.seg_total["checkpoint_stall"] >= 0.3
        assert tr.checkpointer.total_stall_ms >= 300.0
        assert tr.clock.badput()["checkpoint"] > 0.1
        assert tr.clock.goodput() < clean.clock.goodput()
        # the stall is attributed, not lost: accounting stays exhaustive
        attributed = sum(tr.clock.seg_total.values()) + tr.clock.other_total
        assert attributed == pytest.approx(tr.clock.wall_total, rel=1e-6)


def test_preemption_writes_postmortem_naming_last_step(mesh8, tmp_path):
    """Acceptance pin: killing a run mid-stream leaves a postmortem JSON
    whose last_completed_step says where to resume from."""
    with jax.sharding.set_mesh(mesh8):
        out = tmp_path / "run"
        tr = _make_trainer(
            mesh8, out, max_steps=8, save_every=4,
            resilience={"preemption": True, "fault_plan": "step=3:preempt"})
        it = BatchIter()
        with pytest.raises(PreemptionExit) as exc_info:
            tr.fit(it, rng=jax.random.key(0), data_state=it.state_dict)
        pm = out / "postmortem_preemption.json"
        assert pm.exists()
        doc = json.loads(pm.read_text())
        assert doc["reason"] == "preemption"
        assert doc["last_completed_step"] == exc_info.value.step == 3
        kinds = [e["kind"] for e in doc["events"]]
        assert "preempt_requested" in kinds
        assert "preemption_exit" in kinds


# ---------------------------------------------------------------------------
# serving: live /metrics endpoint round-trips through the strict parser
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def serve_setup():
    from dla_tpu.generation.engine import GenerationConfig
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer
    cfg = get_model_config("tiny")
    model = Transformer(cfg)
    params = model.init(jax.random.key(7))
    gen = GenerationConfig(max_new_tokens=5, do_sample=False,
                           eos_token_id=2, pad_token_id=0)
    return model, params, gen


def test_live_metrics_endpoint_round_trips(serve_setup):
    """Pin (c): GET /metrics on a live engine returns valid Prometheus
    text — every line parses strictly — including TTFT/ITL summaries
    and occupancy gauges with real values."""
    from dla_tpu.serving import ServingConfig, ServingEngine
    model, params, gen = serve_setup
    eng = ServingEngine(model, params, gen, ServingConfig(
        page_size=4, num_pages=32, num_slots=2, max_model_len=32,
        max_prefill_batch=2))
    try:
        rs = np.random.RandomState(5)
        for _ in range(3):
            eng.submit(list(rs.randint(3, 500, (4,))), 5)
        eng.run_until_drained(max_steps=500)

        # the JSONL snapshot speaks catalog names, queue-wait included
        snap = eng.metrics.snapshot()
        for k in snap:
            assert is_catalog_name(k), k
        assert snap["serving/queue_wait_ms_count"] == 3.0
        assert not math.isnan(snap["serving/ttft_ms_p50"])

        srv = eng.start_metrics_server(port=0)
        assert eng.start_metrics_server() is srv   # idempotent
        with urllib.request.urlopen(srv.url, timeout=5) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith(
                "text/plain; version=0.0.4")
            text = resp.read().decode()

        samples = parse_prometheus_text(text)      # strict round-trip
        assert samples[("dla_serving_requests_finished_total", ())] == 3.0
        assert samples[("dla_serving_tokens_generated_total", ())] > 0.0
        assert samples[("dla_serving_ttft_ms",
                        (("quantile", "0.5"),))] >= 0.0
        assert samples[("dla_serving_ttft_ms_count", ())] == 3.0
        assert samples[("dla_serving_itl_ms",
                        (("quantile", "0.95"),))] >= 0.0
        assert ("dla_serving_queue_wait_ms_count", ()) in samples
        assert samples[("dla_serving_page_occupancy_peak", ())] > 0.0
        assert samples[("dla_serving_active_requests", ())] == 0.0

        # readiness route (the engine's probe was beaten by its steps,
        # so it reports fresh) + 404 for anything else
        health = srv.url.replace("/metrics", "/healthz")
        with urllib.request.urlopen(health, timeout=5) as resp:
            assert resp.read().startswith(b"ok")
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                srv.url.replace("/metrics", "/nope"), timeout=5)
    finally:
        eng.close()
    assert eng.metrics_server is None              # close() tore it down
