"""Mixtral (MoE) weight import: logits parity with transformers'
MixtralForCausalLM on a tiny randomly-initialized model. Capacity is
raised so nothing drops — HF computes exact top-k routing with no
capacity limit, so parity is only defined drop-free."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def tiny_mixtral_dir(tmp_path_factory):
    from transformers import MixtralConfig, MixtralForCausalLM
    cfg = MixtralConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False)
    torch.manual_seed(1)
    model = MixtralForCausalLM(cfg).eval()
    d = tmp_path_factory.mktemp("hf_mixtral")
    model.save_pretrained(str(d), safe_serialization=True)
    return d, model


def test_mixtral_import_matches_hf_logits(tiny_mixtral_dir):
    d, hf_model = tiny_mixtral_dir
    from dla_tpu.models.hf_import import (
        hf_config_to_model_config,
        import_hf_weights,
        read_hf_config,
    )
    from dla_tpu.models.transformer import Transformer
    import jax.numpy as jnp

    hf_cfg = read_hf_config(d)
    cfg = hf_config_to_model_config(
        hf_cfg, dtype="float32", param_dtype="float32", remat="none",
        moe_capacity_factor=8.0)  # drop-free for exact HF parity
    assert cfg.num_experts == 4 and cfg.num_experts_per_token == 2
    params = import_hf_weights(d, cfg)
    assert params["layers"]["router"].shape == (2, 32, 4)
    assert params["layers"]["w_gate"].shape == (2, 4, 32, 64)
    model = Transformer(cfg)

    rs = np.random.RandomState(0)
    ids = rs.randint(0, 128, (2, 10))
    ours = np.asarray(model.apply(params, jnp.asarray(ids, jnp.int32)))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=2e-3)
