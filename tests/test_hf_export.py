"""HF export: the inverse of hf_import. Round-trip parity (export ->
re-import -> identical trees) and transformers-load parity (export a
dla_tpu-initialized model, load it with LlamaForCausalLM, compare
logits)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from dla_tpu.models.config import get_model_config  # noqa: E402
from dla_tpu.models.hf_export import (  # noqa: E402
    export_hf_weights,
    model_config_to_hf,
)
from dla_tpu.models.hf_import import (  # noqa: E402
    hf_config_to_model_config,
    import_hf_weights,
    read_hf_config,
)
from dla_tpu.models.transformer import Transformer  # noqa: E402


def _tree_equal(a, b):
    ka, kb = sorted(a), sorted(b)
    assert ka == kb, f"key mismatch: {ka} vs {kb}"
    for k in ka:
        va, vb = a[k], b[k]
        if isinstance(va, dict):
            _tree_equal(va, vb)
        else:
            np.testing.assert_allclose(
                np.asarray(va, np.float32), np.asarray(vb, np.float32),
                rtol=1e-6, atol=1e-7, err_msg=k)


def test_export_reimport_roundtrip(tmp_path):
    cfg = get_model_config("tiny-gqa")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    d = export_hf_weights(params, cfg, tmp_path / "hf")

    hf_cfg = read_hf_config(d)
    cfg2 = hf_config_to_model_config(
        hf_cfg, dtype="float32", param_dtype="float32", remat="none")
    assert cfg2.num_kv_heads == cfg.num_kv_heads
    assert cfg2.vocab_size == cfg.vocab_size
    params2 = import_hf_weights(d, cfg2)
    _tree_equal(jax.tree.map(np.asarray, params), params2)


def test_export_loads_in_transformers_with_logit_parity(tmp_path):
    cfg = get_model_config("tiny-gqa")
    model = Transformer(cfg)
    params = model.init(jax.random.key(1))
    d = export_hf_weights(params, cfg, tmp_path / "hf")

    from transformers import LlamaForCausalLM
    hf_model = LlamaForCausalLM.from_pretrained(
        str(d), torch_dtype=torch.float32).eval()

    rs = np.random.RandomState(0)
    ids = rs.randint(0, cfg.vocab_size, (2, 12))
    ours = np.asarray(model.apply(params, jnp.asarray(ids, jnp.int32)))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=2e-4)


def test_export_moe_roundtrip(tmp_path):
    cfg = get_model_config("tiny-moe")
    model = Transformer(cfg)
    params = model.init(jax.random.key(2))
    d = export_hf_weights(params, cfg, tmp_path / "hf_moe")
    hf_cfg = read_hf_config(d)
    assert hf_cfg["model_type"] == "mixtral"
    assert hf_cfg["num_local_experts"] == cfg.num_experts
    cfg2 = hf_config_to_model_config(
        hf_cfg, dtype="float32", param_dtype="float32", remat="none")
    params2 = import_hf_weights(d, cfg2)
    _tree_equal(jax.tree.map(np.asarray, params), params2)


def test_export_checkpoint_cli(tmp_path):
    """Checkpoint dir -> HF dir through the CLI entry (self-describing
    via the model_config aux)."""
    from dla_tpu.checkpoint.checkpointer import Checkpointer
    from dla_tpu.models.hf_export import main

    cfg = get_model_config("tiny-gqa")
    model = Transformer(cfg)
    params = model.init(jax.random.key(3))
    ck = Checkpointer(str(tmp_path / "ckpt"))
    ck.save(1, {"params": params}, aux={"model_config": cfg.to_dict()})
    out = tmp_path / "hf_out"
    main(["--checkpoint", str(tmp_path / "ckpt" / "latest"),
          "--output", str(out)])
    params2 = import_hf_weights(
        out, hf_config_to_model_config(
            read_hf_config(out), dtype="float32", param_dtype="float32",
            remat="none"))
    _tree_equal(jax.tree.map(np.asarray, params), params2)


def test_hf_config_inversion_fields():
    cfg = get_model_config("mistral-7b")
    hf = model_config_to_hf(cfg)
    assert hf["model_type"] == "mistral"
    assert hf["sliding_window"] == 4096
    back = hf_config_to_model_config(hf)
    assert back.sliding_window == 4096
    assert back.num_kv_heads == cfg.num_kv_heads
    assert back.rope_theta == cfg.rope_theta


def test_export_refuses_adapter_checkpoint(tmp_path):
    """A LoRA run's step/final checkpoints hold the adapter tree, not
    base weights — export must refuse with the merged-checkpoint hint."""
    import pytest
    from dla_tpu.checkpoint.checkpointer import Checkpointer
    from dla_tpu.models.hf_export import export_checkpoint

    cfg = get_model_config("tiny-gqa", lora_r=4)
    model = Transformer(cfg)
    adapters = model.init_lora(jax.random.key(0))
    ck = Checkpointer(str(tmp_path / "ckpt"))
    ck.save(1, {"params": adapters}, aux={"model_config": cfg.to_dict()})
    with pytest.raises(ValueError, match="merged"):
        export_checkpoint(tmp_path / "ckpt" / "latest", tmp_path / "out")


def test_export_longrope_round_trips_through_transformers(tmp_path):
    """LongRoPE export must surface original_max_position_embeddings at
    the TOP level of config.json — transformers reads the short/long
    switch point and derived attention factor only from there (a
    dict-level value is silently ignored; verified 4.57). Logits parity
    on reload BEYOND the original context pins it."""
    import dataclasses
    import json

    orig, ext, hd = 16, 4, 16
    short = [1.0 + 0.05 * i for i in range(hd // 2)]
    long = [2.0 + 0.3 * i for i in range(hd // 2)]
    cfg = get_model_config(
        "tiny-gqa", hidden_size=hd * 4, num_heads=4, num_kv_heads=2,
        max_seq_length=orig * ext,
        rope_scaling={"rope_type": "longrope", "short_factor": short,
                      "long_factor": long, "factor": float(ext),
                      "original_max_position_embeddings": orig})
    model = Transformer(cfg)
    params = model.init(jax.random.key(2))
    d = export_hf_weights(params, cfg, tmp_path / "hf_lr")
    conf = json.loads((d / "config.json").read_text())
    assert conf["original_max_position_embeddings"] == orig

    from transformers import LlamaForCausalLM
    hf_model = LlamaForCausalLM.from_pretrained(
        str(d), torch_dtype=torch.float32, attn_implementation="eager"
        ).eval()
    rs = np.random.RandomState(1)
    for t in (orig - 4, orig + 12):   # short branch, then long branch
        ids = rs.randint(0, cfg.vocab_size, (2, t))
        ours = np.asarray(model.apply(params, jnp.asarray(ids, jnp.int32)))
        with torch.no_grad():
            theirs = hf_model(torch.tensor(ids)).logits.numpy()
        np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=3e-4,
                                   err_msg=f"T={t}")


def test_rope_scaling_export_strips_importer_injected_keys():
    """hf_import._validated_rope_scaling folds top-level config.json
    fallbacks INTO the rope_scaling dict (YaRN/longrope switch points,
    dynamic's trained context) so ops/rotary needs no config
    back-reference; export must strip them again so import -> export is
    a fixed point and the exported config.json carries only what the
    source HF config made explicit."""
    def base(**extra):
        d = {"model_type": "llama", "vocab_size": 128, "hidden_size": 64,
             "intermediate_size": 128, "num_hidden_layers": 2,
             "num_attention_heads": 4, "num_key_value_heads": 2,
             "max_position_embeddings": 64}
        d.update(extra)
        return d

    def roundtrip(hf_in):
        cfg = hf_config_to_model_config(hf_in)
        hf_out = model_config_to_hf(cfg)
        cfg2 = hf_config_to_model_config(hf_out)
        assert cfg2.rope_scaling == cfg.rope_scaling  # lossless
        return hf_out

    # yarn missing the switch point: the importer injects the top-level
    # max_position_embeddings; export must NOT persist the injected copy
    out = roundtrip(base(rope_scaling={"rope_type": "yarn",
                                       "factor": 4.0}))
    assert out["rope_scaling"] == {"rope_type": "yarn", "factor": 4.0}

    # an EXPLICIT switch point differing from max_position_embeddings
    # is real information and survives export
    out = roundtrip(base(rope_scaling={
        "rope_type": "yarn", "factor": 4.0,
        "original_max_position_embeddings": 32}))
    assert out["rope_scaling"]["original_max_position_embeddings"] == 32

    # dynamic NTK: importer injects the trained context from the top
    # level; export strips it back out
    out = roundtrip(base(rope_scaling={"rope_type": "dynamic",
                                       "factor": 2.0}))
    assert out["rope_scaling"] == {"rope_type": "dynamic",
                                   "factor": 2.0}

    # longrope (phi-3 style): dict-level orig + derived factor are both
    # importer artifacts; the switch point belongs at the TOP level only
    short, long = [1.0] * 8, [2.0] * 8
    out = roundtrip(base(
        rope_scaling={"type": "longrope", "short_factor": short,
                      "long_factor": long},
        original_max_position_embeddings=16))
    assert out["original_max_position_embeddings"] == 16
    assert "original_max_position_embeddings" not in out["rope_scaling"]
    assert "factor" not in out["rope_scaling"]

    # longrope WITHOUT a top-level switch point: the importer's
    # max_position_embeddings fallback must not materialize one
    out = roundtrip(base(
        rope_scaling={"type": "longrope", "short_factor": short,
                      "long_factor": long}))
    assert "original_max_position_embeddings" not in out
    assert "original_max_position_embeddings" not in out["rope_scaling"]
