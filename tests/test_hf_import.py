"""HF weight import: logits parity with transformers' LlamaForCausalLM on a
tiny randomly-initialized model saved to disk (safetensors)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def tiny_hf_dir(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM
    cfg = LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    model = LlamaForCausalLM(cfg).eval()
    d = tmp_path_factory.mktemp("hf_llama")
    model.save_pretrained(str(d), safe_serialization=True)
    return d, model


def test_import_matches_hf_logits(tiny_hf_dir):
    d, hf_model = tiny_hf_dir
    from dla_tpu.models.hf_import import (
        hf_config_to_model_config,
        import_hf_weights,
        read_hf_config,
    )
    from dla_tpu.models.transformer import Transformer
    import jax.numpy as jnp

    hf_cfg = read_hf_config(d)
    cfg = hf_config_to_model_config(
        hf_cfg, dtype="float32", param_dtype="float32", remat="none")
    assert cfg.num_kv_heads == 2 and cfg.num_layers == 2
    params = import_hf_weights(d, cfg)
    model = Transformer(cfg)

    rs = np.random.RandomState(0)
    ids = rs.randint(0, 128, (2, 10))
    ours = np.asarray(model.apply(params, jnp.asarray(ids, jnp.int32)))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=2e-4)


def test_load_causal_lm_resolves_hf_dir(tiny_hf_dir):
    d, _ = tiny_hf_dir
    import jax
    from dla_tpu.training.model_io import load_causal_lm
    bundle = load_causal_lm(
        str(d), {"tokenizer": "byte", "dtype": "float32",
                 "param_dtype": "float32", "remat": "none"},
        jax.random.key(0))
    assert bundle.config.vocab_size == 128
    assert bundle.params["layers"]["wq"].shape == (2, 32, 32)


def test_hf_config_sliding_window_mapping():
    """mistral's sliding_window maps through; qwen2-style configs that
    ship the key with use_sliding_window: false stay full-causal."""
    from dla_tpu.models.hf_import import hf_config_to_model_config

    base = dict(model_type="mistral", vocab_size=128, hidden_size=32,
                intermediate_size=64, num_hidden_layers=2,
                num_attention_heads=4, num_key_value_heads=2)
    assert hf_config_to_model_config(
        {**base, "sliding_window": 4096}).sliding_window == 4096
    assert hf_config_to_model_config(base).sliding_window is None
    assert hf_config_to_model_config(
        {**base, "model_type": "qwen2", "sliding_window": 131072,
         "use_sliding_window": False}).sliding_window is None
    assert hf_config_to_model_config(
        {**base, "sliding_window": None}).sliding_window is None


def test_hf_config_partial_sliding_window_rejected():
    """qwen2's max_window_layers: the FIRST mwl layers run full
    attention, SWA applies from layer mwl on (HF configuration_qwen2.py
    layer_types). Only mwl=0 (SWA everywhere) maps to the global window;
    mwl >= L disables SWA entirely; in between is per-layer — refused."""
    import pytest
    from dla_tpu.models.hf_import import hf_config_to_model_config

    cfg = dict(model_type="qwen2", vocab_size=128, hidden_size=32,
               intermediate_size=64, num_hidden_layers=28,
               num_attention_heads=4, num_key_value_heads=2,
               sliding_window=4096, use_sliding_window=True,
               max_window_layers=21)
    with pytest.raises(ValueError, match="max_window_layers"):
        hf_config_to_model_config(cfg)
    # mwl >= L: every layer full attention — window must NOT apply
    cfg["max_window_layers"] = 28
    assert hf_config_to_model_config(cfg).sliding_window is None
    # mwl == 0: SWA on every layer — exactly the global window
    cfg["max_window_layers"] = 0
    assert hf_config_to_model_config(cfg).sliding_window == 4096


def test_hub_snapshot_opt_in_and_fallback(tiny_hf_dir, monkeypatch):
    """DLA_HF_HUB_DOWNLOAD gates the hub path: off -> never called; on ->
    snapshot_download's directory imports through the local-dir path; a
    failing fetch falls back to preset init loudly instead of raising."""
    import jax

    from dla_tpu.training import model_io

    d, _ = tiny_hf_dir
    calls = []

    def fake_snapshot(repo_id, **kw):
        calls.append(repo_id)
        return str(d)

    import sys, types
    fake_mod = types.SimpleNamespace(snapshot_download=fake_snapshot)
    monkeypatch.setitem(sys.modules, "huggingface_hub", fake_mod)

    # flag off: hub never consulted, name falls through to the registry
    monkeypatch.delenv("DLA_HF_HUB_DOWNLOAD", raising=False)
    assert model_io._try_hub_snapshot("org/name") is None
    assert calls == []

    # flag on: the snapshot dir loads through the HF import path
    monkeypatch.setenv("DLA_HF_HUB_DOWNLOAD", "1")
    bundle = model_io.load_causal_lm(
        "org/tiny-llama", {"tokenizer": "byte"}, jax.random.key(0))
    assert calls == ["org/tiny-llama"]
    assert bundle.config.num_layers == 2  # the hf dir's architecture

    # failing fetch: loud fallback, no exception
    def broken(repo_id, **kw):
        raise OSError("no egress")
    fake_mod.snapshot_download = broken
    assert model_io._try_hub_snapshot("org/other") is None


def test_llama31_rope_scaling_logits_parity(tmp_path):
    """llama3-type rope_scaling (llama-3.1/3.2): imported weights +
    scaled frequencies must reproduce transformers' logits, with
    positions past original_max_position_embeddings in play."""
    import jax.numpy as jnp
    from transformers import LlamaConfig, LlamaForCausalLM

    from dla_tpu.models.hf_import import (
        hf_config_to_model_config,
        import_hf_weights,
        read_hf_config,
    )
    from dla_tpu.models.transformer import Transformer

    cfg = LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False,
        rope_scaling={"rope_type": "llama3", "factor": 4.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 16})
    torch.manual_seed(1)
    hf_model = LlamaForCausalLM(cfg).eval()
    d = tmp_path / "hf31"
    hf_model.save_pretrained(str(d), safe_serialization=True)

    mc = hf_config_to_model_config(
        read_hf_config(d), dtype="float32", param_dtype="float32",
        remat="none")
    assert mc.rope_scaling and mc.rope_scaling["factor"] == 4.0
    params = import_hf_weights(d, mc)
    model = Transformer(mc)

    rs = np.random.RandomState(0)
    ids = rs.randint(0, 128, (2, 40))  # well past the original 16 ctx
    ours = np.asarray(model.apply(params, jnp.asarray(ids, jnp.int32)))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=2e-4)


def test_dynamic_ntk_rope_matches_hf():
    """Dynamic NTK rope scaling: traced base stretch past the trained
    context, unit parity with ROPE_INIT_FUNCTIONS['dynamic'] on both
    sides, end-to-end logits parity on a tiny llama run BEYOND its
    max_position_embeddings."""
    import jax.numpy as jnp
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM
    from transformers.modeling_rope_utils import ROPE_INIT_FUNCTIONS

    from dla_tpu.models.hf_import import (
        _validated_rope_scaling,
        hf_config_to_model_config,
        import_hf_weights,
        read_hf_config,
    )
    from dla_tpu.ops.rotary import _dynamic_ntk_inv_freq

    hd, theta, max_pos = 16, 10000.0, 32
    hf_cfg = LlamaConfig(
        vocab_size=160, hidden_size=hd * 4, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=max_pos, rope_theta=theta,
        tie_word_embeddings=False,
        rope_scaling={"rope_type": "dynamic", "factor": 4.0})
    scaling = _validated_rope_scaling(hf_cfg.to_dict())
    assert scaling["max_position_embeddings"] == max_pos
    for seq_len in (max_pos - 8, max_pos * 3):
        inv_hf, _ = ROPE_INIT_FUNCTIONS["dynamic"](
            hf_cfg, device="cpu", seq_len=seq_len)
        inv_j = _dynamic_ntk_inv_freq(
            scaling, jnp.arange(seq_len)[None, :], hd, theta)
        np.testing.assert_allclose(np.asarray(inv_j), inv_hf.numpy(),
                                   rtol=1e-6, err_msg=f"seq={seq_len}")

    import tempfile
    torch.manual_seed(5)
    hf_model = LlamaForCausalLM(hf_cfg).eval()
    with tempfile.TemporaryDirectory() as d:
        hf_model.save_pretrained(d, safe_serialization=True)
        cfg = hf_config_to_model_config(
            read_hf_config(d), dtype="float32", param_dtype="float32",
            remat="none", max_seq_length=96)
        params = import_hf_weights(d, cfg)
    from dla_tpu.models.transformer import Transformer
    model = Transformer(cfg)
    for t in (max_pos - 8, max_pos + 16):  # static base, stretched base
        ids = np.random.RandomState(6).randint(0, 160, (2, t))
        ours = np.asarray(model.apply(params, jnp.asarray(ids, np.int32)))
        with torch.no_grad():
            theirs = hf_model(torch.tensor(ids)).logits.numpy()
        np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=3e-4,
                                   err_msg=f"T={t}")


def test_unknown_rope_scaling_refused():
    import pytest
    from dla_tpu.models.hf_import import hf_config_to_model_config

    base = dict(model_type="llama", vocab_size=128, hidden_size=32,
                intermediate_size=64, num_hidden_layers=2,
                num_attention_heads=4, num_key_value_heads=2)
    with pytest.raises(NotImplementedError, match="made_up"):
        hf_config_to_model_config(
            {**base,
             "rope_scaling": {"rope_type": "made_up", "factor": 2.0}})
    # default-type scaling dicts are a no-op, not an error
    assert hf_config_to_model_config(
        {**base, "rope_scaling": {"rope_type": "default"}}
    ).rope_scaling is None


def test_arch_overrides_cover_every_model_config_field():
    """model.<key> YAML overrides flow to ModelConfig through a
    whitelist in model_io._arch_overrides — a field missing from it is
    SILENTLY dropped (round 4: --set model.pipeline_interleave=2 was a
    no-op). Pin that every architecture-shaping ModelConfig field is
    either whitelisted or deliberately excluded."""
    import dataclasses

    from dla_tpu.models.config import ModelConfig
    from dla_tpu.training.model_io import _arch_overrides

    # fields set by structural/weight context, not per-run YAML keys
    excluded = {
        "vocab_size", "hidden_size", "intermediate_size", "num_layers",
        "num_heads", "num_kv_heads", "head_dim", "rope_theta",
        "rope_scaling", "rms_norm_eps", "tie_embeddings",
        "max_seq_length",  # handled explicitly above the whitelist
        "flash_block_q", "flash_block_k",
        "lora_r", "lora_alpha", "lora_dropout", "lora_targets",  # lora block
    }
    fields = {f.name for f in dataclasses.fields(ModelConfig)}
    candidates = fields - excluded
    probe = {k: 1 for k in candidates}
    got = _arch_overrides(probe)
    missing = candidates - set(got)
    assert not missing, (
        f"ModelConfig fields silently dropped by _arch_overrides: "
        f"{sorted(missing)}")
