"""Qwen2-family support: llama block layout + q/k/v projection biases.
Logits parity with transformers' Qwen2ForCausalLM on a tiny random model
saved to disk (zero egress: instantiated locally)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def tiny_qwen_dir(tmp_path_factory):
    from transformers import Qwen2Config, Qwen2ForCausalLM
    cfg = Qwen2Config(
        vocab_size=160, hidden_size=32, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-6, rope_theta=1e6,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    model = Qwen2ForCausalLM(cfg).eval()
    # give the zero-init biases real values so parity actually tests them
    with torch.no_grad():
        for layer in model.model.layers:
            for proj in (layer.self_attn.q_proj, layer.self_attn.k_proj,
                         layer.self_attn.v_proj):
                proj.bias.normal_(std=0.5)
    d = tmp_path_factory.mktemp("hf_qwen2")
    model.save_pretrained(str(d), safe_serialization=True)
    return d, model


def test_qwen2_config_mapping(tiny_qwen_dir):
    d, _ = tiny_qwen_dir
    from dla_tpu.models.hf_import import hf_config_to_model_config, read_hf_config
    cfg = hf_config_to_model_config(read_hf_config(d))
    assert cfg.arch == "llama" and cfg.attention_bias
    assert cfg.rope_theta == 1e6 and cfg.num_kv_heads == 2


def test_qwen2_import_matches_hf_logits(tiny_qwen_dir):
    d, hf_model = tiny_qwen_dir
    import jax.numpy as jnp
    from dla_tpu.models.hf_import import (
        hf_config_to_model_config,
        import_hf_weights,
        read_hf_config,
    )
    from dla_tpu.models.transformer import Transformer

    cfg = hf_config_to_model_config(
        read_hf_config(d), dtype="float32", param_dtype="float32",
        remat="none")
    params = import_hf_weights(d, cfg)
    assert "wq_bias" in params["layers"]
    model = Transformer(cfg)

    rs = np.random.RandomState(0)
    ids = rs.randint(0, 160, (2, 11))
    ours = np.asarray(model.apply(params, jnp.asarray(ids, jnp.int32)))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=2e-4)


def test_qwen2_preset_param_tree_matches_specs():
    import jax
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer

    cfg = get_model_config("qwen2-7b", num_layers=2, hidden_size=32,
                           intermediate_size=64, num_heads=4, num_kv_heads=2,
                           vocab_size=64, dtype="float32",
                           param_dtype="float32", remat="none")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    specs = model.partition_specs()
    assert (jax.tree.structure(params) == jax.tree.structure(specs))
    assert "wq_bias" in params["layers"]
