"""Qwen2-family support: llama block layout + q/k/v projection biases.
Logits parity with transformers' Qwen2ForCausalLM on a tiny random model
saved to disk (zero egress: instantiated locally)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def tiny_qwen_dir(tmp_path_factory):
    from transformers import Qwen2Config, Qwen2ForCausalLM
    cfg = Qwen2Config(
        vocab_size=160, hidden_size=32, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-6, rope_theta=1e6,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    model = Qwen2ForCausalLM(cfg).eval()
    # give the zero-init biases real values so parity actually tests them
    with torch.no_grad():
        for layer in model.model.layers:
            for proj in (layer.self_attn.q_proj, layer.self_attn.k_proj,
                         layer.self_attn.v_proj):
                proj.bias.normal_(std=0.5)
    d = tmp_path_factory.mktemp("hf_qwen2")
    model.save_pretrained(str(d), safe_serialization=True)
    return d, model


def test_qwen2_config_mapping(tiny_qwen_dir):
    d, _ = tiny_qwen_dir
    from dla_tpu.models.hf_import import hf_config_to_model_config, read_hf_config
    cfg = hf_config_to_model_config(read_hf_config(d))
    assert cfg.arch == "llama" and cfg.attention_bias
    assert cfg.rope_theta == 1e6 and cfg.num_kv_heads == 2


def test_qwen2_swa_defaults_follow_hf():
    """Absent use_sliding_window must follow Qwen2Config's default
    (False), and an absent max_window_layers means the HF default 28
    (full attention on early layers), not 0 — a config.json relying on
    HF defaults must not import with SWA silently enabled (round-3
    advisor finding)."""
    from dla_tpu.models.hf_import import hf_config_to_model_config

    base = dict(
        model_type="qwen2", vocab_size=160, hidden_size=32,
        intermediate_size=96, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        sliding_window=1024)
    # neither use_sliding_window nor max_window_layers present: HF
    # defaults say no SWA at all
    assert hf_config_to_model_config(dict(base)).sliding_window is None
    # opted in, but absent max_window_layers = 28 >= 2 layers: all
    # layers stay full-attention
    cfg = hf_config_to_model_config(
        dict(base, use_sliding_window=True))
    assert cfg.sliding_window is None
    # opted in with mwl=0: SWA everywhere
    cfg = hf_config_to_model_config(
        dict(base, use_sliding_window=True, max_window_layers=0))
    assert cfg.sliding_window == 1024
    # mistral semantics unchanged: absent use_sliding_window -> SWA on
    mcfg = hf_config_to_model_config(dict(
        model_type="mistral", vocab_size=160, hidden_size=32,
        intermediate_size=96, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        sliding_window=1024))
    assert mcfg.sliding_window == 1024


def test_qwen2_import_matches_hf_logits(tiny_qwen_dir):
    d, hf_model = tiny_qwen_dir
    import jax.numpy as jnp
    from dla_tpu.models.hf_import import (
        hf_config_to_model_config,
        import_hf_weights,
        read_hf_config,
    )
    from dla_tpu.models.transformer import Transformer

    cfg = hf_config_to_model_config(
        read_hf_config(d), dtype="float32", param_dtype="float32",
        remat="none")
    params = import_hf_weights(d, cfg)
    assert "wq_bias" in params["layers"]
    model = Transformer(cfg)

    rs = np.random.RandomState(0)
    ids = rs.randint(0, 160, (2, 11))
    ours = np.asarray(model.apply(params, jnp.asarray(ids, jnp.int32)))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=2e-4)


def test_qwen2_yarn_rope_matches_hf():
    """YaRN rope scaling (qwen2.5-1M-style long-context checkpoints):
    scaled inv_freq and the attention factor must match transformers'
    _compute_yarn_parameters exactly, and a yarn-configured tiny model
    must hit logits parity end to end."""
    import jax.numpy as jnp
    from transformers import Qwen2Config, Qwen2ForCausalLM
    from transformers.modeling_rope_utils import ROPE_INIT_FUNCTIONS

    from dla_tpu.ops.rotary import _scale_inv_freq, validate_rope_scaling

    # unit parity across factors / contexts / head dims, plus the
    # deepseek-style mscale pair, truncate=False fractional bounds,
    # and the factor<=1 mscale guard
    cases = [
        dict(factor=4.0, original_max_position_embeddings=64),
        dict(factor=2.5, original_max_position_embeddings=128),
        dict(factor=32.0, original_max_position_embeddings=32),
        dict(factor=40.0, original_max_position_embeddings=64,
             mscale=1.0, mscale_all_dim=1.0),
        dict(factor=8.0, original_max_position_embeddings=64,
             mscale=0.707, mscale_all_dim=1.2),
        dict(factor=4.0, original_max_position_embeddings=64,
             truncate=False),
        dict(factor=1.0, original_max_position_embeddings=64),
        dict(factor=4.0, original_max_position_embeddings=64,
             attention_factor=2.5),
    ]
    for hd, theta in [(8, 1e6), (16, 1e4), (64, 1e6)]:
        for case in cases:
            sc = {"rope_type": "yarn", **case}
            c = Qwen2Config(
                vocab_size=160, hidden_size=hd * 4, intermediate_size=96,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2, rope_theta=theta,
                max_position_embeddings=int(
                    case["original_max_position_embeddings"]
                    * case["factor"]),
                rope_scaling=dict(sc))
            inv_hf, att_hf = ROPE_INIT_FUNCTIONS["yarn"](c, device="cpu")
            inv0 = 1.0 / (theta ** (jnp.arange(0, hd, 2,
                                               dtype=jnp.float32) / hd))
            inv_j, att_j = _scale_inv_freq(
                inv0, validate_rope_scaling(sc), hd, theta)
            np.testing.assert_allclose(
                np.asarray(inv_j), inv_hf.numpy(), rtol=1e-6,
                err_msg=f"hd={hd} {case}")
            assert abs(att_j - float(att_hf)) < 1e-9, (hd, case)

    # a yarn dict omitting original_max_position_embeddings gets the
    # checkpoint's max_position_embeddings injected at import (HF's own
    # fallback), and the bare op refuses rather than guessing
    from dla_tpu.models.hf_import import _validated_rope_scaling
    injected = _validated_rope_scaling(
        {"rope_scaling": {"rope_type": "yarn", "factor": 4.0},
         "max_position_embeddings": 1024})
    assert injected["original_max_position_embeddings"] == 1024
    import pytest as _pytest
    with _pytest.raises(ValueError, match="original_max_position"):
        _scale_inv_freq(
            1.0 / (1e6 ** (jnp.arange(0, 8, 2, dtype=jnp.float32) / 8)),
            {"rope_type": "yarn", "factor": 4.0}, 8, 1e6)

    # end-to-end logits parity on a yarn-configured tiny qwen2
    from dla_tpu.models.hf_import import (
        hf_config_to_model_config,
        import_hf_weights,
        read_hf_config,
    )
    from dla_tpu.models.transformer import Transformer
    import tempfile

    hf_cfg = Qwen2Config(
        vocab_size=160, hidden_size=32, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rope_theta=1e6,
        tie_word_embeddings=False,
        rope_scaling={"rope_type": "yarn", "factor": 4.0,
                      "original_max_position_embeddings": 64})
    torch.manual_seed(1)
    hf_model = Qwen2ForCausalLM(hf_cfg).eval()
    with tempfile.TemporaryDirectory() as d:
        hf_model.save_pretrained(d, safe_serialization=True)
        cfg = hf_config_to_model_config(
            read_hf_config(d), dtype="float32", param_dtype="float32",
            remat="none")
        assert cfg.rope_scaling and \
            cfg.rope_scaling.get("rope_type") == "yarn"
        params = import_hf_weights(d, cfg)
    model = Transformer(cfg)
    rs = np.random.RandomState(3)
    ids = rs.randint(0, 160, (2, 90))  # past the original 64 context
    ours = np.asarray(model.apply(params, jnp.asarray(ids, np.int32)))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=2e-4)


def test_qwen2_preset_param_tree_matches_specs():
    import jax
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer

    cfg = get_model_config("qwen2-7b", num_layers=2, hidden_size=32,
                           intermediate_size=64, num_heads=4, num_kv_heads=2,
                           vocab_size=64, dtype="float32",
                           param_dtype="float32", remat="none")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    specs = model.partition_specs()
    assert (jax.tree.structure(params) == jax.tree.structure(specs))
    assert "wq_bias" in params["layers"]
