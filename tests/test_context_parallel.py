"""Context parallelism (ring + ulysses) over the `sequence` mesh axis.

New capability vs the reference (SURVEY.md sec 2.3: no CP anywhere).
Parity bar: sequence-sharded attention == full XLA attention, forward and
gradient, including right-padding and packed segments; and the whole
transformer forward must be unchanged when the sequence axis turns on.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dla_tpu.ops.attention import causal_attention
from dla_tpu.ops.ring_attention import ring_causal_attention
from dla_tpu.ops.ulysses import ulysses_causal_attention
from dla_tpu.parallel.mesh import MeshConfig, build_mesh


@pytest.fixture(scope="module")
def seq_mesh():
    return build_mesh(MeshConfig(data=1, fsdp=2, model=1, sequence=4))


def _mk(b=2, t=32, h=4, kh=2, d=8, seed=0):
    rs = np.random.RandomState(seed)
    q = jnp.asarray(rs.randn(b, t, h, d), jnp.float32)
    k = jnp.asarray(rs.randn(b, t, kh, d), jnp.float32)
    v = jnp.asarray(rs.randn(b, t, kh, d), jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(t)[None, :], (b, t))
    return q, k, v, pos


def _xla_ref(q, k, v, pos, valid=None, seg=None):
    b, t = pos.shape
    mask = None
    if valid is not None:
        mask = jnp.broadcast_to(valid[:, None, :].astype(bool), (b, t, t))
    if seg is not None:
        same = seg[:, :, None] == seg[:, None, :]
        mask = same if mask is None else (mask & same)
    return causal_attention(q, k, v, kv_segment_mask=mask,
                            q_positions=pos, kv_positions=pos)


def test_ring_forward_parity_with_padding(seq_mesh):
    q, k, v, pos = _mk()
    b, t = pos.shape
    valid = (jnp.arange(t)[None, :] <
             jnp.array([t, t - 7])[:, None]).astype(jnp.int32)
    ref = _xla_ref(q, k, v, pos, valid)
    with jax.sharding.set_mesh(seq_mesh):
        out = jax.jit(lambda q, k, v: ring_causal_attention(
            q, k, v, q_positions=pos, kv_positions=pos, kv_valid=valid)
        )(q, k, v)
    err = np.abs(np.asarray(out) - np.asarray(ref))
    assert err[np.asarray(valid).astype(bool)].max() < 1e-5


def test_ring_gradient_parity(seq_mesh):
    q, k, v, pos = _mk()
    t = q.shape[1]
    valid = jnp.ones(pos.shape, jnp.int32)

    def loss_ring(q, k, v):
        o = ring_causal_attention(q, k, v, q_positions=pos,
                                  kv_positions=pos, kv_valid=valid)
        return (o ** 2).sum()

    def loss_ref(q, k, v):
        return (_xla_ref(q, k, v, pos) ** 2).sum()

    with jax.sharding.set_mesh(seq_mesh):
        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=1e-4, rtol=1e-4)


def test_ring_packed_segments(seq_mesh):
    q, k, v, pos = _mk(seed=3)
    b, t = pos.shape
    rs = np.random.RandomState(7)
    seg = jnp.asarray(np.sort(rs.randint(0, 3, (b, t)), axis=1), jnp.int32)
    ref = _xla_ref(q, k, v, pos, seg=seg)
    with jax.sharding.set_mesh(seq_mesh):
        out = jax.jit(lambda q, k, v: ring_causal_attention(
            q, k, v, q_positions=pos, kv_positions=pos, segment_ids=seg)
        )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ulysses_forward_parity(seq_mesh):
    q, k, v, pos = _mk(h=8, kh=4, seed=1)
    b, t = pos.shape
    valid = (jnp.arange(t)[None, :] <
             jnp.array([t, t - 5])[:, None]).astype(jnp.int32)
    ref = _xla_ref(q, k, v, pos, valid)
    with jax.sharding.set_mesh(seq_mesh):
        out = jax.jit(lambda q, k, v: ulysses_causal_attention(
            q, k, v, q_positions=pos, kv_positions=pos, kv_valid=valid)
        )(q, k, v)
    err = np.abs(np.asarray(out) - np.asarray(ref))
    assert err[np.asarray(valid).astype(bool)].max() < 1e-5


def test_ulysses_flash_parity(seq_mesh):
    """use_flash=True routes the per-shard attention through the Pallas
    kernel (O(T) memory); parity with the XLA path on padded + packed
    metadata (validity folds into the kernel's segment mask)."""
    q, k, v, pos = _mk(h=8, kh=4, seed=4)
    b, t = pos.shape
    valid = (jnp.arange(t)[None, :] <
             jnp.array([t, t - 5])[:, None]).astype(jnp.int32)
    rs = np.random.RandomState(9)
    seg = jnp.asarray(np.sort(rs.randint(1, 3, (b, t)), axis=1), jnp.int32)
    ref = _xla_ref(q, k, v, pos, valid, seg=seg)
    with jax.sharding.set_mesh(seq_mesh):
        out = jax.jit(lambda q, k, v: ulysses_causal_attention(
            q, k, v, q_positions=pos, kv_positions=pos, kv_valid=valid,
            segment_ids=seg, use_flash=True))(q, k, v)
    err = np.abs(np.asarray(out) - np.asarray(ref))
    assert err[np.asarray(valid).astype(bool)].max() < 2e-4


def test_ulysses_rejects_indivisible_heads(seq_mesh):
    q, k, v, pos = _mk(h=4, kh=2, seed=2)  # kh=2 not divisible by seq=4
    with jax.sharding.set_mesh(seq_mesh):
        with pytest.raises(ValueError, match="ring attention instead"):
            ulysses_causal_attention(q, k, v, q_positions=pos,
                                     kv_positions=pos)


@pytest.mark.parametrize("mode", ["ring", "ulysses"])
def test_model_forward_parity_under_cp(seq_mesh, mode):
    """Whole-transformer logits must not change when the sequence axis
    turns on (tiny model, padded batch)."""
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer

    kv_heads = {"ring": 2, "ulysses": 4}[mode]
    cfg = get_model_config("tiny", num_kv_heads=kv_heads,
                           context_parallel=mode)
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    b, t = 2, 64
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(1, cfg.vocab_size, (b, t)), jnp.int32)
    mask = (jnp.arange(t)[None, :] <
            jnp.array([t, t - 9])[:, None]).astype(jnp.int32)

    ref = model.apply(params, ids, attention_mask=mask)  # no mesh: cp off
    with jax.sharding.set_mesh(seq_mesh):
        out = jax.jit(lambda p, i, m: model.apply(p, i, attention_mask=m)
                      )(params, ids, mask)
    err = np.abs(np.asarray(out) - np.asarray(ref))
    assert err[np.asarray(mask).astype(bool)].max() < 2e-4


def test_train_step_with_sequence_axis(seq_mesh):
    """One full sharded SFT train step with CP active: loss finite and
    equal to the sequence=1 loss."""
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.ops.losses import cross_entropy_loss
    from dla_tpu.training.trainer import Trainer

    cfg = get_model_config("tiny", context_parallel="ring")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))

    def loss_fn(p, frozen, batch, rng):
        del frozen, rng
        logits = model.apply(p, batch["input_ids"],
                             attention_mask=batch["attention_mask"])
        loss, _ = cross_entropy_loss(logits, batch["labels"])
        return loss, {}

    config = {
        "experiment_name": "cp_test",
        "optimization": {"total_batch_size": 4, "micro_batch_size": 2,
                         "learning_rate": 1e-3, "max_train_steps": 2,
                         "lr_scheduler": "constant", "max_grad_norm": 1.0},
        "logging": {"output_dir": "/tmp/cp_test", "log_dir": None},
        "hardware": {"gradient_accumulation_steps": 2},
    }
    rs = np.random.RandomState(0)
    batch = {
        "input_ids": rs.randint(1, cfg.vocab_size, (4, 32)).astype(np.int32),
        "attention_mask": np.ones((4, 32), np.int32),
        "labels": rs.randint(1, cfg.vocab_size, (4, 32)).astype(np.int32),
    }
    with jax.sharding.set_mesh(seq_mesh):
        trainer = Trainer(config=config, mesh=seq_mesh, loss_fn=loss_fn,
                          params=params,
                          param_specs=model.partition_specs())
        loss, _ = trainer.step_on_batch(batch, jax.random.key(0))
    assert np.isfinite(loss)


def test_ring_sliding_window_parity(seq_mesh):
    """Ring attention with a sliding window == single-device windowed
    attention: the window term is evaluated on absolute positions that
    rotate with kv, so any chunk masks correctly from any ring slot.
    Forward + gradient parity, window unaligned with the shard width."""
    q, k, v, pos = _mk(seed=13)
    window = 11  # 32 tokens over 4 shards of 8: crosses shard boundaries

    def ring_out(q, k, v):
        return ring_causal_attention(
            q, k, v, q_positions=pos, kv_positions=pos, window=window)

    def xla_out(q, k, v):
        return causal_attention(q, k, v, q_positions=pos,
                                kv_positions=pos, window=window)

    with jax.sharding.set_mesh(seq_mesh):
        got = ring_out(q, k, v)
        gf = jax.grad(lambda *a: jnp.sum(ring_out(*a) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
    want = xla_out(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)
    gx = jax.grad(lambda *a: jnp.sum(xla_out(*a) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


@pytest.mark.parametrize("mode", ["ring", "ulysses"])
def test_model_sliding_window_under_cp(seq_mesh, mode):
    """A sliding-window (mistral-family) model trains under BOTH CP
    modes: full-model forward parity vs the no-mesh forward. Ulysses
    folds the window into the per-head-slice attention (r4 VERDICT
    item 6 — previously refused)."""
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.parallel.sharding import sharding_tree

    kv_heads = {"ring": None, "ulysses": 4}[mode]  # ulysses: seq | kv
    kw = {"num_kv_heads": kv_heads} if kv_heads else {}
    cfg = get_model_config("tiny-gqa", sliding_window=6,
                           context_parallel=mode, **kw)
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    rs = np.random.RandomState(3)
    ids = jnp.asarray(rs.randint(1, 100, (2, 32)), jnp.int32)

    want = model.apply(params, ids)
    with jax.sharding.set_mesh(seq_mesh):
        sharded = jax.device_put(
            params, sharding_tree(model.partition_specs(), seq_mesh))
        got = jax.jit(lambda p: model.apply(p, ids))(sharded)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-4)


@pytest.mark.parametrize("window", [1, 8, 9, 17, 32])
def test_ring_window_truncated_scan_parity(seq_mesh, window):
    """The windowed ring truncates its scan to ceil((w-1)/Sl)+1 chunks;
    parity must hold at every boundary: w == Sl, w == Sl+1, multi-chunk,
    and w covering the whole sequence (no truncation)."""
    q, k, v, pos = _mk(seed=21)

    with jax.sharding.set_mesh(seq_mesh):
        got = ring_causal_attention(
            q, k, v, q_positions=pos, kv_positions=pos, window=window)
    want = causal_attention(q, k, v, q_positions=pos, kv_positions=pos,
                            window=window)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_ring_window_gapped_positions_no_truncation(seq_mesh):
    """Gapped masks break the physical-distance bound the truncation
    relies on (positions = cumsum(mask)-1, so a query physically chunks
    away can be only a few POSITIONS past an in-window key). With
    window_truncate=False the windowed ring must stay exact; the model
    path passes that flag whenever it built positions from a gapped
    mask."""
    q, k, v, _ = _mk(seed=23)
    b, t = q.shape[0], q.shape[1]
    mask = np.ones((b, t), np.int32)
    mask[:, 4:24] = 0  # a 20-token hole spanning whole chunks
    valid = jnp.asarray(mask)
    pos = jnp.cumsum(valid, axis=1) - 1  # the gapped_mask=True recipe
    window = 8

    win_mask = ((pos[:, :, None] - pos[:, None, :]) < window)
    ref = causal_attention(
        q, k, v, q_positions=pos, kv_positions=pos,
        kv_segment_mask=(valid[:, None, :].astype(bool)
                         & jnp.broadcast_to(win_mask, (b, t, t))))
    with jax.sharding.set_mesh(seq_mesh):
        out = ring_causal_attention(
            q, k, v, q_positions=pos, kv_positions=pos, kv_valid=valid,
            window=window, window_truncate=False)
    err = np.abs(np.asarray(out) - np.asarray(ref))
    assert err[np.asarray(valid).astype(bool)].max() < 2e-5


def test_model_gapped_mask_window_under_ring(seq_mesh):
    """Whole-model check: a windowed model fed a gapped mask under ring
    CP matches the no-mesh forward (the model disables truncation for
    gapped-position batches)."""
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer

    cfg = get_model_config("tiny-gqa", sliding_window=6,
                           context_parallel="ring")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    rs = np.random.RandomState(5)
    ids = jnp.asarray(rs.randint(1, 100, (2, 32)), jnp.int32)
    mask = np.ones((2, 32), np.int32)
    mask[:, 6:20] = 0
    mask = jnp.asarray(mask)

    want = model.apply(params, ids, attention_mask=mask, gapped_mask=True)
    with jax.sharding.set_mesh(seq_mesh):
        got = jax.jit(lambda p: model.apply(
            p, ids, attention_mask=mask, gapped_mask=True))(params)
    m = np.asarray(mask).astype(bool)
    err = np.abs(np.asarray(got) - np.asarray(want))
    assert err[m].max() < 2e-4


def test_ring_softcap_and_scale_parity(seq_mesh):
    """gemma-2 attention numerics under ring CP: score softcapping and a
    non-default softmax scale must match the XLA path, forward and
    gradient."""
    q, k, v, pos = _mk(seed=21)

    def ring_out(q, k, v):
        return ring_causal_attention(
            q, k, v, q_positions=pos, kv_positions=pos,
            softmax_scale=8 ** -0.5, logit_softcap=5.0)

    def xla_out(q, k, v):
        return causal_attention(q, k, v, q_positions=pos,
                                kv_positions=pos, softmax_scale=8 ** -0.5,
                                logit_softcap=5.0)

    with jax.sharding.set_mesh(seq_mesh):
        got = ring_out(q, k, v)
        gr = jax.grad(lambda *a: jnp.sum(ring_out(*a) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
    want = xla_out(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)
    gx = jax.grad(lambda *a: jnp.sum(xla_out(*a) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_ring_traced_window_parity(seq_mesh):
    """A TRACED window scalar (the gemma-2 per-layer alternating SWA
    mechanism) must mask identically to the static window (which also
    truncates the ring scan)."""
    q, k, v, pos = _mk(seed=22)
    with jax.sharding.set_mesh(seq_mesh):
        static = ring_causal_attention(
            q, k, v, q_positions=pos, kv_positions=pos, window=11)
        traced = jax.jit(lambda w: ring_causal_attention(
            q, k, v, q_positions=pos, kv_positions=pos, window=w)
        )(jnp.int32(11))
    np.testing.assert_allclose(np.asarray(traced), np.asarray(static),
                               rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("mode", ["ring", "ulysses"])
def test_gemma2_model_under_cp(seq_mesh, mode):
    """Full gemma-2 block stack (alternating window + softcaps + custom
    scale) under BOTH CP modes == the no-mesh forward. Under ulysses the
    traced per-layer window rides the shard_map as a replicated scalar
    and masks on the gathered global positions (r4 VERDICT item 6)."""
    import dataclasses

    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.parallel.sharding import sharding_tree

    cfg = dataclasses.replace(
        get_model_config("tiny-gqa", num_kv_heads=4),
        arch="gemma2", sliding_window=6, sliding_window_pattern=2,
        attn_logit_softcap=20.0, final_logit_softcap=10.0,
        query_pre_attn_scalar=8, tie_embeddings=True,
        context_parallel=mode)
    model = Transformer(cfg)
    params = model.init(jax.random.key(7))
    rs = np.random.RandomState(8)
    ids = jnp.asarray(rs.randint(1, 100, (2, 32)), jnp.int32)

    want = model.apply(params, ids)
    with jax.sharding.set_mesh(seq_mesh):
        sharded = jax.device_put(
            params, sharding_tree(model.partition_specs(), seq_mesh))
        got = jax.jit(lambda p: model.apply(p, ids))(sharded)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-4)


def test_ring_vs_chunked_bf16_tolerance(seq_mesh):
    """Pin the bf16 numerics drift between ring and chunked attention at
    representative T (ADVICE r4): ring casts softmax weights to the
    value dtype before the value einsum (ring_attention.py ~:96, the
    flash kernel's convention) while keeping fp32 online-softmax
    accumulators. If a future change regresses the accumulators to bf16
    (or otherwise loosens long-T numerics), the drift blows through this
    bound and the change is caught here instead of in training curves."""
    from dla_tpu.ops.attention import chunked_causal_attention

    b, t, h, kh, d = 2, 512, 4, 2, 64
    rs = np.random.RandomState(11)
    q = jnp.asarray(rs.randn(b, t, h, d), jnp.bfloat16)
    k = jnp.asarray(rs.randn(b, t, kh, d), jnp.bfloat16)
    v = jnp.asarray(rs.randn(b, t, kh, d), jnp.bfloat16)
    pos = jnp.broadcast_to(jnp.arange(t)[None, :], (b, t))

    want = chunked_causal_attention(q, k, v, q_positions=pos,
                                    kv_positions=pos, q_chunk=128)
    with jax.sharding.set_mesh(seq_mesh):
        got = jax.jit(lambda q, k, v: ring_causal_attention(
            q, k, v, q_positions=pos, kv_positions=pos))(q, k, v)
    err = np.abs(np.asarray(got, np.float32) - np.asarray(want, np.float32))
    assert err.max() < 1.6e-2, f"ring vs chunked bf16 drift: {err.max()}"


def test_ulysses_sliding_window_parity(seq_mesh):
    """Op-level: ulysses with a static window == single-device windowed
    attention, on BOTH backends — masked XLA (use_flash=False) and the
    flash kernel (window by index == window by position on contiguous
    rows). Window unaligned with the shard width."""
    q, k, v, pos = _mk(h=8, kh=4, seed=31)
    window = 11

    want = causal_attention(q, k, v, q_positions=pos, kv_positions=pos,
                            window=window)
    with jax.sharding.set_mesh(seq_mesh):
        for use_flash in (False, True):
            got = jax.jit(lambda q, k, v, f=use_flash:
                          ulysses_causal_attention(
                              q, k, v, q_positions=pos, kv_positions=pos,
                              window=window, use_flash=f))(q, k, v)
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4,
                err_msg=f"use_flash={use_flash}")


def test_ulysses_window_gradient_parity(seq_mesh):
    """Training through windowed ulysses: gradient parity vs the XLA
    windowed path (the all-to-alls and gathers transpose cleanly)."""
    q, k, v, pos = _mk(h=8, kh=4, seed=32)
    window = 9

    def uly(q, k, v):
        return ulysses_causal_attention(
            q, k, v, q_positions=pos, kv_positions=pos, window=window)

    def xla(q, k, v):
        return causal_attention(q, k, v, q_positions=pos,
                                kv_positions=pos, window=window)

    with jax.sharding.set_mesh(seq_mesh):
        gu = jax.jit(jax.grad(lambda *a: jnp.sum(uly(*a) ** 2),
                              argnums=(0, 1, 2)))(q, k, v)
    gx = jax.grad(lambda *a: jnp.sum(xla(*a) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gu, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_ulysses_softcap_scale_traced_window_parity(seq_mesh):
    """gemma-2 numerics under ulysses: softcapping + non-default scale +
    a TRACED window scalar (the per-layer alternating-SWA mechanism)
    must match the XLA path exactly."""
    q, k, v, pos = _mk(h=8, kh=4, seed=33)

    want = causal_attention(q, k, v, q_positions=pos, kv_positions=pos,
                            softmax_scale=8 ** -0.5, logit_softcap=5.0,
                            window=7)
    with jax.sharding.set_mesh(seq_mesh):
        got = jax.jit(lambda w: ulysses_causal_attention(
            q, k, v, q_positions=pos, kv_positions=pos,
            softmax_scale=8 ** -0.5, logit_softcap=5.0, window=w)
        )(jnp.int32(7))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_ulysses_gapped_window_no_flash(seq_mesh):
    """Gapped positions (cumsum recipe) with a window: contiguous=False
    must drop the static window off the index-based flash kernel and
    mask on gathered global positions instead — exactness where
    index-window math would be wrong."""
    q, k, v, _ = _mk(h=8, kh=4, seed=34)
    b, t = q.shape[0], q.shape[1]
    mask = np.ones((b, t), np.int32)
    mask[:, 4:24] = 0  # a 20-token hole spanning whole shards
    valid = jnp.asarray(mask)
    pos = jnp.cumsum(valid, axis=1) - 1
    window = 8

    win_mask = ((pos[:, :, None] - pos[:, None, :]) < window)
    ref = causal_attention(
        q, k, v, q_positions=pos, kv_positions=pos,
        kv_segment_mask=(valid[:, None, :].astype(bool)
                         & jnp.broadcast_to(win_mask, (b, t, t))))
    with jax.sharding.set_mesh(seq_mesh):
        out = jax.jit(lambda q, k, v: ulysses_causal_attention(
            q, k, v, q_positions=pos, kv_positions=pos, kv_valid=valid,
            window=window, contiguous=False, use_flash=True))(q, k, v)
    err = np.abs(np.asarray(out) - np.asarray(ref))
    assert err[np.asarray(valid).astype(bool)].max() < 2e-5
