"""Transformer model tests: shapes, masking invariances, decode parity,
sharded-vs-single-device parity (SURVEY.md sec 4 items 2-3)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dla_tpu.models.config import get_model_config
from dla_tpu.models.reward import RewardModel
from dla_tpu.models.transformer import Transformer
from dla_tpu.parallel.sharding import shard_pytree


@pytest.fixture(scope="module")
def tiny_model():
    cfg = get_model_config("tiny")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    return model, params


def test_forward_shapes(tiny_model):
    model, params = tiny_model
    ids = jnp.ones((2, 10), jnp.int32)
    logits = model.apply(params, ids)
    assert logits.shape == (2, 10, model.cfg.vocab_size)


def test_padding_does_not_change_real_positions(tiny_model):
    model, params = tiny_model
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(1, 100, (1, 6)), jnp.int32)
    padded = jnp.concatenate([ids, jnp.zeros((1, 4), jnp.int32)], axis=1)
    mask = jnp.asarray([[1] * 6 + [0] * 4])
    full = model.apply(params, ids, attention_mask=jnp.ones((1, 6), jnp.int32))
    pad = model.apply(params, padded, attention_mask=mask)
    np.testing.assert_allclose(
        np.asarray(full[0]), np.asarray(pad[0, :6]), rtol=2e-4, atol=1e-5)


def test_causality(tiny_model):
    """Changing a future token must not change past logits."""
    model, params = tiny_model
    rs = np.random.RandomState(1)
    ids = jnp.asarray(rs.randint(1, 100, (1, 8)), jnp.int32)
    ids2 = ids.at[0, 6].set(int(ids[0, 6]) % 100 + 1)
    a = model.apply(params, ids)
    b = model.apply(params, ids2)
    np.testing.assert_allclose(
        np.asarray(a[0, :6]), np.asarray(b[0, :6]), rtol=1e-4, atol=1e-6)
    assert not np.allclose(np.asarray(a[0, 6]), np.asarray(b[0, 6]))


def test_packing_segments_are_independent(tiny_model):
    """Two sequences packed with segment_ids == the same sequences unpacked."""
    model, params = tiny_model
    rs = np.random.RandomState(2)
    a = rs.randint(1, 100, (4,))
    b = rs.randint(1, 100, (5,))
    packed = jnp.asarray(np.concatenate([a, b])[None, :], jnp.int32)
    seg = jnp.asarray([[0] * 4 + [1] * 5])
    out_packed = model.apply(params, packed, segment_ids=seg)
    out_a = model.apply(params, jnp.asarray(a[None, :], jnp.int32))
    out_b = model.apply(params, jnp.asarray(b[None, :], jnp.int32))
    np.testing.assert_allclose(
        np.asarray(out_packed[0, :4]), np.asarray(out_a[0]), rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(out_packed[0, 4:]), np.asarray(out_b[0]), rtol=2e-4, atol=1e-5)


def test_decode_matches_full_forward(tiny_model):
    """Greedy decode via KV cache == argmax over full forward re-runs."""
    model, params = tiny_model
    rs = np.random.RandomState(3)
    lens = [5, 3]
    width = 6
    ids = np.zeros((2, width), np.int32)
    mask = np.zeros((2, width), np.int32)
    for i, L in enumerate(lens):
        ids[i, :L] = rs.randint(1, 100, (L,))
        mask[i, :L] = 1
    ids, mask = jnp.asarray(ids), jnp.asarray(mask)
    n_new = 4

    logits, cache = model.start_decode(params, ids, mask, n_new)
    cached_tokens = []
    for _ in range(n_new):
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        cached_tokens.append(np.asarray(tok))
        logits, cache = model.decode_step(params, cache, tok)
    cached_tokens = np.stack(cached_tokens, axis=1)  # [B, n_new]

    # Reference: grow the sequence and re-run the full forward each step.
    want = np.zeros_like(cached_tokens)
    for i, L in enumerate(lens):
        seq = list(np.asarray(ids[i, :L]))
        for s in range(n_new):
            arr = jnp.asarray(np.asarray(seq)[None, :], jnp.int32)
            full = model.apply(params, arr)
            nxt = int(np.argmax(np.asarray(full[0, -1])))
            want[i, s] = nxt
            seq.append(nxt)
    np.testing.assert_array_equal(cached_tokens, want)


def test_swa_decode_matches_full_forward():
    """Greedy KV-cache decode under a sliding window (mistral-style) ==
    full forward re-runs with the same window — exercises the windowed
    mask in the no-copy decode attention path."""
    import dataclasses
    cfg = dataclasses.replace(get_model_config("tiny"), sliding_window=4)
    model = Transformer(cfg)
    params = model.init(jax.random.key(5))
    rs = np.random.RandomState(6)
    lens = [6, 3]
    width = 7
    ids = np.zeros((2, width), np.int32)
    mask = np.zeros((2, width), np.int32)
    for i, L in enumerate(lens):
        ids[i, :L] = rs.randint(1, 100, (L,))
        mask[i, :L] = 1
    ids, mask = jnp.asarray(ids), jnp.asarray(mask)
    n_new = 5  # runs past the window so old keys must drop out

    logits, cache = model.start_decode(params, ids, mask, n_new)
    cached_tokens = []
    for _ in range(n_new):
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        cached_tokens.append(np.asarray(tok))
        logits, cache = model.decode_step(params, cache, tok)
    cached_tokens = np.stack(cached_tokens, axis=1)

    want = np.zeros_like(cached_tokens)
    for i, L in enumerate(lens):
        seq = list(np.asarray(ids[i, :L]))
        for s in range(n_new):
            arr = jnp.asarray(np.asarray(seq)[None, :], jnp.int32)
            full = model.apply(params, arr)
            nxt = int(np.argmax(np.asarray(full[0, -1])))
            want[i, s] = nxt
            seq.append(nxt)
    np.testing.assert_array_equal(cached_tokens, want)


def test_sharded_forward_matches_single_device(mesh8, tiny_model):
    model, params = tiny_model
    rs = np.random.RandomState(4)
    ids = jnp.asarray(rs.randint(1, 100, (4, 8)), jnp.int32)
    want = np.asarray(model.apply(params, ids))

    sharded_params = shard_pytree(params, model.partition_specs(), mesh8)
    with jax.sharding.set_mesh(mesh8):
        got = np.asarray(jax.jit(model.apply)(sharded_params, ids))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_reward_model_pooling():
    cfg = get_model_config("tiny")
    rm = RewardModel(cfg, pooling="last_token")
    params = rm.init(jax.random.key(1))
    ids = jnp.asarray([[5, 6, 7, 0, 0], [8, 9, 10, 11, 12]], jnp.int32)
    mask = jnp.asarray([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], jnp.int32)
    r = rm.apply(params, ids, mask)
    assert r.shape == (2,)
    # padding after the last real token must not affect the reward
    ids2 = jnp.asarray([[5, 6, 7, 99, 99]], jnp.int32)
    mask2 = jnp.asarray([[1, 1, 1, 0, 0]], jnp.int32)
    r2 = rm.apply(params, ids2, mask2)
    np.testing.assert_allclose(float(r[0]), float(r2[0]), rtol=1e-5)

    rm_mean = RewardModel(cfg, pooling="mean")
    r3 = rm_mean.apply(params, ids, mask)
    assert r3.shape == (2,)


def test_tied_embeddings():
    cfg = get_model_config("tiny", tie_embeddings=True)
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    assert "lm_head" not in params
    logits = model.apply(params, jnp.ones((1, 4), jnp.int32))
    assert logits.shape == (1, 4, cfg.vocab_size)


def test_decode_block_matches_sequential_steps_and_retracts():
    """decode_block(G tokens) == G decode_step calls (logits, cache KV,
    validity, positions), and retract_block rolls back a per-row suffix
    exactly (the speculative-decoding verify/reject primitive)."""
    import numpy as np

    from dla_tpu.models.config import ModelConfig
    from dla_tpu.models.transformer import Transformer

    cfg = ModelConfig(
        vocab_size=120, hidden_size=32, intermediate_size=64,
        num_layers=2, num_heads=4, num_kv_heads=2, max_seq_length=64,
        attention="xla", remat="none", dtype="float32",
        param_dtype="float32")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    rng = np.random.RandomState(0)
    b, t, g = 2, 10, 4
    ids = jnp.asarray(rng.randint(3, 110, (b, t)), jnp.int32)
    mask = jnp.ones((b, t), jnp.int32)
    mask = mask.at[1, t - 3:].set(0)
    _, cache0 = model.start_decode(params, ids, mask, 12)
    toks = jnp.asarray(rng.randint(3, 110, (b, g)), jnp.int32)

    c = cache0
    lseq = []
    for i in range(g):
        l, c = model.decode_step(params, c, toks[:, i])
        lseq.append(l)
    lseq = jnp.stack(lseq, 1)
    lblk, cblk = model.decode_block(params, cache0, toks)
    np.testing.assert_allclose(np.asarray(lblk), np.asarray(lseq),
                               atol=1e-5)
    np.testing.assert_array_equal(np.asarray(cblk["valid"]),
                                  np.asarray(c["valid"]))
    assert bool(jnp.where(cblk["valid"], cblk["pos"] == c["pos"],
                          True).all())
    np.testing.assert_allclose(np.asarray(cblk["k"]), np.asarray(c["k"]),
                               atol=1e-5)
    np.testing.assert_array_equal(np.asarray(cblk["lengths"]),
                                  np.asarray(c["lengths"]))

    keep = jnp.asarray([2, 0], jnp.int32)
    r = model.retract_block(cblk, keep, g)
    col0 = int(cache0["prompt_width"])
    want = np.asarray(cblk["valid"]).copy()
    want[0, col0 + 2:col0 + 4] = False
    want[1, col0:col0 + 4] = False
    np.testing.assert_array_equal(np.asarray(r["valid"]), want)
    np.testing.assert_array_equal(
        np.asarray(r["lengths"]),
        np.asarray(cblk["lengths"]) - g + np.asarray(keep))
