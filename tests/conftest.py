"""Test harness: force an 8-device virtual CPU mesh before jax initializes.

This is how the suite exercises multi-chip SPMD (pjit partitioning,
collectives, checkpoint shard round-trips) without TPU hardware —
SURVEY.md sec 4's test strategy.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# A TPU-tunnel PJRT plugin (e.g. platform "axon") may have been registered
# by a sitecustomize hook at interpreter start, which sets jax_platforms
# via jax.config — overriding the env var above. Force it back before any
# backend initializes so the suite gets its 8-device virtual CPU mesh.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    import jax
    from dla_tpu.parallel.mesh import MeshConfig, build_mesh
    assert len(jax.devices()) == 8, (
        "expected 8 virtual CPU devices; run tests via "
        "JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8")
    return build_mesh(MeshConfig(data=2, fsdp=2, model=2, sequence=1))


@pytest.fixture(scope="session")
def tiny_cfg():
    from dla_tpu.models.config import get_model_config
    return get_model_config("tiny")
