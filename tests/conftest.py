"""Test harness: force an 8-device virtual CPU mesh before jax initializes.

This is how the suite exercises multi-chip SPMD (pjit partitioning,
collectives, checkpoint shard round-trips) without TPU hardware —
SURVEY.md sec 4's test strategy.
"""
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

# A TPU-tunnel PJRT plugin (e.g. platform "axon") may have been registered
# by a sitecustomize hook at interpreter start, which sets jax_platforms
# via jax.config — overriding plain env vars. _cpuhost forces the 8-device
# virtual CPU platform back before any backend initializes.
from _cpuhost import force_cpu_platform  # noqa: E402

assert force_cpu_platform(8), (
    "could not force an 8-device virtual CPU platform (a backend with the "
    "wrong platform or device count already initialized in this process); "
    "run pytest in a fresh interpreter")

# NOTE: do not enable jax's persistent compilation cache here. Executables
# containing host callbacks (the trainer's guard / fault-injection path)
# bake callback registry ids into the serialized artifact; a same-process
# cache hit later in the suite deserializes an executable whose ids point
# at different callbacks and segfaults (reproduced on test_resilience).

# CPU async dispatch queues eager computations behind an in-flight
# semaphore shared process-wide; late in the suite (hundreds of jitted
# programs, host callbacks, and 8-virtual-device collectives behind us)
# a dispatch of a sharded eager op can block forever on that semaphore /
# collective rendezvous — reproduced as a futex-wait hang with an idle
# runtime pool in test_train_rlhf's minibatch jnp.take. Synchronous
# dispatch sidesteps the queue entirely; throughput here is bounded by
# the computations themselves, so the cost is noise.
import jax  # noqa: E402

jax.config.update("jax_cpu_enable_async_dispatch", False)

import pytest  # noqa: E402


def pytest_configure(config):
    # tier-1 runs with `-m 'not slow'`: long soaks (e.g. the serving
    # chaos soak) register here so deselection works without warnings
    config.addinivalue_line(
        "markers", "slow: long-running soak/stress test, excluded from "
        "the tier-1 `-m 'not slow'` run")


@pytest.fixture(scope="session", autouse=True)
def lock_witness(tmp_path_factory):
    """Install the runtime lock witness (docs/ANALYSIS.md) for the whole
    tier-1 run: every repo-created threading.Lock/RLock reports its
    acquisition order, so the concurrency-heavy tests double as
    lock-order probes. A cycle in the observed graph fails the session
    and leaves postmortem_lock_cycle.json for tools/dla_doctor.py.
    Disable with DLA_WITNESS=0."""
    if os.environ.get("DLA_WITNESS", "1") == "0":
        yield None
        return
    from dla_tpu.analysis.witness import install_witness, uninstall_witness
    witness = install_witness()
    yield witness
    out = str(tmp_path_factory.mktemp("lock-witness"))
    cycles = witness.check(out)
    uninstall_witness()
    assert not cycles, (
        "runtime lock-order cycle observed during the test session "
        f"(postmortem in {out}/postmortem_lock_cycle.json): {cycles}")


@pytest.fixture(scope="session")
def mesh8():
    import jax
    from dla_tpu.parallel.mesh import MeshConfig, build_mesh
    assert len(jax.devices()) == 8, (
        "expected 8 virtual CPU devices; run tests via "
        "JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8")
    return build_mesh(MeshConfig(data=2, fsdp=2, model=2, sequence=1))


@pytest.fixture(scope="session")
def tiny_cfg():
    from dla_tpu.models.config import get_model_config
    return get_model_config("tiny")
