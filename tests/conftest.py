"""Test harness: force an 8-device virtual CPU mesh before jax initializes.

This is how the suite exercises multi-chip SPMD (pjit partitioning,
collectives, checkpoint shard round-trips) without TPU hardware —
SURVEY.md sec 4's test strategy.
"""
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

# A TPU-tunnel PJRT plugin (e.g. platform "axon") may have been registered
# by a sitecustomize hook at interpreter start, which sets jax_platforms
# via jax.config — overriding plain env vars. _cpuhost forces the 8-device
# virtual CPU platform back before any backend initializes.
from _cpuhost import force_cpu_platform  # noqa: E402

assert force_cpu_platform(8), (
    "could not force an 8-device virtual CPU platform (a backend with the "
    "wrong platform or device count already initialized in this process); "
    "run pytest in a fresh interpreter")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    import jax
    from dla_tpu.parallel.mesh import MeshConfig, build_mesh
    assert len(jax.devices()) == 8, (
        "expected 8 virtual CPU devices; run tests via "
        "JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8")
    return build_mesh(MeshConfig(data=2, fsdp=2, model=2, sequence=1))


@pytest.fixture(scope="session")
def tiny_cfg():
    from dla_tpu.models.config import get_model_config
    return get_model_config("tiny")
