"""Elastic pod resilience tests (docs/RESILIENCE.md "Elastic
training"): the ``host=`` fault-plan scope, GangMonitor heartbeat
leases + lowest-rank-survivor shrink agreement, the timeout-guarded
collectives, grad-accum recomputation on topology-shift resume, and
the clean-closure path for externally-driven (RLHF) loops.

THE acceptance pin: an 8-host simulated pod loses host 1 mid-run; the
survivors detect it within one lease TTL, write a ``host_lost``
postmortem naming the rank, and exit resumably. The run resumes at
world 4 with the global batch preserved (grad accum 1 -> 2) and its
post-resume loss trajectory + final parameters are bit-identical to a
PLANNED fault-free topology shift through the same checkpoint — with
``train_step_compiles == 1`` per world and the whole outage charged as
``elastic`` badput.
"""
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dla_tpu.parallel.dist import (
    CollectiveTimeout,
    _run_with_deadline,
    allgather_floats,
    barrier,
    clear_collective_deadline,
    set_collective_deadline,
)
from dla_tpu.parallel.mesh import MeshConfig, build_mesh
from dla_tpu.resilience import (
    ElasticConfig,
    ElasticRestart,
    FaultPlan,
    GangMonitor,
    ResilienceConfig,
)
from dla_tpu.telemetry.flight_recorder import FlightRecorder


# ---------------------------------------------------------------------------
# fault-plan host scope
# ---------------------------------------------------------------------------

def test_host_fault_grammar_roundtrip_and_one_shot():
    plan = FaultPlan.parse(
        "host=1:step=6:lost; host=2:step=3:slow:2 ;step=4:nan")
    # entries sort by step; host entries spec() back in host= form
    assert plan.spec() == "host=2:step=3:slow:2;step=4:nan;host=1:step=6:lost"
    # host entries only match site="host" (scopes are disjoint)
    assert plan.take("lost", 100) is None
    assert plan.take("nan", 100, site="host") is None
    hit = plan.take("lost", 7, site="host")
    assert hit is not None and hit.host == 1 and hit.step == 6
    assert plan.take("lost", 7, site="host") is None      # one-shot
    slow = plan.take("slow", 3, site="host")
    assert slow.host == 2 and slow.arg == 2.0


def test_host_fault_grammar_rejects_bad_specs():
    with pytest.raises(ValueError, match="host="):
        FaultPlan.parse("host=1:lost")               # missing step=
    with pytest.raises(ValueError, match="known for host="):
        FaultPlan.parse("host=1:step=3:wedge")       # serving kind
    with pytest.raises(ValueError):
        FaultPlan.parse("host=1:at=3:lost")          # wrong step key
    with pytest.raises(ValueError):
        FaultPlan.parse("step=3:lost")               # host kind, wrong scope


def test_elastic_config_defaults_and_block():
    cfg = ElasticConfig.from_config(None)
    assert not cfg.enabled and cfg.lease_ttl_s == 60.0
    assert cfg.lease_ttl_steps == 0 and cfg.sim_world == 0
    cfg = ElasticConfig.from_config(
        {"enabled": True, "lease_ttl_s": 5, "lease_ttl_steps": 3,
         "gang_dir": "/tmp/g", "sim_world": 8, "collective_deadline_s": 2})
    assert cfg.enabled and cfg.lease_ttl_s == 5.0
    assert cfg.lease_ttl_steps == 3 and cfg.gang_dir == "/tmp/g"
    assert cfg.sim_world == 8 and cfg.collective_deadline_s == 2.0
    # rides the resilience block
    rc = ResilienceConfig.from_config(
        {"elastic": {"enabled": True, "sim_world": 4}})
    assert rc.elastic.enabled and rc.elastic.sim_world == 4
    assert not ResilienceConfig.from_config(None).elastic.enabled


def test_elastic_restart_is_clean_systemexit():
    exc = ElasticRestart(7, epoch=1, survivors=(0, 2, 3), lost=(1,))
    assert isinstance(exc, SystemExit)
    assert exc.code == 0                  # resumable to the launcher
    assert exc.step == 7 and exc.epoch == 1
    assert exc.survivors == (0, 2, 3) and exc.lost == (1,)
    assert "lost host(s) [1]" in str(exc)


# ---------------------------------------------------------------------------
# GangMonitor: simulated-pod detection and agreement
# ---------------------------------------------------------------------------

def _sim_gang(tmp_path, plan="", world=4, ttl_steps=2, recorder=None):
    return GangMonitor(
        tmp_path / "gang", rank=0, world=world, lease_ttl_s=0,
        lease_ttl_steps=ttl_steps, faults=FaultPlan.parse(plan),
        recorder=recorder, sim=True)


def test_sim_gang_detects_lost_host_within_ttl(tmp_path):
    rec = FlightRecorder(out_dir=None)
    gang = _sim_gang(tmp_path, "host=2:step=1:lost", recorder=rec)
    decisions = {}
    for s in range(4):
        gang.beat(s)
        d = gang.check(s)
        if d is not None:
            decisions[s] = d
            break
    # host 2's last lease is step 0; stale at step - 0 >= ttl (2)
    assert list(decisions) == [2]
    d = decisions[2]
    assert d.epoch == 1 and d.lost == (2,) and d.survivors == (0, 1, 3)
    assert d.decided_by == 0
    assert gang.check(5) is d             # sticky once made
    assert any(e["kind"] == "host_lost" and e["lost"] == [2]
               for e in rec.events)
    # the membership record is on disk for the resumed process
    rec2 = json.loads((tmp_path / "gang" / "membership.json").read_text())
    assert rec2["epoch"] == 1 and rec2["lost"] == [2]
    assert rec2["resumed"] is False


def test_sim_gang_cannot_lose_the_simulating_host(tmp_path):
    gang = _sim_gang(tmp_path, "host=0:step=0:lost")
    for s in range(5):
        gang.beat(s)
        assert gang.check(s) is None      # entry consumed but inert


def test_sim_gang_slow_host_records_early_warning(tmp_path):
    rec = FlightRecorder(out_dir=None)
    # lag 2 stays below ttl 4: warning, never a shrink
    gang = _sim_gang(tmp_path, "host=3:step=1:slow:2", ttl_steps=4,
                     recorder=rec)
    for s in range(8):
        gang.beat(s)
        assert gang.check(s) is None
    slow = [e for e in rec.events if e["kind"] == "host_slow"]
    assert len(slow) == 1                 # one-shot report
    assert slow[0]["rank"] == 3 and slow[0]["lag_steps"] == 2


def test_two_monitors_agree_and_restart_gap_is_one_shot(tmp_path):
    gdir = tmp_path / "gang"
    m0 = GangMonitor(gdir, rank=0, world=3, lease_ttl_s=0,
                     lease_ttl_steps=2)
    m1 = GangMonitor(gdir, rank=1, world=3, lease_ttl_s=0,
                     lease_ttl_steps=2)
    for s in range(2):                    # host 2 never beats
        m0.beat(s), m1.beat(s)
        assert m0.check(s) is None and m1.check(s) is None
    m0.beat(2), m1.beat(2)
    # rank 1 is not the lowest survivor: it waits for the proposal
    assert m1.check(2) is None
    d0 = m0.check(2)
    assert d0 is not None and d0.lost == (2,) and d0.decided_by == 0
    # rank 1 adopts the SAME decision from membership.json
    d1 = m1.check(2)
    assert d1 == d0

    # the resumed (world-2) gang adopts epoch 1 and consumes the gap once
    fresh = GangMonitor(gdir, rank=0, world=2, lease_ttl_s=0,
                        lease_ttl_steps=2)
    assert fresh.epoch == 1
    info = fresh.consume_restart_gap()
    assert info is not None
    assert info["epoch"] == 1 and info["lost"] == [2]
    assert info["survivors"] == [0, 1] and info["gap_s"] >= 0.0
    assert fresh.consume_restart_gap() is None            # one-shot
    # pre-restart leases were swept; a peer's resumed gang reads None too
    assert not list(gdir.glob("lease_*.json"))
    peer = GangMonitor(gdir, rank=1, world=2, lease_ttl_s=0,
                       lease_ttl_steps=2)
    assert peer.epoch == 1 and peer.consume_restart_gap() is None


# ---------------------------------------------------------------------------
# timeout-guarded collectives
# ---------------------------------------------------------------------------

def test_run_with_deadline_passes_value_and_errors_through():
    assert _run_with_deadline(lambda: 42, "fast", 5.0) == 42
    with pytest.raises(ValueError, match="boom"):
        _run_with_deadline(lambda: (_ for _ in ()).throw(
            ValueError("boom")), "err", 5.0)


def test_run_with_deadline_times_out_with_suspects():
    set_collective_deadline(10.0, suspects=lambda: [3])
    try:
        with pytest.raises(CollectiveTimeout) as ei:
            _run_with_deadline(lambda: time.sleep(2.0), "hung", 0.05)
        exc = ei.value
        assert exc.name == "hung" and exc.suspects == (3,)
        assert "suspect rank(s): [3]" in str(exc)
    finally:
        clear_collective_deadline()
    # a crashing resolver must not mask the timeout itself
    set_collective_deadline(10.0,
                            suspects=lambda: 1 / 0)  # raises at resolve
    try:
        with pytest.raises(CollectiveTimeout) as ei:
            _run_with_deadline(lambda: time.sleep(2.0), "hung2", 0.05)
        assert ei.value.suspects == ()
    finally:
        clear_collective_deadline()


def test_single_process_collectives_skip_the_deadline_machinery():
    # fast paths return before any worker thread exists, so an armed
    # deadline can never false-positive a single-process run
    set_collective_deadline(1e-9, suspects=lambda: [1])
    try:
        assert barrier("b") is None
        row = allgather_floats([1.0, 2.0])
        assert row.shape == (1, 2) and row[0, 1] == 2.0
    finally:
        clear_collective_deadline()


# ---------------------------------------------------------------------------
# trainer integration: the same tiny regression problem test_resilience
# pins its checkpoint-identity guarantees on
# ---------------------------------------------------------------------------

DIM = 8


def _make_batch(i, bs=8):
    rs = np.random.RandomState(1000 + i)
    x = rs.normal(size=(bs, DIM)).astype(np.float32)
    w_true = np.arange(1, DIM + 1, dtype=np.float32)
    return {"x": x, "y": (x @ w_true).astype(np.float32)}


class CountingIter:
    """Deterministic stream whose position is exact resume state — and
    topology-independent: it always yields the GLOBAL batch, which the
    trainer splits by its own (recomputed) grad accum."""

    def __init__(self):
        self.i = 0

    def __iter__(self):
        return self

    def __next__(self):
        b = _make_batch(self.i)
        self.i += 1
        return b

    def state_dict(self):
        return {"i": self.i}

    def load_state_dict(self, state):
        self.i = int(state["i"])


def _linear_loss(params, frozen, batch, rng):
    del frozen, rng
    pred = batch["x"] @ params["w"]
    return jnp.mean((pred - batch["y"]) ** 2), {}


def _make_trainer(mesh, out_dir, *, max_steps=12, save_every=4, accum=1,
                  resilience=None):
    from dla_tpu.training.trainer import Trainer
    config = {
        "experiment_name": "elastic_test",
        "data": {"prefetch": 0},
        "optimization": {"total_batch_size": 8, "micro_batch_size": 1,
                         "learning_rate": 1e-2, "max_train_steps": max_steps,
                         "lr_scheduler": "constant", "max_grad_norm": 1.0},
        "logging": {"output_dir": str(out_dir), "log_dir": None,
                    "save_every_steps": save_every,
                    "log_every_steps": 10 ** 6},
        "hardware": {"gradient_accumulation_steps": accum},
    }
    if resilience is not None:
        config["resilience"] = resilience
    return Trainer(config=config, mesh=mesh, loss_fn=_linear_loss,
                   params={"w": jnp.zeros((DIM,), jnp.float32)},
                   param_specs={"w": P()})


def _elastic_res(world, fault_plan=""):
    return {"elastic": {"enabled": True, "lease_ttl_s": 0,
                        "lease_ttl_steps": 3, "sim_world": world},
            "fault_plan": fault_plan}


def test_adopt_saved_global_batch_rules(mesh8, tmp_path):
    """dp=4 here: a checkpoint batch of 6 has no integral accum; 16 is
    adopted by recomputing accum 2 -> 4; adopting after the train step
    compiled is refused (accum is baked into the traced graph)."""
    with jax.sharding.set_mesh(mesh8):
        tr = _make_trainer(mesh8, tmp_path / "a", accum=2)
        assert tr.global_batch == 8
        tr._adopt_saved_global_batch({"global_batch": 8})     # no-op
        assert tr.accum == 2
        with pytest.raises(ValueError, match="not.*divisible"):
            tr._adopt_saved_global_batch({"global_batch": 6})
        tr._adopt_saved_global_batch({"global_batch": 16})
        assert tr.accum == 4 and tr.global_batch == 16

        tr2 = _make_trainer(mesh8, tmp_path / "b", accum=2)
        tr2.train_step_compiles = 1
        with pytest.raises(RuntimeError, match="already.*compiled"):
            tr2._adopt_saved_global_batch({"global_batch": 16})


def test_planned_global_batch_peeks_checkpoint_aux(mesh8, tmp_path):
    """Entry points size their data iterators before try_resume runs, so
    a topology-shift resume must announce the SAVED global batch up
    front: planned_global_batch(resume=True) peeks the checkpoint aux
    without restoring tensors; fresh runs (or an empty checkpoint dir)
    answer the current geometry."""
    with jax.sharding.set_mesh(mesh8):
        out = tmp_path / "gb"
        tr = _make_trainer(mesh8, out, accum=2)          # global batch 8
        assert tr.planned_global_batch(resume=False) == 8
        assert tr.planned_global_batch(resume=True) == 8  # nothing saved
        tr.global_batch = 16                              # pretend a
        tr.save()                                         # bigger world
        tr.global_batch = 8
        assert tr.checkpointer.peek_aux()["global_batch"] == 16
        assert tr.planned_global_batch(resume=True) == 16
        assert tr.planned_global_batch(resume=False) == 8


def test_poll_preemption_surfaces_elastic_restart(mesh8, tmp_path):
    """Externally-driven loops (the RLHF rollout path) poll at rollout
    boundaries: a lost gang peer must surface there as the same clean
    ElasticRestart the fit loop raises — with the postmortem written."""
    with jax.sharding.set_mesh(mesh8):
        out = tmp_path / "rollout"
        tr = _make_trainer(
            mesh8, out, accum=2,
            resilience={"elastic": {"enabled": True, "lease_ttl_s": 0.05,
                                    "sim_world": 4},
                        "fault_plan": "host=1:step=0:lost"})
        try:
            tr.poll_preemption()          # beats; takes the lost fault
            time.sleep(0.12)              # host 1's lease expires
            with pytest.raises(ElasticRestart) as ei:
                tr.poll_preemption()
            exc = ei.value
            assert exc.code == 0
            assert exc.lost == (1,) and exc.survivors == (0, 2, 3)
            pm = json.loads((out / "postmortem_host_lost.json").read_text())
            assert pm["reason"] == "host_lost"
            assert any(e["kind"] == "host_lost" and e["lost"] == [1]
                       for e in pm["events"])
        finally:
            clear_collective_deadline()


def test_chaos_host_loss_resumes_at_world_4_bit_identical(tmp_path):
    """THE acceptance pin. Arm A: an 8-host simulated pod loses host 1
    at step 5 (``host=1:step=5:lost``); its last lease is step 4, so
    with lease_ttl_steps=3 detection lands at step 7 — within one TTL —
    as an ElasticRestart naming rank 1, after a ``host_lost``
    postmortem. The run resumes on 4 hosts from the step-4 checkpoint
    with the global batch preserved (grad accum 1 -> 2) and the full
    outage charged as ``elastic`` badput. Arm B: a PLANNED fault-free
    topology shift through the same step-4 boundary. Both arms' post-
    resume loss trajectories and final parameters must match
    bit-for-bit, with exactly one train-step compile per world."""
    devices = jax.devices()
    assert len(devices) == 8
    mesh_w8 = build_mesh(MeshConfig(data=1, fsdp=8, model=1, sequence=1),
                         devices=devices[:8])
    mesh_w4 = build_mesh(MeshConfig(data=1, fsdp=4, model=1, sequence=1),
                         devices=devices[:4])

    # ---- arm A: faulted world-8 run
    out_a = tmp_path / "faulted"
    with jax.sharding.set_mesh(mesh_w8):
        tr = _make_trainer(mesh_w8, out_a,
                           resilience=_elastic_res(8, "host=1:step=5:lost"))
        it = CountingIter()
        with pytest.raises(ElasticRestart) as ei:
            tr.fit(it, rng=jax.random.key(42), data_state=it.state_dict)
        exc = ei.value
        assert exc.code == 0              # clean, resumable exit
        assert exc.step == 7              # fault@5, lease@4, ttl 3
        assert exc.epoch == 1
        assert exc.lost == (1,)
        assert exc.survivors == (0, 2, 3, 4, 5, 6, 7)
        assert tr.train_step_compiles == 1
    pm = json.loads((out_a / "postmortem_host_lost.json").read_text())
    assert pm["reason"] == "host_lost"
    assert any(e["kind"] == "host_lost" and e["lost"] == [1]
               for e in pm["events"])

    # ---- arm A resumed at world 4
    with jax.sharding.set_mesh(mesh_w4):
        res = _make_trainer(mesh_w4, out_a, resilience=_elastic_res(4))
        it2 = CountingIter()
        p_res = res.fit(it2, rng=jax.random.key(42),
                        data_state=it2.state_dict, resume=True)
        assert res.step == 12
        assert res.accum == 2             # recomputed: 8 = 1 * dp4 * 2
        assert res.global_batch == 8      # the invariant, preserved
        assert it2.i == 12                # data fast-forwarded to 4
        assert res.train_step_compiles == 1
        assert res.gang.epoch == 1
        ev = [e for e in res.recorder.events
              if e["kind"] == "elastic_resume"]
        assert len(ev) == 1
        assert ev[0]["step"] == 4 and ev[0]["lost"] == [1]
        assert ev[0]["gap_s"] > 0.0
        # the whole detect -> restart -> resume gap is elastic badput
        assert res.clock.lost["elastic"] == pytest.approx(
            ev[0]["gap_s"])
        assert res.clock.badput()["elastic"] > 0.0
        loss_a = [(e["step"], e["loss"]) for e in res.recorder.events
                  if e["kind"] == "step_end"]

    # ---- arm B: planned fault-free shift through the same boundary
    out_b = tmp_path / "planned"
    with jax.sharding.set_mesh(mesh_w8):
        ref = _make_trainer(mesh_w8, out_b, max_steps=4,
                            resilience=_elastic_res(8))
        itb = CountingIter()
        ref.fit(itb, rng=jax.random.key(42), data_state=itb.state_dict)
        assert ref.step == 4
    with jax.sharding.set_mesh(mesh_w4):
        ref_res = _make_trainer(mesh_w4, out_b, resilience=_elastic_res(4))
        itb2 = CountingIter()
        p_ref = ref_res.fit(itb2, rng=jax.random.key(42),
                            data_state=itb2.state_dict, resume=True)
        assert ref_res.step == 12 and ref_res.accum == 2
        assert ref_res.train_step_compiles == 1
        loss_b = [(e["step"], e["loss"]) for e in ref_res.recorder.events
                  if e["kind"] == "step_end"]

    # post-resume trajectories and final params: bit-identical
    assert loss_a == loss_b
    assert np.asarray(p_res["w"]).tobytes() \
        == np.asarray(p_ref["w"]).tobytes()
