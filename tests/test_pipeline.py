"""Pipeline parallelism (`stage` mesh axis, ops/pipeline.py): GPipe
schedule parity with the plain scan-over-layers forward, gradients
through the ppermute ring, composition with TP/FSDP and packing, and the
trainer integration. Closes SURVEY.md sec 2.3's one open parallelism row
(the reference's nearest analog is device_map="auto" layer spilling)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dla_tpu.models.config import get_model_config
from dla_tpu.models.transformer import Transformer
from dla_tpu.ops.fused_ce import model_fused_ce
from dla_tpu.parallel.mesh import MeshConfig, build_mesh
from dla_tpu.parallel.sharding import sharding_tree


def _stage_mesh(stage=2, data=1, fsdp=2, model=2):
    if jax.device_count() < stage * data * fsdp * model:
        pytest.skip("needs the 8-device CPU mesh")
    return build_mesh(MeshConfig(stage=stage, data=data, fsdp=fsdp,
                                 model=model, sequence=1))


@pytest.fixture(scope="module")
def tiny_setup():
    cfg = get_model_config("tiny")   # 2 layers -> 2 stages of 1
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(1, 100, (4, 16)), jnp.int32)
    return model, params, ids


def test_pipeline_forward_matches_plain_scan(tiny_setup):
    model, params, ids = tiny_setup
    want = model.apply(params, ids)
    mesh = _stage_mesh()
    with jax.sharding.set_mesh(mesh):
        sp = jax.device_put(params, sharding_tree(model.partition_specs(),
                                                  mesh))
        got = jax.jit(lambda p: model.apply(p, ids))(sp)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_pipeline_grads_match_plain_scan(tiny_setup):
    model, params, ids = tiny_setup
    batch = {"input_ids": ids, "labels": jnp.where(ids % 5 == 0, -100, ids)}

    def loss(p):
        return model_fused_ce(model, p, batch)[0]

    g_ref = jax.grad(loss)(params)
    mesh = _stage_mesh()
    with jax.sharding.set_mesh(mesh):
        sp = jax.device_put(params, sharding_tree(model.partition_specs(),
                                                  mesh))
        g_pp = jax.jit(jax.grad(loss))(sp)
    for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_pipeline_with_packing_and_mask(tiny_setup):
    """Packed segment ids + right padding flow through the pipeline's aux
    shift register (each stage must see ITS microbatch's mask)."""
    model, params, _ = tiny_setup
    rs = np.random.RandomState(1)
    ids = jnp.asarray(rs.randint(1, 100, (4, 16)), jnp.int32)
    seg = np.zeros((4, 16), np.int32)
    for i in range(4):
        n1 = 4 + i
        seg[i, :n1] = 1
        seg[i, n1:12] = 2
    seg = jnp.asarray(seg)
    mask = (seg > 0).astype(jnp.int32)
    want = model.apply(params, ids, attention_mask=mask, segment_ids=seg)
    mesh = _stage_mesh()
    with jax.sharding.set_mesh(mesh):
        sp = jax.device_put(params, sharding_tree(model.partition_specs(),
                                                  mesh))
        got = jax.jit(lambda p: model.apply(
            p, ids, attention_mask=mask, segment_ids=seg))(sp)
    m = np.asarray(seg) > 0
    for bi in range(4):
        np.testing.assert_allclose(
            np.asarray(got)[bi][m[bi]], np.asarray(want)[bi][m[bi]],
            rtol=2e-4, atol=2e-4)


def test_pipeline_flash_config_keeps_packed_mask(tiny_setup):
    """Regression: with attention='flash' but a flash-INELIGIBLE batch
    (gapped_mask), the pipeline's XLA path must still build and apply
    the segment mask (deciding flash eligibility after the mask gate
    once dropped the mask entirely — cross-segment attention)."""
    import dataclasses
    model, params, _ = tiny_setup
    model_f = Transformer(dataclasses.replace(model.cfg, attention="flash"))
    rs = np.random.RandomState(2)
    ids = jnp.asarray(rs.randint(1, 100, (4, 16)), jnp.int32)
    seg = np.zeros((4, 16), np.int32)
    seg[:, :7] = 1
    seg[:, 7:14] = 2
    seg = jnp.asarray(seg)
    want = model_f.apply(params, ids, segment_ids=seg, gapped_mask=True)
    mesh = _stage_mesh()
    with jax.sharding.set_mesh(mesh):
        sp = jax.device_put(params, sharding_tree(model_f.partition_specs(),
                                                  mesh))
        got = jax.jit(lambda p: model_f.apply(
            p, ids, segment_ids=seg, gapped_mask=True))(sp)
    m = np.asarray(seg) > 0
    for bi in range(4):
        np.testing.assert_allclose(
            np.asarray(got)[bi][m[bi]], np.asarray(want)[bi][m[bi]],
            rtol=2e-4, atol=2e-4)


def test_pipeline_more_microbatches(tiny_setup):
    """pipeline_microbatches > n_stages shrinks the bubble; parity must
    hold for any M dividing the batch."""
    import dataclasses
    model, params, ids = tiny_setup
    cfg4 = dataclasses.replace(model.cfg, pipeline_microbatches=4)
    model4 = Transformer(cfg4)
    want = model4.apply(params, ids)
    mesh = _stage_mesh()
    with jax.sharding.set_mesh(mesh):
        sp = jax.device_put(params, sharding_tree(model4.partition_specs(),
                                                  mesh))
        got = jax.jit(lambda p: model4.apply(p, ids))(sp)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_pipeline_degrades_microbatches_for_odd_batches(tiny_setup):
    """A batch the configured M doesn't divide (last partial eval batch)
    must still run — M degrades to the gcd instead of raising."""
    import dataclasses
    model, params, _ = tiny_setup
    cfg4 = dataclasses.replace(model.cfg, pipeline_microbatches=4)
    model4 = Transformer(cfg4)
    rs = np.random.RandomState(3)
    ids = jnp.asarray(rs.randint(1, 100, (6, 16)), jnp.int32)  # gcd(4,6)=2
    want = model4.apply(params, ids)
    mesh = _stage_mesh()
    with jax.sharding.set_mesh(mesh):
        sp = jax.device_put(params, sharding_tree(model4.partition_specs(),
                                                  mesh))
        got = jax.jit(lambda p: model4.apply(p, ids))(sp)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_pipeline_flash_engages_and_matches(monkeypatch):
    """Under PP the Pallas flash kernel must actually ENGAGE (nested
    partial-manual shard_map inside the stage shard_map) and match the
    plain forward — round-3 verdict item 5 pinned PP to XLA attention."""
    import dataclasses

    import dla_tpu.ops.flash_attention as fa

    cfg = dataclasses.replace(get_model_config("tiny"), attention="flash")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    rs = np.random.RandomState(5)
    ids = jnp.asarray(rs.randint(1, 100, (4, 16)), jnp.int32)
    want = model.apply(params, ids)

    calls = []
    real = fa.flash_causal_attention

    def counting(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(fa, "flash_causal_attention", counting)
    from dla_tpu.models import transformer as tf_mod
    tf_mod._REPLICATED_FLASH_LOGGED.clear()
    mesh = _stage_mesh()
    with jax.sharding.set_mesh(mesh):
        sp = jax.device_put(params, sharding_tree(model.partition_specs(),
                                                  mesh))
        got = jax.jit(lambda p: model.apply(p, ids))(sp)
    assert calls, "flash kernel was not traced under pipeline parallelism"
    # and through the NESTED shard_map path, not the replicated fallback
    # (the exact degradation this feature removes)
    assert not tf_mod._REPLICATED_FLASH_LOGGED, (
        "flash under PP took the replicated fallback: "
        f"{tf_mod._REPLICATED_FLASH_LOGGED}")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_pipeline_flash_packed_matches(tiny_setup):
    """flash x packing x PP: segment ids ride the aux shift register into
    the kernel (no [B,T,T] mask under flash)."""
    import dataclasses

    model0, params, _ = tiny_setup
    cfg = dataclasses.replace(model0.cfg, attention="flash")
    model = Transformer(cfg)
    rs = np.random.RandomState(6)
    ids = jnp.asarray(rs.randint(1, 100, (4, 16)), jnp.int32)
    seg = np.zeros((4, 16), np.int32)
    for i in range(4):
        seg[i, :6] = 1
        seg[i, 6:16] = 2
    seg = jnp.asarray(seg)
    want = model.apply(params, ids, segment_ids=seg)
    mesh = _stage_mesh()
    with jax.sharding.set_mesh(mesh):
        sp = jax.device_put(params, sharding_tree(model.partition_specs(),
                                                  mesh))
        got = jax.jit(lambda p: model.apply(p, ids, segment_ids=seg))(sp)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_pipeline_flash_grads_match(tiny_setup):
    """Backward through flash-in-PP: remat'd kernel bwd nests under the
    stage shard_map's reverse schedule."""
    import dataclasses

    model0, params, _ = tiny_setup
    cfg = dataclasses.replace(model0.cfg, attention="flash")
    model = Transformer(cfg)
    rs = np.random.RandomState(7)
    ids = jnp.asarray(rs.randint(1, 100, (4, 16)), jnp.int32)
    batch = {"input_ids": ids, "labels": jnp.where(ids % 5 == 0, -100, ids)}

    def loss(p):
        return model_fused_ce(model, p, batch)[0]

    g_ref = jax.grad(loss)(params)
    mesh = _stage_mesh()
    with jax.sharding.set_mesh(mesh):
        sp = jax.device_put(params, sharding_tree(model.partition_specs(),
                                                  mesh))
        g_pp = jax.jit(jax.grad(loss))(sp)
    for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_interleaved_pipeline_matches_plain_scan():
    """Circular schedule (virtual stages): 4 layers over 2 stages x
    interleave 2 — stage 0 owns blocks {0, 2}, stage 1 blocks {1, 3},
    microbatches traverse the ring twice. Must equal the plain forward."""
    import dataclasses
    cfg = dataclasses.replace(get_model_config("tiny-gqa"),
                              pipeline_interleave=2)  # 4 layers: c=1
    model = Transformer(cfg)
    params = model.init(jax.random.key(2))
    rs = np.random.RandomState(8)
    ids = jnp.asarray(rs.randint(1, 100, (4, 16)), jnp.int32)
    want = model.apply(params, ids)
    mesh = _stage_mesh()
    with jax.sharding.set_mesh(mesh):
        sp = jax.device_put(params, sharding_tree(model.partition_specs(),
                                                  mesh))
        got = jax.jit(lambda p: model.apply(p, ids))(sp)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_interleaved_pipeline_grads_match(tiny_setup):
    """Backward through the circular schedule: autodiff reverses the
    V-pass shift register."""
    import dataclasses
    cfg = dataclasses.replace(get_model_config("tiny-gqa"),
                              pipeline_interleave=2)
    model = Transformer(cfg)
    params = model.init(jax.random.key(3))
    rs = np.random.RandomState(9)
    ids = jnp.asarray(rs.randint(1, 100, (4, 16)), jnp.int32)
    batch = {"input_ids": ids, "labels": jnp.where(ids % 7 == 0, -100, ids)}

    def loss(p):
        return model_fused_ce(model, p, batch)[0]

    g_ref = jax.grad(loss)(params)
    mesh = _stage_mesh()
    with jax.sharding.set_mesh(mesh):
        sp = jax.device_put(params, sharding_tree(model.partition_specs(),
                                                  mesh))
        g_pp = jax.jit(jax.grad(loss))(sp)
    for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_interleaved_storage_no_weight_collective():
    """Round-5 verdict item 3: with pipeline_stages set, layer weights
    store block-major [V, S, c, ...] and the circular schedule runs with
    ZERO cross-stage weight collectives — the flat layout paid one
    weight-shaped all-to-all per layer leaf per step (~(V-1)/V of all
    layer bytes). HLO-level assertion on a pure stage mesh (no fsdp/tp,
    so any all-gather/all-to-all would be the weight reshard) + parity."""
    import dataclasses
    import re
    cfg0 = get_model_config("tiny-gqa")
    model0 = Transformer(cfg0)
    params0 = model0.init(jax.random.key(2))
    rs = np.random.RandomState(30)
    ids = jnp.asarray(rs.randint(1, 100, (4, 16)), jnp.int32)
    want = model0.apply(params0, ids)

    cfg = dataclasses.replace(cfg0, pipeline_interleave=2,
                              pipeline_stages=2)
    model = Transformer(cfg)
    params = model.to_storage_layout(params0)
    for k, v in params["layers"].items():
        assert v.shape[:3] == (2, 2, 1), (k, v.shape)
    # plain-scan path flattens storage back for free — exact equality
    np.testing.assert_array_equal(np.asarray(model.apply(params, ids)),
                                  np.asarray(want))

    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices")
    mesh = build_mesh(MeshConfig(stage=2, data=1, fsdp=1, model=1,
                                 sequence=1), devices=jax.devices()[:2])
    with jax.sharding.set_mesh(mesh):
        sp = jax.device_put(params, sharding_tree(model.partition_specs(),
                                                  mesh))
        compiled = jax.jit(lambda p: model.apply(p, ids)).lower(sp
                                                               ).compile()
        got = compiled(sp)
    hlo = compiled.as_text()
    counts = {op: len(re.findall(rf'= [^\n]*{op}\(', hlo))
              for op in ("all-gather", "all-to-all")}
    assert counts["all-gather"] == 0 and counts["all-to-all"] == 0, (
        f"cross-stage weight reshard survived: {counts}")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_interleaved_storage_grads_match():
    """Backward under block-major storage: grads come back in storage
    layout and match the canonical reference after flattening."""
    import dataclasses
    cfg0 = get_model_config("tiny-gqa")
    model0 = Transformer(cfg0)
    params0 = model0.init(jax.random.key(3))
    rs = np.random.RandomState(31)
    ids = jnp.asarray(rs.randint(1, 100, (4, 16)), jnp.int32)
    batch = {"input_ids": ids, "labels": jnp.where(ids % 7 == 0, -100, ids)}
    g_ref = jax.grad(lambda p: model_fused_ce(model0, p, batch)[0])(params0)

    cfg = dataclasses.replace(cfg0, pipeline_interleave=2,
                              pipeline_stages=2)
    model = Transformer(cfg)
    params = model.to_storage_layout(params0)
    mesh = _stage_mesh()
    with jax.sharding.set_mesh(mesh):
        sp = jax.device_put(params, sharding_tree(model.partition_specs(),
                                                  mesh))
        g_pp = jax.jit(jax.grad(
            lambda p: model_fused_ce(model, p, batch)[0]))(sp)
    g_flat = model.to_canonical_layout(g_pp)
    for a, b in zip(jax.tree.leaves(g_flat), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_interleaved_storage_gemma2_swa_and_lora():
    """swa_on flags reshape with the block-major storage (canonical
    index semantics survive the row-major reshape), and LoRA
    init/merge/quantize all speak the 5-D leaf layout."""
    import dataclasses
    cfg0 = dataclasses.replace(
        get_model_config("tiny-gqa"),
        arch="gemma2", sliding_window=5, sliding_window_pattern=2,
        attn_logit_softcap=20.0, final_logit_softcap=10.0,
        query_pre_attn_scalar=8, tie_embeddings=True,
        lora_r=4, lora_targets=("wq", "wv"))
    model0 = Transformer(cfg0)
    params0 = model0.init(jax.random.key(13))
    lora0 = model0.init_lora(jax.random.key(14))
    # make B nonzero so merge actually changes weights
    lora0 = jax.tree.map(
        lambda x: x + 0.01 if x.ndim and x.shape[-1] != 4 else x, lora0)
    rs = np.random.RandomState(32)
    ids = jnp.asarray(rs.randint(1, 100, (4, 16)), jnp.int32)
    want = model0.apply(model0.merge_lora(params0, lora0), ids)

    cfg = dataclasses.replace(cfg0, pipeline_interleave=2,
                              pipeline_stages=2)
    model = Transformer(cfg)
    params = model.to_storage_layout(params0)
    lora = model.to_storage_layout(lora0)
    merged = model.merge_lora(params, lora)
    got = model.apply(merged, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    # quantize_weights handles 5-D mats; decode path flattens them
    q = model.quantize_weights(merged)
    assert q["layers"]["wq"].dtype == jnp.int8
    assert q["layers"]["wq_wscale"].shape[:3] == (2, 2, 1)
    logits, cache = model.start_decode(
        q, ids[:, :8], jnp.ones((4, 8), jnp.int32), max_new_tokens=2)
    logits2, _ = model.decode_step(q, cache, jnp.argmax(logits, -1))
    assert np.isfinite(np.asarray(logits2)).all()
    # pipeline parity under the stage mesh
    mesh = _stage_mesh()
    with jax.sharding.set_mesh(mesh):
        sp = jax.device_put(merged, sharding_tree(model.partition_specs(),
                                                  mesh))
        got_pp = jax.jit(lambda p: model.apply(p, ids))(sp)
    np.testing.assert_allclose(np.asarray(got_pp), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_config_loader_sets_pipeline_stages(tmp_path):
    """load_config copies hardware.mesh.stage into model.pipeline_stages
    when pipeline_interleave > 1 (the storage-layout coupling)."""
    import yaml

    from dla_tpu.training.config import load_config
    cfg = {"model": {"model_name_or_path": "tiny-gqa",
                     "pipeline_interleave": 2},
           "hardware": {"mesh": {"stage": 2, "fsdp": 4}}}
    p = tmp_path / "c.yaml"
    p.write_text(yaml.safe_dump(cfg))
    out = load_config(str(p), quiet=True)
    assert out["model"]["pipeline_stages"] == 2
    # explicit value wins; no interleave -> untouched
    cfg["model"]["pipeline_stages"] = 4
    p.write_text(yaml.safe_dump(cfg))
    assert load_config(str(p), quiet=True)["model"]["pipeline_stages"] == 4
    del cfg["model"]["pipeline_stages"]
    cfg["model"]["pipeline_interleave"] = 1
    p.write_text(yaml.safe_dump(cfg))
    assert "pipeline_stages" not in load_config(str(p),
                                                quiet=True)["model"]


def test_interleaved_falls_back_when_batch_too_small(capsys):
    """A batch that cannot split into S microbatches falls back to plain
    GPipe with a warning instead of failing."""
    import dataclasses

    from dla_tpu.ops.pipeline import _DEGRADE_WARNED
    cfg = dataclasses.replace(get_model_config("tiny-gqa"),
                              pipeline_interleave=2)
    model = Transformer(cfg)
    params = model.init(jax.random.key(4))
    rs = np.random.RandomState(10)
    ids = jnp.asarray(rs.randint(1, 100, (1, 16)), jnp.int32)  # 1 row
    want = model.apply(params, ids)
    _DEGRADE_WARNED.clear()
    mesh = _stage_mesh()
    with jax.sharding.set_mesh(mesh):
        sp = jax.device_put(params, sharding_tree(model.partition_specs(),
                                                  mesh))
        got = jax.jit(lambda p: model.apply(p, ids))(sp)
    assert "falls back to plain GPipe" in capsys.readouterr().err
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_resolve_microbatches_default_and_degrade(capsys):
    from dla_tpu.ops.pipeline import _DEGRADE_WARNED, resolve_microbatches
    _DEGRADE_WARNED.clear()
    # default targets 4*S clipped to the largest divisor of the batch
    assert resolve_microbatches(32, None, 2) == 8
    assert resolve_microbatches(6, None, 2) == 6
    # each microbatch must still split over the dp shards: batch 4 on 2
    # shards caps M at 2 (4 microbatches of 1 row would force the
    # replicated-flash fallback)
    assert resolve_microbatches(4, None, 2, dp_shards=2) == 2
    assert resolve_microbatches(32, None, 2, dp_shards=4) == 8
    assert capsys.readouterr().err == ""   # tolerable bubbles stay quiet
    # a materially bad default bubble (> 1/3) announces itself: batch 5
    # over 4 stages only splits M=5 (bubble 3/8)
    assert resolve_microbatches(5, None, 4) == 5
    assert "bubble" in capsys.readouterr().err
    # explicit config that divides: honored, quiet
    assert resolve_microbatches(8, 4, 2) == 4
    assert capsys.readouterr().err == ""
    # explicit config that doesn't divide: largest divisor below, LOUD
    assert resolve_microbatches(6, 4, 2) == 3
    err = capsys.readouterr().err
    assert "WARNING" in err and "M=3" in err
    # prime batch degrades to serial stages, says so
    assert resolve_microbatches(7, 4, 2) == 1
    assert "SERIALLY" in capsys.readouterr().err
    # once per (requested, batch): no repeat line
    assert resolve_microbatches(7, 4, 2) == 1
    assert capsys.readouterr().err == ""
    # default path hitting serial stages also announces (a prime batch
    # with stages > 1 was the silent case the round-3 verdict flagged)
    assert resolve_microbatches(1, None, 2) == 1
    assert "SERIALLY" in capsys.readouterr().err
    # when the only dp-compatible split is serial, pipelining wins and
    # the broken batch sharding is announced instead
    assert resolve_microbatches(7, None, 2, dp_shards=7) == 7
    err = capsys.readouterr().err
    assert "replicated" in err and "SERIALLY" not in err
    # honored explicit M whose microbatches break batch sharding warns
    # about the replicated fallback
    _DEGRADE_WARNED.clear()
    assert resolve_microbatches(128, 64, 2, dp_shards=8) == 64
    assert "replicated" in capsys.readouterr().err
    # degrade prefers a dp-compatible divisor over a larger broken one
    _DEGRADE_WARNED.clear()
    assert resolve_microbatches(24, 16, 2, dp_shards=8) == 3
    assert "M=3" in capsys.readouterr().err


def test_pipeline_rejects_bad_combos(tiny_setup):
    import dataclasses
    model, params, ids = tiny_setup
    # layers not divisible by stages
    cfg3 = get_model_config("tiny-gqa")  # 4 layers
    bad = Transformer(dataclasses.replace(cfg3, num_layers=3))
    mesh = _stage_mesh()
    with jax.sharding.set_mesh(mesh):
        p3 = bad.init(jax.random.key(0))
        with pytest.raises(ValueError, match="divisible by .*stage"):
            bad.apply(p3, ids)


def test_pipeline_train_step_loss_falls(tiny_setup):
    """Full Trainer step over a stage x fsdp x model mesh: grads flow
    through the pipeline, AdamW updates land, loss falls."""
    from dla_tpu.training.trainer import Trainer

    model, params, _ = tiny_setup
    mesh = _stage_mesh()
    config = {
        "experiment_name": "pp_train_test",
        "optimization": {"total_batch_size": 8, "micro_batch_size": 4,
                         "learning_rate": 5e-3, "max_train_steps": 20,
                         "lr_scheduler": "constant", "max_grad_norm": 1.0},
        "logging": {"output_dir": "/tmp/pp_train_test", "log_dir": None},
        "hardware": {"gradient_accumulation_steps": 2},
    }

    def loss_fn(p, frozen, batch, rng):
        del frozen, rng
        loss, _ = model_fused_ce(model, p, batch)
        return loss, {}

    rs = np.random.RandomState(0)
    batch = {"input_ids": rs.randint(1, 100, (8, 16)).astype(np.int32),
             "attention_mask": np.ones((8, 16), np.int32),
             "labels": rs.randint(1, 100, (8, 16)).astype(np.int32)}
    with jax.sharding.set_mesh(mesh):
        trainer = Trainer(config=config, mesh=mesh, loss_fn=loss_fn,
                          params=params,
                          param_specs=model.partition_specs())
        losses = [trainer.step_on_batch(batch, jax.random.key(i))[0]
                  for i in range(20)]
    assert losses[-1] < losses[0] - 0.5, (losses[0], losses[-1])


def _pp_cp_mesh(stage=2, sequence=2, fsdp=2):
    if jax.device_count() < stage * sequence * fsdp:
        pytest.skip("needs the 8-device CPU mesh")
    return build_mesh(MeshConfig(stage=stage, data=1, fsdp=fsdp, model=1,
                                 sequence=sequence))


def test_pipeline_ring_cp_forward_matches(tiny_setup):
    """PP x CP (round-5 verdict item 2): ring attention's shard_map nests
    partial-manual over the still-auto `sequence` axis inside the stage
    schedule, with CP metadata riding the aux shift register."""
    import dataclasses
    model0, _, _ = tiny_setup
    cfg = dataclasses.replace(model0.cfg, context_parallel="ring")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    rs = np.random.RandomState(20)
    ids = jnp.asarray(rs.randint(1, 100, (4, 32)), jnp.int32)
    want = model.apply(params, ids)
    mesh = _pp_cp_mesh()
    with jax.sharding.set_mesh(mesh):
        sp = jax.device_put(params, sharding_tree(model.partition_specs(),
                                                  mesh))
        got = jax.jit(lambda p: model.apply(p, ids))(sp)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_pipeline_ring_cp_grads_match(tiny_setup):
    """Backward through PP x ring: the ring scan's ppermute transpose
    nests under the stage schedule's reverse shift register."""
    import dataclasses
    model0, _, _ = tiny_setup
    cfg = dataclasses.replace(model0.cfg, context_parallel="ring")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    rs = np.random.RandomState(21)
    ids = jnp.asarray(rs.randint(1, 100, (4, 32)), jnp.int32)
    batch = {"input_ids": ids, "labels": jnp.where(ids % 5 == 0, -100, ids)}

    def loss(p):
        return model_fused_ce(model, p, batch)[0]

    g_ref = jax.grad(loss)(params)
    mesh = _pp_cp_mesh()
    with jax.sharding.set_mesh(mesh):
        sp = jax.device_put(params, sharding_tree(model.partition_specs(),
                                                  mesh))
        g_pp = jax.jit(jax.grad(loss))(sp)
    for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_pipeline_ring_cp_sliding_window(tiny_setup):
    """PP x windowed ring (mistral-style SWA): the window's ring-scan
    truncation and absolute-position mask survive the stage nesting."""
    import dataclasses
    model0, _, _ = tiny_setup
    cfg = dataclasses.replace(model0.cfg, context_parallel="ring",
                              sliding_window=7)
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    rs = np.random.RandomState(22)
    ids = jnp.asarray(rs.randint(1, 100, (4, 32)), jnp.int32)
    want = model.apply(params, ids)
    mesh = _pp_cp_mesh()
    with jax.sharding.set_mesh(mesh):
        sp = jax.device_put(params, sharding_tree(model.partition_specs(),
                                                  mesh))
        got = jax.jit(lambda p: model.apply(p, ids))(sp)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_pipeline_ulysses_cp_forward_matches(tiny_setup):
    """PP x ulysses: the head all-to-all nests inside the stage schedule
    the same way (tiny has 2 kv heads — divisible by sequence=2)."""
    import dataclasses
    model0, _, _ = tiny_setup
    cfg = dataclasses.replace(model0.cfg, context_parallel="ulysses")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    rs = np.random.RandomState(23)
    ids = jnp.asarray(rs.randint(1, 100, (4, 32)), jnp.int32)
    want = model.apply(params, ids)
    mesh = _pp_cp_mesh()
    with jax.sharding.set_mesh(mesh):
        sp = jax.device_put(params, sharding_tree(model.partition_specs(),
                                                  mesh))
        got = jax.jit(lambda p: model.apply(p, ids))(sp)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_pipeline_ring_cp_packed_segments(tiny_setup):
    """PP x ring x packing: segment ids and validity microbatch with the
    activations and rotate around the ring correctly."""
    import dataclasses
    model0, _, _ = tiny_setup
    cfg = dataclasses.replace(model0.cfg, context_parallel="ring")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    rs = np.random.RandomState(24)
    ids = jnp.asarray(rs.randint(1, 100, (4, 32)), jnp.int32)
    seg = np.zeros((4, 32), np.int32)
    for i in range(4):
        n1 = 10 + i
        seg[i, :n1] = 1
        seg[i, n1:28] = 2
    seg = jnp.asarray(seg)
    mask = (seg > 0).astype(jnp.int32)
    want = model.apply(params, ids, attention_mask=mask, segment_ids=seg)
    mesh = _pp_cp_mesh()
    with jax.sharding.set_mesh(mesh):
        sp = jax.device_put(params, sharding_tree(model.partition_specs(),
                                                  mesh))
        got = jax.jit(lambda p: model.apply(
            p, ids, attention_mask=mask, segment_ids=seg))(sp)
    m = np.asarray(seg) > 0
    for bi in range(4):
        np.testing.assert_allclose(
            np.asarray(got)[bi][m[bi]], np.asarray(want)[bi][m[bi]],
            rtol=2e-4, atol=2e-4)


def test_pipeline_gemma2_chunked_attention_parity():
    """gemma-2 under PP at T > DEFAULT_Q_CHUNK: the chunked-attention
    scan (checkpointed) nests inside the stage shard_map and matches the
    no-mesh forward — softcaps + alternating window + post-norms
    included."""
    import dataclasses

    cfg = dataclasses.replace(
        get_model_config("tiny-gqa"),
        arch="gemma2", sliding_window=6, sliding_window_pattern=2,
        attn_logit_softcap=20.0, final_logit_softcap=10.0,
        query_pre_attn_scalar=8, tie_embeddings=True, max_seq_length=1024)
    model = Transformer(cfg)
    params = model.init(jax.random.key(11))
    rs = np.random.RandomState(12)
    ids = jnp.asarray(rs.randint(1, 100, (4, 640)), jnp.int32)
    want = model.apply(params, ids)
    mesh = _stage_mesh()
    with jax.sharding.set_mesh(mesh):
        sp = jax.device_put(params, sharding_tree(model.partition_specs(),
                                                  mesh))
        got = jax.jit(lambda p: model.apply(p, ids))(sp)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=3e-3, atol=3e-4)


def test_interleaved_gemma2_swa_flag_follows_layers():
    """gemma-2's per-layer swa_on flag (injected into the layer stream)
    must ride the SAME [L] -> [S, V, c] round-robin reshape as the
    weights under the circular schedule — a mismatch would window the
    wrong layers."""
    import dataclasses

    cfg = dataclasses.replace(
        get_model_config("tiny-gqa"),
        arch="gemma2", sliding_window=5, sliding_window_pattern=2,
        attn_logit_softcap=20.0, final_logit_softcap=10.0,
        query_pre_attn_scalar=8, tie_embeddings=True,
        pipeline_interleave=2)   # 4 layers: 2 stages x 2 blocks of 1
    model = Transformer(cfg)
    params = model.init(jax.random.key(13))
    rs = np.random.RandomState(14)
    ids = jnp.asarray(rs.randint(1, 100, (4, 16)), jnp.int32)
    want = model.apply(params, ids)
    mesh = _stage_mesh()
    with jax.sharding.set_mesh(mesh):
        sp = jax.device_put(params, sharding_tree(model.partition_specs(),
                                                  mesh))
        got = jax.jit(lambda p: model.apply(p, ids))(sp)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
